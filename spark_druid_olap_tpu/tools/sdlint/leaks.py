"""leaks pass: acquired resources must be released on ALL paths.

A registry of acquire/release pairs the engine actually uses (WLM quota
tokens and lane wait-queue entries, admission tickets, inflight-registry
entries, cancel-flag refcounts, WAL file handles, snapshot temp dirs,
device-pin style pairs) is checked over the exception-edge CFG from
``cfg.py``: from each acquire site, is any function exit — normal or
exceptional — reachable without passing a release?

Scope rules that keep this sound-ish without interprocedural ownership
tracking:

- *Pair* resources (quota, waiter, ticket, inflight, cancel-flag,
  tmpdir, pins) are only checked in functions that attempt a release (or
  construct the resource's carrier) at all — a function that acquires
  and never releases is transferring ownership to object state (e.g. a
  session holding a cancel-flag refcount until ``close()``), which a
  per-function pass cannot judge.
- *Constructor* resources (WAL handles) are the opposite: an unbound or
  never-escaping construction with no ``close()`` is flagged even with
  zero releases present — ``WriteAheadLog(p).replay()`` drops the
  handle. Storing into ``self.x``/a container or returning it is an
  ownership transfer and skips the site.
- Branch headers carry their whole AST subtree, so a release nested
  under ``if tok is not None:`` marks the header node too. This is a
  deliberate over-approximation: conditionally-guarded releases are
  accepted; the pass targets *paths with no release attempt at all*.
- The acquire node's own exception edge is exempt ("the acquire itself
  failed" acquires nothing).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import call_chain, \
    walk_shallow
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Project


@dataclasses.dataclass(frozen=True)
class Resource:
    kind: str
    #: call-chain suffixes that acquire (empty for ctor kinds)
    acquires: Tuple[Tuple[str, ...], ...]
    #: call-chain suffixes that release / transfer
    releases: Tuple[Tuple[str, ...], ...]
    #: class names whose construction takes ownership (e.g. Ticket)
    carriers: Tuple[str, ...] = ()
    #: constructor-style resource: acquire is `Ctor(...)`, escape analysis
    ctor: Optional[str] = None
    #: tmpdir-style: acquire arg must trace to a ".tmp" string literal,
    #: and releases must reference the same name
    tmp_named: bool = False


REGISTRY: Tuple[Resource, ...] = (
    Resource("quota", (("quotas", "acquire"),),
             (("quotas", "release"), ("_unhook",)), carriers=("Ticket",)),
    Resource("lane-waiter", (("enqueue",),),
             (("remove",), ("release",), ("_unhook",)),
             carriers=("Ticket",)),
    Resource("wlm-ticket", (("wlm", "admit"),),
             (("wlm", "release"), ("release",))),
    Resource("inflight", (("inflight", "begin"),),
             (("inflight", "done"), ("done",))),
    Resource("cancel-flag", (("register_query",),),
             (("release_query",),)),
    Resource("device-pin", (("pin_array",), ("device_pin",)),
             (("unpin_array",), ("device_unpin",))),
    # cold-tier column pins: an unreleased token keeps every chunk a
    # query faulted resident forever, silently growing the hot set past
    # its byte budget (tier/store.py pin protocol)
    Resource("tier-pin", (("acquire_pins",),), (("release_pins",),)),
    # mesh-dispatch partial buffers: the fused wave loop holds every
    # device's packed partial aggregates resident between dispatch and
    # host unpack (parallel/meshexec.py PartialLedger); an unreleased
    # token leaves the gauge permanently non-zero, misreporting device
    # memory pressure to the stats surface
    Resource("mesh-partials", (("acquire_partials",),),
             (("release_partials",),)),
    # fault-injection scopes: an unbalanced begin_scope leaves the named
    # scope refcounted open forever, so every rule gated on it keeps
    # firing after the leg that opened it ends (fault/plan.py)
    Resource("fault-scope", (("begin_scope",),), (("end_scope",),)),
    # circuit-breaker claims: an unsettled claim wedges a half-open
    # breaker — its single probe slot never frees, so the node is
    # skipped forever even after it recovers (cluster/breaker.py)
    Resource("breaker-claim", (("before_attempt",),), (("settle",),)),
    # hedge races: close() marks the race cancelled so the losing leg's
    # thread stands down instead of holding its reply buffer and done-
    # event waiters alive (cluster/broker.py)
    Resource("hedge-race", (), (("close",),), ctor="_HedgeRace"),
    Resource("wal-handle", (), (("close",),), ctor="WriteAheadLog"),
    # cluster RPC: every HTTPConnection the broker opens (subquery
    # scatter, readyz probes) must close on all paths — leaked sockets
    # exhaust the historical's accept queue under dashboard storms
    Resource("rpc-conn", (), (("close",),), ctor="HTTPConnection"),
    # scatter pool: a locally-constructed executor dropped without
    # shutdown leaks its worker threads (self.x storage transfers
    # ownership to close())
    Resource("scatter-pool", (), (("shutdown",),),
             ctor="ThreadPoolExecutor"),
    Resource("tmpdir", (("os", "makedirs"),),
             (("os", "replace"), ("rmtree",)), tmp_named=True),
    # epoch publish lock: an unreleased claim wedges topology changes
    # cluster-wide until the stale-lock timeout (cluster/epoch.py)
    Resource("epoch-claim", (("claim_publish",),),
             (("release_publish",),)),
    # drain tokens: an unended begin_subquery keeps wait_drained
    # blocked, so a leaving historical can never fence (historical.py
    # DrainGate protocol)
    Resource("drain-token", (("begin_subquery",),),
             (("end_subquery",),)),
    # broadcast-join build tables: an unreleased build token leaves the
    # device-resident hash table + payload counted as outstanding
    # forever, misreporting join memory pressure and masking real
    # leaks of replicated build state (join/broadcast.py BuildLedger)
    Resource("join-build", (("acquire_build",),),
             (("release_build",),)),
)


def _suffix(chain: Sequence[str], suf: Tuple[str, ...]) -> bool:
    return len(chain) >= len(suf) and tuple(chain[-len(suf):]) == suf


def _scan_calls(payload) -> List[ast.Call]:
    """All calls in a node's subtree, not descending into nested defs;
    synthetic and def/class payloads scan as empty."""
    if not isinstance(payload, ast.AST) or isinstance(
            payload, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return []
    return [n for n in walk_shallow(payload) if isinstance(n, ast.Call)]


def _header_exprs(payload) -> List[ast.AST]:
    """Only the part of a compound statement that executes *at* its CFG
    node (acquire detection must not double-count body statements, which
    have nodes of their own)."""
    if isinstance(payload, (ast.If, ast.While)):
        return [payload.test]
    if isinstance(payload, (ast.For, ast.AsyncFor)):
        return [payload.iter]
    if isinstance(payload, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in payload.items]
    if isinstance(payload, ast.ExceptHandler):
        return [payload.type] if payload.type is not None else []
    if isinstance(payload, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
        return []
    if isinstance(payload, ast.AST):
        return [payload]
    return []


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _has_tmp_literal(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Constant) and isinstance(n.value, str)
               and ".tmp" in n.value for n in ast.walk(expr))


def _bound_name(payload, call: ast.Call) -> Optional[str]:
    """`x = <call possibly wrapped>` -> "x" (single Name target only)."""
    if isinstance(payload, ast.Assign) and len(payload.targets) == 1 \
            and isinstance(payload.targets[0], ast.Name):
        return payload.targets[0].id
    return None


def _check_function(project: Project, mod, qual: str,
                    fn) -> List[Finding]:
    out: List[Finding] = []
    g = project.cfg(fn)
    nodes = g.stmt_nodes()
    # per-node call lists (full subtree: release/avoid detection) and
    # header-only lists (acquire detection)
    full_calls = {n: _scan_calls(g.nodes[n]) for n in nodes}
    head_calls = {n: [c for h in _header_exprs(g.nodes[n])
                      for c in _scan_calls(h)] for n in nodes}

    ordinal: Dict[str, int] = {}
    for res in REGISTRY:
        # acquire sites -------------------------------------------------
        sites = []   # (node, call, varname)
        for n in nodes:
            for c in head_calls[n]:
                ch = call_chain(c.func)
                if res.ctor is not None:
                    if not (ch and ch[-1] == res.ctor):
                        continue
                elif not any(_suffix(ch, a) for a in res.acquires):
                    continue
                if res.tmp_named:
                    if not c.args:
                        continue
                    arg = c.args[0]
                    traced = _has_tmp_literal(arg)
                    dirname = arg.id if isinstance(arg, ast.Name) else None
                    if dirname and not traced:
                        for p in (g.nodes[m] for m in nodes):
                            if isinstance(p, ast.Assign) \
                                    and len(p.targets) == 1 \
                                    and isinstance(p.targets[0], ast.Name) \
                                    and p.targets[0].id == dirname \
                                    and _has_tmp_literal(p.value):
                                traced = True
                                break
                    if not traced:
                        continue
                    sites.append((n, c, dirname))
                else:
                    sites.append((n, c, _bound_name(g.nodes[n], c)))
        if not sites:
            continue

        # release / carrier / escape nodes ------------------------------
        def _is_release(c: ast.Call, var: Optional[str]) -> bool:
            ch = call_chain(c.func)
            hit = any(_suffix(ch, r) for r in res.releases) \
                or (res.carriers and ch and ch[-1] in res.carriers)
            if not hit:
                return False
            if res.tmp_named and var is not None:
                return var in _names_in(c)
            return True

        for site_n, call, var in sites:
            payload = g.nodes[site_n]
            escapes = False
            if res.ctor is not None and isinstance(
                    payload, (ast.With, ast.AsyncWith)):
                # `with Ctor(...):` — __exit__ releases on every path,
                # including the exception edges this pass walks
                escapes = True
            if res.ctor is not None:
                # ownership transfer: stored into an attribute/container
                # at the acquire itself, or the bound name is later
                # stored/returned
                if isinstance(payload, ast.Assign) and any(
                        not isinstance(t, ast.Name)
                        for t in payload.targets):
                    escapes = True
                if var is not None:
                    for m in nodes:
                        p = g.nodes[m]
                        if isinstance(p, ast.Assign) \
                                and not isinstance(p, str) \
                                and any(not isinstance(t, ast.Name)
                                        for t in p.targets) \
                                and var in _names_in(p.value):
                            escapes = True
                        if isinstance(p, ast.Return) \
                                and p.value is not None \
                                and var in _names_in(p.value):
                            escapes = True
            if escapes:
                continue

            avoid: Set[int] = set()
            any_release = False
            for m in nodes:
                if m == site_n:
                    # the acquire node may also contain a release (e.g.
                    # an `if` header with the whole protocol under it) —
                    # still counts as "release attempted"
                    if any(_is_release(c, var) for c in full_calls[m]
                           if c is not call):
                        any_release = True
                        avoid.add(m)
                    continue
                rel = any(_is_release(c, var) for c in full_calls[m])
                p = g.nodes[m]
                if not rel and var is not None and res.ctor is None \
                        and isinstance(p, ast.Return) \
                        and p.value is not None \
                        and var in _names_in(p.value):
                    rel = True      # resource returned to the caller
                if rel:
                    any_release = True
                    avoid.add(m)
            if res.ctor is None and not any_release:
                # no release attempted anywhere: ownership lives in
                # object state; out of scope for a per-function check
                continue

            path = g.reachable_avoiding(site_n, {g.exit, g.raise_exit},
                                        avoid, skip_start_raise=True)
            if path is None:
                continue
            how = "an exception path" if path[-1] == g.raise_exit \
                else "a normal return path"
            rule = ("unclosed-" if res.ctor is not None
                    else "unreleased-") + res.kind
            k = f"{qual}:{res.kind}"
            ordinal[k] = ordinal.get(k, 0) + 1
            sym = k if ordinal[k] == 1 else f"{k}#{ordinal[k]}"
            out.append(Finding(
                "leaks", rule, mod.relpath, call.lineno, sym,
                f"{res.kind} acquired here can reach {how} without "
                f"release (witness escapes via "
                f"{'raise' if path[-1] == g.raise_exit else 'return'}); "
                f"release in a finally/context manager covering the "
                f"acquire"))
    return out


def run(project: Project) -> List[Finding]:
    idx = project.index()
    out: List[Finding] = []
    for (mod_name, qual), fn in sorted(idx.functions.items()):
        mod = project.modules[mod_name].mod \
            if hasattr(project.modules[mod_name], "mod") \
            else project.modules[mod_name]
        out.extend(_check_function(project, mod, qual, fn))
    return out
