"""SPMD mesh-safety pass.

The sharded execution tier (``parallel/mesh.py``, ``parallel/
multihost.py``, and every ``shard_map`` site in the executor) runs ONE
traced program replicated across chips; the only cross-replica
communication is the collective calls inside it. Four properties keep
that replication safe, and all four are checkable without a mesh:

- **unknown-axis-name** — a collective's axis (and the axis names in
  ``P(...)`` specs at ``shard_map`` sites) must be an axis the mesh
  module actually declares (``SEGMENT_AXIS``/``Mesh`` construction).
  A typo'd axis string fails only when the sharded path finally runs —
  which, on CPU CI, is never. Names threaded through parameters are
  accepted (the binding site is checked instead).
- **sketch-merge-mismatch** — register-valued aggregates merge by
  *register algebra*, not addition: HLL rho registers are maxima,
  theta k-min registers are minima. The expected operator is declared
  per sketch in ``ops/agg_registry.py:AGG_CLOSURE`` (``merge`` field);
  ``ops/<sketch>.py:merge_registers`` must use the matching collective
  — a ``psum`` over HLL/theta registers double-counts silently.
- **merge-op-mismatch** — in any branch dispatching on an aggregate
  ``kind == "min"``/``"max"``, the collective used must be
  ``pmin``/``pmax``; a ``psum`` there sums extrema across chips.
- **host-call-in-shard** / **host-state-write-in-shard** — code
  reachable from a ``shard_map`` body must not call host callbacks
  (``io_callback``/``pure_callback``/``jax.debug.*``), draw from
  ``jax.random`` (replicas would diverge unless keys are split per
  axis index — thread keys in explicitly), or write host-global state
  (``self.*`` attributes, module-level caches/registries/stats dicts —
  the same write vocabulary the locks pass checks): the body traces
  ONCE, so the write happens at trace time on every host, not per
  shard, and the replicas' view of it diverges from the host's.

Shard bodies are discovered exactly like the purity pass discovers
traced roots: direct ``shard_map(fn, ...)`` sites (any spelling whose
last segment is ``shard_map`` — ``jax.shard_map``, the repo's
version-compat ``parallel.mesh.shard_map``, lambdas), plus wrapper
functions that pass one of their own parameters into a shard_map call
(``QueryEngine._shard_wrap``), whose call-site arguments then root.
Anchors resolve by path suffix; a missing anchor skips its checks.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import (FuncId, call_chain,
                                                       dotted_name,
                                                       resolve_kernel_refs,
                                                       walk_shallow)
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Module, Project

_MESH_SUFFIX = "parallel/mesh.py"
_REGISTRY_SUFFIX = "ops/agg_registry.py"

_COLLECTIVES = frozenset({"psum", "pmin", "pmax", "pmean", "all_gather",
                          "all_to_all", "ppermute", "psum_scatter",
                          "axis_index"})
#: collectives that MERGE values (the ones a wrong operator corrupts)
_MERGE_COLLECTIVES = frozenset({"psum", "pmin", "pmax", "pmean"})
#: register algebra per sketch when the registry predates the
#: ``merge`` field; the registry declaration wins when present
_SKETCH_MERGE_DEFAULT = {"hll": "max", "theta": "min", "kll": "minsum"}
#: merge algebra -> the collective(s) it may lower to. Composite
#: algebras (KLL "minsum": lex-min survivor lanes via pmin + exact
#: level counts via psum) legitimately use more than one collective in
#: the same merge body.
_MERGE_TO_COLLECTIVE = {"sum": {"psum"}, "max": {"pmax"},
                        "min": {"pmin"}, "minsum": {"pmin", "psum"}}

#: host-callback / RNG vocabulary the purity pass does NOT already
#: flag (purity covers time/random/np.random/threading/os/...; these
#: are the jax-native escapes that only matter under replication)
_HOST_CALL_PREFIXES = ("jax.debug.", "jax.experimental.host_callback",
                       "host_callback.", "hcb.", "jax.random.",
                       "jrandom.")
_HOST_CALL_LEAVES = frozenset({"io_callback", "pure_callback",
                               "debug_callback"})

# same container-mutation vocabulary as locks._MUTATORS
_MUTATORS = frozenset({"append", "add", "update", "pop", "popitem",
                       "clear", "discard", "remove", "extend", "insert",
                       "setdefault", "appendleft"})


def _registry(mod: Module) -> Optional[Dict[str, dict]]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "AGG_CLOSURE":
            try:
                v = ast.literal_eval(node.value)
            except ValueError:
                return None
            return v if isinstance(v, dict) else None
    return None


def _declared_axes(mod: Module) -> Dict[str, str]:
    """Axis constants the mesh module declares: ``NAME = "axis"``
    top-level string assignments plus literal axis tuples in
    ``Mesh(..., ("axis", ...))`` constructions."""
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and call_chain(node.func)[-1:] == ["Mesh"] \
                and len(node.args) >= 2 \
                and isinstance(node.args[1], (ast.Tuple, ast.List)):
            for i, e in enumerate(node.args[1].elts):
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, str):
                    out.setdefault(f"<mesh-axis-{i}>", e.value)
    return out


class _Mesh:
    def __init__(self, project: Project):
        self.project = project
        self.index = project.index()
        mesh_mod = project.by_suffix(_MESH_SUFFIX)
        self.axis_consts = _declared_axes(mesh_mod) \
            if mesh_mod is not None else {}
        self.declared = set(self.axis_consts.values())
        # module name -> top-level assigned names (host-global state)
        self.module_globals: Dict[str, Set[str]] = {}
        for name, mi in self.index.modules.items():
            tops: Set[str] = set()
            for node in mi.mod.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tops.add(t.id)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    tops.add(node.target.id)
            self.module_globals[name] = tops
        self.wrapper_params: Dict[FuncId, Set[str]] = {}
        self._find_wrapper_params()
        self.roots: Dict[FuncId, Tuple[str, int]] = {}
        self._find_roots()
        self.reachable = self._reach()

    # -- shard-body discovery (mirrors purity's root discovery) ---------------
    def _find_wrapper_params(self) -> None:
        for fid, fn in self.index.functions.items():
            params = {a.arg for a in fn.args.args}
            aliases: Dict[str, str] = {}
            traced: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in params:
                    aliases[node.targets[0].id] = node.value.id
                if isinstance(node, ast.Call) and node.args \
                        and isinstance(node.func, (ast.Name,
                                                   ast.Attribute)) \
                        and call_chain(node.func)[-1:] == ["shard_map"]:
                    a = node.args[0]
                    if isinstance(a, ast.Name):
                        p = a.id if a.id in params else aliases.get(a.id)
                        if p:
                            traced.add(p)
            if traced:
                self.wrapper_params[fid] = traced

    def _add_root(self, mi, ci, expr: ast.expr, local,
                  enclosing_qual: str, site: Tuple[str, int]) -> None:
        idx = self.index
        if isinstance(expr, ast.Lambda):
            for node in ast.walk(expr.body):
                if isinstance(node, ast.Call):
                    for callee in idx.resolve_call(
                            mi, ci, node, local,
                            enclosing_qual=enclosing_qual):
                        self.roots.setdefault(callee, site)
            return
        for ref in resolve_kernel_refs(idx, mi, ci, expr, local,
                                       enclosing_qual=enclosing_qual):
            self.roots.setdefault(ref, site)

    def _find_roots(self) -> None:
        idx = self.index
        for fid, fn in idx.functions.items():
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, (ast.Name, ast.Attribute)) \
                        and call_chain(node.func)[-1:] == ["shard_map"] \
                        and node.args:
                    # skip the compat wrapper's own body (it forwards
                    # its parameter; the real bodies root at call sites)
                    self._add_root(mi, ci, node.args[0], local, fid[1],
                                   (mi.mod.relpath, node.lineno))
                    continue
                for callee in idx.resolve_call(mi, ci, node, local,
                                               enclosing_qual=fid[1],
                                               unique_fallback=True):
                    traced = self.wrapper_params.get(callee)
                    if not traced:
                        continue
                    cfn = idx.functions[callee]
                    pnames = [a.arg for a in cfn.args.args]
                    if pnames and pnames[0] == "self":
                        pnames = pnames[1:]
                    for i, a in enumerate(node.args):
                        if i < len(pnames) and pnames[i] in traced:
                            self._add_root(mi, ci, a, local, fid[1],
                                           (mi.mod.relpath, node.lineno))
                    for kw in node.keywords:
                        if kw.arg in traced:
                            self._add_root(mi, ci, kw.value, local,
                                           fid[1],
                                           (mi.mod.relpath, node.lineno))

    def _reach(self) -> Set[FuncId]:
        idx = self.index
        seen = set(self.roots)
        stack = list(self.roots)
        while stack:
            fid = stack.pop()
            fn = idx.functions.get(fid)
            if fn is None:
                continue
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            for node in walk_shallow(fn):
                if isinstance(node, ast.Call):
                    for callee in idx.resolve_call(mi, ci, node, local,
                                                   enclosing_qual=fid[1]):
                        if callee not in seen:
                            seen.add(callee)
                            stack.append(callee)
        return seen

    # -- unknown-axis-name -----------------------------------------------------
    def _axis_value(self, mi, fn: ast.FunctionDef,
                    expr: ast.expr) -> Optional[str]:
        """Statically resolvable axis value of ``expr``; None when
        unknown (parameters, computed values) — unknown is accepted."""
        if isinstance(expr, ast.Constant):
            return expr.value if isinstance(expr.value, str) else None
        if isinstance(expr, ast.Attribute):
            return self.axis_consts.get(expr.attr)
        if isinstance(expr, ast.Name):
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                      + fn.args.kwonlyargs}
            if expr.id in params:
                return None
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == expr.id:
                    if isinstance(node.value, ast.Constant) \
                            and isinstance(node.value.value, str):
                        return node.value.value
                    return None
            imp = mi.imports.get(expr.id)
            if imp and imp[0] == "symbol":
                return self.axis_consts.get(imp[2])
            return self.axis_consts.get(expr.id)
        return None

    def axis_findings(self) -> List[Finding]:
        if not self.declared:
            return []          # no mesh anchor: nothing to check against
        out: List[Finding] = []
        idx = self.index
        mesh_mod = self.project.by_suffix(_MESH_SUFFIX)
        for fid, fn in sorted(idx.functions.items()):
            mi = idx.modules[fid[0]]
            if mesh_mod is not None and mi.mod is mesh_mod:
                continue       # the declaration site itself
            for node in walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = call_chain(node.func)
                if chain and chain[-1] in _COLLECTIVES:
                    ax = self._collective_axis_arg(node, chain[-1])
                    if ax is None:
                        continue
                    val = self._axis_value(mi, fn, ax)
                    if val is not None and val not in self.declared:
                        out.append(Finding(
                            "mesh", "unknown-axis-name", mi.mod.relpath,
                            node.lineno, f"{fid[1]}:{val}",
                            f"{fid[1]} runs {chain[-1]} over axis "
                            f"{val!r} but the mesh "
                            f"({_MESH_SUFFIX}) only declares "
                            f"{sorted(self.declared)}; this fails only "
                            f"when the sharded path finally runs"))
                elif chain and chain[-1] == "shard_map":
                    out.extend(self._spec_axis_findings(fid, mi, fn,
                                                        node))
        return out

    @staticmethod
    def _collective_axis_arg(node: ast.Call,
                             leaf: str) -> Optional[ast.expr]:
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return kw.value
        pos = 0 if leaf == "axis_index" else 1
        if len(node.args) > pos:
            return node.args[pos]
        return None

    def _spec_axis_findings(self, fid, mi, fn,
                            call: ast.Call) -> List[Finding]:
        out: List[Finding] = []
        spec_exprs = list(call.args[1:]) \
            + [kw.value for kw in call.keywords
               if kw.arg in ("in_specs", "out_specs")]
        for root in spec_exprs:
            for node in ast.walk(root):
                if isinstance(node, ast.Call) and call_chain(
                        node.func)[-1:] in (["P"], ["PartitionSpec"]):
                    for a in node.args:
                        val = self._axis_value(mi, fn, a)
                        if val is not None \
                                and val not in self.declared:
                            out.append(Finding(
                                "mesh", "unknown-axis-name",
                                mi.mod.relpath, node.lineno,
                                f"{fid[1]}:{val}",
                                f"{fid[1]} partitions over axis "
                                f"{val!r} in a shard_map spec but the "
                                f"mesh ({_MESH_SUFFIX}) only declares "
                                f"{sorted(self.declared)}"))
        return out

    # -- sketch-merge-mismatch -------------------------------------------------
    def sketch_findings(self) -> List[Finding]:
        reg_mod = self.project.by_suffix(_REGISTRY_SUFFIX)
        if reg_mod is None:
            return []
        registry = _registry(reg_mod)
        if not registry:
            return []
        out: List[Finding] = []
        seen_sketches: Set[str] = set()
        for kind in sorted(registry):
            entry = registry[kind]
            sketch = entry.get("sketch") if isinstance(entry, dict) \
                else None
            if not sketch or sketch in seen_sketches:
                continue
            seen_sketches.add(sketch)
            merge = entry.get("merge") \
                or _SKETCH_MERGE_DEFAULT.get(sketch)
            allowed = _MERGE_TO_COLLECTIVE.get(merge)
            if allowed is None:
                continue
            smod = self.project.by_suffix(f"ops/{sketch}.py")
            if smod is None:
                continue
            fid = (smod.name, "merge_registers")
            fn = self.index.functions.get(fid)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                leaf = call_chain(node.func)[-1:]
                if leaf and leaf[0] in _MERGE_COLLECTIVES \
                        and leaf[0] not in allowed:
                    out.append(Finding(
                        "mesh", "sketch-merge-mismatch", smod.relpath,
                        node.lineno, f"{sketch}.merge_registers",
                        f"{sketch} registers merge via {leaf[0]} but "
                        f"AGG_CLOSURE declares the {merge!r} register "
                        f"algebra ({sorted(allowed)}); "
                        f"{'summing' if leaf[0] == 'psum' else 'folding'}"
                        f" registers with the wrong operator corrupts "
                        f"every cross-chip cardinality silently"))
        return out

    # -- merge-op-mismatch -----------------------------------------------------
    def merge_op_findings(self) -> List[Finding]:
        out: List[Finding] = []
        idx = self.index
        for fid, fn in sorted(idx.functions.items()):
            mi = idx.modules[fid[0]]
            for node in walk_shallow(fn):
                if not isinstance(node, ast.If):
                    continue
                kind = _kind_branch(node.test)
                if kind is None:
                    continue
                expected = {"min": "pmin", "max": "pmax"}[kind]
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        leaf = call_chain(sub.func)[-1:]
                        if leaf and leaf[0] in _MERGE_COLLECTIVES \
                                and leaf[0] != expected:
                            out.append(Finding(
                                "mesh", "merge-op-mismatch",
                                mi.mod.relpath, sub.lineno,
                                f"{fid[1]}:{kind}",
                                f"{fid[1]} merges kind == {kind!r} "
                                f"partials with {leaf[0]}; extrema "
                                f"merge with {expected} — "
                                f"{leaf[0]} over per-chip "
                                f"{kind}s returns garbage whenever "
                                f"more than one chip holds the group"))
        return out

    # -- host calls / host-state writes in shard bodies ------------------------
    def shard_body_findings(self) -> List[Finding]:
        out: List[Finding] = []
        idx = self.index
        for fid in sorted(self.reachable):
            fn = idx.functions.get(fid)
            if fn is None:
                continue
            mi = idx.modules[fid[0]]
            path = mi.mod.relpath
            site = self.roots.get(fid)
            via = f" (sharded via {site[0]}:{site[1]})" if site else ""
            local_names = _local_bindings(fn)
            globals_here = self.module_globals.get(fid[0], set())
            global_decls: Set[str] = set()
            for node in walk_shallow(fn):
                if isinstance(node, ast.Global):
                    global_decls.update(node.names)
            for node in walk_shallow(fn):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    if name and (name.startswith(_HOST_CALL_PREFIXES)
                                 or name.split(".")[-1]
                                 in _HOST_CALL_LEAVES):
                        out.append(Finding(
                            "mesh", "host-call-in-shard", path,
                            node.lineno, f"{fid[1]}:{name}",
                            f"{fid[1]} runs inside a shard_map body"
                            f"{via} but calls {name}(); host callbacks "
                            f"and untracked RNG break replication — "
                            f"every replica re-enters the host (or "
                            f"diverges), and multi-host runs deadlock "
                            f"or silently disagree"))
                        continue
                    chain = call_chain(node.func)
                    if len(chain) >= 3 and chain[0] == "self" \
                            and chain[-1] in _MUTATORS:
                        out.append(self._write_finding(
                            fid, path, node.lineno, via,
                            f"self.{chain[1]}.{chain[-1]}()"))
                    elif len(chain) == 2 and chain[-1] in _MUTATORS \
                            and chain[0] in globals_here \
                            and chain[0] not in local_names:
                        out.append(self._write_finding(
                            fid, path, node.lineno, via,
                            f"{chain[0]}.{chain[-1]}()"))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets \
                        if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        w = self._write_target(t, local_names,
                                               globals_here,
                                               global_decls)
                        if w is not None:
                            out.append(self._write_finding(
                                fid, path, node.lineno, via, w))
        return out

    @staticmethod
    def _write_target(t: ast.expr, local_names: Set[str],
                      globals_here: Set[str],
                      global_decls: Set[str]) -> Optional[str]:
        if isinstance(t, ast.Subscript):
            t2 = t.value
            if isinstance(t2, ast.Name) and t2.id in globals_here \
                    and t2.id not in local_names:
                return f"{t2.id}[...]"
            t = t2
        if isinstance(t, ast.Attribute):
            base = call_chain(t)
            if base and base[0] == "self":
                return f"self.{t.attr}"
            return None
        if isinstance(t, ast.Name) and t.id in global_decls:
            return t.id
        return None

    @staticmethod
    def _write_finding(fid: FuncId, path: str, line: int, via: str,
                       what: str) -> Finding:
        return Finding(
            "mesh", "host-state-write-in-shard", path, line,
            f"{fid[1]}:{what}",
            f"{fid[1]} runs inside a shard_map body{via} but writes "
            f"host state ({what}); the body traces once, so the write "
            f"happens at trace time on every host — stats/caches/"
            f"registries mutated here diverge from what actually "
            f"executed per shard")


def _kind_branch(test: ast.expr) -> Optional[str]:
    """``<x>.kind == "min"`` / ``kind == "max"`` comparison -> the
    literal, else None."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        return None
    left, right = test.left, test.comparators[0]
    for a, b in ((left, right), (right, left)):
        named = (isinstance(a, ast.Attribute) and a.attr == "kind") \
            or (isinstance(a, ast.Name) and a.id == "kind")
        if named and isinstance(b, ast.Constant) \
                and b.value in ("min", "max"):
            return b.value
    return None


def _local_bindings(fn: ast.FunctionDef) -> Set[str]:
    out = {a.arg for a in fn.args.posonlyargs + fn.args.args
           + fn.args.kwonlyargs}
    if fn.args.vararg is not None:
        out.add(fn.args.vararg.arg)
    if fn.args.kwarg is not None:
        out.add(fn.args.kwarg.arg)
    def bind(t: ast.expr) -> None:
        # Subscript/Attribute stores mutate an EXISTING object — they
        # bind nothing (and their base name must stay visible to the
        # host-global-write check)
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                bind(e)
        elif isinstance(t, ast.Starred):
            bind(t.value)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bind(t)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
    return out


def run(project: Project) -> List[Finding]:
    m = _Mesh(project)
    out = m.axis_findings()
    out.extend(m.sketch_findings())
    out.extend(m.merge_op_findings())
    out.extend(m.shard_body_findings())
    return out
