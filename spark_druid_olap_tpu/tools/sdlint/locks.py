"""Lock-order / race pass.

Builds the interprocedural lock-acquisition graph over every
``threading.Lock/RLock/Condition`` attribute in the project:

- **deadlock-cycle** — two locks acquired in opposite orders on any pair
  of (resolved) call paths form a cycle in the acquired-while-holding
  graph. Self-edges through an ``RLock`` are reentrancy, not deadlock,
  and are skipped.
- **unguarded-write** — an attribute of a lock-owning class that is
  mutated under the class's lock on some paths (so it is evidently
  shared state) but is also mutated with **no** lock held in a function
  reachable from a thread entrypoint (``Thread(target=...)``, HTTP
  ``do_GET/do_POST`` handlers, Flight ``do_get/do_action``, the
  background checkpointer). ``__init__`` writes are construction
  (happens-before publication) and never count.

The analysis is conservative where resolution fails: an unresolved call
drops its edges, so every reported cycle is grounded in resolved code
paths (read the edge sites in the finding message).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import (FuncId, Index,
                                                       _threading_factory)
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Project

# container-mutator method names: self.attr.<m>(...) counts as a write
_MUTATORS = {"append", "add", "update", "pop", "popitem", "clear",
             "discard", "remove", "extend", "insert", "setdefault",
             "appendleft"}

_HTTP_ENTRYPOINTS = {"do_GET", "do_POST", "do_PUT", "do_DELETE"}
_FLIGHT_ENTRYPOINTS = {"do_get", "do_put", "do_action", "do_exchange",
                       "get_flight_info", "list_flights"}


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "Thread"):
        return isinstance(f, ast.Name) and f.id == "Thread"
    base = f.value
    if isinstance(base, ast.Name) and base.id == "threading":
        return True
    return (isinstance(base, ast.Call)
            and isinstance(base.func, ast.Name)
            and base.func.id == "__import__" and base.args
            and isinstance(base.args[0], ast.Constant)
            and base.args[0].value == "threading")


@dataclasses.dataclass
class _Summary:
    fid: FuncId
    # (lock_id, kind, held-at-acquire tuple, line)
    acquires: List[Tuple[str, str, Tuple[str, ...], int]] = \
        dataclasses.field(default_factory=list)
    # (callee fid, held tuple, line)
    calls: List[Tuple[FuncId, Tuple[str, ...], int]] = \
        dataclasses.field(default_factory=list)
    # (class ref string, attr, any-own-lock-held, line)
    writes: List[Tuple[str, str, bool, int]] = \
        dataclasses.field(default_factory=list)
    thread_targets: List[Tuple[FuncId, int]] = \
        dataclasses.field(default_factory=list)


class LockAnalysis:
    """Holds the graph for findings AND for the regression tests / docs
    (tests assert on ``edges`` and ``cycles`` directly)."""

    def __init__(self, project: Project):
        self.project = project
        self.index = project.index()   # shared: parsed/typed once for all passes
        self.lock_kinds: Dict[str, str] = {}
        self.summaries: Dict[FuncId, _Summary] = {}
        for mi in self.index.modules.values():
            for name, kind in mi.module_locks.items():
                self.lock_kinds[f"{mi.mod.name}.{name}"] = kind
            for ci in set(mi.classes.values()):
                for attr, kind in ci.lock_attrs.items():
                    self.lock_kinds[f"{ci.module}.{ci.qual}.{attr}"] = kind
        for fid, fn in self.index.functions.items():
            self.summaries[fid] = self._summarize(fid, fn)
        self.may_acquire = self._fixpoint_acquires()
        # (held, acquired) -> [(path, line, via)] witness sites
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        self._build_edges()
        self.entrypoints = self._entrypoints()
        self.reachable = self._reachable(self.entrypoints)
        self.lockfree_entry = self._lockfree_entry()
        self.cycles = self._cycles()

    # -- per-function summaries ------------------------------------------------
    def _summarize(self, fid: FuncId, fn: ast.FunctionDef) -> _Summary:
        idx = self.index
        mi = idx.modules[fid[0]]
        ci = idx.func_class[fid]
        local = idx.local_types(mi, ci, fn)
        s = _Summary(fid)
        own_locks = set()
        if ci is not None:
            own_locks = {f"{ci.module}.{ci.qual}.{a}"
                         for a in ci.lock_attrs}

        def scan_expr(node: ast.expr, held: Tuple[str, ...]) -> None:
            """Record calls/acquires in an expression, skipping deferred
            bodies (lambdas, nested defs run later, not under ``held``)."""
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                return
            if isinstance(node, ast.Call):
                self._scan_call(s, mi, ci, fid, node, held, local)
            for child in ast.iter_child_nodes(node):
                scan_expr(child, held)

        def note_write(target: ast.expr, held: Tuple[str, ...],
                       line: int) -> None:
            if ci is None:
                return
            # self.attr = / self.attr[k] = / self.attr += ...
            t = target
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                held_own = any(h in own_locks for h in held)
                s.writes.append((f"{ci.module}.{ci.qual}", t.attr,
                                 held_own, line))

        def walk(stmts: Sequence[ast.stmt],
                 held: Tuple[str, ...]) -> None:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue        # separate summaries
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    new = list(held)
                    for item in st.items:
                        lk = idx.resolve_lock(mi, ci, item.context_expr,
                                              local)
                        if lk is not None:
                            lid, kind = lk
                            s.acquires.append((lid, kind, tuple(new),
                                               st.lineno))
                            new.append(lid)
                        else:
                            scan_expr(item.context_expr, tuple(new))
                    walk(st.body, tuple(new))
                    continue
                if isinstance(st, (ast.Assign, ast.AugAssign)):
                    targets = st.targets if isinstance(st, ast.Assign) \
                        else [st.target]
                    for t in targets:
                        note_write(t, held, st.lineno)
                    scan_expr(st.value, held)
                    continue
                # compound statements: scan own expressions, recurse
                for field in ("test", "iter", "value", "exc", "msg",
                              "subject"):
                    sub = getattr(st, field, None)
                    if isinstance(sub, ast.expr):
                        scan_expr(sub, held)
                if isinstance(st, ast.Expr):
                    # self.attr.append(...) style container mutation
                    if isinstance(st.value, ast.Call) \
                            and isinstance(st.value.func, ast.Attribute) \
                            and st.value.func.attr in _MUTATORS:
                        note_write(st.value.func.value, held, st.lineno)
                    scan_expr(st.value, held)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if isinstance(sub, list):
                        walk(sub, held)
                for h in getattr(st, "handlers", ()):
                    walk(h.body, held)

        walk(fn.body, ())
        return s

    def _scan_call(self, s: _Summary, mi, ci, fid: FuncId, call: ast.Call,
                   held: Tuple[str, ...], local) -> None:
        idx = self.index
        # Thread(target=X): X runs on a fresh thread holding nothing
        if _is_thread_ctor(call):
            for kw in call.keywords:
                if kw.arg == "target":
                    ref = idx.resolve_func_ref(mi, ci, kw.value, local,
                                               enclosing_qual=fid[1])
                    if ref is not None:
                        s.thread_targets.append((ref, call.lineno))
            return
        # bare lock.acquire() outside a with-statement
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "acquire":
            lk = idx.resolve_lock(mi, ci, call.func.value, local)
            if lk is not None:
                s.acquires.append((lk[0], lk[1], held, call.lineno))
                return
        for callee in idx.resolve_call(mi, ci, call, local,
                                       enclosing_qual=fid[1],
                                       unique_fallback=True):
            s.calls.append((callee, held, call.lineno))

    # -- interprocedural propagation -------------------------------------------
    def _fixpoint_acquires(self) -> Dict[FuncId, Set[str]]:
        acq = {fid: {a[0] for a in s.acquires}
               for fid, s in self.summaries.items()}
        changed = True
        while changed:
            changed = False
            for fid, s in self.summaries.items():
                cur = acq[fid]
                before = len(cur)
                for callee, _, _ in s.calls:
                    cur |= acq.get(callee, set())
                for callee, _ in s.thread_targets:
                    # a spawned thread acquires on its own stack, not
                    # under the spawner's held set — no propagation
                    pass
                if len(cur) != before:
                    changed = True
        return acq

    def _build_edges(self) -> None:
        def add(a: str, b: str, path: str, line: int, via: str) -> None:
            if a == b:
                return              # handled as self-cycle separately
            self.edges.setdefault((a, b), []).append((path, line, via))

        for fid, s in self.summaries.items():
            path = self.index.modules[fid[0]].mod.relpath
            for lid, _, held, line in s.acquires:
                for h in held:
                    add(h, lid, path, line, f"{fid[1]} acquires directly")
            for callee, held, line in s.calls:
                if not held:
                    continue
                for lid in self.may_acquire.get(callee, ()):
                    for h in held:
                        add(h, lid, path, line,
                            f"{fid[1]} -> {callee[1]}()")

    def _entrypoints(self) -> Set[FuncId]:
        out: Set[FuncId] = set()
        for fid, s in self.summaries.items():
            for ref, _ in s.thread_targets:
                out.add(ref)
            name = fid[1].rsplit(".", 1)[-1]
            if name in _HTTP_ENTRYPOINTS or name in _FLIGHT_ENTRYPOINTS:
                out.add(fid)
        return out

    def _reachable(self, roots: Set[FuncId]) -> Set[FuncId]:
        seen = set(roots)
        stack = list(roots)
        while stack:
            fid = stack.pop()
            s = self.summaries.get(fid)
            if s is None:
                continue
            for callee, _, _ in s.calls:
                if callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
            for callee, _ in s.thread_targets:
                if callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
        return seen

    def _lockfree_entry(self) -> Set[FuncId]:
        """Functions that can be ENTERED from a thread entrypoint with no
        lock held: the entrypoints themselves, plus the closure over call
        events whose held-set is empty. A helper only ever called under
        ``with self.lock`` never appears here, so its lock-free writes
        are correctly treated as guarded by the caller."""
        lf = {e for e in self.entrypoints if e in self.summaries}
        stack = list(lf)
        while stack:
            fid = stack.pop()
            s = self.summaries.get(fid)
            if s is None:
                continue
            for callee, held, _ in s.calls:
                if not held and callee not in lf \
                        and callee in self.summaries:
                    lf.add(callee)
                    stack.append(callee)
            for callee, _ in s.thread_targets:
                if callee not in lf and callee in self.summaries:
                    lf.add(callee)
                    stack.append(callee)
        return lf

    # -- cycles ----------------------------------------------------------------
    def _cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock graph (DFS with a canonical
        smallest-first rotation; the graph has a handful of nodes)."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str],
                on_path: Set[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) > 1:
                    rot = min(range(len(path)),
                              key=lambda i: path[i])
                    cycles.add(tuple(path[rot:] + path[:rot]))
                elif nxt not in on_path and nxt > start:
                    # only explore nodes > start: each cycle found once,
                    # from its smallest node
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        # self-cycles: holding A while (transitively) re-acquiring A is a
        # guaranteed deadlock for a plain Lock
        self.self_cycle_sites: Dict[str, Tuple[str, int, str]] = {}
        for fid, s in self.summaries.items():
            path = self.index.modules[fid[0]].mod.relpath
            for lid, kind, held, line in s.acquires:
                if lid in held and self.lock_kinds.get(lid) == "Lock":
                    cycles.add((lid,))
                    self.self_cycle_sites.setdefault(
                        lid, (path, line, f"{fid[1]} re-acquires"))
            for callee, held, line in s.calls:
                for lid in self.may_acquire.get(callee, ()):
                    if lid in held and self.lock_kinds.get(lid) == "Lock":
                        cycles.add((lid,))
                        self.self_cycle_sites.setdefault(
                            lid, (path, line,
                                  f"{fid[1]} -> {callee[1]}() re-acquires"))
        return [list(c) for c in sorted(cycles)]

    # -- findings --------------------------------------------------------------
    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        for cyc in self.cycles:
            if len(cyc) == 1:
                a = cyc[0]
                label = f"{a} -> {a}"
                path, line, via = self.self_cycle_sites.get(
                    a, (self._lock_path(a), 1, "?"))
                wits = f"{path}:{line} ({via})"
            else:
                a, b = cyc[0], cyc[1]
                label = " -> ".join(cyc + [cyc[0]])
                sites = self.edges.get((a, b),
                                       [(self._lock_path(a), 1, "?")])
                path, line, via = sites[0]
                wits = "; ".join(
                    f"{p}:{ln} ({v})"
                    for (x, y) in zip(cyc, cyc[1:] + cyc[:1])
                    for (p, ln, v) in self.edges.get((x, y), [])[:1])
            out.append(Finding(
                "locks", "deadlock-cycle", path, line, label,
                f"lock-order cycle: {label}; witness edges: {wits}"))
        out.extend(self._race_findings())
        return out

    def _lock_path(self, lock_id: str) -> str:
        best = ""
        path = "?"
        for mi in self.index.modules.values():
            pre = mi.mod.name + "."
            if lock_id.startswith(pre) and len(pre) > len(best):
                best, path = pre, mi.mod.relpath
        return path

    def _race_findings(self) -> List[Finding]:
        guarded: Set[Tuple[str, str]] = set()
        writes: Dict[Tuple[str, str], List[Tuple[FuncId, bool, int]]] = {}
        for fid, s in self.summaries.items():
            in_init = fid[1].endswith("__init__")
            for cls, attr, held, line in s.writes:
                if in_init:
                    continue
                writes.setdefault((cls, attr), []).append(
                    (fid, held, line))
                if held:
                    guarded.add((cls, attr))
        out = []
        for (cls, attr), sites in sorted(writes.items()):
            if (cls, attr) not in guarded:
                continue            # never lock-guarded: not shared state
                #                     by this pass's evidence standard
            for fid, held, line in sites:
                if held or fid not in self.lockfree_entry:
                    continue
                path = self.index.modules[fid[0]].mod.relpath
                out.append(Finding(
                    "locks", "unguarded-write", path, line,
                    f"{cls}.{attr}@{fid[1]}",
                    f"{cls}.{attr} is mutated under its class lock "
                    f"elsewhere, but {fid[1]} (reachable from a thread "
                    f"entrypoint) writes it with no lock held"))
        return out


def run(project: Project) -> List[Finding]:
    return LockAnalysis(project).findings()
