"""keys pass: cache keys must cover exactly the result-affecting state.

Four cross-checks, all pure AST over the shared index:

- **K1 compile-sig-missing-config** — every ``self._cached_program(sig,
  build)`` site: config keys read anywhere in the build closure
  (transitively, depth ≤ 4 through resolvable calls) must appear as
  ``config.get(...)`` terms of the signature expression. A key read
  during program build but absent from the sig means an operator ``SET``
  keeps serving the previously compiled program — stale results that
  only show up after a mid-session config change.
- **K2 key-missing-field** — fields that ``cache/keys.py:normalize_spec``
  *strips* (replaces with a constant not derived from ``q``) but that
  planner//parallel code actually reads while planning/executing. A
  stripped-but-read field aliases two queries with different answers to
  one cache entry (poisoning). ``KEY_EXEMPT_FIELDS`` in cache/keys.py
  declares the audited exceptions (execution-only knobs).
- **K3 key-field-never-read** — spec fields the canonical key keeps but
  nothing in the engine ever reads: needless churn, every variation
  fragments the cache.
- **K4 fingerprint-(missing-key|churn-key|unfiltered)** —
  ``Config.fingerprint()`` feeds every canonical key, so the registry's
  ``semantic=`` classification is cross-checked against where each key
  is read: a ``semantic=False`` key read by result-defining code
  (planner//ops//ir//mv//cache-keys) is poisoning; a default-semantic
  key read only by operational subsystems (wlm//persist//http//cache
  internals//utils) churns every cache on unrelated tuning; and the
  fingerprint body itself must reference the semantic filter at all.
  Reads from ambiguous layers (parallel//sql//segment) are never flagged
  either way — a human classifies those via ``semantic=``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_druid_olap_tpu.tools.sdlint.astutil import call_chain
from spark_druid_olap_tpu.tools.sdlint.core import Finding, Project
from spark_druid_olap_tpu.tools.sdlint.leaks import _suffix

#: spec fields K3 tolerates unread (forward/compat fields); keep empty —
#: grow only with a justification comment
K3_EXEMPT: frozenset = frozenset()

#: receiver names treated as "the query spec" when scanning reads
SPEC_RECEIVERS = frozenset({"q", "spec", "query", "qs", "sub"})


def _key_const(arg: ast.expr) -> Optional[str]:
    """``config.get(TZ_ID)`` / ``config.get(C.TZ_ID)`` -> "TZ_ID"."""
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Attribute):
        return arg.attr
    return None


def _is_config_get(chain: Sequence[str]) -> bool:
    # receiver spellings in the tree: self.config / eng.config / conf /
    # cfg — a bare `conf.get(KEY)` read is still a config read
    return len(chain) >= 2 and chain[-1] == "get" \
        and ("config" in chain[-2].lower()
             or chain[-2].lower() in ("conf", "cfg"))


def _config_reads(node: ast.AST) -> List[Tuple[str, int]]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and n.args \
                and _is_config_get(call_chain(n.func)):
            k = _key_const(n.args[0])
            if k is not None:
                out.append((k, n.lineno))
    return out


# -- registry (utils/config.py) -----------------------------------------------

class _Registry:
    def __init__(self) -> None:
        self.entries: Dict[str, Tuple[str, bool, int]] = {}  # NAME->(key,sem,line)

    @classmethod
    def parse(cls, project: Project) -> "_Registry":
        reg = cls()
        mod = project.by_suffix("utils/config.py")
        if mod is None:
            return reg
        reg.relpath = mod.relpath
        for stmt in mod.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                continue
            ch = call_chain(stmt.value.func)
            if not ch or ch[-1] != "_entry" or not stmt.value.args:
                continue
            a0 = stmt.value.args[0]
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)):
                continue
            semantic = True
            for kw in stmt.value.keywords:
                if kw.arg == "semantic" \
                        and isinstance(kw.value, ast.Constant):
                    semantic = bool(kw.value.value)
            reg.entries[stmt.targets[0].id] = (a0.value, semantic,
                                               stmt.lineno)
        return reg

    relpath: str = "utils/config.py"


# -- K1: compile signatures ---------------------------------------------------

def _sig_keys(fn: ast.AST, sig_expr: ast.expr) -> Set[str]:
    """Config-key constants appearing in the sig expression, following
    Name bindings within the function (``sigA = ("aggtable", base_sig,
    ...)`` nests one sig in another)."""
    bindings: Dict[str, List[ast.expr]] = {}
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            bindings.setdefault(n.targets[0].id, []).append(n.value)
    keys: Set[str] = set()
    frontier, seen_names = [sig_expr], set()
    for _ in range(4):
        nxt: List[ast.expr] = []
        for e in frontier:
            keys.update(k for k, _ in _config_reads(e))
            for n in ast.walk(e):
                if isinstance(n, ast.Name) and n.id not in seen_names:
                    seen_names.add(n.id)
                    nxt.extend(bindings.get(n.id, ()))
        frontier = nxt
        if not frontier:
            break
    return keys


def _build_roots(idx, mi, ci, fn, fid, build_expr: ast.expr) -> List[tuple]:
    """FuncIds the build closure calls into (or is)."""
    local = idx.local_types(mi, ci, fn)
    roots: List[tuple] = []
    if isinstance(build_expr, ast.Lambda):
        for n in ast.walk(build_expr.body):
            if isinstance(n, ast.Call):
                roots.extend(idx.resolve_call(mi, ci, n, local, fid[1],
                                              unique_fallback=True))
    else:
        r = idx.resolve_func_ref(mi, ci, build_expr, local, fid[1])
        if r is not None:
            roots.append(r)
    return roots


def _closure_reads(idx, roots: Sequence[tuple],
                   depth: int = 4) -> Dict[str, Tuple[str, str, int]]:
    """key-name -> (module, qual, line) of one read site, BFS over
    resolvable calls from the build roots."""
    reads: Dict[str, Tuple[str, str, int]] = {}
    seen: Set[tuple] = set()
    frontier = list(roots)
    for _ in range(depth):
        nxt: List[tuple] = []
        for fid in frontier:
            if fid in seen:
                continue
            seen.add(fid)
            fn = idx.functions.get(fid)
            if fn is None:
                continue
            mi = idx.modules[fid[0]]
            ci = idx.func_class[fid]
            local = idx.local_types(mi, ci, fn)
            for k, line in _config_reads(fn):
                reads.setdefault(k, (fid[0], fid[1], line))
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    # no unique_fallback here: a name-only match deep in
                    # the walk drags in unrelated subsystems' reads
                    nxt.extend(idx.resolve_call(mi, ci, n, local, fid[1]))
        frontier = nxt
    return reads


def _k1(project: Project, reg: _Registry) -> List[Finding]:
    idx = project.index()
    out: List[Finding] = []
    for fid, fn in sorted(idx.functions.items()):
        mi = idx.modules[fid[0]]
        mod = project.modules[fid[0]]
        ci = idx.func_class[fid]
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Call) and len(n.args) >= 2):
                continue
            if not _suffix(call_chain(n.func), ("_cached_program",)):
                continue
            sig_keys = _sig_keys(fn, n.args[0])
            roots = _build_roots(idx, mi, ci, fn, fid, n.args[1])
            for key, (rm, rq, rl) in sorted(
                    _closure_reads(idx, roots).items()):
                if key in sig_keys:
                    continue
                out.append(Finding(
                    "keys", "compile-sig-missing-config", mod.relpath,
                    n.lineno, f"{fid[1]}:{key}",
                    f"program build reads config {key} (in {rq}, "
                    f"{rm.replace('.', '/')}.py:{rl}) but the compile "
                    f"signature never folds it in — a SET of that key "
                    f"keeps serving the stale compiled program"))
    return out


# -- K2/K3: canonical key fields ----------------------------------------------

def _spec_fields(project: Project, keysmod) -> Set[str]:
    """Union of dataclass fields across CACHEABLE_TYPES."""
    wanted: Set[str] = set()
    for stmt in keysmod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "CACHEABLE_TYPES":
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Attribute):
                    wanted.add(n.attr)
                elif isinstance(n, ast.Name):
                    wanted.add(n.id)
    fields: Set[str] = set()
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name in wanted:
                for s in node.body:
                    if isinstance(s, ast.AnnAssign) \
                            and isinstance(s.target, ast.Name):
                        fields.add(s.target.id)
    return fields


def _stripped_fields(keysmod) -> Dict[str, int]:
    """Fields normalize_spec replaces with values NOT derived from the
    spec parameter — i.e. excluded from the canonical key."""
    fn = None
    for stmt in keysmod.tree.body:
        if isinstance(stmt, ast.FunctionDef) \
                and stmt.name == "normalize_spec":
            fn = stmt
    if fn is None or not fn.args.args:
        return {}
    param = fn.args.args[0].arg
    stripped: Dict[str, int] = {}
    for n in ast.walk(fn):
        if not isinstance(n, ast.Call):
            continue
        ch = call_chain(n.func)
        if not (ch and (ch[-1] == "dict" or ch[-1] == "replace")):
            continue
        for kw in n.keywords:
            if kw.arg is None:
                continue
            refs_param = any(isinstance(x, ast.Name) and x.id == param
                             for x in ast.walk(kw.value))
            if not refs_param:
                stripped[kw.arg] = kw.value.lineno
    return stripped


def _exempt_fields(keysmod) -> Set[str]:
    for stmt in keysmod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "KEY_EXEMPT_FIELDS":
            return {n.value for n in ast.walk(stmt.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
    return set()


def _field_reads(project: Project, fields: Set[str],
                 dirs: Tuple[str, ...]) -> Set[str]:
    """Spec fields read as ``q.<field>`` / ``getattr(q, "<field>")`` in
    the given subtrees."""
    read: Set[str] = set()
    for mod in project.modules.values():
        top = mod.relpath.split(os.sep)[0]
        if dirs and top not in dirs:
            continue
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id in SPEC_RECEIVERS \
                    and n.attr in fields:
                read.add(n.attr)
            elif isinstance(n, ast.Call) and call_chain(n.func) \
                    == ["getattr"] and len(n.args) >= 2 \
                    and isinstance(n.args[0], ast.Name) \
                    and n.args[0].id in SPEC_RECEIVERS \
                    and isinstance(n.args[1], ast.Constant) \
                    and n.args[1].value in fields:
                read.add(n.args[1].value)
    return read


def _k23(project: Project) -> List[Finding]:
    keysmod = project.by_suffix("cache/keys.py")
    if keysmod is None:
        return []
    fields = _spec_fields(project, keysmod)
    if not fields:
        return []
    stripped = _stripped_fields(keysmod)
    exempt = _exempt_fields(keysmod)
    planner_reads = _field_reads(project, fields, ("planner", "parallel"))
    any_reads = _field_reads(project, fields, ())
    out: List[Finding] = []
    for f in sorted(set(stripped) & planner_reads - exempt):
        out.append(Finding(
            "keys", "key-missing-field", keysmod.relpath, stripped[f],
            f"normalize_spec:{f}",
            f"normalize_spec strips spec field {f!r} from the canonical "
            f"key but planner//parallel reads it — two queries differing "
            f"only in {f!r} alias to one cache entry (poisoning); key it "
            f"or declare it in KEY_EXEMPT_FIELDS with a justification"))
    kept = fields - set(stripped) - exempt - K3_EXEMPT
    for f in sorted(kept - any_reads):
        out.append(Finding(
            "keys", "key-field-never-read", keysmod.relpath, 1,
            f"normalize_spec:{f}",
            f"spec field {f!r} is serialized into every canonical key "
            f"but nothing in the engine reads it — pure cache churn"))
    return out


# -- K4: Config.fingerprint semantic classification ---------------------------

_SEM_DIRS = ("planner", "ops", "ir", "mv")
_SEM_FILES = ("cache/keys.py", "cache/subsume.py")
_OPS_DIRS = ("wlm", "persist", "http", "utils", "cache", "tools")


def _k4(project: Project, reg: _Registry) -> List[Finding]:
    if not reg.entries:
        return []
    out: List[Finding] = []
    reads: Dict[str, Set[str]] = {name: set() for name in reg.entries}
    for mod in project.modules.values():
        if mod.relpath.endswith(os.path.join("utils", "config.py")):
            continue
        for k, _ in _config_reads(mod.tree):
            if k in reads:
                reads[k].add(mod.relpath)
    sem_files = tuple(p.replace("/", os.sep) for p in _SEM_FILES)
    for name, (key, semantic, line) in sorted(reg.entries.items()):
        sites = reads[name]
        if not sites:
            continue
        in_sem = [p for p in sites
                  if p.split(os.sep)[0] in _SEM_DIRS or p in sem_files]
        in_ops_only = all(p.split(os.sep)[0] in _OPS_DIRS
                          and p not in sem_files for p in sites)
        if not semantic and in_sem:
            out.append(Finding(
                "keys", "fingerprint-missing-key", reg.relpath, line,
                f"config:{name}",
                f"{key} is declared semantic=False (excluded from "
                f"Config.fingerprint) but result-defining code reads it "
                f"({in_sem[0]}) — cached results go stale when it "
                f"changes"))
        elif semantic and in_ops_only:
            out.append(Finding(
                "keys", "fingerprint-churn-key", reg.relpath, line,
                f"config:{name}",
                f"{key} is folded into Config.fingerprint but only "
                f"operational code reads it ({sorted(sites)[0]}) — "
                f"every tuning change invalidates all result/plan "
                f"caches; declare semantic=False"))
    # the fingerprint body must actually apply the classification
    cfgmod = project.by_suffix("utils/config.py")
    if cfgmod is not None:
        for node in ast.walk(cfgmod.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "fingerprint":
                names = {n.id for n in ast.walk(node)
                         if isinstance(n, ast.Name)}
                names |= {n.attr for n in ast.walk(node)
                          if isinstance(n, ast.Attribute)}
                if not any("semantic" in x.lower() for x in names):
                    out.append(Finding(
                        "keys", "fingerprint-unfiltered", cfgmod.relpath,
                        node.lineno, "Config.fingerprint",
                        "fingerprint() folds the raw override map "
                        "without consulting the semantic classification "
                        "— operational tuning (quotas, cadence, cache "
                        "sizing) invalidates every result/plan cache"))
    return out


def run(project: Project) -> List[Finding]:
    reg = _Registry.parse(project)
    return _k1(project, reg) + _k23(project) + _k4(project, reg)
