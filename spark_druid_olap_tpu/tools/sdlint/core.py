"""sdlint core: project model, findings, suppression, baseline, reporters.

Everything is pure ``ast`` — no module under analysis is ever imported,
so the linter runs identically with or without jax/pandas installed and
fixture modules with seeded violations stay import-free.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# `# sdlint: disable=locks` or `# sdlint: disable=locks,purity` or
# `# sdlint: disable=all` — applies to that line, or to a whole function
# when placed anywhere on its def header (decorators and multi-line
# signatures included). `# sdlint: disable-file=<pass>` within the
# first 10 lines silences a pass for the whole module.
_SUPPRESS_RE = re.compile(r"#\s*sdlint:\s*disable=([a-z,]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*sdlint:\s*disable-file=([a-z,]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    pass_name: str     # locks | purity | contracts | mergeclosure
    rule: str          # stable rule slug within the pass
    path: str          # path relative to the scanned root
    line: int
    symbol: str        # stable anchor (qualified function, key, ...)
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        """Baseline identity: line numbers churn, symbols don't."""
        return (self.pass_name, self.rule, self.path, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.rule}] "
                f"{self.symbol}: {self.message}")


class Module:
    """One parsed source file: AST + per-line suppressions + def spans."""

    def __init__(self, root: str, relpath: str, source: str):
        self.relpath = relpath
        self.name = relpath[:-3].replace(os.sep, ".")
        if self.name.endswith(".__init__"):
            self.name = self.name[: -len(".__init__")]
        self.source = source
        self.tree = ast.parse(source, filename=os.path.join(root, relpath))
        self.suppress: Dict[int, set] = {}
        self.suppress_file: set = set()
        for i, ln in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(ln)
            if m:
                self.suppress[i] = set(m.group(1).split(","))
            if i <= 10:
                m = _SUPPRESS_FILE_RE.search(ln)
                if m:
                    self.suppress_file |= set(m.group(1).split(","))
        # innermost-enclosing-def lookup for def-header suppressions.
        # The span starts at the FIRST DECORATOR, and the whole header
        # (def line through the closing paren of a multi-line signature)
        # counts as "the def line" for suppression comments.
        self._def_spans: List[Tuple[int, int, Tuple[int, ...]]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = getattr(node, "end_lineno", node.lineno)
                start = min([node.lineno]
                            + [d.lineno for d in node.decorator_list])
                # header ends where the first body statement starts
                hdr_end = node.body[0].lineno - 1 if node.body \
                    else node.lineno
                header = tuple(range(start, max(hdr_end,
                                                node.lineno) + 1))
                self._def_spans.append((start, end, header))
        self._def_spans.sort()

    def suppressed(self, pass_name: str, line: int) -> bool:
        if pass_name in self.suppress_file or "all" in self.suppress_file:
            return True
        for at in (line,) + self._enclosing_def_header(line):
            s = self.suppress.get(at)
            if s and (pass_name in s or "all" in s):
                return True
        return False

    def _enclosing_def_header(self, line: int) -> Tuple[int, ...]:
        best: Tuple[int, ...] = ()
        for start, end, header in self._def_spans:
            if start <= line <= end:
                best = header       # spans sorted by start: innermost last
        return best


class Project:
    """The scanned tree. ``root`` is the package directory itself (e.g.
    ``.../spark_druid_olap_tpu``) or any directory of fixture modules;
    ``package`` is the dotted import name that prefix maps onto ``root``
    (used to resolve intra-package imports)."""

    def __init__(self, root: str, package: str = "spark_druid_olap_tpu",
                 skip: Sequence[str] = ("tools/sdlint",),
                 only: Optional[Sequence[str]] = None):
        self.root = os.path.abspath(root)
        self.package = package
        self.modules: Dict[str, Module] = {}
        self._index = None          # shared astutil.Index, built once
        self._cfgs: Dict[object, object] = {}   # fn node -> cfg.CFG
        only_rel = None if only is None else {
            o.replace("/", os.sep) for o in only}
        skip = tuple(s.replace("/", os.sep) for s in skip)
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                if any(rel == s or rel.startswith(s + os.sep)
                       for s in skip):
                    continue
                if only_rel is not None and rel not in only_rel:
                    continue
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as f:
                    src = f.read()
                try:
                    mod = Module(self.root, rel, src)
                except SyntaxError:
                    continue        # not this linter's business
                self.modules[mod.name] = mod

    def module_for_import(self, dotted: str) -> Optional[Module]:
        """Resolve an absolute import like ``<package>.ops.groupby`` (or a
        bare ``ops.groupby`` in fixture trees) to a scanned module."""
        if dotted.startswith(self.package + "."):
            dotted = dotted[len(self.package) + 1:]
        elif dotted == self.package:
            dotted = ""
        return self.modules.get(dotted)

    def index(self):
        """The one shared :class:`astutil.Index` — every pass resolves
        through the same parse (v1 re-built it per pass)."""
        if self._index is None:
            from spark_druid_olap_tpu.tools.sdlint.astutil import Index
            self._index = Index(self)
        return self._index

    def cfg(self, fn):
        """Memoized per-function CFG (leaks + ordering share them)."""
        c = self._cfgs.get(fn)
        if c is None:
            from spark_druid_olap_tpu.tools.sdlint import cfg as _cfg
            c = self._cfgs[fn] = _cfg.build(fn)
        return c

    def by_suffix(self, suffix: str) -> Optional[Module]:
        """Find the one module whose relpath ends with ``suffix`` (anchor
        files like ``parallel/executor.py``); None when absent (fixture
        trees carry only the anchors their seeded violation needs)."""
        suffix = suffix.replace("/", os.sep)
        hits = [m for m in self.modules.values()
                if m.relpath == suffix
                or m.relpath.endswith(os.sep + suffix)]
        return hits[0] if len(hits) == 1 else None


class Baseline:
    """Checked-in known findings. Every entry must carry a one-line
    ``justification``; matching is on Finding.key() (no line numbers, so
    unrelated edits don't churn the file)."""

    def __init__(self, entries: Iterable[dict] = ()):
        self.entries = list(entries)
        self._keys = {}
        for e in self.entries:
            k = (e.get("pass"), e.get("rule"), e.get("path"),
                 e.get("symbol"))
            self._keys[k] = e

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("findings", []))

    def matches(self, f: Finding) -> bool:
        return f.key() in self._keys

    def unmatched(self, findings: Sequence[Finding]) -> List[dict]:
        """Baseline entries no current finding hits — stale, should be
        deleted (surfaced by the CLI as a warning, not a failure)."""
        seen = {f.key() for f in findings}
        return [e for k, e in self._keys.items() if k not in seen]

    def missing_justifications(self) -> List[dict]:
        return [e for e in self.entries
                if not str(e.get("justification", "")).strip()]


def run_passes(project: Project,
               passes: Sequence[str] = ("locks", "purity", "contracts",
                                        "mergeclosure", "keys", "leaks",
                                        "ordering", "kernels", "mesh"),
               timing: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Run the named passes; returns suppression-filtered findings.
    With ``timing`` a dict, per-pass wall seconds are written into it
    (plus ``"index"`` for the shared parse/index build)."""
    import time as _time
    from spark_druid_olap_tpu.tools.sdlint import (contracts, kernels, keys,
                                                   leaks, locks, mergeclosure,
                                                   mesh, ordering, purity)
    impl = {"locks": locks.run, "purity": purity.run,
            "contracts": contracts.run, "mergeclosure": mergeclosure.run,
            "keys": keys.run, "leaks": leaks.run, "ordering": ordering.run,
            "kernels": kernels.run, "mesh": mesh.run}
    if timing is not None:
        t0 = _time.perf_counter()
        project.index()
        timing["index"] = _time.perf_counter() - t0
    out: List[Finding] = []
    for name in passes:
        t0 = _time.perf_counter()
        found = impl[name](project)
        if timing is not None:
            timing[name] = _time.perf_counter() - t0
        for f in found:
            mod = project.modules.get(
                f.path[:-3].replace(os.sep, ".")) if f.path.endswith(".py") \
                else None
            if mod is None:
                for m in project.modules.values():
                    if m.relpath == f.path:
                        mod = m
                        break
            if mod is not None and mod.suppressed(f.pass_name, f.line):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.pass_name, f.path, f.line, f.rule, f.symbol))
    return out


# -- reporters ----------------------------------------------------------------

def report_human(findings: Sequence[Finding], baseline: Baseline,
                 write=print) -> int:
    """Human report; returns the count of NON-baselined findings."""
    new = [f for f in findings if not baseline.matches(f)]
    known = [f for f in findings if baseline.matches(f)]
    for f in new:
        write(f.render())
    if known:
        write(f"sdlint: {len(known)} baselined finding(s) suppressed "
              f"(tools/sdlint/baseline.json)")
    stale = baseline.unmatched(findings)
    if stale:
        write(f"sdlint: warning: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer fire(s): "
              + ", ".join(sorted(str(e.get("symbol")) for e in stale)))
    write(f"sdlint: {len(new)} finding(s), {len(known)} baselined")
    return len(new)


#: bump ONLY on a breaking change to the JSON document shape — CI diffs
#: and downstream tooling key on this (golden-tested in tests/test_lint.py)
JSON_SCHEMA_VERSION = 2


def report_json(findings: Sequence[Finding], baseline: Baseline) -> str:
    """Stable machine output: findings sorted by (pass, path, rule,
    symbol, line), keys sorted, schema versioned."""
    ordered = sorted(findings, key=lambda f: (f.pass_name, f.path, f.rule,
                                              f.symbol, f.line))
    doc = {"schema_version": JSON_SCHEMA_VERSION,
           "findings": [dict(sorted((dataclasses.asdict(f) | {
               "baselined": baseline.matches(f)}).items()))
               for f in ordered],
           "new": sum(1 for f in findings if not baseline.matches(f)),
           "baselined": sum(1 for f in findings if baseline.matches(f)),
           "stale_baseline": sorted(
               baseline.unmatched(findings),
               key=lambda e: (str(e.get("pass")), str(e.get("rule")),
                              str(e.get("symbol"))))}
    return json.dumps(doc, indent=2, sort_keys=True)
