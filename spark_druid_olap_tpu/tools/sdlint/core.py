"""sdlint core: project model, findings, suppression, baseline, reporters.

Everything is pure ``ast`` — no module under analysis is ever imported,
so the linter runs identically with or without jax/pandas installed and
fixture modules with seeded violations stay import-free.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# `# sdlint: disable=locks` or `# sdlint: disable=locks,purity` or
# `# sdlint: disable=all` — applies to that line, or to a whole function
# when placed on its `def` line.
_SUPPRESS_RE = re.compile(r"#\s*sdlint:\s*disable=([a-z,]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    pass_name: str     # locks | purity | contracts | mergeclosure
    rule: str          # stable rule slug within the pass
    path: str          # path relative to the scanned root
    line: int
    symbol: str        # stable anchor (qualified function, key, ...)
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        """Baseline identity: line numbers churn, symbols don't."""
        return (self.pass_name, self.rule, self.path, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.rule}] "
                f"{self.symbol}: {self.message}")


class Module:
    """One parsed source file: AST + per-line suppressions + def spans."""

    def __init__(self, root: str, relpath: str, source: str):
        self.relpath = relpath
        self.name = relpath[:-3].replace(os.sep, ".")
        if self.name.endswith(".__init__"):
            self.name = self.name[: -len(".__init__")]
        self.source = source
        self.tree = ast.parse(source, filename=os.path.join(root, relpath))
        self.suppress: Dict[int, set] = {}
        for i, ln in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(ln)
            if m:
                self.suppress[i] = set(m.group(1).split(","))
        # innermost-enclosing-def lookup for def-line suppressions
        self._def_spans: List[Tuple[int, int, int]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = getattr(node, "end_lineno", node.lineno)
                self._def_spans.append((node.lineno, end, node.lineno))
        self._def_spans.sort()

    def suppressed(self, pass_name: str, line: int) -> bool:
        for at in (line, self._enclosing_def_line(line)):
            if at is None:
                continue
            s = self.suppress.get(at)
            if s and (pass_name in s or "all" in s):
                return True
        return False

    def _enclosing_def_line(self, line: int) -> Optional[int]:
        best = None
        for start, end, defline in self._def_spans:
            if start <= line <= end:
                best = defline      # spans sorted by start: innermost last
        return best


class Project:
    """The scanned tree. ``root`` is the package directory itself (e.g.
    ``.../spark_druid_olap_tpu``) or any directory of fixture modules;
    ``package`` is the dotted import name that prefix maps onto ``root``
    (used to resolve intra-package imports)."""

    def __init__(self, root: str, package: str = "spark_druid_olap_tpu",
                 skip: Sequence[str] = ("tools/sdlint",)):
        self.root = os.path.abspath(root)
        self.package = package
        self.modules: Dict[str, Module] = {}
        skip = tuple(s.replace("/", os.sep) for s in skip)
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                if any(rel == s or rel.startswith(s + os.sep)
                       for s in skip):
                    continue
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as f:
                    src = f.read()
                try:
                    mod = Module(self.root, rel, src)
                except SyntaxError:
                    continue        # not this linter's business
                self.modules[mod.name] = mod

    def module_for_import(self, dotted: str) -> Optional[Module]:
        """Resolve an absolute import like ``<package>.ops.groupby`` (or a
        bare ``ops.groupby`` in fixture trees) to a scanned module."""
        if dotted.startswith(self.package + "."):
            dotted = dotted[len(self.package) + 1:]
        elif dotted == self.package:
            dotted = ""
        return self.modules.get(dotted)

    def by_suffix(self, suffix: str) -> Optional[Module]:
        """Find the one module whose relpath ends with ``suffix`` (anchor
        files like ``parallel/executor.py``); None when absent (fixture
        trees carry only the anchors their seeded violation needs)."""
        suffix = suffix.replace("/", os.sep)
        hits = [m for m in self.modules.values()
                if m.relpath == suffix
                or m.relpath.endswith(os.sep + suffix)]
        return hits[0] if len(hits) == 1 else None


class Baseline:
    """Checked-in known findings. Every entry must carry a one-line
    ``justification``; matching is on Finding.key() (no line numbers, so
    unrelated edits don't churn the file)."""

    def __init__(self, entries: Iterable[dict] = ()):
        self.entries = list(entries)
        self._keys = {}
        for e in self.entries:
            k = (e.get("pass"), e.get("rule"), e.get("path"),
                 e.get("symbol"))
            self._keys[k] = e

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("findings", []))

    def matches(self, f: Finding) -> bool:
        return f.key() in self._keys

    def unmatched(self, findings: Sequence[Finding]) -> List[dict]:
        """Baseline entries no current finding hits — stale, should be
        deleted (surfaced by the CLI as a warning, not a failure)."""
        seen = {f.key() for f in findings}
        return [e for k, e in self._keys.items() if k not in seen]

    def missing_justifications(self) -> List[dict]:
        return [e for e in self.entries
                if not str(e.get("justification", "")).strip()]


def run_passes(project: Project,
               passes: Sequence[str] = ("locks", "purity", "contracts",
                                        "mergeclosure")) -> List[Finding]:
    """Run the named passes; returns suppression-filtered findings."""
    from spark_druid_olap_tpu.tools.sdlint import (contracts, locks,
                                                   mergeclosure, purity)
    impl = {"locks": locks.run, "purity": purity.run,
            "contracts": contracts.run, "mergeclosure": mergeclosure.run}
    out: List[Finding] = []
    for name in passes:
        for f in impl[name](project):
            mod = project.modules.get(
                f.path[:-3].replace(os.sep, ".")) if f.path.endswith(".py") \
                else None
            if mod is None:
                for m in project.modules.values():
                    if m.relpath == f.path:
                        mod = m
                        break
            if mod is not None and mod.suppressed(f.pass_name, f.line):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.pass_name, f.path, f.line, f.rule, f.symbol))
    return out


# -- reporters ----------------------------------------------------------------

def report_human(findings: Sequence[Finding], baseline: Baseline,
                 write=print) -> int:
    """Human report; returns the count of NON-baselined findings."""
    new = [f for f in findings if not baseline.matches(f)]
    known = [f for f in findings if baseline.matches(f)]
    for f in new:
        write(f.render())
    if known:
        write(f"sdlint: {len(known)} baselined finding(s) suppressed "
              f"(tools/sdlint/baseline.json)")
    stale = baseline.unmatched(findings)
    if stale:
        write(f"sdlint: warning: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer fire(s): "
              + ", ".join(sorted(str(e.get("symbol")) for e in stale)))
    write(f"sdlint: {len(new)} finding(s), {len(known)} baselined")
    return len(new)


def report_json(findings: Sequence[Finding], baseline: Baseline) -> str:
    doc = {"findings": [dataclasses.asdict(f) | {
        "baselined": baseline.matches(f)} for f in findings],
        "new": sum(1 for f in findings if not baseline.matches(f)),
        "baselined": sum(1 for f in findings if baseline.matches(f)),
        "stale_baseline": baseline.unmatched(findings)}
    return json.dumps(doc, indent=2)
