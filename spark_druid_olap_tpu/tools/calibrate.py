"""Cost-model calibration from MEASURED wall times (VERDICT r2 item 9).

The reference validated its cost structure with a calibrated
``DruidQueryCostModelTest``; here the constants themselves are fit on
the live backend: run probe group-bys single-chip and mesh-sharded,
time the warm executions, and least-squares the model's terms —

    single  ~= rows * scan_c + groups * 16 * byte_c
    sharded ~= rows * scan_c / (n_dev * eff) + groups * n_aggs * merge_c
               + groups * 16 * byte_c

Units become SECONDS (the defaults are unit-free hand-set numbers).
``eff`` is the mesh's real parallel efficiency — ~1.0 on ICI-connected
chips, far lower on a virtual CPU mesh sharing host cores — which is
exactly what makes the single-vs-sharded decision transfer between
environments.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.parallel.mesh import mesh_size
from spark_druid_olap_tpu.utils.config import (
    COST_COMPILE, COST_PER_BYTE_TRANSPORT, COST_PER_ROW_MERGE,
    COST_PER_ROW_SCAN, COST_SHARD_EFFICIENCY)


def default_shapes(datasource: str, ds) -> List[S.GroupByQuerySpec]:
    """Three probe shapes with distinct (rows x groups) profiles: a
    low-cardinality full scan, a filtered scan, and a high-cardinality
    group-by (merge-term heavy)."""
    dims = sorted(ds.dims, key=lambda d: ds.cardinality(d) or 0)
    if not dims:
        raise ValueError("calibration needs at least one dimension")
    lo = dims[0]
    hi = dims[-1]
    metric = next((m for m in ds.metrics), None)
    aggs = [S.AggregationSpec("count", "n")]
    if metric is not None:
        kind = "doublesum" if ds.column_kind(metric).name == "DOUBLE" \
            else "longsum"
        aggs.append(S.AggregationSpec(kind, "s", field=metric))
    aggs = tuple(aggs)
    filt = None
    d0 = ds.dims[lo]
    if len(d0.dictionary):
        filt = S.SelectorFilter(lo, str(d0.dictionary[0]))
    return [
        S.GroupByQuerySpec(datasource=datasource,
                           dimensions=(S.DimensionSpec(lo, lo),),
                           aggregations=aggs),
        S.GroupByQuerySpec(datasource=datasource,
                           dimensions=(S.DimensionSpec(lo, lo),),
                           aggregations=aggs, filter=filt),
        S.GroupByQuerySpec(datasource=datasource,
                           dimensions=(S.DimensionSpec(hi, hi),),
                           aggregations=aggs),
    ]


def _measure(engine, q, reps: int) -> Tuple[float, dict]:
    engine.execute(q)                       # warm (compile + upload)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        engine.execute(q)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), dict(engine.last_stats)


def measure_samples(single_engine, mesh_engine, shapes,
                    reps: int = 3) -> List[dict]:
    """One sample per shape: measured single/sharded wall seconds plus
    the model's inputs (rows, groups, n_aggs)."""
    out = []
    for q in shapes:
        t1, st1 = _measure(single_engine, q, reps)
        sample = {"rows": int(st1.get("rows_scanned", 0)),
                  "groups": max(1, int(st1.get("groups", 1))),
                  "n_aggs": max(1, len(S.query_aggregations(q))),
                  "single_s": t1, "spec": q}
        if mesh_engine is not None:
            t8, st8 = _measure(mesh_engine, q, reps)
            sample["sharded_s"] = t8
            sample["sharded_really"] = bool(st8.get("sharded"))
        out.append(sample)
    return out


def fit(samples: List[dict], n_dev: int) -> Dict[str, float]:
    """Least-squares fit of the model constants (clamped positive)."""
    rows = np.array([s["rows"] for s in samples], dtype=np.float64)
    grp = np.array([s["groups"] for s in samples], dtype=np.float64)
    naggs = np.array([s["n_aggs"] for s in samples], dtype=np.float64)
    t1 = np.array([s["single_s"] for s in samples], dtype=np.float64)

    a1 = np.stack([rows, grp * 16.0], axis=1)
    (scan_c, byte_c), *_ = np.linalg.lstsq(a1, t1, rcond=None)
    scan_c = max(float(scan_c), 1e-12)
    byte_c = max(float(byte_c), 1e-13)

    out = {COST_PER_ROW_SCAN.key: scan_c,
           COST_PER_BYTE_TRANSPORT.key: byte_c,
           COST_COMPILE.key: 0.0}
    if any("sharded_s" in s for s in samples) and n_dev > 1:
        # only timings where the mesh engine REALLY sharded inform the
        # sharded-side terms (a cost-model single-chip run would fit
        # eff ~= 1/n_dev and a noise merge_c)
        t8 = np.array([s["sharded_s"]
                       if s.get("sharded_really", True)
                       and "sharded_s" in s else np.nan
                       for s in samples])
        ok = ~np.isnan(t8)
        if not ok.any():
            return out
        a8 = np.stack([rows[ok], grp[ok] * naggs[ok]], axis=1)
        resid = t8[ok] - grp[ok] * 16.0 * byte_c
        (alpha, merge_c), *_ = np.linalg.lstsq(a8, resid, rcond=None)
        merge_c = max(float(merge_c), 1e-13)
        # alpha = scan_c / (n_dev * eff)
        eff = scan_c / (max(float(alpha), 1e-15) * n_dev)
        out[COST_PER_ROW_MERGE.key] = merge_c
        out[COST_SHARD_EFFICIENCY.key] = float(np.clip(eff, 0.01, 1.0))
    return out


def _amortized_s(fn, args, reps: int = 4) -> float:
    """Median amortized seconds of one jitted program: chained dispatches
    between DATA-DEPENDENT syncs (block_until_ready is unreliable on the
    tunneled plugin — docs/bench/README.md)."""
    import jax
    import jax.numpy as jnp

    def sync(r):
        leaf = jax.tree_util.tree_leaves(r)[0]
        np.asarray(jnp.ravel(leaf)[:1])

    sync(fn(*args))                         # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = None
        for _ in range(4):
            r = fn(*args)
        sync(r)
        ts.append((time.perf_counter() - t0) / 4)
    return float(np.median(ts))


def calibrate_primitives(config, n_rows: int = 1 << 21,
                         apply: bool = True) -> Dict[str, float]:
    """Fit the per-backend UNIT costs the perf gates consume (VERDICT r3
    weak 6): 2-op sort s/row, extra-payload s/row, scatter s/update at an
    in-cache AND a past-cache table size, and 1D-gather s/probe. Applied
    to the session config, these drive `_plan_compact_m`, the sorted-run
    gate, and the ffl compaction ceiling from measurement instead of
    hand-tuned literals."""
    import jax
    import jax.numpy as jnp
    from spark_druid_olap_tpu.utils.config import (
        COST_GATHER_PROBE, COST_SCATTER_UPDATE, COST_SCATTER_UPDATE_BIG,
        COST_SORT_PAYLOAD_ROW, COST_SORT_ROW, COST_TABLE_CACHE_BYTES)

    n = int(n_rows)
    rng = np.random.default_rng(11)
    k1 = jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int32))
    k2 = jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int32))
    p1 = jnp.asarray(rng.integers(0, 100, n).astype(np.int32))
    p2 = jnp.asarray(rng.normal(size=n).astype(np.float32))

    sort2 = jax.jit(lambda a, b: jax.lax.sort((a, b), num_keys=2))
    sort4 = jax.jit(lambda a, b, c, d: jax.lax.sort((a, b, c, d),
                                                    num_keys=2))
    t_sort2 = _amortized_s(sort2, (k1, k2))
    t_sort4 = _amortized_s(sort4, (k1, k2, p1, p2))

    t_small = 1 << 15                   # ~128KB table: comfortably cached
    # big table: slots = cache-threshold BYTES, i.e. a 4x-past-threshold
    # f32 table, so the thrash regime (if this backend has one) is what
    # gets measured
    t_big = max(1 << 18, int(config.get(COST_TABLE_CACHE_BYTES)))

    def scat(tbl_slots):
        idx = jnp.asarray(rng.integers(0, tbl_slots, n).astype(np.int32))

        def f(v):
            return jnp.zeros(tbl_slots, jnp.float32).at[idx].add(v)
        return _amortized_s(jax.jit(f), (p2,))

    t_scat_small = scat(t_small)
    t_scat_big = scat(t_big)

    lut = jnp.asarray(rng.normal(size=t_small).astype(np.float32))
    gidx = jnp.asarray(rng.integers(0, t_small, n).astype(np.int32))
    t_gather = _amortized_s(jax.jit(lambda i: jnp.take(lut, i)), (gidx,))

    fitted = {
        COST_SORT_ROW.key: max(t_sort2 / n, 1e-13),
        COST_SORT_PAYLOAD_ROW.key: max((t_sort4 - t_sort2) / (2 * n),
                                       1e-13),
        COST_SCATTER_UPDATE.key: max(t_scat_small / n, 1e-13),
        COST_SCATTER_UPDATE_BIG.key: max(t_scat_big / n, 1e-13),
        COST_GATHER_PROBE.key: max(t_gather / n, 1e-13),
    }
    if apply:
        for k, v in fitted.items():
            config.set(k, v)
    return fitted


def calibrate(ctx, datasource: Optional[str] = None, reps: int = 3,
              mesh_ctx=None, apply: bool = True) -> Dict[str, float]:
    """Fit the cost constants on the LIVE backend and (optionally) apply
    them to the session config. ``mesh_ctx`` supplies the sharded side;
    without one, only the single-chip terms are fit."""
    datasource = datasource or sorted(ctx.store.names())[0]
    ds = ctx.store.get(datasource)
    shapes = default_shapes(datasource, ds)
    mesh_engine = mesh_ctx.engine if mesh_ctx is not None else None
    n_dev = mesh_size(mesh_engine.mesh) if mesh_engine is not None else 1
    from spark_druid_olap_tpu.utils.config import COST_MODEL_ENABLED
    prev_cm = None
    if mesh_ctx is not None:
        # the sharded probes must REALLY shard, whatever the current
        # (uncalibrated) model would decide
        prev_cm = mesh_ctx.config.get(COST_MODEL_ENABLED)
        mesh_ctx.config.set(COST_MODEL_ENABLED.key, False)
    try:
        samples = measure_samples(ctx.engine, mesh_engine, shapes, reps)
    finally:
        if mesh_ctx is not None:
            mesh_ctx.config.set(COST_MODEL_ENABLED.key, prev_cm)
    fitted = fit(samples, n_dev)
    if apply:
        for k, v in fitted.items():
            ctx.config.set(k, v)
            if mesh_ctx is not None:
                mesh_ctx.config.set(k, v)
    return fitted
