"""TPC-H data generation, flattening, and star-schema wiring.

≈ the reference's benchmark/test data stack: the dbgen-derived CSVs under
``src/test/resources/tpch/``, the flattened 52-column BI table
(``execution/tools/BenchMark.scala:49-103``), the star-schema declaration of
``StarSchemaBaseTest`` (lineitem + orders/customer/part/supplier/partsupp +
doubled nation/region for the customer and supplier paths), and the
``TpchBenchMark`` driver queries.

The generator is a fast, deterministic, schema-faithful approximation of
dbgen (uniform/zipf-ish draws, real TPC-H value domains) — correctness tests
are differential (engine vs host on identical data), so exact dbgen
distributions are unnecessary; benchmarks report rows/sec which is
distribution-insensitive.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.metadata.star import StarRelation, StarSchema

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2),
    ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0),
    ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3), ("SAUDI ARABIA", 4),
    ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
TYPES = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE",
                                  "ECONOMY", "PROMO")
         for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
         for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
              for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                        "DRUM")]


def generate(sf: float = 0.01, seed: int = 20260729) -> Dict[str, pd.DataFrame]:
    """Generate all eight TPC-H tables at scale factor ``sf``."""
    r = np.random.default_rng(seed)
    n_orders = max(10, int(1_500_000 * sf))
    n_cust = max(5, int(150_000 * sf))
    n_part = max(5, int(200_000 * sf))
    n_supp = max(3, int(10_000 * sf))

    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
        "r_comment": [f"region {i}" for i in range(5)]})

    nation = pd.DataFrame({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": np.array([k for _, k in NATIONS], dtype=np.int64),
        "n_comment": [f"nation {i}" for i in range(25)]})

    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_address": [f"addr{i}" for i in range(n_supp)],
        "s_nationkey": r.integers(0, 25, n_supp),
        "s_phone": [f"{r.integers(10,35)}-{i:07d}" for i in range(n_supp)],
        "s_acctbal": np.round(r.uniform(-999.99, 9999.99, n_supp), 2),
        "s_comment": [("Customer Complaints" if r.random() < 0.005
                       else f"supplier comment {i}") for i in range(n_supp)]})

    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_address": [f"caddr{i}" for i in range(n_cust)],
        "c_nationkey": r.integers(0, 25, n_cust),
        "c_phone": [f"{10 + i % 25}-{i:07d}" for i in range(n_cust)],
        "c_acctbal": np.round(r.uniform(-999.99, 9999.99, n_cust), 2),
        "c_mktsegment": r.choice(SEGMENTS, n_cust),
        "c_comment": [f"customer comment {i}" for i in range(n_cust)]})

    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": [f"part {i} "
                   + " ".join(r.choice(["green", "blue", "red", "ivory",
                                        "magenta", "plum", "puff", "powder",
                                        "forest", "lace"],
                                       3))
                   for i in range(1, n_part + 1)],
        "p_mfgr": [f"Manufacturer#{1 + i % 5}" for i in range(n_part)],
        "p_brand": [f"Brand#{1 + (i % 5)}{1 + (i // 5) % 5}"
                    for i in range(n_part)],
        "p_type": r.choice(TYPES, n_part),
        "p_size": r.integers(1, 51, n_part),
        "p_container": r.choice(CONTAINERS, n_part),
        "p_retailprice": np.round(900 + (np.arange(1, n_part + 1) % 1000)
                                  / 10.0, 2),
        "p_comment": [f"part comment {i}" for i in range(n_part)]})

    # partsupp: 4 suppliers per part
    ps_part = np.repeat(part.p_partkey.to_numpy(), 4)
    ps_supp = ((ps_part + np.tile(np.arange(4), n_part)
                * (n_supp // 4 + 1)) % n_supp) + 1
    partsupp = pd.DataFrame({
        "ps_partkey": ps_part,
        "ps_suppkey": ps_supp.astype(np.int64),
        "ps_availqty": r.integers(1, 10000, len(ps_part)),
        "ps_supplycost": np.round(r.uniform(1.0, 1000.0, len(ps_part)), 2),
        "ps_comment": [f"ps comment {i}" for i in range(len(ps_part))]})

    start = np.datetime64("1992-01-01")
    o_dates = start + r.integers(0, 2406, n_orders).astype("timedelta64[D]")
    orders = pd.DataFrame({
        "o_orderkey": np.arange(1, n_orders + 1, dtype=np.int64),
        "o_custkey": r.integers(1, n_cust + 1, n_orders),
        "o_orderstatus": r.choice(["O", "F", "P"], n_orders,
                                  p=[0.49, 0.49, 0.02]),
        "o_totalprice": np.round(r.uniform(800, 500000, n_orders), 2),
        "o_orderdate": o_dates.astype("datetime64[ns]"),
        "o_orderpriority": r.choice(PRIORITIES, n_orders),
        "o_clerk": [f"Clerk#{1 + i % 1000:09d}" for i in range(n_orders)],
        "o_shippriority": np.zeros(n_orders, dtype=np.int64),
        "o_comment": [("special requests" if r.random() < 0.01
                       else f"order comment {i}") for i in range(n_orders)]})

    # lineitem: 1-7 lines per order (avg 4)
    lines_per = r.integers(1, 8, n_orders)
    li_order = np.repeat(orders.o_orderkey.to_numpy(), lines_per)
    n_li = len(li_order)
    li_odate = np.repeat(o_dates, lines_per)
    ship_delay = r.integers(1, 122, n_li).astype("timedelta64[D]")
    l_ship = li_odate + ship_delay
    l_commit = li_odate + r.integers(30, 91, n_li).astype("timedelta64[D]")
    l_receipt = l_ship + r.integers(1, 31, n_li).astype("timedelta64[D]")
    l_part = r.integers(1, n_part + 1, n_li)
    # supplier consistent with partsupp: one of the 4 for the part
    l_supp = ((l_part + r.integers(0, 4, n_li) * (n_supp // 4 + 1))
              % n_supp) + 1
    qty = r.integers(1, 51, n_li).astype(np.int64)
    extprice = np.round(qty * (900 + (l_part % 1000) / 10.0), 2)
    # returnflag: R/A only for ship dates in the past relative to 1995-06-17
    cutoff = np.datetime64("1995-06-17")
    rf = np.where(l_receipt <= cutoff,
                  r.choice(["R", "A"], n_li), "N")
    ls = np.where(l_ship > np.datetime64("1995-06-17"), "O", "F")
    lineitem = pd.DataFrame({
        "l_orderkey": li_order,
        "l_partkey": l_part.astype(np.int64),
        "l_suppkey": l_supp.astype(np.int64),
        "l_linenumber": np.concatenate(
            [np.arange(1, k + 1) for k in lines_per]).astype(np.int64),
        "l_quantity": qty,
        "l_extendedprice": extprice,
        "l_discount": np.round(r.integers(0, 11, n_li) / 100.0, 2),
        "l_tax": np.round(r.integers(0, 9, n_li) / 100.0, 2),
        "l_returnflag": rf,
        "l_linestatus": ls,
        "l_shipdate": l_ship.astype("datetime64[ns]"),
        "l_commitdate": l_commit.astype("datetime64[ns]"),
        "l_receiptdate": l_receipt.astype("datetime64[ns]"),
        "l_shipinstruct": r.choice(INSTRUCTS, n_li),
        "l_shipmode": r.choice(SHIPMODES, n_li),
        "l_comment": [f"line comment {i}" for i in range(n_li)]})

    return {"region": region, "nation": nation, "supplier": supplier,
            "customer": customer, "part": part, "partsupp": partsupp,
            "orders": orders, "lineitem": lineitem}


def nation_region_views(tables) -> Dict[str, pd.DataFrame]:
    """The doubled nation/region dims for the customer and supplier join
    paths, with globally-unique column names (≈ the reference's
    custnation/custregion/suppnation/suppregion tables in
    StarSchemaBaseTest)."""
    nation, region = tables["nation"], tables["region"]
    cn = nation.rename(columns={
        "n_nationkey": "cn_nationkey", "n_name": "cn_name",
        "n_regionkey": "cn_regionkey", "n_comment": "cn_comment"})
    cr = region.rename(columns={
        "r_regionkey": "cr_regionkey", "r_name": "cr_name",
        "r_comment": "cr_comment"})
    sn = nation.rename(columns={
        "n_nationkey": "sn_nationkey", "n_name": "sn_name",
        "n_regionkey": "sn_regionkey", "n_comment": "sn_comment"})
    sr = region.rename(columns={
        "r_regionkey": "sr_regionkey", "r_name": "sr_name",
        "r_comment": "sr_comment"})
    return {"custnation": cn, "custregion": cr, "suppnation": sn,
            "suppregion": sr}


def flatten(tables) -> pd.DataFrame:
    """Denormalize the full star onto lineitem (≈ the reference's flattened
    52-column BI table indexed into Druid)."""
    nr = nation_region_views(tables)
    df = tables["lineitem"].merge(tables["orders"], left_on="l_orderkey",
                                  right_on="o_orderkey")
    df = df.merge(tables["customer"], left_on="o_custkey",
                  right_on="c_custkey")
    df = df.merge(nr["custnation"], left_on="c_nationkey",
                  right_on="cn_nationkey")
    df = df.merge(nr["custregion"], left_on="cn_regionkey",
                  right_on="cr_regionkey")
    df = df.merge(tables["part"], left_on="l_partkey", right_on="p_partkey")
    df = df.merge(tables["supplier"], left_on="l_suppkey",
                  right_on="s_suppkey")
    df = df.merge(nr["suppnation"], left_on="s_nationkey",
                  right_on="sn_nationkey")
    df = df.merge(nr["suppregion"], left_on="sn_regionkey",
                  right_on="sr_regionkey")
    df = df.merge(tables["partsupp"],
                  left_on=["l_partkey", "l_suppkey"],
                  right_on=["ps_partkey", "ps_suppkey"])
    return df.reset_index(drop=True)


def flatten_stream(tables, lineitem_path: str, out_path: str,
                   batch_rows: int = 1 << 20,
                   drop_columns=None) -> int:
    """Out-of-core flatten: stream lineitem from Parquet and denormalize
    chunk-by-chunk against the (smaller) dimension tables, writing the flat
    index to Parquet incrementally — the full flat frame never
    materializes (the pandas peak at SF>=10 would be several times the
    ~25GB+ flat size). Returns rows written."""
    from spark_druid_olap_tpu.segment.stream_ingest import flatten_join_stream
    nr = nation_region_views(tables)
    joins = [
        (tables["orders"], "l_orderkey", "o_orderkey"),
        (tables["customer"], "o_custkey", "c_custkey"),
        (nr["custnation"], "c_nationkey", "cn_nationkey"),
        (nr["custregion"], "cn_regionkey", "cr_regionkey"),
        (tables["part"], "l_partkey", "p_partkey"),
        (tables["supplier"], "l_suppkey", "s_suppkey"),
        (nr["suppnation"], "s_nationkey", "sn_nationkey"),
        (nr["suppregion"], "sn_regionkey", "sr_regionkey"),
        (tables["partsupp"], ["l_partkey", "l_suppkey"],
         ["ps_partkey", "ps_suppkey"]),
    ]
    return flatten_join_stream(lineitem_path, out_path, joins,
                               batch_rows=batch_rows,
                               drop_columns=drop_columns)


def flatten_partsupp(tables) -> pd.DataFrame:
    """Denormalize the partsupp-grain star (partsupp x part x supplier x
    supp-nation/region). TPC-H q2/q11/q16/q20 aggregate at partsupp grain,
    where folding onto the lineitem flat index would multiply rows; Druid
    deployments likewise index one datasource per fact grain."""
    nr = nation_region_views(tables)
    df = tables["partsupp"].merge(tables["part"], left_on="ps_partkey",
                                  right_on="p_partkey")
    df = df.merge(tables["supplier"], left_on="ps_suppkey",
                  right_on="s_suppkey")
    df = df.merge(nr["suppnation"], left_on="s_nationkey",
                  right_on="sn_nationkey")
    df = df.merge(nr["suppregion"], left_on="sn_regionkey",
                  right_on="sr_regionkey")
    return df.reset_index(drop=True)


def partsupp_star_schema(
        flat_datasource: str = "partsupp_flat") -> StarSchema:
    """Second star: partsupp fact with part/supplier/nation/region dims."""
    return StarSchema("partsupp", flat_datasource, [
        StarRelation("partsupp", "part", (("ps_partkey", "p_partkey"),)),
        StarRelation("partsupp", "supplier",
                     (("ps_suppkey", "s_suppkey"),)),
        StarRelation("supplier", "suppnation",
                     (("s_nationkey", "sn_nationkey"),)),
        StarRelation("suppnation", "suppregion",
                     (("sn_regionkey", "sr_regionkey"),)),
    ])


def star_schema(flat_datasource: str = "tpch_flat") -> StarSchema:
    """The TPC-H star graph (≈ StarSchemaBaseTest's starSchema json)."""
    return StarSchema("lineitem", flat_datasource, [
        StarRelation("lineitem", "orders",
                     (("l_orderkey", "o_orderkey"),)),
        StarRelation("orders", "customer", (("o_custkey", "c_custkey"),)),
        StarRelation("customer", "custnation",
                     (("c_nationkey", "cn_nationkey"),)),
        StarRelation("custnation", "custregion",
                     (("cn_regionkey", "cr_regionkey"),)),
        StarRelation("lineitem", "part", (("l_partkey", "p_partkey"),)),
        StarRelation("lineitem", "supplier", (("l_suppkey", "s_suppkey"),)),
        StarRelation("supplier", "suppnation",
                     (("s_nationkey", "sn_nationkey"),)),
        StarRelation("suppnation", "suppregion",
                     (("sn_regionkey", "sr_regionkey"),)),
        StarRelation("lineitem", "partsupp",
                     (("l_partkey", "ps_partkey"),
                      ("l_suppkey", "ps_suppkey"))),
    ])


def setup_context(ctx, sf: float = 0.01, seed: int = 20260729,
                  target_rows: int = 1 << 20, flat_only: bool = False):
    """Ingest the TPC-H star into a Context: every base table as its own
    datasource (host-fallback/joins) plus the flat index, and register the
    star schema so star joins collapse onto it."""
    tables = generate(sf, seed)
    flat = flatten(tables)
    ctx.ingest_dataframe("tpch_flat", flat, time_column="l_shipdate",
                         target_rows=target_rows)
    if not flat_only:
        for name, df in tables.items():
            if name in ("nation", "region"):
                continue
            tcol = {"lineitem": "l_shipdate", "orders": "o_orderdate"}.get(name)
            ctx.ingest_dataframe(name, df, time_column=tcol,
                                 target_rows=target_rows)
        for name, df in nation_region_views(tables).items():
            ctx.ingest_dataframe(name, df, target_rows=target_rows)
        ctx.ingest_dataframe("partsupp_flat", flatten_partsupp(tables),
                             target_rows=target_rows)
        ctx.register_star_schema(partsupp_star_schema("partsupp_flat"))
    ctx.register_star_schema(star_schema("tpch_flat"))
    return tables, flat


# -- benchmark queries (altered TPC-H, reference BenchMarkDetails.org:69-78) --

QUERIES: Dict[str, str] = {
    # reference "Basic Aggregation"
    "basic_agg": """
        select l_returnflag, l_linestatus, count(*) as count_order,
               sum(l_extendedprice) as s, max(ps_supplycost) as m,
               avg(ps_availqty) as a, count(distinct o_orderkey) as od
        from lineitem li join orders o on li.l_orderkey = o.o_orderkey
             join partsupp ps on li.l_partkey = ps.ps_partkey
                  and li.l_suppkey = ps.ps_suppkey
        group by l_returnflag, l_linestatus
    """,
    # reference "Ship Date Range"
    "shipdate_range": """
        select l_returnflag, l_linestatus, count(*) as count_order
        from lineitem
        where l_shipdate >= date '1994-01-01' and l_shipdate <= date '1997-01-01'
        group by l_returnflag, l_linestatus
    """,
    # reference "SubQry + filters + ShpDt Range" (flattened form)
    "filters_range": """
        select s_nation, count(*) as count_order
        from (select l_returnflag, l_linestatus, sn_name as s_nation,
                     l_shipdate
              from lineitem li join supplier s on li.l_suppkey = s.s_suppkey
                   join suppnation sn on s.s_nationkey = sn.sn_nationkey) t
        where l_returnflag = 'R'
              and l_shipdate >= date '1994-01-01'
              and l_shipdate <= date '1995-01-01'
        group by s_nation
    """,
    "q1": """
        select l_returnflag, l_linestatus,
               sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
               avg(l_quantity) as avg_qty,
               avg(l_extendedprice) as avg_price,
               avg(l_discount) as avg_disc,
               count(*) as count_order
        from lineitem
        where l_shipdate <= date '1998-12-01' - interval '90' day
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus
    """,
    "q3": """
        select o_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer c join orders o on c.c_custkey = o.o_custkey
             join lineitem l on l.l_orderkey = o.o_orderkey
        where c_mktsegment = 'BUILDING'
              and o_orderdate < date '1995-03-15'
              and l_shipdate > date '1995-03-15'
        group by o_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate
        limit 10
    """,
    "q5": """
        select sn_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer c join orders o on c.c_custkey = o.o_custkey
             join lineitem l on l.l_orderkey = o.o_orderkey
             join supplier s on l.l_suppkey = s.s_suppkey
             join suppnation n on s.s_nationkey = n.sn_nationkey
             join suppregion r on n.sn_regionkey = r.sr_regionkey
        where sr_name = 'ASIA'
              and o_orderdate >= date '1994-01-01'
              and o_orderdate < date '1995-01-01'
        group by sn_name
        order by revenue desc
    """,
    "q6": """
        select sum(l_extendedprice * l_discount) as revenue
        from lineitem
        where l_shipdate >= date '1994-01-01'
              and l_shipdate < date '1995-01-01'
              and l_discount between 0.05 and 0.07
              and l_quantity < 24
    """,
    "q7": """
        select sn_name, cn_name, year(l_shipdate) as l_year,
               sum(l_extendedprice * (1 - l_discount)) as revenue
        from supplier s join lineitem l on s.s_suppkey = l.l_suppkey
             join orders o on o.o_orderkey = l.l_orderkey
             join customer c on c.c_custkey = o.o_custkey
             join suppnation n1 on s.s_nationkey = n1.sn_nationkey
             join custnation n2 on c.c_nationkey = n2.cn_nationkey
        where ((sn_name = 'FRANCE' and cn_name = 'GERMANY')
               or (sn_name = 'GERMANY' and cn_name = 'FRANCE'))
              and l_shipdate between date '1995-01-01' and date '1996-12-31'
        group by sn_name, cn_name, year(l_shipdate)
        order by sn_name, cn_name, l_year
    """,
    "q8": """
        select year(o_orderdate) as o_year,
               sum(case when sn_name = 'BRAZIL'
                        then l_extendedprice * (1 - l_discount)
                        else 0 end) as brazil_rev,
               sum(l_extendedprice * (1 - l_discount)) as total_rev
        from part p join lineitem l on p.p_partkey = l.l_partkey
             join supplier s on s.s_suppkey = l.l_suppkey
             join orders o on o.o_orderkey = l.l_orderkey
             join customer c on c.c_custkey = o.o_custkey
             join custnation n1 on c.c_nationkey = n1.cn_nationkey
             join custregion r1 on n1.cn_regionkey = r1.cr_regionkey
             join suppnation n2 on s.s_nationkey = n2.sn_nationkey
        where cr_name = 'AMERICA'
              and o_orderdate between date '1995-01-01' and date '1996-12-31'
              and p_type = 'ECONOMY ANODIZED STEEL'
        group by year(o_orderdate)
        order by o_year
    """,
    "q10": """
        select c_custkey, c_name, sum(l_extendedprice * (1 - l_discount))
               as revenue, c_acctbal, cn_name, c_phone
        from customer c join orders o on c.c_custkey = o.o_custkey
             join lineitem l on l.l_orderkey = o.o_orderkey
             join custnation n on c.c_nationkey = n.cn_nationkey
        where o_orderdate >= date '1993-10-01'
              and o_orderdate < date '1994-01-01'
              and l_returnflag = 'R'
        group by c_custkey, c_name, c_acctbal, c_phone, cn_name
        order by revenue desc
        limit 20
    """,
    "q12": """
        select l_shipmode,
               sum(case when o_orderpriority = '1-URGENT'
                        or o_orderpriority = '2-HIGH' then 1 else 0 end)
                   as high_line_count,
               sum(case when o_orderpriority <> '1-URGENT'
                        and o_orderpriority <> '2-HIGH' then 1 else 0 end)
                   as low_line_count
        from orders o join lineitem l on o.o_orderkey = l.l_orderkey
        where l_shipmode in ('MAIL', 'SHIP')
              and l_receiptdate >= date '1994-01-01'
              and l_receiptdate < date '1995-01-01'
        group by l_shipmode
        order by l_shipmode
    """,
    "q14": """
        select 100.00 * sum(case when p_type like 'PROMO%'
                                 then l_extendedprice * (1 - l_discount)
                                 else 0 end)
               / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
        from lineitem l join part p on l.l_partkey = p.p_partkey
        where l_shipdate >= date '1995-09-01'
              and l_shipdate < date '1995-10-01'
    """,
    # -- the remaining TPC-H queries, adapted to the star dialect (ANSI
    # joins, globally-unique column names per StarSchemaInfo.scala:127-165;
    # self-joined tables renamed through derived tables). Correlated
    # subqueries route through the host executor's decorrelation.
    "q2": """
        select s_acctbal, s_name, sn_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        from part p join partsupp ps on p.p_partkey = ps.ps_partkey
             join supplier s on s.s_suppkey = ps.ps_suppkey
             join suppnation n on s.s_nationkey = n.sn_nationkey
             join suppregion r on n.sn_regionkey = r.sr_regionkey
        where p_size = 15 and p_type like '%BRASS' and sr_name = 'EUROPE'
              and ps_supplycost =
                  (select min(ps_supplycost)
                   from partsupp join supplier on s_suppkey = ps_suppkey
                        join suppnation on s_nationkey = sn_nationkey
                        join suppregion on sn_regionkey = sr_regionkey
                   where p_partkey = ps_partkey and sr_name = 'EUROPE')
        order by s_acctbal desc, sn_name, s_name, p_partkey
        limit 100
    """,
    "q4": """
        select o_orderpriority, count(*) as order_count
        from orders
        where o_orderdate >= date '1993-07-01'
              and o_orderdate < date '1993-10-01'
              and exists (select 1 from lineitem
                          where l_orderkey = o_orderkey
                                and l_commitdate < l_receiptdate)
        group by o_orderpriority
        order by o_orderpriority
    """,
    "q9": """
        select sn_name as nation, year(o_orderdate) as o_year,
               sum(l_extendedprice * (1 - l_discount)
                   - ps_supplycost * l_quantity) as sum_profit
        from lineitem l join part p on p.p_partkey = l.l_partkey
             join supplier s on s.s_suppkey = l.l_suppkey
             join partsupp ps on ps.ps_partkey = l.l_partkey
                  and ps.ps_suppkey = l.l_suppkey
             join orders o on o.o_orderkey = l.l_orderkey
             join suppnation n on s.s_nationkey = n.sn_nationkey
        where p_name like '%green%'
        group by sn_name, year(o_orderdate)
        order by nation, o_year desc
    """,
    "q11": """
        select ps_partkey, sum(ps_supplycost * ps_availqty) as value
        from partsupp ps join supplier s on ps.ps_suppkey = s.s_suppkey
             join suppnation n on s.s_nationkey = n.sn_nationkey
        where sn_name = 'GERMANY'
        group by ps_partkey
        having sum(ps_supplycost * ps_availqty) >
               (select sum(ps_supplycost * ps_availqty) * 0.0001
                from partsupp join supplier on ps_suppkey = s_suppkey
                     join suppnation on s_nationkey = sn_nationkey
                where sn_name = 'GERMANY')
        order by value desc
    """,
    "q13": """
        select c_count, count(*) as custdist
        from (select c_custkey, count(o_orderkey) as c_count
              from customer left outer join orders
                   on c_custkey = o_custkey
                      and o_comment not like '%special%requests%'
              group by c_custkey) c_orders
        group by c_count
        order by custdist desc, c_count desc
    """,
    "q15": """
        select s_suppkey, s_name, s_address, s_phone, total_revenue
        from supplier s join
             (select l_suppkey as supplier_no,
                     sum(l_extendedprice * (1 - l_discount)) as total_revenue
              from lineitem
              where l_shipdate >= date '1996-01-01'
                    and l_shipdate < date '1996-04-01'
              group by l_suppkey) revenue
             on s.s_suppkey = supplier_no
        where total_revenue =
              (select max(total_revenue2)
               from (select sum(l_extendedprice * (1 - l_discount))
                            as total_revenue2
                     from lineitem
                     where l_shipdate >= date '1996-01-01'
                           and l_shipdate < date '1996-04-01'
                     group by l_suppkey) r2)
        order by s_suppkey
    """,
    "q16": """
        select p_brand, p_type, p_size,
               count(distinct ps_suppkey) as supplier_cnt
        from partsupp ps join part p on p.p_partkey = ps.ps_partkey
        where p_brand <> 'Brand#45'
              and p_type not like 'MEDIUM POLISHED%'
              and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
              and ps_suppkey not in
                  (select s_suppkey from supplier
                   where s_comment like '%Customer%Complaints%')
        group by p_brand, p_type, p_size
        order by supplier_cnt desc, p_brand, p_type, p_size
    """,
    "q17": """
        select sum(l_extendedprice) / 7.0 as avg_yearly
        from lineitem l join part p on p.p_partkey = l.l_partkey
        where p_brand = 'Brand#23' and p_container = 'MED BOX'
              and l_quantity < (select 0.2 * avg(l_quantity)
                                from lineitem
                                where l_partkey = p_partkey)
    """,
    "q18": """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity) as total_qty
        from customer c join orders o on c.c_custkey = o.o_custkey
             join lineitem l on o.o_orderkey = l.l_orderkey
        where o_orderkey in (select l_orderkey from lineitem
                             group by l_orderkey
                             having sum(l_quantity) > 300)
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate
        limit 100
    """,
    "q19": """
        select sum(l_extendedprice * (1 - l_discount)) as revenue
        from lineitem l join part p on p.p_partkey = l.l_partkey
        where (p_brand = 'Brand#12'
               and p_container in ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
               and l_quantity >= 1 and l_quantity <= 11
               and p_size between 1 and 5
               and l_shipmode in ('AIR', 'REG AIR')
               and l_shipinstruct = 'DELIVER IN PERSON')
              or (p_brand = 'Brand#23'
                  and p_container in ('MED BAG', 'MED BOX', 'MED PKG',
                                      'MED PACK')
                  and l_quantity >= 10 and l_quantity <= 20
                  and p_size between 1 and 10
                  and l_shipmode in ('AIR', 'REG AIR')
                  and l_shipinstruct = 'DELIVER IN PERSON')
              or (p_brand = 'Brand#34'
                  and p_container in ('LG CASE', 'LG BOX', 'LG PACK',
                                      'LG PKG')
                  and l_quantity >= 20 and l_quantity <= 30
                  and p_size between 1 and 15
                  and l_shipmode in ('AIR', 'REG AIR')
                  and l_shipinstruct = 'DELIVER IN PERSON')
    """,
    "q20": """
        select s_name, s_address
        from supplier s join suppnation n on s.s_nationkey = n.sn_nationkey
        where sn_name = 'CANADA'
              and s_suppkey in
                  (select ps_suppkey from partsupp
                   where ps_partkey in (select p_partkey from part
                                        where p_name like '%forest%')
                         and ps_availqty >
                             (select 0.5 * sum(l_quantity)
                              from lineitem
                              where l_partkey = ps_partkey
                                    and l_suppkey = ps_suppkey
                                    and l_shipdate >= date '1994-01-01'
                                    and l_shipdate < date '1995-01-01'))
        order by s_name
    """,
    "q21": """
        select s_name, count(*) as numwait
        from supplier s join lineitem l1 on s.s_suppkey = l1.l_suppkey
             join orders o on o.o_orderkey = l1.l_orderkey
             join suppnation n on s.s_nationkey = n.sn_nationkey
        where o_orderstatus = 'F'
              and l_receiptdate > l_commitdate
              and sn_name = 'SAUDI ARABIA'
              and exists
                  (select 1
                   from (select l_orderkey as l2_orderkey,
                                l_suppkey as l2_suppkey from lineitem) l2
                   where l2_orderkey = l_orderkey
                         and l2_suppkey <> l_suppkey)
              and not exists
                  (select 1
                   from (select l_orderkey as l3_orderkey,
                                l_suppkey as l3_suppkey,
                                l_receiptdate as l3_receiptdate,
                                l_commitdate as l3_commitdate
                         from lineitem) l3
                   where l3_orderkey = l_orderkey
                         and l3_suppkey <> l_suppkey
                         and l3_receiptdate > l3_commitdate)
        group by s_name
        order by numwait desc, s_name
        limit 100
    """,
    "q22": """
        select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
        from (select substring(c_phone from 1 for 2) as cntrycode, c_acctbal,
                     c_custkey
              from customer
              where substring(c_phone from 1 for 2) in
                    ('13', '31', '23', '29', '30', '18', '17')
                    and c_acctbal > (select avg(c_acctbal) from customer
                                     where c_acctbal > 0.00
                                           and substring(c_phone from 1 for 2)
                                               in ('13', '31', '23', '29',
                                                   '30', '18', '17'))
                    and not exists (select 1 from orders
                                    where o_custkey = c_custkey)) custsale
        group by cntrycode
        order by cntrycode
    """,
}
