"""``python -m spark_druid_olap_tpu.server [--port P] [--tpch SF]``

≈ ``scripts/start-sparklinedatathriftserver.sh`` launching the wrapper
thriftserver; ``--tpch`` preloads the TPC-H star for demos/benchmarks.
"""

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8082)
    ap.add_argument("--tpch", type=float, default=None,
                    help="preload TPC-H at this scale factor")
    ap.add_argument("--parquet", action="append", default=[],
                    metavar="NAME=PATH[:TIMECOL]",
                    help="ingest a parquet file as a datasource")
    args = ap.parse_args()

    def setup(ctx):
        if args.tpch is not None:
            from spark_druid_olap_tpu.tools import tpch
            print(f"loading TPC-H SF{args.tpch} ...")
            tpch.setup_context(ctx, sf=args.tpch)
        for spec in args.parquet:
            name, rest = spec.split("=", 1)
            path, _, tcol = rest.partition(":")
            ctx.ingest_parquet(name, path, time_column=tcol or None)

    from spark_druid_olap_tpu.server.http import serve
    serve(host=args.host, port=args.port, setup=setup)


if __name__ == "__main__":
    main()
