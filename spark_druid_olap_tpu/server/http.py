"""HTTP serving layer — the thriftserver equivalent.

≈ the reference's L7: ``HiveThriftServer2.scala`` fronts the engine for BI
tools over JDBC/ODBC, with a query-history UI tab and SQL-visible metadata
views. Here the endpoint is HTTP:

- ``POST /sql``           {"sql": "...", "format": "json"|"arrow"} -> rows
- ``POST /query``         raw engine query-spec JSON (≈ ON DATASOURCE ...
                          EXECUTE QUERY) with {"dataSource": ...}
- ``POST /sql/cancel``    {"queryId": "..."} -> cooperative cancel
- ``GET  /explain?sql=``  rewrite + cost explanation (≈ EXPLAIN REWRITE)
- ``GET  /status``        liveness + device inventory
- ``GET  /metadata/datasources|segments|columns``  catalog views
- ``GET  /metadata/wlm``  workload-management state (lanes, tenants)
- ``GET  /metadata/persist``  deep-storage state (snapshots, WAL,
                          checkpointer counters, last recovery report)
- ``GET  /history``       query history (≈ the Druid-queries UI tab)

Workload management (wlm/) fronts every query: the request's lane /
tenant / priority come from the JSON body (``lane``/``tenant``/
``priority``) or the ``X-Sdot-Lane`` / ``X-Sdot-Tenant`` /
``X-Sdot-Priority`` headers, and a load-shed admission rejection maps
to **429 Too Many Requests** with a ``Retry-After`` hint (≈ Druid's
QueryCapacityExceededException → 429 at the broker).

The Arrow IPC-stream response format is the binary wire analog of the
reference's Jackson **Smile** protocol (``SmileJson4sScalaModule.scala``):
same role — compact columnar results for programmatic clients — chosen
because Arrow is the TPU-era lingua franca for columnar interchange.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np
import pandas as pd


def _df_to_json_rows(df: pd.DataFrame) -> bytes:
    # native C++ row encoder (GIL-released) when available/eligible
    from spark_druid_olap_tpu.segment.native import encode_json_rows
    rows_b = encode_json_rows(df)
    if rows_b is not None:
        head = json.dumps({"columns": list(df.columns)})[:-1].encode()
        return (head + b', "rows": ' + rows_b +
                b', "numRows": %d}' % len(df))

    def conv(v):
        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, (np.floating,)):
            f = float(v)
            return None if f != f else f
        if isinstance(v, (np.datetime64, pd.Timestamp)):
            return pd.Timestamp(v).isoformat()
        if v is None or v is pd.NaT:
            return None
        return v

    rows = [{c: conv(v) for c, v in zip(df.columns, row)}
            for row in df.itertuples(index=False, name=None)]
    return json.dumps({"columns": list(df.columns), "rows": rows,
                       "numRows": len(df)}).encode()


def _df_to_arrow(df: pd.DataFrame) -> bytes:
    import io
    import pyarrow as pa
    table = pa.Table.from_pandas(df, preserve_index=False)
    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, table.schema) as w:
        w.write_table(table)
    return buf.getvalue()


class SqlServer:
    """Embeds a Context behind a threading HTTP server."""

    def __init__(self, ctx, host: str = "127.0.0.1", port: int = 8082):
        self.ctx = ctx
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._handler_threads: set = set()
        # readiness predicate for GET /readyz: None = ready once the
        # server accepts (plain single-process serving). A cluster
        # historical points this at its boot flag (recovery complete +
        # assigned shards loaded). MUST be lock-free and engine-free:
        # health answers may not queue behind long queries.
        self.ready_check = None
        # optional () -> dict merged into the /readyz body (same
        # lock-free contract): a cluster historical advertises its
        # epoch, boot generation, draining flag and per-epoch warm
        # shard lists here so the broker can gate an epoch handover
        # on actual shard readiness instead of process liveness
        self.ready_info = None
        # queries run CONCURRENTLY (one thread per request, like the
        # reference thriftserver's pooled sessions, DruidClient.scala:46-74);
        # the engine serializes only compile-cache population internally,
        # and per-query state (stats, temp frames) is thread-local

    # -- lifecycle ------------------------------------------------------------
    def start(self, background: bool = True):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def handle(self):
                # track live handler threads so stop() can join them with
                # a bound instead of leaking sockets (daemon_threads alone
                # abandons in-flight connections at interpreter exit)
                t = threading.current_thread()
                server._handler_threads.add(t)
                try:
                    super().handle()
                finally:
                    server._handler_threads.discard(t)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, exc: BaseException):
                body = json.dumps({
                    "error": type(exc).__name__,
                    "message": str(exc)}).encode()
                self._send(code, body)

            def do_GET(self):
                # liveness/readiness FIRST, touching no context, engine
                # or lock: a long query can hold every other handler
                # thread (and the engine's compile lock), and the
                # broker's health prober must never be judged by query
                # latency — only by whether this process accepts and
                # answers
                path = self.path.split("?", 1)[0]
                if path in ("/healthz", "/readyz"):
                    try:
                        server._handle_health(self, path)
                    except (BrokenPipeError, ConnectionResetError):
                        pass
                    return
                try:
                    server._handle_get(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    traceback.print_exc()
                    self._error(500, e)

            def do_POST(self):
                try:
                    server._handle_post(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    traceback.print_exc()
                    self._error(500, e)

        class _Httpd(ThreadingHTTPServer):
            # under a dashboard storm every handler thread can sit
            # inside the engine; a deeper accept backlog keeps health
            # probes and new clients out of connection-refused while
            # the accept loop catches up
            request_queue_size = 128

        self._httpd = _Httpd((self.host, self.port), Handler)
        # handler threads must not pin the process (tests start/stop many
        # servers; a hung client connection would otherwise block exit),
        # and server_close() must not join them unboundedly either —
        # stop() does its own bounded join over the tracked set
        self._httpd.daemon_threads = True
        self._httpd.block_on_close = False
        self.port = self._httpd.server_address[1]
        if background:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self, join_timeout_s: float = 5.0):
        """Idempotent shutdown that cannot leak the listen socket:
        stop accepting, close the socket, then give in-flight handler
        threads and the serve loop a bounded join."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()           # stop the serve_forever loop
        httpd.server_close()       # release the listen socket NOW
        deadline = __import__("time").monotonic() + join_timeout_s
        for t in list(self._handler_threads):
            remaining = deadline - __import__("time").monotonic()
            if remaining <= 0:
                break
            t.join(remaining)      # daemons: a hung one won't pin exit
        if self._thread is not None:
            self._thread.join(max(0.0, deadline
                                  - __import__("time").monotonic()))
            self._thread = None

    # -- handlers -------------------------------------------------------------
    def _handle_health(self, h, path: str):
        """GET /healthz (liveness) and /readyz (readiness). Reads one
        attribute and calls one user predicate — no context, engine, or
        lock access, so it answers even while long queries hold every
        other handler thread."""
        if path == "/healthz":
            h._send(200, b'{"status": "alive"}')
            return
        chk = self.ready_check
        try:
            ok = True if chk is None else bool(chk())
        except Exception:  # noqa: BLE001 — a broken predicate is "not ready"
            ok = False
        body = {"ready": ok}
        info = self.ready_info
        if info is not None:
            try:
                body.update(info())
            except Exception:  # noqa: BLE001 — advert failure ≠ unhealthy
                pass
        h._send(200 if ok else 503, json.dumps(body).encode())

    def _handle_get(self, h):
        url = urlparse(h.path)
        qs = parse_qs(url.query)
        if url.path == "/status":
            import jax
            body = json.dumps({
                "status": "ok",
                "backend": jax.default_backend(),
                "devices": [str(d) for d in jax.devices()],
                "datasources": self.ctx.store.names(),
            }).encode()
            h._send(200, body)
            return
        if url.path == "/explain":
            sql = qs.get("sql", [""])[0]
            text = self.ctx.explain(sql)
            h._send(200, json.dumps({"plan": text.split("\n")}).encode())
            return
        if url.path.startswith("/metadata/"):
            kind = url.path[len("/metadata/"):]
            if kind == "cache":
                # semantic result cache counters (hit/miss/subsumed/
                # evictions/bytes) — ≈ Druid's cache metrics endpoint
                h._send(200, json.dumps(
                    self.ctx.engine.result_cache.stats()).encode())
                return
            if kind == "wlm":
                # lanes (occupancy, sheds, high-water marks) + tenant
                # quota state — ≈ Druid's query-scheduler lane metrics
                h._send(200, json.dumps(
                    self.ctx.engine.wlm.stats()).encode())
                return
            if kind == "cluster":
                # distributed serving tier: shard plan, node health,
                # scatter/merge counters (broker), or role stub
                cl = getattr(self.ctx, "cluster", None)
                if cl is None:
                    h._send(200, b'{"enabled": false}')
                    return
                h._send(200, json.dumps(cl.stats()).encode())
                return
            if kind == "sharedscan":
                # shared-scan coalescer counters; the cluster loadtest
                # polls this per historical for per-node coalesce rate
                h._send(200, json.dumps(
                    self.ctx.engine.sharedscan.stats()).encode())
                return
            if kind == "persist":
                # deep-storage state: per-ds snapshot versions, WAL
                # bytes, checkpointer counters, last recovery report
                if self.ctx.persist is None:
                    h._send(200, b'{"enabled": false}')
                    return
                h._send(200, json.dumps(
                    self.ctx.persist.stats()).encode())
                return
            from spark_druid_olap_tpu.mv.registry import rollups_view
            views = {"datasources": self.ctx.catalog.datasources_view,
                     "segments": self.ctx.catalog.segments_view,
                     "columns": self.ctx.catalog.columns_view,
                     "rollups": lambda: rollups_view(self.ctx)}
            if kind not in views:
                h._send(404, b'{"error": "unknown metadata view"}')
                return
            h._send(200, _df_to_json_rows(views[kind]()))
            return
        if url.path == "/history":
            rows = [r.to_dict() for r in self.ctx.history.entries()]
            h._send(200, json.dumps({"history": rows},
                                    default=str).encode())
            return
        if url.path in ("/ui", "/ui/"):
            h._send(200, self._ui_page(), "text/html; charset=utf-8")
            return
        h._send(404, b'{"error": "not found"}')

    def _ui_page(self) -> bytes:
        """Engine-queries page (≈ the reference's Druid-queries web-UI tab,
        ui/DruidQueriesPage.scala): query history newest-first with mode,
        datasource, segments, groups, timing, and the SQL text."""
        import html as _html
        import time as _time
        rows = []
        for r in reversed(self.ctx.history.entries()):
            st = r.stats
            ts = _time.strftime("%Y-%m-%d %H:%M:%S",
                                _time.gmtime(r.started_at))
            rows.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "<td>{}</td><td>{}</td><td>{:.1f}</td>"
                "<td class=sql>{}</td></tr>".format(
                    ts, _html.escape(str(r.query_type or "")),
                    _html.escape(str(r.datasource or "")),
                    _html.escape(str(st.get("mode", ""))),
                    st.get("segments", ""), st.get("groups", ""),
                    float(st.get("total_ms", 0.0)),
                    _html.escape((r.sql or "")[:500])))
        page = (
            "<!doctype html><html><head><title>sdot queries</title><style>"
            "body{font-family:sans-serif;margin:1em}"
            "table{border-collapse:collapse;width:100%}"
            "td,th{border:1px solid #ccc;padding:4px 8px;font-size:13px;"
            "text-align:left}th{background:#eee}"
            ".sql{font-family:monospace;max-width:40em;overflow-wrap:"
            "anywhere}</style></head><body>"
            "<h2>Engine queries</h2>"
            f"<p>{len(rows)} recorded; datasources: "
            f"{', '.join(self.ctx.store.names()) or '(none)'}</p>"
            "<table><tr><th>started (UTC)</th><th>type</th>"
            "<th>datasource</th><th>mode</th><th>segments</th>"
            "<th>groups</th><th>total ms</th><th>sql</th></tr>"
            + "".join(rows) + "</table></body></html>")
        return page.encode()

    def _read_json(self, h) -> dict:
        n = int(h.headers.get("Content-Length", "0"))
        raw = h.rfile.read(n) if n else b"{}"
        return json.loads(raw.decode())

    @staticmethod
    def _wlm_request(h, req: dict):
        """Lane / tenant / priority for admission: JSON body fields win,
        ``X-Sdot-*`` headers cover clients that can't touch the body
        (BI-tool gateways tagging traffic per tool/user)."""
        lane = req.get("lane") or h.headers.get("X-Sdot-Lane")
        tenant = req.get("tenant") or h.headers.get("X-Sdot-Tenant")
        prio = req.get("priority")
        if prio is None:
            prio = h.headers.get("X-Sdot-Priority")
        try:
            prio = int(prio) if prio is not None else None
        except (TypeError, ValueError):
            prio = None
        return lane, tenant, prio

    @staticmethod
    def _send_shed(h, e, qid=None):
        """AdmissionRejected -> 429 + Retry-After (≈ Druid's
        QueryCapacityExceededException at the broker)."""
        retry_after = max(1, int(-(-e.retry_after_s // 1)))  # ceil, >= 1s
        body = {"error": type(e).__name__, "message": str(e),
                "retryAfterSeconds": retry_after}
        if qid is not None:
            body["queryId"] = qid
        payload = json.dumps(body).encode()
        h.send_response(429)
        h.send_header("Content-Type", "application/json")
        h.send_header("Retry-After", str(retry_after))
        h.send_header("Content-Length", str(len(payload)))
        h.end_headers()
        h.wfile.write(payload)

    def _handle_post(self, h):
        url = urlparse(h.path)
        if url.path == "/sql":
            req = self._read_json(h)
            sql = req.get("sql")
            if not sql:
                h._send(400, b'{"error": "missing \'sql\'"}')
                return
            fmt = req.get("format", "json")
            # the client supplies (or we mint) a query id; supplying one is
            # what makes POST /sql/cancel reachable mid-flight (≈ Druid's
            # client-set queryId in QuerySpecContext). Restricted charset:
            # the id is echoed into the JSON envelope and a response header
            qid = str(req.get("queryId") or uuid.uuid4().hex)
            import re as _re
            if not _re.fullmatch(r"[A-Za-z0-9_.:\-]{1,128}", qid):
                h._send(400, b'{"error": "invalid queryId"}')
                return
            from spark_druid_olap_tpu.sql.lexer import SqlSyntaxError
            from spark_druid_olap_tpu.parallel.executor import (
                QueryCancelled, QueryTimeout)
            from spark_druid_olap_tpu.wlm.lanes import AdmissionRejected
            lane, tenant, prio = self._wlm_request(h, req)
            try:
                r = self.ctx.sql(sql, query_id=qid, lane=lane,
                                 tenant=tenant, priority=prio)
            except SqlSyntaxError as e:
                h._error(400, e)
                return
            except KeyError as e:
                h._error(404, e)
                return
            except AdmissionRejected as e:
                self._send_shed(h, e, qid)
                return
            except (QueryCancelled, QueryTimeout) as e:
                body = json.dumps({"error": type(e).__name__,
                                   "message": str(e),
                                   "queryId": qid}).encode()
                h._send(499 if isinstance(e, QueryCancelled) else 504, body)
                return
            df = r.to_pandas()
            if fmt == "arrow":
                body = _df_to_arrow(df)   # serialize BEFORE the status line
                h.send_response(200)
                h.send_header("Content-Type",
                              "application/vnd.apache.arrow.stream")
                h.send_header("Content-Length", str(len(body)))
                h.send_header("X-Query-Id", qid)
                h.end_headers()
                h.wfile.write(body)
            else:
                body = _df_to_json_rows(df)
                # splice the id into the JSON envelope
                body = body[:-1] + b', "queryId": "%s"}' % qid.encode()
                h._send(200, body)
            return
        if url.path == "/query":
            req = self._read_json(h)
            from spark_druid_olap_tpu.ir.serde import query_from_dict
            from spark_druid_olap_tpu.wlm.lanes import AdmissionRejected
            q = query_from_dict(req)
            lane, tenant, prio = self._wlm_request(h, req.get("context")
                                                   or {})
            if lane or tenant or prio is not None:
                self.ctx.engine.wlm.push_request(lane, tenant, prio)
            try:
                r = self.ctx.execute(q)
            except AdmissionRejected as e:
                self._send_shed(h, e)
                return
            finally:
                if lane or tenant or prio is not None:
                    self.ctx.engine.wlm.pop_request()
            h._send(200, _df_to_json_rows(r.to_pandas()))
            return
        if url.path == "/sql/cancel":
            req = self._read_json(h)
            qid = req.get("queryId", "")
            ok = self.ctx.engine.cancel(qid)
            h._send(200, json.dumps({"cancelled": bool(ok)}).encode())
            return
        h._send(404, b'{"error": "not found"}')


def serve(ctx=None, host="0.0.0.0", port=8082, setup=None):
    """Blocking entry point (``python -m spark_druid_olap_tpu.server``)."""
    if ctx is None:
        import spark_druid_olap_tpu as sdot
        ctx = sdot.Context()
    if setup:
        setup(ctx)
    print(f"sdot SQL server listening on http://{host}:{port}")
    SqlServer(ctx, host, port).start(background=False)
