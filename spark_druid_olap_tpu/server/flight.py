"""Arrow Flight (SQL) facade — BI-tool wire compatibility.

The reference's L7 is a HiveServer2 thrift endpoint so JDBC/ODBC tools
connect out of the box (HiveThriftServer2.scala:55-79). The TPU build's
native seam is HTTP+Arrow (server/http.py); this module adds the
columnar wire protocol BI tools standardize on today: an Arrow Flight
server that understands BOTH

- plain-SQL flight descriptors/tickets (``descriptor.for_command(sql)``
  → ``do_get`` streams the result), the generic Flight convention, and
- the Flight SQL command envelope (``CommandStatementQuery`` /
  ``TicketStatementQuery`` wrapped in ``google.protobuf.Any``) that
  ADBC / JDBC-Flight-SQL drivers emit for statement execution.

The envelope is decoded with a ~40-line wire-format reader rather than
a protobuf dependency: both messages are a single length-delimited
string field (field 1 = query / statement_handle), and ``Any`` is
field 1 type_url + field 2 value.
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa

try:
    import pyarrow.flight as flight
    _FLIGHT_OK = True
except Exception:  # noqa: BLE001 — keep importable without flight
    flight = None
    _FLIGHT_OK = False


# -- minimal protobuf wire helpers -------------------------------------------

def _read_varint(buf: bytes, i: int):
    out = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) for a protobuf message;
    only varint(0) and length-delimited(2) appear in the Flight SQL
    envelope messages."""
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i: i + ln]
            i += ln
        elif wt == 5:
            v = buf[i: i + 4]
            i += 4
        elif wt == 1:
            v = buf[i: i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


def _emit_field(fno: int, value: bytes) -> bytes:
    out = bytearray()
    tag = (fno << 3) | 2
    while True:
        b = tag & 0x7F
        tag >>= 7
        out.append(b | (0x80 if tag else 0))
        if not tag:
            break
    ln = len(value)
    while True:
        b = ln & 0x7F
        ln >>= 7
        out.append(b | (0x80 if ln else 0))
        if not ln:
            break
    return bytes(out) + value


_SQL_TYPE_PREFIX = b"type.googleapis.com/arrow.flight.protocol.sql."


def decode_sql_command(cmd: bytes) -> Optional[str]:
    """SQL text from a Flight SQL ``Any``-wrapped command (or None when
    the bytes are not such an envelope — plain-SQL descriptors decode
    as raw UTF-8 by the caller)."""
    try:
        type_url = value = None
        for fno, wt, v in _fields(cmd):
            if fno == 1 and wt == 2:
                type_url = v
            elif fno == 2 and wt == 2:
                value = v
        if type_url is None or value is None \
                or not type_url.startswith(_SQL_TYPE_PREFIX):
            return None
        kind = type_url[len(_SQL_TYPE_PREFIX):].decode()
        if kind not in ("CommandStatementQuery", "TicketStatementQuery"):
            return None
        for fno, wt, v in _fields(value):
            if fno == 1 and wt == 2:
                return v.decode("utf-8")
        return ""
    except Exception:  # noqa: BLE001 — not an envelope
        return None


def encode_statement_query(sql: str) -> bytes:
    """The ``Any``-wrapped ``CommandStatementQuery`` a Flight SQL client
    would send (used by tests to prove wire-shape compatibility)."""
    inner = _emit_field(1, sql.encode("utf-8"))
    return _emit_field(1, _SQL_TYPE_PREFIX + b"CommandStatementQuery") \
        + _emit_field(2, inner)


# -- server -------------------------------------------------------------------

if _FLIGHT_OK:
    class SdotFlightServer(flight.FlightServerBase):
        """≈ the thriftserver wrapper: every statement runs through the
        full session path (planner, engine, history)."""

        def __init__(self, ctx, location: str = "grpc://0.0.0.0:8083"):
            super().__init__(location)
            # concurrent statements are safe on one Context: the session
            # layer keeps per-thread state (thread-local stats/temp
            # frames, double-checked compile locking — hammer-tested by
            # tests/test_server.py), so gRPC's thread pool needs no
            # serialization here
            self.ctx = ctx
            self.location = location

        # -- helpers ---------------------------------------------------------
        def _sql_of(self, raw: bytes) -> str:
            sql = decode_sql_command(raw)
            if sql is None:
                try:
                    sql = raw.decode("utf-8")
                except UnicodeDecodeError:
                    raise flight.FlightServerError(
                        "descriptor/ticket is neither a Flight SQL "
                        "command envelope nor UTF-8 SQL text")
            return sql

        def _execute(self, sql: str) -> pa.Table:
            from spark_druid_olap_tpu.wlm.lanes import AdmissionRejected
            try:
                df = self.ctx.sql(sql).to_pandas()
            except AdmissionRejected as e:
                # gRPC's RESOURCE_EXHAUSTED is the 429 analog; the retry
                # hint rides the message (Flight carries no headers here)
                raise flight.FlightServerError(
                    f"admission rejected (retry after "
                    f"{e.retry_after_s:.1f}s): {e}") from e
            return pa.Table.from_pandas(df, preserve_index=False)

        # -- Flight handlers -------------------------------------------------
        def get_flight_info(self, context, descriptor):
            # executing here just for the schema would double-run big
            # results: return an empty-schema info whose ticket echoes
            # the command. EMPTY locations = "fetch from the service you
            # contacted" (the Flight convention — advertising the bind
            # address would hand clients an unroutable 0.0.0.0)
            ticket = flight.Ticket(descriptor.command)
            endpoint = flight.FlightEndpoint(ticket, [])
            return flight.FlightInfo(pa.schema([]), descriptor,
                                     [endpoint], -1, -1)

        def do_get(self, context, ticket):
            sql = self._sql_of(ticket.ticket)
            table = self._execute(sql)
            return flight.RecordBatchStream(table)

        def do_action(self, context, action):
            if action.type == "healthcheck":
                yield flight.Result(b"ok")
            else:
                raise KeyError(f"unknown action {action.type!r}")
else:                                       # pragma: no cover
    SdotFlightServer = None


def serve_flight(ctx, host: str = "0.0.0.0", port: int = 8083):
    """Blocking entry point
    (``python -m spark_druid_olap_tpu.server.flight``)."""
    if not _FLIGHT_OK:
        raise RuntimeError("pyarrow.flight is not available")
    server = SdotFlightServer(ctx, f"grpc://{host}:{port}")
    print(f"sdot Arrow Flight SQL endpoint on grpc://{host}:{port}")
    server.serve()


if __name__ == "__main__":               # pragma: no cover
    import spark_druid_olap_tpu as sdot
    serve_flight(sdot.Context())
