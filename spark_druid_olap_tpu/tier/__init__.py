"""Out-of-core tiered storage: persist/ snapshots as a first-class cold
tier behind a byte-budgeted hot set (see docs/TIERING.md)."""
