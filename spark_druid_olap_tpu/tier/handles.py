"""Loadable column handles: tiered columns behind the Datasource API.

A tiered datasource looks exactly like an in-memory one — same
``Datasource`` surface, same column classes — but its arrays live in the
cold tier (persist/ snapshot blobs) as per-segment :class:`BlobRef`
ranges. Two access paths share the same hot-set chunks:

- the **device bind path**: ``ops/scan.py:build_array`` asks
  ``_tier_build`` first, which faults ONLY the segments of the wave
  being bound straight into the stacked ``[n, padded_rows]`` layout —
  this is what keeps a budget-exceeding scan's working set O(wave);
- the **host path**: ``codes`` / ``values`` / ``days`` / … are
  properties that fault every segment's chunk and return a transient
  concatenation, so host-tier fallback, rollup builds, and metadata
  endpoints keep working unchanged (at full-column cost — the
  documented trade, see docs/TIERING.md).

The classes subclass the dataclass columns with custom ``__init__``
(properties are data descriptors, so the array fields cannot be plain
attributes); ``dataclasses.replace`` therefore does NOT work on them —
tiered datasources are sliced with ``tier/loader.py:slice_tiered`` and
materialized with ``materialize()`` where an eager copy is required
(WAL-tail append).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.segment.column import (
    ColumnKind, DimColumn, MetricColumn, TimeColumn)
from spark_druid_olap_tpu.segment.store import Datasource
from spark_druid_olap_tpu.tier.store import BlobRef, TieredColumnStore

NULLS_PREFIX = "__nulls__"
TIME_MS_KEY = "__time_ms__"


@dataclasses.dataclass(frozen=True)
class RefArray:
    """One logical 1-D column array as per-segment blob element ranges
    (refs[i] covers segment i's rows; len(refs) == num_segments)."""

    refs: Tuple[BlobRef, ...]
    dtype: str

    @property
    def nbytes(self) -> int:
        return sum(r.nbytes for r in self.refs)

    def materialize(self, tier: TieredColumnStore, ns: str,
                    column: str) -> np.ndarray:
        parts = [tier.fault(ns, column, r) for r in self.refs if r.count]
        if not parts:
            return np.empty(0, dtype=np.dtype(self.dtype))
        if len(parts) == 1:
            return parts[0]
        return np.concatenate(parts)


class TieredDimColumn(DimColumn):
    """codes/validity fault through the hot set on access."""

    def __init__(self, name, dictionary, tier, ns,
                 codes_ra: RefArray, valid_ra: Optional[RefArray]):
        self.name = name
        self.dictionary = dictionary
        self.kind = ColumnKind.DIM
        self._tier = tier
        self._ns = ns
        self._codes_ra = codes_ra
        self._valid_ra = valid_ra

    @property
    def codes(self):
        return self._codes_ra.materialize(self._tier, self._ns, self.name)

    @property
    def validity(self):
        if self._valid_ra is None:
            return None
        return self._valid_ra.materialize(self._tier, self._ns, self.name)

    def data_dtype(self):
        return np.dtype(self._codes_ra.dtype)

    def has_nulls(self) -> bool:
        return self._valid_ra is not None

    def data_nbytes(self) -> int:
        return self._codes_ra.nbytes

    def footprint_nbytes(self) -> int:
        v = self._valid_ra.nbytes if self._valid_ra is not None else 0
        return self._codes_ra.nbytes + v

    def materialize(self) -> DimColumn:
        return DimColumn(name=self.name, dictionary=self.dictionary,
                         codes=np.array(self.codes),
                         validity=None if self._valid_ra is None
                         else np.array(self.validity))


class TieredMetricColumn(MetricColumn):
    def __init__(self, name, kind, tier, ns,
                 values_ra: RefArray, valid_ra: Optional[RefArray]):
        self.name = name
        self.kind = kind
        self._tier = tier
        self._ns = ns
        self._values_ra = values_ra
        self._valid_ra = valid_ra

    @property
    def values(self):
        return self._values_ra.materialize(self._tier, self._ns, self.name)

    @property
    def validity(self):
        if self._valid_ra is None:
            return None
        return self._valid_ra.materialize(self._tier, self._ns, self.name)

    def data_dtype(self):
        return np.dtype(self._values_ra.dtype)

    def has_nulls(self) -> bool:
        return self._valid_ra is not None

    def data_nbytes(self) -> int:
        return self._values_ra.nbytes

    def footprint_nbytes(self) -> int:
        v = self._valid_ra.nbytes if self._valid_ra is not None else 0
        return self._values_ra.nbytes + v

    def materialize(self) -> MetricColumn:
        m = MetricColumn(name=self.name, values=np.array(self.values),
                         validity=None if self._valid_ra is None
                         else np.array(self.validity), kind=self.kind)
        b = getattr(self, "_bounds_cache", None)
        if b is not None:
            m._bounds_cache = b
        return m


class TieredTimeColumn(TimeColumn):
    def __init__(self, name, tier, ns,
                 days_ra: RefArray, ms_ra: RefArray):
        self.name = name
        self.kind = ColumnKind.TIME
        self._tier = tier
        self._ns = ns
        self._days_ra = days_ra
        self._ms_ra = ms_ra

    @property
    def days(self):
        return self._days_ra.materialize(self._tier, self._ns, self.name)

    @property
    def ms_in_day(self):
        return self._ms_ra.materialize(self._tier, self._ns, self.name)

    def data_dtype(self):
        return np.dtype(self._days_ra.dtype)

    def ms_dtype(self):
        return np.dtype(self._ms_ra.dtype)

    def has_nulls(self) -> bool:
        return False

    def data_nbytes(self) -> int:
        return self._days_ra.nbytes

    def footprint_nbytes(self) -> int:
        return self._days_ra.nbytes + self._ms_ra.nbytes

    def materialize(self) -> TimeColumn:
        return TimeColumn(name=self.name, days=np.array(self.days),
                          ms_in_day=np.array(self.ms_in_day))


class TieredDatasource(Datasource):
    """A complete datasource whose column bytes live in the cold tier.

    ``_tier_refs`` maps every scan array key (ops/scan.py) to
    ``(column_name, RefArray)``; ``build_array`` consults ``_tier_build``
    before any stacked-cache path, and the wave loop's prefetch hook
    calls ``tier_prefetch``. The chunk namespace is this datasource's
    registered name, so a store drop/clear releases exactly its hot
    entries (PersistManager wires the listener)."""

    def __init__(self, *args, tier: TieredColumnStore, **kwargs):
        super().__init__(*args, **kwargs)
        self.tier = tier
        self._tier_refs: Dict[str, Tuple[str, RefArray]] = {}

    def _index_refs(self) -> None:
        """Populate the scan-key map from the (already-set) tiered
        columns. Called once by the loader after construction."""
        refs = self._tier_refs
        refs.clear()
        for k, d in self.dims.items():
            refs[k] = (k, d._codes_ra)
            if d._valid_ra is not None:
                refs[NULLS_PREFIX + k] = (k, d._valid_ra)
        for k, m in self.metrics.items():
            refs[k] = (k, m._values_ra)
            if m._valid_ra is not None:
                refs[NULLS_PREFIX + k] = (k, m._valid_ra)
        if self.time is not None:
            refs[self.time.name] = (self.time.name, self.time._days_ra)
            refs[TIME_MS_KEY] = (self.time.name, self.time._ms_ra)

    # -- scan integration -----------------------------------------------------
    def _tier_build(self, key: str, segment_indices,
                    pad_segments_to) -> Optional[np.ndarray]:
        """Stacked [n, padded_rows] block for a scan key, faulting only
        the requested segments. None -> caller falls back to the base
        path (metadata-only keys like row validity)."""
        ent = self._tier_refs.get(key)
        if ent is None:
            return None
        column, ra = ent
        if segment_indices is None:
            idx = list(range(self.num_segments))
        else:
            idx = [int(i) for i in segment_indices]
        n = len(idx)
        if pad_segments_to:
            n = max(n, int(pad_segments_to))
        out = np.zeros((n, self.padded_rows), dtype=np.dtype(ra.dtype))
        for row, si in enumerate(idx):
            r = ra.refs[si]
            if r.count:
                out[row, : r.count] = self.tier.fault(self.name, column, r)
        return out

    def tier_prefetch(self, names, segment_indices) -> None:
        """Enqueue the chunks a future wave will bind (best-effort)."""
        work: List[Tuple[str, BlobRef]] = []
        for key in names:
            ent = self._tier_refs.get(key)
            if ent is None:
                continue
            column, ra = ent
            for si in segment_indices:
                r = ra.refs[int(si)]
                if r.count:
                    work.append((column, r))
        if work:
            self.tier.prefetch(self.name, work)

    # -- planning metadata without whole-column faults ------------------------
    def segment_metric_bounds(self, name: str):
        """Zone maps computed one segment chunk at a time (the base impl
        reads the whole column, which on a tiered store would fault every
        segment at once and blow straight through the budget)."""
        hit = self._bounds_cache.get(name)
        if hit is not None:
            return hit
        ent = self._tier_refs.get(name)
        if ent is None or name not in self.metrics:
            return super().segment_metric_bounds(name)
        column, ra = ent
        vent = self._tier_refs.get(NULLS_PREFIX + name)
        if vent is None:
            # encoded columns carry per-chunk (vmin, vmax) in the codec
            # headers: zone maps come straight off the refs with ZERO
            # faults. Only valid without a null mask — headers bound
            # every stored value, including rows a validity mask voids.
            from spark_druid_olap_tpu.encode import exec as EX
            hb = EX.segment_bounds_from_refs(ra.refs)
            if hb is not None:
                self._bounds_cache[name] = hb
                return hb
        mins = np.full(self.num_segments, np.inf)
        maxs = np.full(self.num_segments, -np.inf)
        for i in range(self.num_segments):
            r = ra.refs[i]
            if not r.count:
                continue
            v = self.tier.fault(self.name, column, r).astype(
                np.float64, copy=False)
            if vent is not None:
                valid = self.tier.fault(self.name, column, vent[1].refs[i])
                v = v[valid]
            v = v[~np.isnan(v)]
            if len(v):
                mins[i] = v.min()
                maxs[i] = v.max()
        self._bounds_cache[name] = (mins, maxs)
        return mins, maxs

    # -- encoded-store metadata ----------------------------------------------
    def host_bytes_per_segment(self, names=None) -> int:
        """Max over segments of the summed HOT-SET bytes the given scan
        keys fault for one segment — compressed bytes for encoded refs,
        logical bytes for raw ones. The wave planner divides its io
        budget by THIS instead of the logical segment size, so a
        compressed store admits ratio× more segments per wave under the
        same ``sdot.tier.wave.io.bytes``."""
        keys = list(self._tier_refs) if names is None else \
            [k for k in names if k in self._tier_refs]
        best = 0
        for i in range(self.num_segments):
            tot = 0
            for k in keys:
                tot += self._tier_refs[k][1].refs[i].nbytes
            best = max(best, tot)
        return best

    def encoding_info(self) -> dict:
        """Residency economics of this datasource's encoded refs (the
        source of the executor's ``last_stats["encoding"]``)."""
        enc_bytes = dec_bytes = 0
        cols = set()
        for key, (_, ra) in self._tier_refs.items():
            for r in ra.refs:
                if r.enc is not None:
                    enc_bytes += r.nbytes
                    dec_bytes += r.decoded_nbytes
                    cols.add(key)
        return {
            "encoded_keys": len(cols),
            "encoded_bytes": int(enc_bytes),
            "decoded_bytes": int(dec_bytes),
            "ratio": round(dec_bytes / enc_bytes, 3) if enc_bytes else 1.0,
        }

    # -- escape hatch ---------------------------------------------------------
    def materialize(self) -> Datasource:
        """Eager in-memory copy (plain column classes) — the escape
        hatch for paths that mutate/extend columns (WAL-tail append via
        ``dataclasses.replace``)."""
        time = self.time.materialize() if self.time is not None else None
        dims = {k: d.materialize() for k, d in self.dims.items()}
        mets = {k: m.materialize() for k, m in self.metrics.items()}
        return Datasource(name=self.name, time=time, dims=dims,
                          metrics=mets, segments=list(self.segments),
                          spatial=dict(self.spatial))
