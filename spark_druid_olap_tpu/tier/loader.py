"""Build tiered datasources from persist/ snapshots, and slice them.

``load_tiered_snapshot`` is the cold-tier counterpart of
``persist/snapshot.py:load_snapshot``: instead of reading every blob
into memory it performs O(manifest) structural verification (file
present, size matches the manifest, size matches dtype x shape) and
hands back a :class:`TieredDatasource` whose per-segment
:class:`BlobRef` ranges fault on demand. Blob CRC verification moves to
first-fault time (``TieredColumnStore._verify_blob``) — the same
quarantine-on-mismatch semantics, paid only for blobs a query actually
touches. Dictionaries are small JSON and load (and CRC-verify) eagerly:
planning binary-searches them constantly.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.persist import snapshot as SNAP
from spark_druid_olap_tpu.persist.snapshot import SnapshotCorrupt
from spark_druid_olap_tpu.segment.column import ColumnKind
from spark_druid_olap_tpu.segment.store import Segment
from spark_druid_olap_tpu.tier.handles import (
    RefArray, TieredDatasource, TieredDimColumn, TieredMetricColumn,
    TieredTimeColumn)
from spark_druid_olap_tpu.tier.store import BlobRef, TieredColumnStore


def _ref_array(vdir: str, rel: str, files: dict,
               bounds: List[Tuple[int, int]]) -> RefArray:
    """Per-segment BlobRefs over one column blob, structurally verified
    against the manifest (content CRC stays lazy)."""
    meta = files.get(rel)
    if meta is None:
        raise SnapshotCorrupt(f"blob {rel} not in manifest")
    path = os.path.join(vdir, rel)
    try:
        size = os.path.getsize(path)
    except OSError as e:
        raise SnapshotCorrupt(f"missing blob {rel}: {e}") from e
    if size != int(meta["bytes"]):
        raise SnapshotCorrupt(
            f"blob {rel}: {size} bytes on disk, manifest says "
            f"{meta['bytes']}")
    dtype = np.dtype(meta["dtype"])
    total = bounds[-1][1] if bounds else 0
    enc = meta.get("enc")
    if enc is not None:
        # encoded blob: the file holds concatenated per-segment
        # compressed chunks; verify the chunk table covers the file and
        # the segment map exactly, then hand out byte-range refs. The
        # codec header rides each ref as a JSON string so fault-time
        # decode and header-level zone maps never reopen the manifest.
        segs = enc.get("segments", [])
        if len(segs) != len(bounds):
            raise SnapshotCorrupt(
                f"blob {rel}: {len(segs)} encoded chunks, segment map "
                f"says {len(bounds)}")
        rows = sum(int(h["n"]) for _, _, h in segs)
        if rows != total:
            raise SnapshotCorrupt(
                f"blob {rel}: encoded chunks hold {rows} rows, segment "
                f"map says {total}")
        span = (int(segs[-1][0]) + int(segs[-1][1])) if segs else 0
        if span != size:
            raise SnapshotCorrupt(
                f"blob {rel}: chunk table spans {span} bytes, file has "
                f"{size}")
        refs = []
        for (s, e), (off, length, header) in zip(bounds, segs):
            if int(header["n"]) != e - s:
                raise SnapshotCorrupt(
                    f"blob {rel}: chunk at {off} holds {header['n']} "
                    f"rows, segment [{s}, {e}) wants {e - s}")
            refs.append(BlobRef(
                path=path, dtype=dtype.str, start=int(s),
                count=int(e - s), crc=int(meta["crc"]),
                file_bytes=int(meta["bytes"]),
                enc=json.dumps(header, sort_keys=True),
                byte_start=int(off), byte_len=int(length)))
        return RefArray(refs=tuple(refs), dtype=dtype.str)
    shape = meta.get("shape", None)
    n = int(np.prod(shape, dtype=np.int64)) if shape is not None \
        else size // dtype.itemsize
    if n * dtype.itemsize != size:
        raise SnapshotCorrupt(
            f"blob {rel}: {size} bytes is not {n} x {dtype}")
    if n != total:
        raise SnapshotCorrupt(
            f"blob {rel}: {n} elements, segment map says {total}")
    refs = tuple(
        BlobRef(path=path, dtype=dtype.str, start=int(s),
                count=int(e - s), crc=int(meta["crc"]),
                file_bytes=int(meta["bytes"]))
        for s, e in bounds)
    return RefArray(refs=refs, dtype=dtype.str)


def load_tiered_snapshot(ds_root: str, version: int,
                         tier: TieredColumnStore,
                         verify: bool = True):
    """(TieredDatasource, manifest, structural_verify_ms). Raises
    :class:`SnapshotCorrupt` on any structural failure (blob CRC
    failures surface later, on first fault)."""
    t0 = time.perf_counter()
    try:
        manifest = SNAP.load_manifest(ds_root, version)
    except (OSError, ValueError) as e:
        raise SnapshotCorrupt(f"unreadable manifest: {e}") from e
    if int(manifest.get("format", -1)) != SNAP.FORMAT_VERSION:
        raise SnapshotCorrupt(
            f"unknown snapshot format {manifest.get('format')!r}")
    vdir = os.path.join(ds_root, SNAP.version_dirname(version))
    files = manifest.get("files", {})
    segments = [Segment(id=s[0], start_row=int(s[1]), end_row=int(s[2]),
                        min_millis=int(s[3]), max_millis=int(s[4]))
                for s in manifest["segments"]]
    bounds = [(s.start_row, s.end_row) for s in segments]
    total = bounds[-1][1] if bounds else 0
    if total != int(manifest["num_rows"]):
        raise SnapshotCorrupt(
            f"segment map rows {total} != manifest num_rows "
            f"{manifest['num_rows']}")
    name = manifest["datasource"]

    time_col = None
    if manifest["time"] is not None:
        t = manifest["time"]
        time_col = TieredTimeColumn(
            name=t["name"], tier=tier, ns=name,
            days_ra=_ref_array(vdir, t["days"], files, bounds),
            ms_ra=_ref_array(vdir, t["ms"], files, bounds))
    dims = {}
    for e in manifest["dims"]:
        dict_raw = SNAP._read_blob(vdir, e["dictionary"], files, verify)
        try:
            dictionary = np.asarray(json.loads(dict_raw.decode()),
                                    dtype=object)
        except ValueError as ex:
            raise SnapshotCorrupt(
                f"dictionary {e['dictionary']}: {ex}") from ex
        dims[e["name"]] = TieredDimColumn(
            name=e["name"], dictionary=dictionary, tier=tier, ns=name,
            codes_ra=_ref_array(vdir, e["codes"], files, bounds),
            valid_ra=None if e["validity"] is None
            else _ref_array(vdir, e["validity"], files, bounds))
    metrics = {}
    for e in manifest["metrics"]:
        m = TieredMetricColumn(
            name=e["name"], kind=ColumnKind(e["kind"]), tier=tier,
            ns=name,
            values_ra=_ref_array(vdir, e["values"], files, bounds),
            valid_ra=None if e["validity"] is None
            else _ref_array(vdir, e["validity"], files, bounds))
        # manifest-published global bounds (snapshots written before the
        # field existed fall back to a one-time whole-column fault)
        if e.get("min") is not None:
            m._bounds_cache = (np.dtype(m.data_dtype()).type(e["min"]),
                               np.dtype(m.data_dtype()).type(e["max"]))
        metrics[e["name"]] = m
    ds = TieredDatasource(
        name, time_col, dims, metrics, segments,
        spatial={k: tuple(v) for k, v in manifest["spatial"].items()},
        tier=tier)
    ds._index_refs()
    # per-segment zone maps from the manifest (``seg_bounds``, written
    # alongside the global min/max): with these injected, broker and
    # planner pruning over a freshly recovered tiered store never
    # decodes a chunk or faults a cold blob just to bound a segment.
    # None entries are all-null segments -> (inf, -inf), prune-nothing.
    for e in manifest["metrics"]:
        sb = e.get("seg_bounds")
        if sb is not None and len(sb) == len(segments):
            mins = np.array([np.inf if b is None else float(b[0])
                             for b in sb])
            maxs = np.array([-np.inf if b is None else float(b[1])
                             for b in sb])
            ds._bounds_cache[e["name"]] = (mins, maxs)
    return ds, manifest, (time.perf_counter() - t0) * 1000.0


def slice_tiered(ds: TieredDatasource, segment_indexes,
                 name: Optional[str] = None) -> TieredDatasource:
    """Tiered counterpart of ``segment/store.py:slice_segments``: a
    complete tiered datasource over only the given segments, SHARING the
    parent's blob files (the refs simply select the member segments'
    element ranges — no bytes move). Used by cluster historicals so an
    owned-shard boot stays O(manifest): the shard's data loads on first
    query, within this node's budget."""
    ids = sorted(int(i) for i in segment_indexes)

    def _sel(ra: Optional[RefArray]) -> Optional[RefArray]:
        if ra is None:
            return None
        return RefArray(refs=tuple(ra.refs[i] for i in ids),
                        dtype=ra.dtype)

    new_name = name or ds.name
    time_col = None
    if ds.time is not None:
        time_col = TieredTimeColumn(
            name=ds.time.name, tier=ds.tier, ns=new_name,
            days_ra=_sel(ds.time._days_ra), ms_ra=_sel(ds.time._ms_ra))
    dims = {}
    for k, d in ds.dims.items():
        dims[k] = TieredDimColumn(
            name=k, dictionary=d.dictionary, tier=ds.tier, ns=new_name,
            codes_ra=_sel(d._codes_ra), valid_ra=_sel(d._valid_ra))
    mets = {}
    for k, m in ds.metrics.items():
        mm = TieredMetricColumn(
            name=k, kind=m.kind, tier=ds.tier, ns=new_name,
            values_ra=_sel(m._values_ra), valid_ra=_sel(m._valid_ra))
        # parent (global) bounds carry over: min/max feed cost-model
        # selectivity only — exact pruning uses per-segment zone maps,
        # which recompute on the shard's own chunks
        b = getattr(m, "_bounds_cache", None)
        if b is not None:
            mm._bounds_cache = b
        mets[k] = mm
    segs, row = [], 0
    for i in ids:
        s = ds.segments[i]
        n = s.end_row - s.start_row
        segs.append(Segment(s.id, row, row + n, s.min_millis,
                            s.max_millis))
        row += n
    out = TieredDatasource(new_name, time_col, dims, mets, segs,
                           spatial=dict(ds.spatial), tier=ds.tier)
    out._index_refs()
    return out
