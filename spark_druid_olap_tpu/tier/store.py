"""Byte-budgeted hot set over memory-mapped snapshot blobs.

The cold tier IS deep storage (persist/ snapshot blobs): one
:class:`TieredColumnStore` per PersistManager demand-loads per-segment
column chunks through ``np.memmap`` into an explicit hot set bounded by
``sdot.tier.budget.bytes``. The design follows the reference's
historical tier (deep storage holds every segment; a node memory-maps
only what it serves), Sparkle's explicit memory-hierarchy management
(arxiv 1708.05746), and Theseus's overlap of data movement with compute
(arxiv 2508.05029).

Mechanics:

- **Fault unit** is one segment's rows of one column array (a
  :class:`BlobRef` element range into a blob file). The double-buffered
  wave loop faults exactly the segments it binds, so the working set of
  a budget-exceeding scan is O(wave), not O(column).
- **CRC verification is lazy**: a blob file is checksummed ONCE, on the
  first fault that touches it (``sdot.tier.verify.checksums``) — boot
  stays O(manifest), corruption still can't serve silently. A mismatch
  invokes the corruption callback (PersistManager quarantines the
  version and re-recovers per PERSIST semantics) and raises
  ``SnapshotCorrupt`` into the faulting query.
- **Eviction** is by query-history popularity (the same signal that
  drives recovery warmup order, metadata/history.py) with recency as
  the tiebreak; entries pinned by in-flight queries are never evicted,
  so peak residency is budget + pinned bytes, never a dangling array.
- **Pin protocol**: ``acquire_pins()`` pushes a token onto a
  thread-local stack; every fault on that thread registers its chunk
  into the open tokens; ``release_pins(token)`` drops the refcounts.
  The engine wraps query execution in acquire/release (sdlint's leaks
  pass checks the pair on all exits).
- **Prefetcher**: daemon threads drain a queue of (column, ref) work;
  the wave loop enqueues wave i+2's chunks while wave i computes on
  device, so cold loads hide behind dispatch. Prefetched entries are
  flagged; a later demand fault that lands on one counts as prefetch
  overlap (``prefetch_hit_bytes``).
- **Decode-ahead** (``sdot.tier.decoded.cache.bytes`` > 0): the
  prefetch worker also DECODES encoded chunks into a separate
  LRU cache accounted at decoded size, so a hot repeated scan stops
  paying the per-serve decode on the demand path (the saving lands in
  ``decode_ms_saved``). Decoded copies are derived data: they evict
  before any encoded payload — their own LRU bounds steady state, and
  encoded-budget pressure flushes them entirely before the eviction
  loop touches a single compressed payload. A served decoded array
  stays alive with its query via numpy refcounting, so mid-query
  eviction is safe without pin integration.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
import zlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.persist.snapshot import SnapshotCorrupt
from spark_druid_olap_tpu.utils import phases as PH


@dataclasses.dataclass(frozen=True)
class BlobRef:
    """One element range of a snapshot blob file (a 1-D column array):
    the unit the hot set faults, pins, and evicts.

    An ENCODED ref (``enc`` set) additionally carries the byte range of
    its compressed chunk and the chunk's codec header as a JSON string
    (strings keep the dataclass hashable). The hot set then holds the
    compressed payload and ``nbytes`` is the COMPRESSED size — the same
    byte budget keeps ratio× more segments resident — while ``dtype``/
    ``count`` still describe the logical rows a fault decodes to."""

    path: str          # absolute blob file path (inside a version dir)
    dtype: str         # numpy dtype str (manifest "dtype")
    start: int         # element offset into the blob (logical rows)
    count: int         # element count (logical rows)
    crc: int           # whole-file CRC32 from the manifest
    file_bytes: int    # whole-file size from the manifest
    enc: Optional[str] = None   # JSON codec header (encode/codecs.py)
    byte_start: int = 0         # chunk byte offset (encoded refs)
    byte_len: int = -1          # chunk byte length (encoded refs)

    @property
    def itemsize(self) -> int:
        return int(np.dtype(self.dtype).itemsize)

    @property
    def nbytes(self) -> int:
        """Hot-set residency cost: compressed bytes for encoded refs,
        logical bytes for raw ones."""
        if self.enc is not None:
            return max(0, int(self.byte_len))
        return int(self.count) * self.itemsize

    @property
    def decoded_nbytes(self) -> int:
        """Logical (decoded) size — what a query actually scans."""
        return int(self.count) * self.itemsize

    def header(self) -> Optional[dict]:
        """Parsed codec header (None for raw refs)."""
        if self.enc is None:
            return None
        import json
        return json.loads(self.enc)


class _Entry:
    __slots__ = ("arr", "nbytes", "tick", "column", "prefetched")

    def __init__(self, arr, nbytes, tick, column, prefetched):
        self.arr = arr
        self.nbytes = nbytes
        self.tick = tick
        self.column = column
        self.prefetched = prefetched


class _DecEntry:
    """One decode-ahead chunk: the decoded ndarray, its DECODED size
    (what the cache budget charges), the measured decode cost a future
    demand fault is spared, and whether the prefetcher produced it."""

    __slots__ = ("arr", "nbytes", "decode_ms", "prefetched")

    def __init__(self, arr, nbytes, decode_ms, prefetched):
        self.arr = arr
        self.nbytes = nbytes
        self.decode_ms = decode_ms
        self.prefetched = prefetched


class PinToken:
    """Per-query pin set: chunk key -> refcount contributed.
    ``devices`` records how many mesh devices the pinned wave feeds
    (1 = single-device) — a mesh-parallel fault pins ``n_dev``x more
    segments per wave, and eviction pressure accounting wants to see
    that multiplier, not infer it."""

    __slots__ = ("keys", "devices")

    def __init__(self, devices: int = 1):
        self.keys: Dict[tuple, int] = {}
        self.devices = max(1, int(devices))


class TieredColumnStore:
    """The hot set. One instance per process (PersistManager-owned);
    shared by every tiered datasource it loaded, including cluster
    historicals' shard slices — the budget is per NODE, which is what
    makes N-node memory truly bounded."""

    def __init__(self, budget_bytes: int, verify: bool = True,
                 popularity: Optional[Callable[[str, str], float]] = None,
                 on_corrupt: Optional[Callable[[str, str, str], None]] = None,
                 decoded_budget: int = 0):
        self.budget = max(1, int(budget_bytes))
        self.dec_budget = max(0, int(decoded_budget))   # 0 = decode-ahead off
        self.verify = bool(verify)
        self.popularity = popularity
        self.on_corrupt = on_corrupt
        # fault injector (docs/CHAOS.md); named "chaos" because "fault"
        # is this store's demand-fault method
        self.chaos = None
        self._lock = threading.RLock()
        self._hot: Dict[tuple, _Entry] = {}
        self._pins: Dict[tuple, int] = {}
        self._mesh_pins: Dict[tuple, int] = {}   # pins from devices>1 scopes
        self._bytes = 0
        self._tick = 0
        self._verified = set()                 # blob paths CRC-checked OK
        self._loading: Dict[tuple, threading.Event] = {}
        self._tls = threading.local()
        self.counters = {
            "faults": 0, "hits": 0, "bytes_faulted": 0,
            "evictions": 0, "bytes_evicted": 0,
            "crc_verified_files": 0, "crc_failures": 0,
            "crc_verify_ms": 0.0,
            "pin_tokens": 0, "pin_tokens_mesh": 0,
            "prefetch_submitted": 0, "prefetch_loaded": 0,
            "prefetch_dropped": 0,
            "prefetch_hits": 0, "prefetch_hit_bytes": 0,
            "decode_ms_saved": 0.0, "decoded_evictions": 0,
        }
        self._dec: "OrderedDict[tuple, _DecEntry]" = OrderedDict()
        self._dec_bytes = 0
        self._pf_queue: Optional[queue.Queue] = None
        self._pf_threads: List[threading.Thread] = []
        self._pf_stop = threading.Event()

    # -- pins ------------------------------------------------------------------
    def _token_stack(self) -> list:
        s = getattr(self._tls, "tokens", None)
        if s is None:
            s = self._tls.tokens = []
        return s

    def acquire_pins(self, devices: int = 1) -> PinToken:
        """Open a pin scope on THIS thread: every chunk faulted until the
        matching release is held out of eviction's reach. ``devices`` > 1
        marks a mesh-parallel scope (parallel/meshexec.py): the wave
        being pinned spans the whole device mesh, so its chunks are
        additionally tracked in the mesh-pin gauge the stats surface
        reports (eviction itself treats every pin identically)."""
        tok = PinToken(devices)
        self._token_stack().append(tok)
        with self._lock:
            self.counters["pin_tokens"] += 1
            if tok.devices > 1:
                self.counters["pin_tokens_mesh"] += 1
        return tok

    def release_pins(self, tok: PinToken) -> None:
        s = getattr(self._tls, "tokens", None)
        if s is not None and tok in s:
            s.remove(tok)
        with self._lock:
            for k, n in tok.keys.items():
                r = self._pins.get(k, 0) - n
                if r <= 0:
                    self._pins.pop(k, None)
                else:
                    self._pins[k] = r
                if tok.devices > 1:
                    rm = self._mesh_pins.get(k, 0) - n
                    if rm <= 0:
                        self._mesh_pins.pop(k, None)
                    else:
                        self._mesh_pins[k] = rm
            tok.keys.clear()
            self._evict_locked()   # deferred evictions land here

    def _pin_into_active_locked(self, key: tuple) -> None:
        for tok in getattr(self._tls, "tokens", ()):
            tok.keys[key] = tok.keys.get(key, 0) + 1
            self._pins[key] = self._pins.get(key, 0) + 1
            if tok.devices > 1:
                self._mesh_pins[key] = self._mesh_pins.get(key, 0) + 1

    def pinned_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for k, e in self._hot.items()
                       if self._pins.get(k))

    # -- faulting --------------------------------------------------------------
    def fault(self, ds_name: str, column: str, ref: BlobRef,
              prefetch: bool = False) -> np.ndarray:
        """The chunk's hot ndarray, loading it from the cold tier if
        needed. Demand faults (prefetch=False) pin into the calling
        thread's open tokens and count hit/prefetch-overlap stats.

        Encoded refs are held hot in COMPRESSED form and decoded
        OUTSIDE the store lock — the decode is per-segment numpy work
        and must not serialize concurrent faulting threads. With
        decode-ahead ON (``dec_budget`` > 0) the prefetch path decodes
        into the decoded-chunk cache so a later demand fault skips the
        decode entirely (served at decoded size, ``decode_ms_saved``
        credited); with it off, prefetch serves only warm bytes and the
        demand fault pays the decode, as before."""
        if ref.enc is None:
            if prefetch:
                return self._fault_stored(ds_name, column, ref, True)
            t0 = time.perf_counter()
            arr = self._fault_stored(ds_name, column, ref, False)
            PH.add("tier.fault", time.perf_counter() - t0)
            return arr
        key = (ds_name, ref.path, int(ref.start), int(ref.count))
        if prefetch:
            stored = self._fault_stored(ds_name, column, ref, True)
            if self.dec_budget > 0:
                self._decode_ahead(key, stored, ref)
            return stored
        if self.dec_budget > 0:
            hit = self._serve_decoded(key)
            if hit is not None:
                return hit
        t0 = time.perf_counter()
        stored = self._fault_stored(ds_name, column, ref, False)
        PH.add("tier.fault", time.perf_counter() - t0)
        from spark_druid_olap_tpu.encode import codecs as EN
        t0 = time.perf_counter()
        arr = EN.decode_array(stored, ref.header())
        dms = (time.perf_counter() - t0) * 1000.0
        PH.add("tier.decode", dms / 1000.0)
        if self.dec_budget > 0:
            # demand-decoded chunks are cache-worthy too: the NEXT
            # repeat of this scan serves decoded even when the
            # prefetcher never saw the chunk (single-wave scans)
            self._dec_install(key, arr, ref, dms, prefetched=False)
        return arr

    def _serve_decoded(self, key: tuple) -> Optional[np.ndarray]:
        """Demand serve from the decode-ahead cache. Counts the serve
        as a hot hit, credits the spared decode, and — when the chunk
        was prefetcher-produced — counts prefetch overlap at DECODED
        size (that is what the demand path was spared end to end)."""
        with self._lock:
            d = self._dec.get(key)
            if d is None:
                return None
            self._dec.move_to_end(key)
            self._tick += 1
            self.counters["hits"] += 1
            self.counters["decode_ms_saved"] += d.decode_ms
            if d.prefetched:
                d.prefetched = False
                self.counters["prefetch_hits"] += 1
                self.counters["prefetch_hit_bytes"] += d.nbytes
                e = self._hot.get(key)
                if e is not None:
                    # the compressed twin was never demand-served; it
                    # must not claim the same overlap again later
                    e.prefetched = False
            self._pin_into_active_locked(key)
            return d.arr

    def _decode_ahead(self, key: tuple, stored: np.ndarray,
                      ref: BlobRef) -> None:
        """Prefetch-worker decode, outside the lock; first-wins."""
        with self._lock:
            if key in self._dec:
                return
        from spark_druid_olap_tpu.encode import codecs as EN
        t0 = time.perf_counter()
        try:
            arr = EN.decode_array(stored, ref.header())
        except Exception:  # noqa: BLE001 — advisory; demand decode re-raises
            return
        dms = (time.perf_counter() - t0) * 1000.0
        self._dec_install(key, arr, ref, dms, prefetched=True)

    def _dec_install(self, key: tuple, arr: np.ndarray, ref: BlobRef,
                     decode_ms: float, prefetched: bool) -> None:
        nb = int(ref.decoded_nbytes)
        if nb > self.dec_budget:
            return   # a chunk larger than the whole budget never admits
        with self._lock:
            if key in self._dec:
                return
            self._dec[key] = _DecEntry(arr, nb, decode_ms, prefetched)
            self._dec_bytes += nb
            while self._dec_bytes > self.dec_budget and self._dec:
                _, old = self._dec.popitem(last=False)
                self._dec_bytes -= old.nbytes
                self.counters["decoded_evictions"] += 1

    def _fault_stored(self, ds_name: str, column: str, ref: BlobRef,
                      prefetch: bool) -> np.ndarray:
        key = (ds_name, ref.path, int(ref.start), int(ref.count))
        with self._lock:
            e = self._hot.get(key)
            if e is not None:
                return self._serve_locked(key, e, prefetch)
            ev = self._loading.get(key)
            if ev is None:
                ev = self._loading[key] = threading.Event()
                loader = True
            else:
                loader = False
        if not loader:
            # another thread (usually the prefetcher) is mid-load: wait
            # for it rather than reading the same bytes twice
            ev.wait(timeout=120.0)
            with self._lock:
                e = self._hot.get(key)
                if e is not None:
                    return self._serve_locked(key, e, prefetch)
                # loader failed or the entry was already evicted: take
                # over the load ourselves
                self._loading.setdefault(key, threading.Event())
        try:
            arr = self._load_cold(ds_name, ref)
        finally:
            with self._lock:
                done = self._loading.pop(key, None)
            if done is not None:
                done.set()
        with self._lock:
            e = self._hot.get(key)
            if e is None:
                self._tick += 1
                e = self._hot[key] = _Entry(arr, ref.nbytes, self._tick,
                                            column, prefetch)
                self._bytes += ref.nbytes
                self.counters["faults"] += 1
                self.counters["bytes_faulted"] += ref.nbytes
                if prefetch:
                    self.counters["prefetch_loaded"] += 1
                if not prefetch:
                    self._pin_into_active_locked(key)
                self._evict_locked()
                return e.arr
            return self._serve_locked(key, e, prefetch)

    def _serve_locked(self, key: tuple, e: _Entry,
                      prefetch: bool) -> np.ndarray:
        self._tick += 1
        e.tick = self._tick
        if not prefetch:
            self.counters["hits"] += 1
            if e.prefetched:
                e.prefetched = False
                self.counters["prefetch_hits"] += 1
                self.counters["prefetch_hit_bytes"] += e.nbytes
            self._pin_into_active_locked(key)
        return e.arr

    def _load_cold(self, ds_name: str, ref: BlobRef) -> np.ndarray:
        inj = self.chaos
        if inj is not None:
            # chaos site: delay = slow cold read, error = mmap I/O error
            inj.fire("tier.read", key=ref.path)
        self._verify_blob(ds_name, ref)
        if ref.enc is not None:
            # encoded chunk: the stored hot entry IS the compressed
            # payload (uint8); decode happens on serve, in fault()
            n = max(0, int(ref.byte_len))
            with open(ref.path, "rb") as f:
                f.seek(int(ref.byte_start))
                data = f.read(n)
            if len(data) != n:
                raise SnapshotCorrupt(
                    f"cold blob {os.path.basename(ref.path)}: short read "
                    f"({len(data)} of {n} bytes at {ref.byte_start})")
            return np.frombuffer(data, dtype=np.uint8)
        if ref.count == 0:
            return np.empty(0, dtype=np.dtype(ref.dtype))
        mm = np.memmap(ref.path, dtype=np.dtype(ref.dtype), mode="r",
                       offset=int(ref.start) * ref.itemsize,
                       shape=(int(ref.count),))
        try:
            # materialize the hot copy (writable; memmap pages release)
            return np.array(mm)
        finally:
            del mm

    def _verify_blob(self, ds_name: str, ref: BlobRef) -> None:
        """Whole-file CRC on the FIRST fault touching a blob — the lazy
        half of PERSIST's recovery-time verification."""
        if not self.verify:
            return
        with self._lock:
            if ref.path in self._verified:
                return
        t0 = time.perf_counter()
        try:
            with open(ref.path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise SnapshotCorrupt(f"missing blob {ref.path}: {e}") from e
        inj = self.chaos
        if inj is not None:
            # chaos site: a flip rule simulates cold-tier bit rot — the
            # CRC below catches it and triggers quarantine/re-recovery
            data = inj.mutate("tier.verify", data, key=ref.path)
        ok = len(data) == int(ref.file_bytes) \
            and zlib.crc32(data) == int(ref.crc)
        ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self.counters["crc_verify_ms"] += ms
            if ok:
                self._verified.add(ref.path)
                self.counters["crc_verified_files"] += 1
            else:
                self.counters["crc_failures"] += 1
        if ok:
            return
        reason = (f"cold blob {os.path.basename(ref.path)}: "
                  f"{len(data)} bytes crc {zlib.crc32(data)}, manifest "
                  f"says {ref.file_bytes} bytes crc {ref.crc}")
        cb = self.on_corrupt
        if cb is not None:
            # PersistManager: quarantine the version, re-recover this
            # datasource from an older snapshot + WAL tail
            cb(ds_name, os.path.dirname(ref.path), reason)
        raise SnapshotCorrupt(reason)

    # -- eviction --------------------------------------------------------------
    def _score(self, e: _Entry, ds_name: str) -> float:
        pop = self.popularity
        if pop is None:
            return 0.0
        try:
            return float(pop(ds_name, e.column))
        except Exception:  # noqa: BLE001 — scoring never breaks a fault
            return 0.0

    def _evict_locked(self) -> None:
        if self._bytes <= self.budget:
            return
        # decoded copies are DERIVED data (recreatable from the encoded
        # payloads below): under encoded-budget pressure they all go
        # before a single compressed payload is touched
        while self._dec:
            _, old = self._dec.popitem(last=False)
            self._dec_bytes -= old.nbytes
            self.counters["decoded_evictions"] += 1
        cand = [(self._score(e, k[0]), e.tick, k)
                for k, e in self._hot.items() if not self._pins.get(k)]
        cand.sort()
        for _, _, k in cand:
            if self._bytes <= self.budget:
                break
            e = self._hot.pop(k)
            self._bytes -= e.nbytes
            self.counters["evictions"] += 1
            self.counters["bytes_evicted"] += e.nbytes
        # if everything left is pinned we run over budget until the
        # pinning queries release — bounded by budget + in-flight bytes

    # -- lifecycle -------------------------------------------------------------
    def drop_datasource(self, name: str) -> None:
        """Forget a datasource's chunks (store drop, quarantine
        re-recovery). Pin refcounts for dropped keys die with them;
        release_pins tolerates the missing entries."""
        with self._lock:
            dead = [k for k in self._hot if k[0] == name]
            paths = set()
            for k in dead:
                e = self._hot.pop(k)
                self._bytes -= e.nbytes
                self._pins.pop(k, None)
                paths.add(k[1])
            for k in [k for k in self._dec if k[0] == name]:
                self._dec_bytes -= self._dec.pop(k).nbytes
            live_paths = {k[1] for k in self._hot}
            self._verified -= (paths - live_paths)

    def clear(self) -> None:
        with self._lock:
            self._hot.clear()
            self._pins.clear()
            self._verified.clear()
            self._bytes = 0
            self._dec.clear()
            self._dec_bytes = 0

    # -- prefetch --------------------------------------------------------------
    def start_prefetcher(self, threads: int = 2,
                         depth: int = 4096) -> None:
        if self._pf_queue is not None or threads <= 0:
            return
        self._pf_stop.clear()
        self._pf_queue = queue.Queue(maxsize=max(16, int(depth)))
        for i in range(int(threads)):
            t = threading.Thread(target=self._pf_loop,
                                 name=f"sdot-tier-prefetch-{i}",
                                 daemon=True)
            t.start()
            self._pf_threads.append(t)

    def prefetch(self, ds_name: str,
                 work: List[Tuple[str, BlobRef]]) -> None:
        """Enqueue cold chunks to load behind compute. Best-effort: a
        full queue drops work (the demand fault still serves it)."""
        q = self._pf_queue
        if q is None:
            return
        for column, ref in work:
            key = (ds_name, ref.path, int(ref.start), int(ref.count))
            with self._lock:
                if key in self._hot or key in self._loading:
                    continue
                self.counters["prefetch_submitted"] += 1
            try:
                q.put_nowait((ds_name, column, ref))
            except queue.Full:
                with self._lock:
                    self.counters["prefetch_dropped"] += 1

    def _pf_loop(self) -> None:
        while not self._pf_stop.is_set():
            try:
                item = self._pf_queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                break
            ds_name, column, ref = item
            try:
                self.fault(ds_name, column, ref, prefetch=True)
            except Exception:  # noqa: BLE001 — prefetch is advisory;
                pass           # the demand fault re-raises for real

    def stop(self) -> None:
        self._pf_stop.set()
        q = self._pf_queue
        if q is not None:
            for _ in self._pf_threads:
                try:
                    q.put_nowait(None)
                except queue.Full:
                    break
        for t in self._pf_threads:
            t.join(timeout=2.0)
        self._pf_threads = []
        self._pf_queue = None

    # -- observability ---------------------------------------------------------
    def stats_snapshot(self) -> dict:
        with self._lock:
            c = dict(self.counters)
            c["crc_verify_ms"] = round(c["crc_verify_ms"], 3)
            c["decode_ms_saved"] = round(c["decode_ms_saved"], 3)
            faulted = max(1, c["bytes_faulted"])
            return {
                "budget_bytes": self.budget,
                "hot_bytes": self._bytes,
                "hot_entries": len(self._hot),
                "decoded_budget_bytes": self.dec_budget,
                "decoded_cache_bytes": self._dec_bytes,
                "decoded_cache_entries": len(self._dec),
                "pinned_entries": sum(1 for k in self._hot
                                      if self._pins.get(k)),
                "mesh_pinned_entries": sum(1 for k in self._hot
                                           if self._mesh_pins.get(k)),
                "mesh_pinned_bytes": sum(e.nbytes
                                         for k, e in self._hot.items()
                                         if self._mesh_pins.get(k)),
                "prefetch_overlap_ratio": round(
                    c["prefetch_hit_bytes"] / faulted, 4),
                **c,
            }
