"""Broadcast hash join: build once per node, probe in the wave loop.

The build side (already under ``sdot.join.broadcast.max.bytes`` by the
planner's estimate) materializes host-side, canonicalizes its keys, and
becomes one device-resident pytree — the open-addressing table from
``ops/hash_join.py`` plus payload/group columns. The probe side then
streams through the SAME segment wave loop the scan executor uses:
waves sized by ``parallel/cost.py:plan_waves``, arrays bound through
the engine's cached device bind (``_bind_arrays`` — so repeated join
queries never re-upload columns), cold-tier chunks pinned for the whole
join (``tier/store.py`` pin pair) and prefetched a wave ahead.

On a multi-chip mesh the table pytree replicates per device (in-spec
``P()``) while probe waves shard over the segment axis — each device
probes its slice and per-group partials merge on the interconnect with
the same register algebra the mesh scan tier uses
(``groupby.merge_partials``: psum sums/counts, pmin/pmax extrema).

Device residency of the build table is a checked acquire/release pair
(``BuildLedger`` — sdlint leaks resource ``join-build``), mirroring the
mesh tier's partial-buffer ledger: no decline/exception path may leave
phantom build bytes in the gauge.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ops import groupby as G
from spark_druid_olap_tpu.ops import hash_join as HJ
from spark_druid_olap_tpu.ops.hash_join import JoinUnsupported
from spark_druid_olap_tpu.ops.scan import (
    NULL_VALID_PREFIX,
    ROW_VALID_KEY,
    array_dtype,
    array_names,
)
from spark_druid_olap_tpu.parallel import cost as C
from spark_druid_olap_tpu.parallel.executor import (
    EngineFallback,
    _pad_segments,
)
from spark_druid_olap_tpu.parallel.mesh import (
    SEGMENT_AXIS,
    mesh_size,
    shard_map,
)
from spark_druid_olap_tpu.utils.config import (
    GROUPBY_MATMUL_MAX_KEYS,
    JOIN_MAX_MATCHES,
    MESH_ENABLED,
)

#: dense group-key ceiling for the join group-by (same order as the
#: engine's dense tier; a wider group space declines to the host)
MAX_GROUP_KEYS = 1 << 22


# =============================================================================
# build-table residency ledger (sdlint leaks pair: join-build)
# =============================================================================

class _BuildToken:
    __slots__ = ("nbytes", "released")

    def __init__(self, nbytes: int):
        self.nbytes = int(nbytes)
        self.released = False


class BuildLedger:
    """Device-byte accounting for broadcast build tables while a join
    holds them resident (table + payload pytree, per node — replicated
    copies on a mesh count once; the mesh replicates for free from the
    ledger's point of view, like a weight pytree)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.outstanding_bytes = 0
        self.peak_bytes = 0
        self.acquires = 0

    def acquire_build(self, nbytes: int) -> _BuildToken:
        tok = _BuildToken(nbytes)
        with self._lock:
            self.acquires += 1
            self.outstanding_bytes += tok.nbytes
            self.peak_bytes = max(self.peak_bytes, self.outstanding_bytes)
        return tok

    def release_build(self, tok: _BuildToken) -> None:
        with self._lock:
            if not tok.released:
                tok.released = True
                self.outstanding_bytes -= tok.nbytes

    def stats(self) -> dict:
        with self._lock:
            return {"outstanding_bytes": self.outstanding_bytes,
                    "peak_bytes": self.peak_bytes,
                    "acquires": self.acquires}


#: process-wide gauge (surfaced through stats["join"]["build_ledger"])
LEDGER = BuildLedger()


# =============================================================================
# host-side helpers shared with the partitioned tier's local exec
# =============================================================================

def null_mask(vals) -> np.ndarray:
    """NaN/None-coded null mask for a host column (pandas convention)."""
    return np.asarray(pd.isna(np.asarray(vals)), dtype=bool)


def factorize_group(vals: np.ndarray):
    """Host group-column factorization: sorted non-null uniques + codes
    with the null lane at ``len(uniques)``. Returns
    ``(codes int32, card_with_null, decoder)``."""
    vals = np.asarray(vals)
    nulls = null_mask(vals)
    nn = vals[~nulls]
    if nn.dtype == object or nn.dtype.kind in ("U", "S"):
        uniq = np.unique(nn.astype(str)) if len(nn) else \
            np.empty(0, dtype=object)
        pos = np.searchsorted(uniq, vals.astype(str)) if len(uniq) else \
            np.zeros(len(vals), dtype=np.int64)
    else:
        uniq = np.unique(nn)
        pos = np.searchsorted(uniq, np.where(nulls, uniq[0] if len(uniq)
                                             else 0, vals)) \
            if len(uniq) else np.zeros(len(vals), dtype=np.int64)
    card = len(uniq)
    codes = np.where(nulls, card, np.clip(pos, 0, max(0, card - 1))) \
        .astype(np.int32)

    def decode(cs: np.ndarray) -> np.ndarray:
        cs = np.asarray(cs, dtype=np.int64)
        isnull = cs >= card
        if uniq.dtype == object or uniq.dtype.kind in ("U", "S"):
            out = np.empty(len(cs), dtype=object)
            out[~isnull] = uniq[np.clip(cs[~isnull], 0,
                                        max(0, card - 1))].astype(str) \
                if card else None
            out[isnull] = None
            return out
        out = uniq[np.clip(cs, 0, max(0, card - 1))] if card else \
            np.zeros(len(cs))
        if isnull.any():
            out = out.astype(np.float64)
            out[isnull] = np.nan
        return out

    return codes, card + 1, decode


def numeric_payload(vals: np.ndarray, x64: bool):
    """Host agg/residual column -> (device value array, valid mask).
    Integers keep an exact integer route when the backend can carry it;
    strings decline (the planner should have caught them)."""
    vals = np.asarray(vals)
    nulls = null_mask(vals)
    if vals.dtype == object or vals.dtype.kind in ("U", "S"):
        raise JoinUnsupported("string column in a numeric join payload")
    if vals.dtype.kind in ("i", "u"):
        if x64:
            return vals.astype(np.int64), ~nulls
        a = vals.astype(np.float64)
        if len(a) and np.abs(a[~nulls]).max(initial=0) >= 2 ** 31:
            raise JoinUnsupported(
                "wide integer join payload on a 32-bit backend")
        return vals.astype(np.int32), ~nulls
    out = np.where(nulls, 0.0, vals).astype(
        np.float64 if x64 else np.float32)
    return out, ~nulls


def agg_is_int(arg: Optional[E.Expr], kindof) -> bool:
    """Static integer-route hint: a bare integer column aggregates on
    the exact integer route; any compound expression goes float."""
    return isinstance(arg, E.Column) and kindof(arg.name) == "int"


_F32_SENT = np.float32(3.4e38)
_SENTINELS = {
    ("f64", "min"): np.inf, ("f64", "max"): -np.inf,
    ("i64", "min"): G.I64_MAX, ("i64", "max"): G.I64_MIN,
    ("i32", "min"): G.I32_MAX, ("i32", "max"): G.I32_MIN,
    ("f32", "min"): _F32_SENT, ("f32", "max"): -_F32_SENT,
}


def sentinel_of(route: G.Route):
    return _SENTINELS.get((route.tag, route.kind))


def finalize_agg(spec_fn: str, out_name: str, acc: Dict[str, np.ndarray],
                 routes: Dict[str, G.Route]) -> np.ndarray:
    """One aggregation's exact cross-wave accumulator -> final column
    with SQL null semantics (empty-group sum/avg/min/max -> NULL)."""
    if spec_fn == "count":
        return np.asarray(acc[out_name], dtype=np.int64)
    if spec_fn in ("sum", "avg"):
        raw = np.asarray(acc[out_name])
        vc = np.asarray(acc["__vc__" + out_name], dtype=np.int64)
        if spec_fn == "avg":
            return np.where(vc > 0, raw / np.maximum(vc, 1), np.nan) \
                .astype(np.float64)
        if (vc == 0).any():
            return np.where(vc > 0, raw.astype(np.float64), np.nan)
        return raw
    # min / max: the route sentinel marks all-null groups
    val = np.asarray(acc[out_name])
    sent = sentinel_of(routes[out_name])
    if sent is not None and (val == sent).any():
        return np.where(val == sent, np.nan, val.astype(np.float64))
    return val


def combine_wave(acc: Dict[str, np.ndarray], wave_out: Dict[str, object],
                 routes: Dict[str, G.Route], n_keys: int) -> None:
    """Fold one wave's device outputs into the exact host accumulator
    (f64/i64 adds for sums/counts, sentinel-preserving elementwise
    min/max for extrema)."""
    np_out = {k: np.asarray(v) for k, v in wave_out.items()}
    for name, route in routes.items():
        arr = G.combine_route(route, np_out, n_keys)
        cur = acc.get(name)
        if cur is None:
            acc[name] = arr
        elif route.kind == "min":
            acc[name] = np.minimum(cur, arr)
        elif route.kind == "max":
            acc[name] = np.maximum(cur, arr)
        else:
            acc[name] = cur + arr


# =============================================================================
# the broadcast executor
# =============================================================================

def execute_broadcast(ctx, plan) -> Tuple[Dict[str, np.ndarray], dict]:
    """Run ``plan`` (planner/joinplan.JoinPlan) on the broadcast tier.

    Returns ``(grouped data, join stats dict)`` — group columns keyed by
    query name, agg columns keyed by output name, all finalized; the
    planner's shared epilogue does having/order/limit/projection."""
    eng = ctx.engine
    conf = ctx.config
    store = ctx.store
    x64 = G._x64()
    ds = store.get(plan.probe.ds)
    if getattr(ds, "is_partial", False):
        raise JoinUnsupported("probe side is a multi-host partial store")

    # ---- build side: materialize, filter, canonicalize ----------------------
    from spark_druid_olap_tpu.planner import host_exec
    from spark_druid_olap_tpu.utils import host_eval
    bcols = plan.build_cols()
    bdf = host_exec.datasource_frame(ctx, plan.build.ds, columns=bcols)
    if plan.build_filter is not None:
        env = {c: bdf[c].to_numpy() for c in bdf.columns}
        bdf = bdf[host_eval.eval_pred3(plan.build_filter, env)]
    bdf = bdf.reset_index(drop=True)

    key_pcols = [pc for pc, _ in plan.keys]
    key_bcols = [bc for _, bc in plan.keys]
    bvals = [bdf[c].to_numpy() for c in key_bcols]
    bvalid = [~null_mask(v) for v in bvals]
    uniques, comps, keep = HJ.build_key_components(bvals, bvalid)
    cards = [len(u) for u in uniques]
    if HJ.key_domain(cards) >= HJ.MAX_KEY_DOMAIN:
        raise JoinUnsupported(
            f"composite key domain {HJ.key_domain(cards)} exceeds int32")
    bdf = bdf[keep].reset_index(drop=True)
    fused = HJ.fuse_components(comps, cards)
    table = HJ.build_table(fused, conf.get(JOIN_MAX_MATCHES))
    n_build = table.n_build
    C_w = max(1, table.max_count)

    # probe-side key maps (dictionary LUT / numeric searchsorted)
    keymaps = []
    for pc, uniq in zip(key_pcols, uniques):
        dcol = ds.dims.get(pc)
        if dcol is not None:
            if uniq.dtype != object and uniq.dtype.kind not in ("U", "S"):
                raise JoinUnsupported(
                    f"join key {pc!r} is a dimension but the build side "
                    f"is numeric")
            keymaps.append(HJ.dim_keymap(dcol.dictionary, uniq))
        else:
            if uniq.dtype == object or uniq.dtype.kind in ("U", "S"):
                raise JoinUnsupported(
                    f"join key {pc!r} is numeric but the build side is "
                    f"a string column")
            keymaps.append(HJ.numeric_keymap(
                uniq, array_dtype(ds, pc)))

    # ---- build payload / group columns --------------------------------------
    build_used = plan.build_value_cols()
    pay, payv = {}, {}
    for c in build_used:
        pay[c], payv[c] = numeric_payload(bdf[c].to_numpy(), x64)
    bgrp: Dict[str, Tuple[np.ndarray, int, object]] = {}
    group_meta: List[Tuple[str, int, object]] = []
    probe_group_cols = []
    for g in plan.group_by:
        side, phys = plan.colside[g]
        if side == "build":
            codes, cardn, dec = factorize_group(bdf[phys].to_numpy())
            bgrp[g] = (codes, cardn, dec)
            group_meta.append((g, cardn, dec))
        else:
            dcol = ds.dims.get(phys)
            card = dcol.cardinality

            def dec_dim(cs, _d=dcol, _card=card):
                cs = np.asarray(cs, dtype=np.int64)
                out = np.empty(len(cs), dtype=object)
                nn = cs < _card
                out[nn] = _d.decode(cs[nn])
                out[~nn] = None
                return out

            probe_group_cols.append(phys)
            group_meta.append((g, card + 1, dec_dim))
    gcards = [m[1] for m in group_meta]
    n_keys = 1
    for c in gcards:
        n_keys *= c
    n_keys = max(1, n_keys)
    if n_keys > MAX_GROUP_KEYS:
        raise JoinUnsupported(
            f"join group-by cardinality {n_keys} exceeds the dense "
            f"tier's ceiling {MAX_GROUP_KEYS}")

    if n_build == 0:
        # an empty build side (after its filter) joins to nothing: skip
        # the device loop entirely — a gather over zero-length payload
        # arrays is ill-formed — and emit the empty grouped shape (or
        # the single global-aggregate zero row) directly
        data0: Dict[str, np.ndarray] = {}
        if group_meta:
            for g, _, dec in group_meta:
                data0[g] = dec(np.empty(0, dtype=np.int64))
            for spec in plan.aggs:
                data0[spec.out] = (np.zeros(0, dtype=np.int64)
                                   if spec.fn == "count"
                                   else np.zeros(0, dtype=np.float64))
        else:
            for spec in plan.aggs:
                data0[spec.out] = (np.zeros(1, dtype=np.int64)
                                   if spec.fn == "count"
                                   else np.full(1, np.nan))
        js0 = {"mode": "broadcast", "build_rows": 0, "build_bytes": 0,
               "table_slots": int(table.n_slots), "match_width": 0,
               "waves": 0, "segments_per_wave": 0, "devices": 0,
               "mesh": "empty-build", "groups": 0,
               "build_ledger": LEDGER.stats()}
        return data0, js0

    # ---- probe plan: columns, waves, mesh decision --------------------------
    pcols = sorted(plan.probe_cols())
    names = array_names(ds, pcols, need_time_ms=False)
    n_segments = ds.num_segments
    mesh_reason = "no-mesh"
    n_dev = 1
    if eng.mesh is not None and mesh_size(eng.mesh) > 1:
        n = mesh_size(eng.mesh)
        if not bool(conf.get(MESH_ENABLED)):
            mesh_reason = "disabled"
        elif jax.process_count() > 1:
            mesh_reason = "multihost"
        elif n_segments < n:
            mesh_reason = "few-segments"
        else:
            n_dev, mesh_reason = n, "sharded"
    seg_bytes = C.bytes_per_segment(ds, names)
    spw, n_waves = C.plan_waves(
        n_segments, n_dev, seg_bytes, C.wave_budget_bytes(conf), conf,
        output_groups=n_keys, n_aggs=len(plan.aggs),
        io_budget=C.tier_io_budget(ds, conf),
        io_seg_bytes=C.tier_io_seg_bytes(ds, names))

    # ---- routes -------------------------------------------------------------
    matmul_max = int(conf.get(GROUPBY_MATMUL_MAX_KEYS))
    Rrows = ds.padded_rows
    n_flat = spw * Rrows * C_w

    def kindof(qname: str) -> str:
        side, phys = plan.colside[qname]
        if side == "probe":
            if phys in ds.dims:
                return "dim"
            k = ds.column_kind(phys)
            return "int" if k.value == "long" else "float"
        v = pay.get(phys)
        if v is None:
            return "dim"
        return "int" if v.dtype.kind in ("i", "u") else "float"

    meta_inputs = [G.AggInput(ROW_VALID_KEY, "count")]
    for spec in plan.aggs:
        kind = "sum" if spec.fn == "avg" else spec.fn
        if kind == "count":
            meta_inputs.append(G.AggInput(spec.out, "count"))
        else:
            is_int = agg_is_int(spec.arg, kindof)
            meta_inputs.append(G.AggInput(spec.out, kind, is_int=is_int))
            if kind == "sum":
                meta_inputs.append(G.AggInput("__vc__" + spec.out,
                                              "count"))
    routes = G.plan_routes(meta_inputs, n_keys, matmul_max,
                           n_rows=n_flat)
    if n_dev > 1 and not all(r.merged for r in routes.values()):
        # unmerged Neumaier pairs want a per-chip host combine the
        # join's replicated out-spec doesn't carry — single-device
        n_dev, mesh_reason = 1, "unmerged-routes"
        spw, n_waves = C.plan_waves(
            n_segments, 1, seg_bytes, C.wave_budget_bytes(conf), conf,
            output_groups=n_keys, n_aggs=len(plan.aggs),
            io_budget=C.tier_io_budget(ds, conf),
            io_seg_bytes=C.tier_io_seg_bytes(ds, names))

    # ---- the jitted wave core ----------------------------------------------
    dimlk = ds.dims.get

    def jdim(qname: str):
        side, phys = plan.colside.get(qname, (None, None))
        return ds.dims.get(phys) if side == "probe" else None

    def core(arrays, tdev):
        rowv = arrays[ROW_VALID_KEY]

        def pget(phys):
            v = arrays[phys]
            if phys in ds.dims:
                v = v.astype(jnp.int32)
            nv = arrays.get(NULL_VALID_PREFIX + phys)
            valid = rowv if nv is None else jnp.logical_and(rowv, nv)
            return v, valid

        keep = rowv
        fm = HJ.pred_mask(plan.probe_filter, pget, dimlk)
        if fm is not None:
            keep = jnp.logical_and(keep, fm)
        kvals, kvalids = [], []
        for pc in key_pcols:
            v, ok = pget(pc)
            kvals.append(v)
            kvalids.append(jnp.logical_and(ok, keep))
        kdevs = [tdev["keys"][i] for i in range(len(keymaps))]
        key, kvalid = HJ.canonical_key(keymaps, kdevs, kvals, kvalids)
        key = key.reshape(-1)
        kvalid = kvalid.reshape(-1)
        start, count = HJ.probe(
            tdev["table"], key, kvalid, n_slots=table.n_slots,
            shift=table.shift, max_disp=table.max_disp)
        bidx, mvalid = HJ.expand(tdev["table"], start, count,
                                 width=C_w, n_build=n_build)
        N = key.shape[0]
        shape = (N, C_w)

        def jget(qname):
            side, phys = plan.colside[qname]
            if side == "probe":
                v, ok = pget(phys)
                return (v.reshape(-1)[:, None],
                        jnp.logical_and(ok.reshape(-1)[:, None], mvalid))
            return (tdev["pay"][phys][bidx],
                    jnp.logical_and(tdev["payv"][phys][bidx], mvalid))

        pairmask = mvalid
        if plan.residual is not None:
            pairmask = jnp.logical_and(
                pairmask, HJ.pred_mask(plan.residual, jget, jdim))

        gcodes = []
        for g in plan.group_by:
            side, phys = plan.colside[g]
            if side == "build":
                gcodes.append(tdev["bgrp"][g][bidx])
            else:
                code, ok = pget(phys)
                card = ds.dims[phys].cardinality
                gc = jnp.where(ok, code, jnp.int32(card))
                gcodes.append(jnp.broadcast_to(
                    gc.reshape(-1)[:, None], shape))
        if gcodes:
            gkey, _ = G.fuse_keys(gcodes, gcards)
        else:
            gkey = jnp.zeros(shape, dtype=jnp.int32)
        gkey = gkey.reshape(-1)
        flatmask = pairmask.reshape(-1)

        inputs = [G.AggInput(ROW_VALID_KEY, "count", mask=flatmask)]
        for spec in plan.aggs:
            kind = "sum" if spec.fn == "avg" else spec.fn
            if kind == "count":
                if spec.arg is None:
                    m = flatmask
                else:
                    _, ok = jget(_arg_col(spec.arg))
                    m = jnp.logical_and(pairmask, ok).reshape(-1)
                inputs.append(G.AggInput(spec.out, "count", mask=m))
                continue
            v, ok = HJ._num(spec.arg, jget, jdim)
            v = jnp.broadcast_to(v, shape).reshape(-1)
            m = jnp.logical_and(pairmask, ok).reshape(-1)
            is_int = agg_is_int(spec.arg, kindof)
            inputs.append(G.AggInput(spec.out, kind, values=v, mask=m,
                                     is_int=is_int))
            if kind == "sum":
                inputs.append(G.AggInput("__vc__" + spec.out, "count",
                                         mask=m))
        return G.dense_groupby(gkey, flatmask, n_keys, inputs, routes,
                               matmul_max)

    if n_dev > 1:
        def core_merged(arrays, tdev):
            out = core(arrays, tdev)
            return G.merge_partials(out, routes, SEGMENT_AXIS)

        smfn = shard_map(core_merged, mesh=eng.mesh,
                         in_specs=(P(SEGMENT_AXIS, None), P()),
                         out_specs=P(), check_vma=False)
        prog = jax.jit(smfn)
    else:
        prog = jax.jit(core)

    # ---- device residency + the wave loop -----------------------------------
    tree = {"table": table.device_tree(),
            "keys": {i: km.device_tree()
                     for i, km in enumerate(keymaps)},
            "pay": pay,
            "payv": payv,
            "bgrp": {g: codes for g, (codes, _, _) in bgrp.items()}}
    build_bytes = int(sum(a.nbytes for a in jax.tree_util.tree_leaves(
        tree)))
    sharding = NamedSharding(eng.mesh, P()) if n_dev > 1 else None
    tiers, pins = [], []
    for name in {plan.probe.ds, plan.build.ds}:
        t = getattr(store._datasources.get(name), "tier", None)
        if t is not None:
            tiers.append(t)
    acc: Dict[str, np.ndarray] = {}
    btok = LEDGER.acquire_build(build_bytes)
    try:
        pins = [t.acquire_pins() for t in tiers]
        eng._tick(1, len(jax.tree_util.tree_leaves(tree)))
        tdev = jax.device_put(tree, sharding) if sharding is not None \
            else jax.device_put(tree)
        seg_idx = np.arange(n_segments, dtype=np.int64)
        s_pad = spw if n_waves > 1 else _pad_segments(n_segments, n_dev)
        waves = [seg_idx[i: i + s_pad]
                 for i in range(0, n_segments, s_pad)]
        try:
            for i, w in enumerate(waves):
                arrays = eng._bind_arrays(ds, names, w, s_pad, n_dev > 1)
                eng._tier_prefetch(ds, names, waves, i + 1)
                eng._tick()
                out = prog(arrays, tdev)
                eng._tick(1)
                combine_wave(acc, out, routes, n_keys)
        except EngineFallback as e:
            raise JoinUnsupported(str(e)) from e
    finally:
        try:
            for t, tok in zip(tiers, pins):
                t.release_pins(tok)
        finally:
            LEDGER.release_build(btok)

    # ---- finalize -----------------------------------------------------------
    rows = np.asarray(acc[ROW_VALID_KEY], dtype=np.int64)
    idx = np.nonzero(rows > 0)[0]
    if not group_meta:
        idx = np.arange(1)     # global aggregate: always one row

    codes = G.unfuse_key(idx, gcards) if group_meta else []
    data: Dict[str, np.ndarray] = {}
    for (g, _, dec), cs in zip(group_meta, codes):
        data[g] = dec(cs)
    for spec in plan.aggs:
        data[spec.out] = finalize_agg(spec.fn, spec.out, acc,
                                      routes)[idx]
    js = {
        "mode": "broadcast",
        "build_rows": int(n_build),
        "build_bytes": build_bytes,
        "table_slots": int(table.n_slots),
        "match_width": int(C_w),
        "waves": int(n_waves),
        "segments_per_wave": int(spw),
        "devices": int(n_dev),
        "mesh": mesh_reason,
        "groups": int(len(idx)),
        "build_ledger": LEDGER.stats(),
    }
    return data, js


def _arg_col(e: E.Expr) -> str:
    if isinstance(e, E.Column):
        return e.name
    raise JoinUnsupported("count() over a compound expression")
