"""General (non-star) join execution tiers.

The reference model only pushes STAR joins down to the engine
(``planner/builder.py``'s FD-closure rewrite); everything else used to
fall to the host pandas tier. This package is the device-native join
surface above ``ops/hash_join.py``:

- :mod:`spark_druid_olap_tpu.join.broadcast` — broadcast hash join:
  the build side fits ``sdot.join.broadcast.max.bytes``, its hash
  table is built once per node and probed inside the segment wave
  loop (composing with the tier pins, the device-array cache, and the
  local device mesh).
- :mod:`spark_druid_olap_tpu.join.partitioned` — shard-aligned
  partitioned join: a broker re-shards both sides on the join key
  through the historicals (hash-partition exchange over the SDW1 wire
  with exact shuffle-bytes accounting) and each node joins its
  aligned partitions locally.

``planner/joinplan.py`` recognizes join statements, picks the tier via
``parallel/cost.py:join_estimate``, and applies the shared epilogue.
"""

from spark_druid_olap_tpu.ops.hash_join import JoinUnsupported  # noqa: F401
