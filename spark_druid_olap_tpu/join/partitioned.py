"""Shard-aligned partitioned join: hash-partition exchange + local joins.

The broadcast tier caps out at ``sdot.join.broadcast.max.bytes`` of
build table; past that the cluster re-shards BOTH sides on the join key
so every key lands on exactly one node and each node joins only aligned
partitions:

1. **Partition hop** — the broker asks every shard owner (over the
   normal guarded RPC path: breakers, health marks) to filter its shard,
   drop null-key rows, and tag each surviving row with a partition id
   (``partition_ids`` — a deterministic value hash both sides compute
   identically, strings by crc32, numerics through float64, so probe row
   and build row with equal keys always land in the same partition).
   Rows come back as normal SDW1 frames.
2. **Exec hop** — the broker regroups rows by partition and ships each
   partition's (probe, build) pair to one node as an ``SDJ1`` frame
   (``wire.encode_join_exec``); the node runs :func:`local_join` — the
   same ``ops/hash_join.py`` device probe the broadcast tier uses, on
   flat arrays — and returns per-group partials.
3. **Merge** — the broker folds partials through the SAME exact merge
   the scatter path uses (``cluster/merge.py``): Python-int sums, NaN /
   None-aware min/max, so distributed answers match local ones.

Every byte that crosses the wire for the join is counted exactly —
hop-1 response frames plus hop-2 request frames — and surfaced as
``stats["join"]["shuffle_bytes"]`` (and the broker's
``join_shuffle_bytes`` counter), priced by the cost model like
interconnect bytes. Any RPC or node failure raises
:class:`JoinUnsupported`; the planner then falls back to the broker's
local broadcast join (the broker holds the full store), mirroring the
scatter path's local-fallback posture.
"""

from __future__ import annotations

import json
import time
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
import pandas as pd

from spark_druid_olap_tpu.cluster import wire as WIRE
from spark_druid_olap_tpu.ir import serde as SERDE
from spark_druid_olap_tpu.ops import hash_join as HJ
from spark_druid_olap_tpu.ops.hash_join import JoinUnsupported
from spark_druid_olap_tpu.utils.config import JOIN_PARTITIONS

_MIX = np.uint64(0xFF51AFD7ED558CCD)
_STEP = np.uint64(1000003)


# =============================================================================
# side-independent partition hash
# =============================================================================

def _col_hash(vals: np.ndarray) -> np.ndarray:
    """Per-row uint64 hash of one key column. Strings hash their utf-8
    crc32; numerics go THROUGH float64 first so an int32 probe key and
    an int64 (or float) build key with equal value hash identically."""
    vals = np.asarray(vals)
    if vals.dtype == object or vals.dtype.kind in ("U", "S"):
        return np.fromiter(
            (zlib.crc32(str(v).encode("utf-8")) for v in vals),
            dtype=np.uint64, count=len(vals))
    x = vals.astype(np.float64).view(np.uint64).copy()
    x ^= x >> np.uint64(33)
    x *= _MIX
    x ^= x >> np.uint64(29)
    return x


def partition_ids(key_cols: List[np.ndarray], n_parts: int) -> np.ndarray:
    """Deterministic partition id per row from the key column tuple."""
    h = np.zeros(len(key_cols[0]) if key_cols else 0, dtype=np.uint64)
    for vals in key_cols:
        h = h * _STEP ^ _col_hash(vals)
    return (h % np.uint64(max(1, n_parts))).astype(np.int64)


# =============================================================================
# historical side: partition + local exec handlers
# =============================================================================

def partition_request(ctx, req: dict) -> bytes:
    """Hop 1 on a shard owner: filter one shard store, drop null-key
    rows, tag partition ids. Returns an SDW1 frame of the ship columns
    plus ``__part__``."""
    from spark_druid_olap_tpu.planner import host_exec
    from spark_druid_olap_tpu.utils import host_eval
    store_name = str(req["store"])
    keys = [str(k) for k in req["keys"]]
    ship = [str(c) for c in req["ship"]]
    read = set(ship) | set(keys) | set(str(c) for c in req.get("read", []))
    n_parts = int(req["npartitions"])
    df = host_exec.datasource_frame(ctx, store_name, columns=read)
    if req.get("filter") is not None:
        flt = SERDE.expr_from_dict(req["filter"])
        env = {c: df[c].to_numpy() for c in df.columns}
        df = df[host_eval.eval_pred3(flt, env)]
    kvals = [df[k].to_numpy() for k in keys]
    if kvals:
        keep = ~np.logical_or.reduce([pd.isna(np.asarray(v))
                                      for v in kvals])
        df = df[keep]
        kvals = [v[keep] for v in kvals]
    df = df.reset_index(drop=True)
    cols = list(dict.fromkeys(ship + keys))
    data = {c: df[c].to_numpy() for c in cols}
    data["__part__"] = partition_ids(kvals, n_parts)
    return WIRE.encode_result(cols + ["__part__"], data,
                              {"rows": int(len(df))})


def _canon_probe_keys(uniques, kvals):
    """Host canonicalization of exchanged probe keys against the build
    uniques: component positions (-1 miss), mixed-radix fuse, valid."""
    comps, valid = [], None
    for uniq, vals in zip(uniques, kvals):
        vals = np.asarray(vals)
        if uniq.dtype == object or uniq.dtype.kind in ("U", "S"):
            u = uniq.astype(str)
            v = vals.astype(str)
        else:
            u = uniq
            v = vals.astype(u.dtype)
        if len(u) == 0:
            comp = np.full(len(v), -1, dtype=np.int64)
        else:
            pos = np.searchsorted(u, v)
            pos_c = np.clip(pos, 0, len(u) - 1)
            comp = np.where(u[pos_c] == v, pos_c, -1)
        ok = comp >= 0
        valid = ok if valid is None else (valid & ok)
        comps.append(comp)
    cards = [len(u) for u in uniques]
    fused = np.zeros(len(comps[0]) if comps else 0, dtype=np.int64)
    for comp, card in zip(comps, cards):
        fused = fused * max(1, card) + np.where(comp >= 0, comp, 0)
    return fused.astype(np.int64), (valid if valid is not None
                                    else np.zeros(0, dtype=bool))


def local_join(spec: dict, probe: Tuple[List[str], Dict[str, np.ndarray]],
               build: Tuple[List[str], Dict[str, np.ndarray]]):
    """Join one aligned partition pair on this node's device and return
    per-group partials: group VALUE columns (query names, ``None`` for
    null) + per-agg object columns (exact Python ints / floats, ``None``
    for null) + ``__vc__<agg>`` counts for avg.

    The probe path is the device kernel from ``ops/hash_join.py`` —
    build table device-put, probe/expand in-trace — over the exchanged
    flat arrays; the surviving pairs' partial aggregation runs host-side
    (it is O(matched pairs), already past the data-reduction point)."""
    from spark_druid_olap_tpu.utils import host_eval
    _, pdata = probe
    _, bdata = build
    keys = [(str(a), str(b)) for a, b in spec["keys"]]
    colside = {q: (str(s), str(c)) for q, (s, c) in spec["colside"].items()}
    group_by = [str(g) for g in spec["group_by"]]
    aggs = spec["aggs"]
    n_probe = len(next(iter(pdata.values()))) if pdata else 0

    bvals = [np.asarray(bdata[bc]) for _, bc in keys]
    bvalid = [~pd.isna(v) for v in bvals]
    uniques, comps, keep = HJ.build_key_components(bvals, bvalid)
    cards = [len(u) for u in uniques]
    if HJ.key_domain(cards) >= HJ.MAX_KEY_DOMAIN:
        raise JoinUnsupported("partition key domain exceeds int32")
    bsel = {c: np.asarray(v)[keep] for c, v in bdata.items()}
    fused_b = HJ.fuse_components(comps, cards)
    table = HJ.build_table(fused_b, int(spec.get("max_matches", 1 << 20)))
    width = max(1, table.max_count)

    pk, pvalid = _canon_probe_keys(uniques,
                                   [pdata[pc] for pc, _ in keys])
    if n_probe == 0 or table.n_build == 0:
        pi = np.zeros(0, dtype=np.int64)
        bi = np.zeros(0, dtype=np.int64)
    else:
        tdev = jax.device_put(table.device_tree())
        start, count = HJ.probe(
            tdev, jax.numpy.asarray(pk.astype(np.int32)),
            jax.numpy.asarray(pvalid), n_slots=table.n_slots,
            shift=table.shift, max_disp=table.max_disp)
        bidx, mvalid = HJ.expand(tdev, start, count, width=width,
                                 n_build=table.n_build)
        mvalid = np.asarray(mvalid)
        bidx = np.asarray(bidx)
        pi, lane = np.nonzero(mvalid)
        bi = bidx[pi, lane]

    def cell_env(qname: str) -> np.ndarray:
        side, phys = colside[qname]
        src = pdata if side == "probe" else bsel
        arr = np.asarray(src[phys])
        return arr[pi] if side == "probe" else arr[bi]

    env = {q: cell_env(q) for q in colside}
    if spec.get("residual") is not None:
        res = SERDE.expr_from_dict(spec["residual"])
        m = host_eval.eval_pred3(res, env)
        env = {q: v[m] for q, v in env.items()}

    n_pairs = len(next(iter(env.values()))) if env else \
        (len(pi) if spec.get("residual") is None else 0)
    frame_cols = {}
    for g in group_by:
        frame_cols[g] = env[g]
    df = pd.DataFrame(frame_cols) if frame_cols else \
        pd.DataFrame(index=range(n_pairs))

    def agg_vals(a) -> Optional[np.ndarray]:
        if a.get("arg") is None:
            return None
        e = SERDE.expr_from_dict(a["arg"])
        return np.asarray(host_eval.eval_expr(e, env))

    out_cols: List[str] = list(group_by)
    out: Dict[str, np.ndarray] = {}

    def obj(vals: list) -> np.ndarray:
        arr = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            arr[i] = v
        return arr

    if group_by:
        grouped = df.assign(__row__=np.arange(n_pairs)) \
            .groupby(group_by, dropna=False, sort=False)["__row__"] \
            .agg(list)
        gkeys = list(grouped.index)
        gidx = [np.asarray(v, dtype=np.int64) for v in grouped]
        if len(group_by) == 1:
            gkeys = [(k,) for k in gkeys]
    else:
        gkeys = [()]
        gidx = [np.arange(n_pairs, dtype=np.int64)]
    for ki, g in enumerate(group_by):
        out[g] = obj([None if pd.isna(k[ki]) else
                      (k[ki].item() if isinstance(k[ki], np.generic)
                       else k[ki]) for k in gkeys])
    for a in aggs:
        name = str(a["out"])
        fn = str(a["fn"])
        vals = agg_vals(a)
        cells, vcs = [], []
        for rows in gidx:
            if fn == "count":
                if vals is None:
                    cells.append(int(len(rows)))
                else:
                    cells.append(int((~pd.isna(vals[rows])).sum()))
                vcs.append(0)
                continue
            v = vals[rows]
            ok = ~pd.isna(v)
            nv = v[ok]
            vcs.append(int(len(nv)))
            if len(nv) == 0:
                cells.append(None)
            elif fn in ("sum", "avg"):
                tot = nv.sum()
                cells.append(tot.item() if isinstance(tot, np.generic)
                             else tot)
            elif fn == "min":
                cells.append(nv.min().item())
            else:
                cells.append(nv.max().item())
        out[name] = obj(cells)
        out_cols.append(name)
        if fn == "avg":
            vc_name = "__vc__" + name
            out[vc_name] = np.asarray(vcs, dtype=np.int64)
            out_cols.append(vc_name)
    return out_cols, out


def exec_request(ctx, raw: bytes) -> bytes:
    """Hop 2 on a node: decode an SDJ1 exec frame, run the local join,
    return the partials as an SDW1 frame."""
    spec, sides = WIRE.decode_join_exec(raw)
    cols, data = local_join(spec, sides["probe"], sides["build"])
    return WIRE.encode_result(cols, data, {"rows": int(
        len(data[cols[0]]) if cols else 0)})


# =============================================================================
# broker side
# =============================================================================

def _merge_kind(fn: str) -> str:
    return {"count": "longsum", "sum": "longsum", "avg": "longsum",
            "min": "longmin", "max": "longmax"}[fn]


def execute_partitioned(ctx, plan, spec: dict):
    """Run ``plan`` across the cluster. Returns ``(data, js)`` in the
    same shape the broadcast tier returns (the planner epilogue is
    shared). Raises :class:`JoinUnsupported` on any cluster failure —
    the caller falls back to the local broadcast tier."""
    from spark_druid_olap_tpu.cluster import merge as MG
    cl = ctx.cluster
    if cl is None:
        raise JoinUnsupported("no cluster attached")
    st = cl._active
    n_nodes = len(st.nodes)
    n_parts = int(ctx.config.get(JOIN_PARTITIONS)) or n_nodes
    shuffle = 0
    scatters = 0
    deadline = time.time() + cl.rpc_timeout * 4

    from spark_druid_olap_tpu.cluster.assign import shard_name

    def side_rows(side_key: str, side, flt, ship: List[str],
                  read: List[str]):
        dp = st.plan.datasources.get(side.ds)
        if dp is None:
            raise JoinUnsupported(
                f"datasource {side.ds!r} has no cluster plan")
        keys = [pc for pc, _ in plan.keys] if side_key == "probe" \
            else [bc for _, bc in plan.keys]
        frames = []
        nonlocal shuffle, scatters
        for sh in dp.shards:
            payload = json.dumps({
                "store": shard_name(side.ds, sh.index, dp.n_shards),
                "keys": keys, "ship": ship, "read": read,
                "filter": SERDE.expr_to_dict(flt)
                if flt is not None else None,
                "npartitions": n_parts,
            }, separators=(",", ":")).encode("utf-8")
            err = None
            for nid in sh.owners:
                try:
                    scatters += 1
                    status, resp = cl._guarded_rpc(
                        st, nid, payload, deadline,
                        path="/cluster/join/partition")
                except Exception as e:          # breaker open / IO
                    err = e
                    continue
                if status != 200:
                    err = JoinUnsupported(
                        f"partition rpc {status}: "
                        f"{WIRE.decode_error(resp).get('message')}")
                    continue
                shuffle += len(resp)
                try:
                    cols, data, _ = WIRE.decode_result(resp)
                except ValueError as e:
                    err = e
                    continue
                frames.append((cols, data))
                err = None
                break
            if err is not None:
                raise JoinUnsupported(
                    f"partition hop failed for {side.ds!r} shard "
                    f"{sh.index}: {err}")
        return frames

    probe_ship = sorted({phys for q, (s, phys) in plan.colside.items()
                         if s == "probe"}
                        | {pc for pc, _ in plan.keys})
    build_ship = sorted({phys for q, (s, phys) in plan.colside.items()
                         if s == "build"}
                        | {bc for _, bc in plan.keys})
    pframes = side_rows("probe", plan.probe, plan.probe_filter,
                        probe_ship, sorted(plan.probe_cols()))
    bframes = side_rows("build", plan.build, plan.build_filter,
                        build_ship, sorted(plan.build_cols()))

    def split(frames, cols: List[str]):
        parts = [{c: [] for c in cols} for _ in range(n_parts)]
        for fcols, data in frames:
            pid = np.asarray(data["__part__"], dtype=np.int64)
            for p in range(n_parts):
                m = pid == p
                if not m.any():
                    continue
                for c in cols:
                    parts[p][c].append(np.asarray(data[c])[m])
        out = []
        for p in range(n_parts):
            out.append({c: (np.concatenate(v) if v else
                            np.zeros(0, dtype=object))
                        for c, v in parts[p].items()})
        return out

    pparts = split(pframes, probe_ship)
    bparts = split(bframes, build_ship)

    partials = []
    for p in range(n_parts):
        nid = p % n_nodes
        payload = WIRE.encode_join_exec(
            spec, {"probe": (probe_ship, pparts[p]),
                   "build": (build_ship, bparts[p])})
        shuffle += len(payload)
        scatters += 1
        try:
            status, resp = cl._guarded_rpc(
                st, nid, payload, deadline, path="/cluster/join/exec")
        except Exception as e:
            raise JoinUnsupported(f"exec hop failed on node {nid}: {e}")
        if status != 200:
            raise JoinUnsupported(
                f"exec rpc {status} on node {nid}: "
                f"{WIRE.decode_error(resp).get('message')}")
        try:
            _, data, _ = WIRE.decode_result(resp)
        except ValueError as e:
            raise JoinUnsupported(f"exec hop bad frame: {e}")
        partials.append(data)

    mg_aggs = []
    for s in plan.aggs:
        mg_aggs.append((s.out, _merge_kind(s.fn)))
        if s.fn == "avg":
            mg_aggs.append(("__vc__" + s.out, "longsum"))
    _, merged, n_rows = MG.merge_partials(partials, list(plan.group_by),
                                          mg_aggs)
    data: Dict[str, np.ndarray] = {}
    for g in plan.group_by:
        data[g] = merged[g]
    for s in plan.aggs:
        col = merged[s.out]
        if s.fn == "avg":
            vc = np.asarray(merged["__vc__" + s.out], dtype=np.float64)
            tot = np.asarray([np.nan if v is None else float(v)
                              for v in col.tolist()], dtype=np.float64) \
                if col.dtype == object else col.astype(np.float64)
            data[s.out] = np.where(vc > 0, tot / np.maximum(vc, 1),
                                   np.nan)
        elif s.fn == "count":
            data[s.out] = np.zeros(n_rows, dtype=np.int64) \
                if col.dtype == object and n_rows == 0 \
                else np.asarray([0 if v is None else v
                                 for v in col.tolist()],
                                dtype=np.int64) \
                if col.dtype == object else col.astype(np.int64)
        else:
            data[s.out] = col
    if not plan.group_by and n_rows == 0:
        for s in plan.aggs:
            data[s.out] = np.asarray(
                [0] if s.fn == "count" else [np.nan])
    with cl._lock:
        cl.counters["join_scatters"] += scatters
        cl.counters["join_shuffle_bytes"] += shuffle
    stats = {
        "mode": "partitioned",
        "partitions": int(n_parts),
        "nodes": int(n_nodes),
        "scatters": int(scatters),
        "build_rows": int(sum(len(next(iter(b.values()), []))
                              for b in bparts if b)),
        "groups": int(len(data[plan.group_by[0]]) if plan.group_by
                      else 1),
    }
    stats["shuffle_bytes"] = int(shuffle)
    return data, stats
