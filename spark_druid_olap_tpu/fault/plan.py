"""Deterministic, seed-reproducible fault injection.

A :class:`FaultPlan` is a seeded schedule of :class:`FaultRule`\\ s parsed
from the ``sdot.fault.plan`` config key (JSON). Each rule names an
injection *site* (a string the instrumented code passes at the call
point, e.g. ``"rpc.connect"``), an optional ``match`` substring applied
to the site's *key* (e.g. ``"node:1"``), and an *action*:

- ``error``  — raise an exception (``arg`` names the class; default
  :class:`FaultInjected`) at a ``fire()`` site
- ``delay``  — sleep ``arg`` seconds at a ``fire()`` or ``mutate()`` site
- ``truncate`` — drop the last ``arg`` bytes at a ``mutate()`` site
- ``flip``   — XOR one seeded-random byte at a ``mutate()`` site

Rules carry ``p`` (fire probability), ``count`` (max fires; ``null`` =
unlimited), ``after`` (matching evaluations to skip first), and an
optional ``scope`` name: scoped rules only fire while a matching
:meth:`FaultInjector.scope` is open, which lets one long-lived context
run several chaos legs from a single plan.

Determinism: every rule gets its own ``random.Random`` seeded from
``(plan seed, rule index)``, so ``count``/``after`` rules are exact and
``p`` rules replay statistically from the seed (thread interleaving can
reorder which *evaluation* draws which number, but the draw sequence per
rule is fixed). Injection sites are zero-cost no-ops when no plan is
configured — callers hold ``inj = <owner>.fault`` and guard on ``None``.

The full site catalog lives in ``docs/CHAOS.md``. The durability sites
deserve a note here because their *placement* is the contract:
``wal.group_commit`` fires in the group-commit leader after the batch's
writes but before the covering fsync (so an injected crash leaves every
frame in the batch un-acked — none may survive as committed);
``compact.publish`` fires before the compacted generation's snapshot is
written (an injected crash must leave the OLD generation fully readable
with the WAL untouched); ``hist.ingest`` / ``rpc.ingest`` sit on the
distributed-ingest push path, where the broker's local journal — not
the push — is the durability point, so injected failures may only
affect read-your-writes scatter eligibility, never ACKed data.
"""

from __future__ import annotations

import json
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from ..utils.config import FAULT_PLAN

_ACTIONS = ("error", "delay", "truncate", "flip")


class FaultInjected(Exception):
    """Default exception raised by an ``error`` rule with no ``arg``."""


@dataclass(frozen=True)
class FaultRule:
    """One line of a fault schedule; see the module docstring."""
    site: str
    match: str = ""
    action: str = "error"
    arg: object = None
    p: float = 1.0
    count: int | None = None
    after: int = 0
    scope: str | None = None

    def __post_init__(self):
        if not self.site:
            raise ValueError("fault rule needs a non-empty 'site'")
        if self.action not in _ACTIONS:
            raise ValueError(
                f"fault rule action {self.action!r} not in {_ACTIONS}")
        if not (0.0 <= float(self.p) <= 1.0):
            raise ValueError(f"fault rule p={self.p} outside [0, 1]")


@dataclass(frozen=True)
class FaultPlan:
    """A seed plus an ordered tuple of rules."""
    seed: int
    rules: tuple

    @classmethod
    def parse(cls, text):
        """Parse the ``sdot.fault.plan`` JSON document."""
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError("fault plan must be a JSON object")
        known = {"site", "match", "action", "arg", "p", "count", "after",
                 "scope"}
        rules = []
        for i, r in enumerate(doc.get("rules", ())):
            extra = set(r) - known
            if extra:
                raise ValueError(
                    f"fault rule {i}: unknown fields {sorted(extra)}")
            rules.append(FaultRule(
                site=str(r.get("site", "")),
                match=str(r.get("match", "") or ""),
                action=str(r.get("action", "error")),
                arg=r.get("arg"),
                p=float(r.get("p", 1.0)),
                count=None if r.get("count") is None else int(r["count"]),
                after=int(r.get("after", 0)),
                scope=r.get("scope")))
        return cls(seed=int(doc.get("seed", 0)), rules=tuple(rules))


def _build_exc(name, site):
    """Map a rule's ``arg`` class name to an exception instance."""
    msg = f"fault-injected {name or 'FaultInjected'} at {site}"
    table = {
        None: FaultInjected,
        "FaultInjected": FaultInjected,
        "OSError": OSError,
        "ConnectionRefusedError": ConnectionRefusedError,
        "ConnectionResetError": ConnectionResetError,
        "TimeoutError": TimeoutError,
        "ValueError": ValueError,
    }
    if name == "LaneFullError":
        from ..wlm.admit import LaneFullError
        return LaneFullError(msg, retry_after_s=0.01)
    if name not in table:
        raise ValueError(f"fault rule arg {name!r} is not a known exception")
    return table[name](msg)


class FaultInjector:
    """Evaluates a :class:`FaultPlan` at named injection sites.

    Threaded through the stack as a ``.fault`` attribute (engine, broker,
    historical, WAL, tier store, WLM); every site guards on ``None`` so
    the un-injected hot path pays nothing.
    """

    def __init__(self, plan):
        self._lock = threading.Lock()   # leaf: never calls out while held
        self.plan = plan
        n = len(plan.rules)
        self._rngs = [random.Random((plan.seed << 16) ^ (i * 1000003 + 1))
                      for i in range(n)]
        self._evals = [0] * n
        self._fired = [0] * n
        self._scopes = {}               # scope name -> open depth

    # -- scope activation tokens (sdlint leaks pair: fault-scope) ---------
    def begin_scope(self, name):
        """Activate rules tagged ``scope: name``; returns a token for
        :meth:`end_scope`. Prefer the :meth:`scope` context manager."""
        with self._lock:
            self._scopes[name] = self._scopes.get(name, 0) + 1
        return name

    def end_scope(self, token):
        with self._lock:
            d = self._scopes.get(token, 0) - 1
            if d <= 0:
                self._scopes.pop(token, None)
            else:
                self._scopes[token] = d

    @contextmanager
    def scope(self, name):
        tok = self.begin_scope(name)
        try:
            yield tok
        finally:
            self.end_scope(tok)

    # -- evaluation -------------------------------------------------------
    def _decide(self, site, key):
        """Indices of rules that fire for this evaluation (under lock)."""
        hits = []
        with self._lock:
            for i, r in enumerate(self.plan.rules):
                if r.site != site:
                    continue
                if r.match and r.match not in (key or ""):
                    continue
                if r.scope is not None and not self._scopes.get(r.scope):
                    continue
                self._evals[i] += 1
                if self._evals[i] <= r.after:
                    continue
                if r.count is not None and self._fired[i] >= r.count:
                    continue
                if r.p < 1.0 and self._rngs[i].random() >= r.p:
                    continue
                self._fired[i] += 1
                hits.append(i)
        return hits

    def fire(self, site, key=None):
        """Evaluate ``fire``-style rules: ``delay`` sleeps, ``error``
        raises. Byte-mutation actions are ignored here."""
        for i in self._decide(site, key):
            r = self.plan.rules[i]
            if r.action == "delay":
                time.sleep(float(r.arg or 0.01))
            elif r.action == "error":
                raise _build_exc(r.arg, site)

    def mutate(self, site, data, key=None):
        """Evaluate ``mutate``-style rules against a byte payload;
        returns ``data`` itself (same object) when nothing fired."""
        for i in self._decide(site, key):
            r = self.plan.rules[i]
            if r.action == "truncate":
                data = data[:max(0, len(data) - int(r.arg or 1))]
            elif r.action == "flip":
                if len(data):
                    j = self._rngs[i].randrange(len(data))
                    data = data[:j] + bytes([data[j] ^ 0xFF]) + data[j + 1:]
            elif r.action == "delay":
                time.sleep(float(r.arg or 0.01))
        return data

    def stats(self):
        """Snapshot for ``last_stats["fault"]`` / chaos reports."""
        with self._lock:
            by_site = {}
            for i, r in enumerate(self.plan.rules):
                if self._fired[i]:
                    by_site[r.site] = by_site.get(r.site, 0) + self._fired[i]
            return {"seed": self.plan.seed, "rules": len(self.plan.rules),
                    "fired": sum(self._fired), "by_site": by_site,
                    "scopes": sorted(self._scopes)}

    @classmethod
    def from_config(cls, config):
        """Build from ``sdot.fault.plan``; ``None`` when unset."""
        text = str(config.get(FAULT_PLAN) or "").strip()
        if not text:
            return None
        return cls(FaultPlan.parse(text))
