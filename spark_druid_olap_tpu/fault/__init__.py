"""Deterministic fault injection (chaos) — see docs/CHAOS.md."""

from .plan import FaultInjected, FaultInjector, FaultPlan, FaultRule

__all__ = ["FaultInjected", "FaultInjector", "FaultPlan", "FaultRule"]
