"""Shared-scan multi-query execution: coalesce concurrent eligible queries
over one datasource into ONE fused device program.

The BI-dashboard storm the reference system was built for is K small
concurrent star-schema queries over the *same* columns; executed solo,
they pay K× scan bandwidth and K× dispatch overhead (each tunneled
round-trip costs the dispatch floor). Classic shared-scan / fused-
operator results (Flare, arxiv 1703.08219; Theseus, arxiv 2508.05029)
say the win is multiplicative with concurrency, so this tier converts
concurrency into a throughput multiplier instead of a queue:

- The first eligible query on a datasource becomes the *leader* of an
  open group and holds for ``sdot.wlm.batch.window.ms`` (group-commit
  semantics; held time counts against the query's own timeout).
- Companions arriving inside the window join as *followers* and park.
- At close, the leader plans every constituent, binds the COLUMN UNION
  of the group once per segment wave (through the engine's shared
  device-array cache), runs one fused program evaluating every
  constituent's filter mask + aggregation lanes against the shared
  in-HBM bind, and demultiplexes per-query results.
- Every constituent that cannot ride the fused program (hashed-tier
  cardinality, sketch-over-unsupported, empty pruning, host residual)
  falls back to its own solo execution on its own thread — coalescing
  is an optimization, never a semantics change.

Cache interaction: the coalescer runs *under* the result-cache layer
(QueryEngine._execute_admitted), so each constituent still populates /
serves the semantic cache under its own canonical key.

Fused-program shape: one ``ScanContext`` over the union bind; per-lane
``base = row_valid & filter & interval`` masks feed per-lane
``dense_groupby`` calls; outputs pack through the engine's existing
two-buffer packers per lane. ``row_valid`` travels IN the bound arrays
(ops/scan.py), so the compiled program is segment-selection independent
and keys the compile cache on the sorted tuple of constituent plan
signatures — a warm dashboard mix reuses one executable.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ops import filters as F
from spark_druid_olap_tpu.ops import groupby as G
from spark_druid_olap_tpu.ops import hll as HLL
from spark_druid_olap_tpu.ops import kll as KLL
from spark_druid_olap_tpu.ops import theta as TH
from spark_druid_olap_tpu.ops import pallas_wave as PW
from spark_druid_olap_tpu.ops import time_ops as T
from spark_druid_olap_tpu.ops.scan import ScanContext, array_dtype, array_names
from spark_druid_olap_tpu.parallel import cost as C
from spark_druid_olap_tpu.parallel import mesh as M
from spark_druid_olap_tpu.parallel import meshexec as MX
from spark_druid_olap_tpu.planner import fusion as FU
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.utils import phases as PH
from spark_druid_olap_tpu.utils.config import (
    GROUPBY_DENSE_MAX_KEYS,
    GROUPBY_MATMUL_MAX_KEYS,
    HLL_LOG2M,
    PALLAS_WAVE_ENABLED,
    PALLAS_WAVE_MAX_LANES,
    PALLAS_WAVE_TILE_BYTES,
    QUANTILE_LANES,
    SHAREDSCAN_ENABLED,
    SHAREDSCAN_FUSION_ENABLED,
    SHAREDSCAN_FUSION_MAX_NODES,
    SHAREDSCAN_MAX_QUERIES,
    TZ_ID,
    WLM_BATCH_WINDOW_MS,
)

# a member's outcome slot: None = pending, _FALLBACK = run solo on the
# member's own thread, an exception instance = raise it there, anything
# else = the demultiplexed QueryResult
_FALLBACK = object()

# how often a parked follower re-checks its own cancel flag / deadline
# while waiting for the leader to deliver
_WAIT_POLL_S = 0.02


class _Member:
    __slots__ = ("q", "t0", "leader", "event", "outcome", "stats", "tok")

    def __init__(self, q, t0, leader: bool, tok=None):
        self.q = q
        self.t0 = t0
        self.leader = leader
        self.event = threading.Event()
        self.outcome = None
        self.stats = None
        self.tok = tok


class _Group:
    __slots__ = ("gid", "ds_name", "members", "state", "close_ev")

    def __init__(self, gid: int, ds_name: str):
        self.gid = gid
        self.ds_name = ds_name
        self.members: List[_Member] = []
        self.state = "open"          # open -> closing -> closed
        self.close_ev = threading.Event()


class _LanePlan:
    """One fused-program lane: the planned form of one distinct
    constituent spec (members sharing a plan signature share a lane)."""

    __slots__ = ("q", "sig", "dims", "aggs", "post", "having", "limit",
                 "gran", "seg", "dim_plans", "agg_plans", "n_keys",
                 "routes", "needed", "time_in_play", "names")

    def __init__(self, q, sig, dims, aggs, post, having, limit, gran, seg):
        self.q = q
        self.sig = sig
        self.dims = dims
        self.aggs = aggs
        self.post = post
        self.having = having
        self.limit = limit
        self.gran = gran
        self.seg = seg


class SharedScanCoalescer:
    """One per QueryEngine. ``run`` replaces ``_execute_inner`` for
    eligible queries; everything ineligible (or racing a closed group)
    degrades to the solo path."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._groups: Dict[str, _Group] = {}
        self._next_gid = 0
        # monotone global counters (GET /metadata/wlm, loadtest)
        self.groups_coalesced = 0     # groups that ran >= 2 fused lanes
        self.solo_groups = 0          # window expired with one live member
        self.queries_coalesced = 0    # constituents served by fused runs
        self.fallbacks = 0            # members bounced to solo execution
        self.binds_saved_bytes = 0
        self.dispatches_saved = 0
        self.wlm_handoffs = 0         # queued waiters bypassed into groups
        # fusion planner (planner/fusion.py) — deterministic plan-time
        # counters, ticked on EVERY fused run (warm program cache too)
        self.fusion_groups = 0          # fused runs that planned CSE
        self.fusion_fallbacks = 0       # planning errors -> unfused lowering
        self.fusion_shared_predicates = 0
        self.fusion_predicate_evals_saved = 0
        self.fusion_predicate_evals_total = 0
        self.fusion_column_streams_saved = 0
        # solo-path CSE (parallel/executor.py threads the same cache
        # through the dense/hashed cores; one query's tree can repeat
        # sub-predicates, e.g. OR-of-bounds over one column)
        self.fusion_solo_evals_saved = 0
        self.fusion_solo_evals_total = 0
        # pallas wave mega-kernel (ops/pallas_wave.py): one hand-
        # scheduled kernel launch per dispatch wave when the group is
        # wave-eligible; fallbacks count build-time lowerings back to
        # the jaxpr program (routing tiers unchanged)
        self.pallas_launches = 0
        self.pallas_tiles = 0
        self.pallas_fallbacks = 0
        self.pallas_vmem_peak = 0
        # multi-chip mesh tier (parallel/meshexec.py): fused groups whose
        # segment waves sharded across the local device mesh, with
        # per-device partials merged on the interconnect. Fallback
        # reasons mirror the docs/MESH.md matrix; collective_bytes is
        # the STATIC route-metadata accounting (the mesh lint pass
        # forbids measuring inside shard bodies)
        self.mesh_groups = 0            # fused groups dispatched sharded
        self.mesh_dispatches = 0        # sharded wave dispatches
        self.mesh_collective_bytes = 0  # est. interconnect merge bytes
        self.mesh_fallbacks: Dict[str, int] = {}   # reason -> groups

    # -- eligibility -----------------------------------------------------------
    def enabled(self) -> bool:
        return bool(self.engine.config.get(SHAREDSCAN_ENABLED))

    def should_try(self, q) -> bool:
        """Cheap pre-gate: spec shapes the fused tier can demultiplex.
        Select (pagination state) and Search never coalesce; neither does
        anything when the backend is lost (the host tier is serving)."""
        if not self.enabled():
            return False
        if self.engine._backend_lost_at is not None:
            return False
        return isinstance(q, (S.GroupByQuerySpec, S.TimeseriesQuerySpec,
                              S.TopNQuerySpec))

    def open_group_hint(self, datasource) -> bool:
        """True when an open group on ``datasource`` still has room — the
        WLM poll loop uses this to hand a queued compatible query to the
        coalescer instead of draining it serially."""
        if not self.enabled() or datasource is None:
            return False
        maxq = int(self.engine.config.get(SHAREDSCAN_MAX_QUERIES))
        with self._lock:
            g = self._groups.get(datasource)
            return g is not None and g.state == "open" \
                and len(g.members) < maxq

    # -- group membership ------------------------------------------------------
    def run(self, q, t0: float) -> QueryResult:
        """Join (or lead) the open group for q's datasource; return the
        demultiplexed result, or fall back to solo execution."""
        eng = self.engine
        window_s = max(0.0,
                       float(eng.config.get(WLM_BATCH_WINDOW_MS)) / 1000.0)
        maxq = max(1, int(eng.config.get(SHAREDSCAN_MAX_QUERIES)))
        tok = getattr(eng._tls, "inflight_tok", None)
        with self._lock:
            g = self._groups.get(q.datasource)
            if g is not None and g.state == "open" and len(g.members) < maxq:
                m = _Member(q, t0, leader=False, tok=tok)
                g.members.append(m)
                if len(g.members) >= maxq:
                    g.state = "closing"
                    g.close_ev.set()
            else:
                self._next_gid += 1
                g = _Group(self._next_gid, q.datasource)
                m = _Member(q, t0, leader=True, tok=tok)
                g.members.append(m)
                self._groups[q.datasource] = g

        if m.leader:
            self._hold_window(g, m, window_s)
            with self._lock:
                g.state = "closed"
                if self._groups.get(q.datasource) is g:
                    del self._groups[q.datasource]
                members = list(g.members)
            self._close_group(g, members)
        else:
            while not m.event.wait(_WAIT_POLL_S):
                # honors the follower's OWN cancel/timeout while parked;
                # a late delivery into an abandoned slot is harmless
                eng._stage_check(q, t0)

        out = m.outcome
        if out is _FALLBACK:
            return eng._execute_inner(q, t0)
        if isinstance(out, BaseException):
            raise out
        if m.stats:
            eng.last_stats.update(m.stats)
        eng.last_stats["total_ms"] = (_time.perf_counter() - t0) * 1000
        return out

    def _hold_window(self, g: _Group, m: _Member, window_s: float) -> None:
        """Leader parks for the micro-batch window (early close when the
        group fills, or when the leader's own cancel/deadline fires —
        held time counts against timeout_millis)."""
        deadline = _time.perf_counter() + window_s
        while not g.close_ev.is_set():
            rem = deadline - _time.perf_counter()
            if rem <= 0:
                break
            g.close_ev.wait(min(rem, 0.005))
            try:
                self.engine._stage_check(m.q, m.t0)
            except BaseException:
                break   # close now; _close_group re-checks and drops us

    def _close_group(self, g: _Group, members: List[_Member]) -> None:
        """Runs on the leader's thread. Every member gets an outcome and
        (followers) a set event, no matter what — a fused-path crash
        degrades the whole group to solo execution, never a hang."""
        eng = self.engine
        live = []
        for m in members:
            try:
                eng._stage_check(m.q, m.t0)
                live.append(m)
            except BaseException as e:  # noqa: BLE001 — delivered as outcome
                m.outcome = e           # cancelled/timed out while held:
                #                         drops out before execution
        fused_tried = len(live) >= 2
        try:
            if fused_tried:
                self._run_fused(g, live)
            else:
                with self._lock:
                    self.solo_groups += 1
        except BaseException:  # noqa: BLE001 — degrade, don't strand
            pass
        finally:
            n_fallback = 0
            for m in members:
                if m.outcome is None:
                    m.outcome = _FALLBACK
                    if fused_tried:
                        n_fallback += 1
                if not m.leader:
                    m.event.set()
            if n_fallback:
                with self._lock:
                    self.fallbacks += n_fallback

    # -- fused planning + execution -------------------------------------------
    def _run_fused(self, g: _Group, live: List[_Member]) -> None:
        """Plan every live member against the union segment selection,
        build/fetch ONE fused program keyed on the sorted tuple of lane
        signatures, bind the column union once per wave, dispatch, and
        demultiplex. Members that cannot ride stay at _FALLBACK."""
        from spark_druid_olap_tpu.parallel import executor as X
        eng = self.engine
        ds_name = live[0].q.datasource
        try:
            ds = eng.store.get(ds_name)
        except Exception:  # noqa: BLE001 — solo path reports the real error
            return
        if getattr(ds, "is_partial", False) or ds.num_rows == 0:
            return

        shaped = []
        for m in live:
            lp = self._shape_member(eng, ds, m.q)
            if lp is not None:
                shaped.append((m, lp))
        if len(shaped) < 2:
            return

        seg_u = np.unique(np.concatenate([lp.seg for _, lp in shaped]))
        mins, maxs = ds.segment_time_bounds()
        min_day = int(mins[seg_u].min() // T.MILLIS_PER_DAY)
        max_day = int(maxs[seg_u].max() // T.MILLIS_PER_DAY)

        planned = []
        for m, lp in shaped:
            if self._plan_lane(eng, ds, lp, min_day, max_day):
                planned.append((m, lp))
        if len(planned) < 2:
            return

        # dedup identical specs into shared lanes, sorted by signature so
        # the compile-cache key is order-independent across arrivals
        by_sig: Dict[str, _LanePlan] = {}
        for _, lp in planned:
            by_sig.setdefault(lp.sig, lp)
        sigs = tuple(sorted(by_sig))
        lanes = [by_sig[s] for s in sigs]
        lane_idx = {s: i for i, s in enumerate(sigs)}

        union_cols = sorted(set().union(*[lp.needed for lp in lanes]))
        union_time = any(lp.time_in_play for lp in lanes)
        union_names = array_names(ds, union_cols, union_time)
        seg_bytes = C.bytes_per_segment(ds, union_names)
        # mesh tier (parallel/meshexec.py): static precheck; any
        # disqualifying condition falls back to single-device with a
        # named reason. The decision shapes the traced program AND the
        # wave plan (per-device budgets multiply by n_dev)
        dec = MX.decide(eng, ds, lanes, len(seg_u))
        n_dev = dec.n_dev
        spw, n_waves = C.plan_waves(
            len(seg_u), n_dev, seg_bytes, C.wave_budget_bytes(eng.config),
            eng.config, max(lp.n_keys for lp in lanes),
            sum(len(lp.agg_plans) for lp in lanes),
            io_budget=C.tier_io_budget(ds, eng.config))
        s_pad = spw if n_waves > 1 else X._pad_segments(len(seg_u), n_dev)

        # fusion planning is advisory: any error lowers the unfused way
        # (routing tiers never change). Runs on EVERY fused execution —
        # warm program-cache runs included — so the counters below are
        # deterministic and CI-guardable without a chip.
        fplan = None
        if bool(eng.config.get(SHAREDSCAN_FUSION_ENABLED)):
            try:
                fplan = FU.plan_lanes(
                    [(lp.q.filter, lp.q.intervals,
                      tuple(a.filter for a in lp.aggs)) for lp in lanes],
                    per_lane_cols=[len(lp.needed) for lp in lanes],
                    union_cols=len(union_cols),
                    max_nodes=int(
                        eng.config.get(SHAREDSCAN_FUSION_MAX_NODES)))
            except Exception:  # noqa: BLE001 — fall back to unfused
                fplan = None
                with self._lock:
                    self.fusion_fallbacks += 1

        wave_ok = bool(eng.config.get(PALLAS_WAVE_ENABLED)) \
            and PW.wave_eligible(
                lanes, int(eng.config.get(PALLAS_WAVE_MAX_LANES)))

        sig = ("aggmulti", ds.name, id(ds), s_pad, ds.padded_rows,
               min_day, max_day, tuple(union_names),
               eng.config.get(TZ_ID),
               eng.config.get(GROUPBY_MATMUL_MAX_KEYS),
               eng.config.get(HLL_LOG2M),
               eng.config.get(QUANTILE_LANES), jax.default_backend(),
               bool(jax.config.jax_enable_x64), sigs,
               # the fusion plan shapes the traced program: the token is
               # a pure function of the sorted lane set (arrival-order
               # independent), None when planning declined or failed
               bool(eng.config.get(SHAREDSCAN_FUSION_ENABLED)),
               int(eng.config.get(SHAREDSCAN_FUSION_MAX_NODES)),
               fplan.token() if fplan is not None else None,
               # wave mega-kernel routing: eligibility is re-derived on
               # EVERY fused execution from plan metadata + env + config,
               # so a config flip or backend change re-keys the program
               wave_ok,
               bool(eng.config.get(PALLAS_WAVE_ENABLED)),
               int(eng.config.get(PALLAS_WAVE_TILE_BYTES)),
               int(eng.config.get(PALLAS_WAVE_MAX_LANES)),
               # mesh decision re-derived on EVERY fused execution (a
               # sdot.mesh.* flip, device-count change, or cost-model
               # swing re-keys the program — sdlint K1)
               dec.sig_fields())

        def _build():
            """Wave first (one pallas launch per wave), jaxpr-fused on
            any lowering reject — the group stays FUSED either way, so
            the wave path can never change routing tiers."""
            if wave_ok:
                try:
                    return self._build_wave_program(
                        ds, lanes, min_day, max_day, fplan,
                        union_names=union_names, s_pad=s_pad,
                        mesh_dec=dec)
                except Exception:  # noqa: BLE001 — WaveFallback + lowering errors
                    with self._lock:
                        self.pallas_fallbacks += 1
            fn, unp = self._build_fused_program(ds, lanes, min_day,
                                                max_day, fplan,
                                                mesh_dec=dec)
            return fn, unp, None

        prog_fn, unpacks, wave_info = eng._cached_program(sig, _build)

        per_lane_finals = self._dispatch(ds, union_names, seg_u, s_pad,
                                         spw, n_waves, prog_fn, unpacks,
                                         lanes, live[0],
                                         wave_info=wave_info,
                                         mesh_dec=dec)
        results = [self._decode_lane(eng, ds, lp, fin)
                   for lp, fin in zip(lanes, per_lane_finals)]

        solo_bytes = sum(
            C.bytes_per_segment(ds, lp.names) * len(lp.seg)
            for _, lp in planned)
        saved_bytes = max(0, solo_bytes - int(seg_bytes) * len(seg_u))
        saved_disp = (len(planned) - 1) * n_waves
        wave_tiles = 0
        if wave_info is not None:
            wave_tiles = -(-(s_pad * ds.padded_rows)
                           // (wave_info["block_rows"] * PW.LANES))
        # per-device kernel launches: each mesh shard runs its own wave
        # kernel over its segment slice
        launches = n_waves * (n_dev if dec.sharded else 1)
        cbytes = MX.collective_bytes(eng, lanes, n_dev) * n_waves \
            if dec.sharded else 0
        with self._lock:
            self.groups_coalesced += 1
            if dec.sharded:
                self.mesh_groups += 1
                self.mesh_dispatches += n_waves
                self.mesh_collective_bytes += cbytes
            else:
                self.mesh_fallbacks[dec.reason] = \
                    self.mesh_fallbacks.get(dec.reason, 0) + 1
            if wave_info is not None:
                self.pallas_launches += launches
                # total tiles are launch-count invariant: the mesh splits
                # the SAME [s_pad x rows] scan across devices
                self.pallas_tiles += n_waves * wave_tiles
                self.pallas_vmem_peak = max(self.pallas_vmem_peak,
                                            wave_info["vmem_bytes"])
            self.queries_coalesced += len(planned)
            self.binds_saved_bytes += saved_bytes
            self.dispatches_saved += saved_disp
            if fplan is not None:
                self.fusion_groups += 1
                self.fusion_shared_predicates += fplan.shared_predicates
                self.fusion_predicate_evals_saved += \
                    fplan.predicate_evals_saved
                self.fusion_predicate_evals_total += fplan.n_nodes
                self.fusion_column_streams_saved += \
                    fplan.column_streams_saved

        for m, lp in planned:
            li = lane_idx[lp.sig]
            fin = per_lane_finals[li]
            m.stats = {
                "datasource": ds.name, "segments": int(len(lp.seg)),
                "sharded": bool(dec.sharded),
                "rows_scanned": int(ds.num_rows),
                "groups": int(np.count_nonzero(fin["__rows__"] > 0)),
                "waves": int(n_waves), "segments_per_wave": int(spw),
                "bytes_scanned": int(seg_bytes) * int(len(seg_u)),
                "mesh": {"devices": int(n_dev),
                         "decision": dec.reason,
                         "collective_bytes": int(cbytes)},
                "sharedscan": {
                    "group": g.gid, "queries": len(planned),
                    "lanes": len(lanes),
                    "role": "leader" if m.leader else "follower",
                    "binds_saved_bytes": saved_bytes,
                    "dispatches_saved": saved_disp,
                    "fusion": (fplan.counters()
                               if fplan is not None else None),
                    "pallas": ({"launches": int(launches),
                                "tiles": int(n_waves * wave_tiles),
                                "block_rows": wave_info["block_rows"],
                                "vmem_bytes": wave_info["vmem_bytes"]}
                               if wave_info is not None else None)}}
            m.outcome = results[li]
            eng.inflight.annotate(m.tok, sharedscan_group=g.gid)

    @staticmethod
    def _shape_member(eng, ds, q) -> Optional[_LanePlan]:
        """Map the spec to the engine's (dims, aggs, post, having, limit,
        gran) shape (mirrors _execute_inner) + prune segments. None =
        this member runs solo (e.g. empty pruning takes the engine's own
        empty/identity-row path, which never touches the device)."""
        from spark_druid_olap_tpu.parallel.executor import _cache_repr
        try:
            if isinstance(q, S.GroupByQuerySpec):
                dims, having, limit = list(q.dimensions), q.having, q.limit
            elif isinstance(q, S.TimeseriesQuerySpec):
                dims, having, limit = [], None, None
            elif isinstance(q, S.TopNQuerySpec):
                dims, having = [q.dimension], None
                limit = S.LimitSpec(
                    (S.OrderByColumn(q.metric, ascending=False),),
                    q.threshold)
            else:
                return None
            seg = ds.prune_segments(q.intervals, q.filter)
            if len(seg) == 0:
                return None
            return _LanePlan(q, _cache_repr(q), dims, q.aggregations,
                             q.post_aggregations, having, limit,
                             q.granularity, seg)
        except Exception:  # noqa: BLE001 — solo path reports the real error
            return None

    @staticmethod
    def _plan_lane(eng, ds, lp: _LanePlan, min_day: int,
                   max_day: int) -> bool:
        """Detailed planning against the GROUP's min/max day (every lane
        must share one ScanContext day basis). False = member falls back
        (hashed-tier cardinality, unsupported aggregation, wide ints on a
        32-bit backend — everything the solo path handles specially)."""
        from spark_druid_olap_tpu.parallel import executor as X
        from spark_druid_olap_tpu.utils import config as CF
        try:
            gran_kind = lp.gran.kind if lp.gran else "all"
            tz = eng.config.get(TZ_ID)
            dim_plans = [X.plan_dimension(d, ds, min_day, max_day, tz)
                         for d in lp.dims]
            if gran_kind != "all":
                dim_plans = [X.plan_granularity_dim(
                    lp.gran, ds, min_day, max_day, tz)] + dim_plans
            agg_plans = [X.plan_aggregation(a, ds) for a in lp.aggs]
            n_keys = 1
            for p in dim_plans:
                n_keys *= p.card
            if n_keys > eng.config.get(GROUPBY_DENSE_MAX_KEYS):
                return False    # hashed tier: solo handles it
            min_k = int(eng.config.get(CF.GROUPBY_SORTED_MIN_KEYS))
            if min_k > 0 and n_keys >= min_k \
                    and not any(p.kind in ("hll", "theta", "kll")
                                for p in agg_plans) \
                    and eng._sorted_run_wanted():
                return False    # medium-K reroute territory: keep parity
            needed = set()
            for p in dim_plans:
                needed |= set(p.source_cols)
            for p in agg_plans:
                needed |= set(p.source_cols)
            needed |= F.columns_of_filter(lp.q.filter)
            time_in_play = ds.time is not None and (
                lp.q.intervals is not None or gran_kind != "all"
                or ds.time.name in needed)
            if time_in_play:
                needed.add(ds.time.name)
            names = array_names(ds, sorted(needed), time_in_play)
            if not G._x64():
                for k in names:
                    if array_dtype(ds, k) == np.int64:
                        return False   # wide ints on a 32-bit backend
            lp.dim_plans = dim_plans
            lp.agg_plans = agg_plans
            lp.n_keys = n_keys
            lp.routes = eng._plan_routes(agg_plans, n_keys, ds)
            lp.needed = needed
            lp.time_in_play = time_in_play
            lp.names = names
            return True
        except Exception:  # noqa: BLE001 — solo path reports the real error
            return False

    def _build_fused_program(self, ds, lanes: List[_LanePlan],
                             min_day: int, max_day: int, fplan=None,
                             mesh_dec=None):
        """(jit_fn, [per-lane unpack]). One ScanContext over the union
        bind; each lane is the engine's dense core (mask -> fused keys ->
        dense_groupby -> sketch registers) packed through its own
        two-buffer packers, so per-lane decode reuses the solo path
        byte-for-byte. With a sharded mesh decision the same per-lane
        core wraps in ``shard_map`` (parallel/meshexec.py): each device
        scans its segment slice and partials merge on the interconnect
        before packing — unpack/decode stay byte-for-byte shared.

        With a fusion plan, the program is single-pass with predicate
        CSE: cross-lane shared masks lower FIRST (each union column
        streams through VMEM once while they compute), then every lane's
        ``base = row_valid & shared & residual`` combine reuses them via
        the trace-time CSE cache — bit-identical to the unfused trace
        because masks only combine with exact bool ops."""
        eng = self.engine
        matmul_max = eng.config.get(GROUPBY_MATMUL_MAX_KEYS)
        log2m = eng.config.get(HLL_LOG2M)
        kll_lanes = eng.config.get(QUANTILE_LANES)
        tz = eng.config.get(TZ_ID)
        packers = [eng._agg_meta_packers(lp.agg_plans, lp.routes,
                                         lp.n_keys, with_idx=False)
                   for lp in lanes]

        def lane_outs(arrays):
            """Per-lane route-conformant output dicts — the shared inner
            loop both the single-device pack and the mesh shard body
            close over (each mesh shard runs it over its own slice)."""
            ctx = ScanContext(ds, arrays, min_day, max_day, tz=tz)
            rv = ctx.row_valid()
            cse = None
            if fplan is not None:
                cse = FU.CSECache(ctx)
                cse.prelower(fplan)
            outs = []
            for lp in lanes:
                base = rv
                fm = cse.lower(lp.q.filter) if cse is not None \
                    else F.lower_filter(lp.q.filter, ctx)
                if fm is not None:
                    base = base & fm
                im = cse.interval(lp.q.intervals) if cse is not None \
                    else F.interval_mask(lp.q.intervals, ctx)
                if im is not None:
                    base = base & im
                if lp.dim_plans:
                    codes = [p.build(ctx) for p in lp.dim_plans]
                    key, _ = G.fuse_keys(codes,
                                         [p.card for p in lp.dim_plans])
                else:
                    key = jnp.zeros_like(base, dtype=jnp.int32)
                inputs = []
                for p in lp.agg_plans:
                    if p.kind in ("hll", "theta", "kll"):
                        continue
                    inputs.append(G.AggInput(p.spec.name, p.kind,
                                             p.build_values(ctx),
                                             p.build_mask(ctx, cse=cse),
                                             is_int=p.is_int,
                                             maxabs=p.maxabs))
                inputs.append(G.AggInput("__rows__", "count", is_int=True,
                                         maxabs=1.0))
                out = G.dense_groupby(key, base, lp.n_keys, inputs,
                                      lp.routes, matmul_max)
                for p in lp.agg_plans:
                    if p.kind not in ("hll", "theta", "kll"):
                        continue
                    vals = p.build_values(ctx)
                    am = p.build_mask(ctx, cse=cse)
                    m = base if am is None else (base & am)
                    if p.kind == "hll":
                        out[p.spec.name] = HLL.hll_registers(
                            key, m, vals, lp.n_keys, log2m)
                    elif p.kind == "kll":
                        tcol = ctx.col(ds.time.name) \
                            if ds.time is not None else None
                        out[p.spec.name] = KLL.kll_registers(
                            key, m, vals, tcol, lp.n_keys, kll_lanes)
                    else:
                        out[p.spec.name] = TH.theta_registers(
                            key, m, vals, lp.n_keys)
                outs.append(out)
            return outs

        if mesh_dec is not None and mesh_dec.sharded:
            fn = MX.build_sharded_program(eng, lane_outs, lanes, packers)
        else:
            def fused(arrays):
                return tuple(pack(o) for (pack, _), o
                             in zip(packers, lane_outs(arrays)))
            fn = jax.jit(fused)
        return fn, [u for _, u in packers]

    def _build_wave_program(self, ds, lanes: List[_LanePlan],
                            min_day: int, max_day: int, fplan=None, *,
                            union_names, s_pad, mesh_dec=None):
        """(jit_fn, [per-lane unpack], wave_info). The group's whole wave
        lowers through ONE hand-scheduled Pallas mega-kernel
        (ops/pallas_wave.py); outputs are route-conformant per lane, so
        the same packers/unpackers/decode as the jaxpr program apply.
        Raises (typically :class:`PW.WaveFallback`) when the group cannot
        lower — the caller then builds the jaxpr-fused program, keeping
        the group fused."""
        eng = self.engine
        log2m = eng.config.get(HLL_LOG2M)
        tz = eng.config.get(TZ_ID)
        wave_fn, info = PW.build_wave_fn(
            ds, lanes, min_day, max_day, fplan,
            union_names=union_names, tz=tz, log2m=log2m,
            tile_bytes=int(eng.config.get(PALLAS_WAVE_TILE_BYTES)),
            kll_lanes=eng.config.get(QUANTILE_LANES))
        packers = [eng._agg_meta_packers(lp.agg_plans, lp.routes,
                                         lp.n_keys, with_idx=False)
                   for lp in lanes]

        if mesh_dec is not None and mesh_dec.sharded:
            # the wave mega-kernel is shape-generic over the segment dim:
            # inside shard_map each device launches it over its own
            # [s_pad / n_dev, R] slice, partials merge on the
            # interconnect, and the SAME packers/unpacks apply
            fn = MX.build_sharded_program(eng, wave_fn, lanes, packers)
        else:
            def fused(arrays):
                outs = wave_fn(arrays)
                return tuple(pack(o)
                             for (pack, _), o in zip(packers, outs))
            fn = jax.jit(fused)
        # surface trace/shape errors at BUILD time (abstract eval — no
        # device compile), so a bad lowering falls back here instead of
        # failing the group's first dispatch; with a mesh decision this
        # traces THROUGH shard_map, so per-shard lowering rejects also
        # land here (the group then falls back to the jaxpr program)
        shapes = {k: jax.ShapeDtypeStruct(
            (s_pad, ds.padded_rows),
            jnp.zeros((), dtype=array_dtype(ds, k)).dtype)
            for k in union_names}
        jax.eval_shape(fn, shapes)
        return fn, [u for _, u in packers], info

    def _dispatch(self, ds, union_names, seg_u, s_pad, spw, n_waves,
                  prog_fn, unpacks, lanes: List[_LanePlan], leader,
                  wave_info=None, mesh_dec=None):
        """One shared bind + ONE program dispatch per wave (double-
        buffered like _run_waves); per-lane unpack -> finals -> cross-
        wave merge. All device ticks land on the leader's thread —
        including the wave-kernel launch tick (dispatch_counts[2]) when
        the wave program is live. With a sharded mesh decision binds
        carry the segment-axis sharding, launch ticks count per device,
        and the packed per-device partial buffers the wave loop holds on
        device are accounted through the meshexec partial ledger
        (acquire/release pair — sdlint leaks)."""
        from spark_druid_olap_tpu.parallel import executor as X
        eng = self.engine
        sharded = mesh_dec is not None and mesh_dec.sharded
        n_dev = mesh_dec.n_dev if sharded else 1
        if wave_info is not None:
            # pallas kernel launches: one per device per wave
            eng._tick(2, n_waves * n_dev)
        sketch = [[p for p in lp.agg_plans
                   if p.kind in ("hll", "theta", "kll")]
                  for lp in lanes]
        payload = MX.merged_payload_bytes(eng, lanes) * n_dev
        if n_waves == 1:
            dev = eng._bind_arrays(ds, union_names, seg_u, s_pad, sharded)
            eng._stage_check(leader.q, leader.t0)
            eng._tick()
            tok = MX.LEDGER.acquire_partials(payload)
            try:
                bufs = prog_fn(dev)
                return [X._finals_from_out(unpacks[i](bufs[i]), lp.routes,
                                           lp.n_keys, sketch[i])
                        for i, lp in enumerate(lanes)]
            finally:
                MX.LEDGER.release_partials(tok)
        seg_rows = None
        if sharded:
            try:
                seg_rows = {int(s): int(ds.segments[int(s)].num_rows)
                            for s in seg_u}
            except Exception:  # noqa: BLE001 — handles without segment objects
                seg_rows = None
        wave_segs = FU.plan_device_waves(seg_u, spw, n_dev, seg_rows)
        sharding = M.segment_sharding(eng.mesh) if sharded else None
        finals: List[Optional[dict]] = [None] * len(lanes)
        # mesh-parallel cold-tier faults: open a devices-aware pin scope
        # so eviction sees the whole n_dev-wide wave as one pinned unit
        tier = getattr(ds, "tier", None)
        ptok = tier.acquire_pins(devices=n_dev) \
            if (sharded and tier is not None) else None
        try:
            tok = MX.LEDGER.acquire_partials(payload)
            try:
                # cold tier: wave 1's chunks load while wave 0 binds+computes
                eng._tier_prefetch(ds, union_names, wave_segs, 1)
                cur = eng._bind_wave(ds, union_names, wave_segs[0], spw,
                                     sharding, False)
                for i in range(len(wave_segs)):
                    eng._stage_check(leader.q, leader.t0)
                    eng._tick()
                    _td = _time.perf_counter()
                    bufs = prog_fn(cur)            # async dispatch
                    eng._tier_prefetch(ds, union_names, wave_segs, i + 2)
                    nxt = eng._bind_wave(ds, union_names, wave_segs[i + 1],
                                         spw, sharding, False) \
                        if i + 1 < len(wave_segs) else None
                    for li, lp in enumerate(lanes):
                        f = X._finals_from_out(unpacks[li](bufs[li]),
                                               lp.routes, lp.n_keys,
                                               sketch[li])
                        finals[li] = f if finals[li] is None \
                            else X._merge_wave_finals(finals[li], f,
                                                      lp.routes, sketch[li])
                    # leader-thread attribution: overlapped prefetch/bind
                    # charge to their own phases inside this interval
                    PH.add("dispatch", _time.perf_counter() - _td)
                    cur = nxt
            finally:
                MX.LEDGER.release_partials(tok)
        finally:
            if ptok is not None:
                tier.release_pins(ptok)
        return finals

    @staticmethod
    def _decode_lane(eng, ds, lp: _LanePlan, finals) -> QueryResult:
        """Host demultiplex of one lane: the solo dense decode (group
        selection, dictionary decode, identity row, epilogue) minus the
        device-topk/having specializations the fused tier never plans.
        Charged to the ``demux`` phase of whichever statement's thread
        runs the decode."""
        with PH.phase("demux"):
            return SharedScanCoalescer._decode_lane_inner(
                eng, ds, lp, finals)

    @staticmethod
    def _decode_lane_inner(eng, ds, lp: _LanePlan, finals) -> QueryResult:
        from spark_druid_olap_tpu.parallel import executor as X
        rows = finals["__rows__"]
        sel = np.nonzero(rows > 0)[0]
        gran_kind = lp.gran.kind if lp.gran else "all"
        global_empty = (not lp.dim_plans and gran_kind == "all"
                        and len(sel) == 0)
        if global_empty:
            sel = np.zeros(1, dtype=np.int64)
        data: Dict[str, np.ndarray] = {}
        columns: List[str] = []
        if lp.dim_plans:
            code_lists = G.unfuse_key(sel, [p.card for p in lp.dim_plans])
            for p, codes in zip(lp.dim_plans, code_lists):
                data[p.output_name] = p.decode(codes)
                columns.append(p.output_name)
        for p in lp.agg_plans:
            name = p.spec.name
            if p.kind in ("hll", "theta", "kll"):
                regs = finals[name]
                if eng.partial_sketches:
                    # cluster historical: ship the raw [G, m] register
                    # block exactly like the solo decode — the broker
                    # merges registers across shards and finalizes once
                    data[name] = np.asarray(regs)[sel]
                    columns.append(name)
                    continue
                if p.kind == "kll":
                    data[name] = KLL.estimate(
                        regs, p.spec.fraction or 0.5)[sel]
                    columns.append(name)
                    continue
                est = (HLL.estimate(regs) if p.kind == "hll"
                       else TH.estimate(regs))[sel]
                data[name] = np.round(est).astype(np.int64)
                columns.append(name)
                continue
            data[name] = X._decode_agg_value(ds, p, lp.routes[name],
                                             finals[name][sel])
            columns.append(name)
        if global_empty:
            data.update(X._identity_row(
                {p.spec.name: p.kind for p in lp.agg_plans
                 if p.kind in ("sum", "min", "max")}))
        data = eng._agg_epilogue(data, columns, lp.post, lp.having,
                                 lp.limit)
        return QueryResult(columns, data)

    def note_handoff(self) -> None:
        """Called by the WLM poll loop when a queued waiter bypasses its
        lane to ride an open group's dispatch."""
        with self._lock:
            self.wlm_handoffs += 1

    def note_solo_cse(self, saved: int, total: int) -> None:
        """Called by the solo executor path's plan-time CSE accounting
        (one query's own tree repeating sub-predicates)."""
        with self._lock:
            self.fusion_solo_evals_saved += int(saved)
            self.fusion_solo_evals_total += int(total)

    # -- observability ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            total = self.fusion_predicate_evals_total \
                + self.fusion_solo_evals_total
            saved = self.fusion_predicate_evals_saved \
                + self.fusion_solo_evals_saved
            return {"enabled": self.enabled(),
                    "groups_coalesced": self.groups_coalesced,
                    "solo_groups": self.solo_groups,
                    "queries_coalesced": self.queries_coalesced,
                    "fallbacks": self.fallbacks,
                    "binds_saved_bytes": self.binds_saved_bytes,
                    "dispatches_saved": self.dispatches_saved,
                    "wlm_handoffs": self.wlm_handoffs,
                    "pallas": {
                        "launches": self.pallas_launches,
                        "tiles": self.pallas_tiles,
                        "fallbacks": self.pallas_fallbacks,
                        "vmem_bytes_peak": self.pallas_vmem_peak},
                    "mesh": {
                        "devices": M.mesh_size(self.engine.mesh),
                        "groups": self.mesh_groups,
                        "dispatches": self.mesh_dispatches,
                        "collective_bytes": self.mesh_collective_bytes,
                        "fallbacks": dict(self.mesh_fallbacks),
                        "partials": MX.LEDGER.stats()},
                    "fusion": {
                        "groups": self.fusion_groups,
                        "plan_fallbacks": self.fusion_fallbacks,
                        "shared_predicates":
                            self.fusion_shared_predicates,
                        "predicate_evals_saved":
                            self.fusion_predicate_evals_saved,
                        "predicate_evals_total":
                            self.fusion_predicate_evals_total,
                        "column_streams_saved":
                            self.fusion_column_streams_saved,
                        "solo_evals_saved": self.fusion_solo_evals_saved,
                        "solo_evals_total": self.fusion_solo_evals_total,
                        "cse_hit_rate": round(saved / total, 4)
                        if total else 0.0}}
