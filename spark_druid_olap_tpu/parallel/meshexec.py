"""Multi-chip device-mesh execution tier for the fused shared-scan path.

The reference system scales a scan by fanning segment groups out across
historical servers and merging per-server partial aggregates at the
broker (``DruidRDD.getPartitions:244-277``). On a TPU host the same
shape exists one level down: several chips hang off one interconnect,
and a fused shared-scan wave — K dashboard queries riding one column
bind — is exactly a scan that wants to fan out. This module is the
local analog of that broker contract, built data-movement-first
(Theseus, arxiv 2508.05029): per-device partial aggregates never leave
HBM; only the merged registers cross the interconnect.

Execution shape (used by ``parallel/sharedscan.py``):

- ``decide`` is the static eligibility precheck. Every disqualifying
  condition falls back to single-device execution with a named reason
  (the fallback matrix in docs/MESH.md); nothing is decided inside a
  traced program.
- ``build_sharded_program`` wraps a per-lane program — the jaxpr-fused
  core or the Pallas wave mega-kernel from ops/pallas_wave.py, both of
  which already produce route-conformant per-lane output dicts — in
  ``shard_map`` over the 1-D segment axis. Inside the body each lane's
  partials merge with exactly the register algebra ``AGG_CLOSURE.merge``
  declares and the sdlint mesh pass statically enforces:

  * ``psum``  — sums / counts (limb routes; Neumaier-compensated
    ff/ffl pairs stay per-chip, sharded out, and are summed as
    f64-exact pairs by the host ``combine_route`` decode),
  * ``pmax``  — max aggregates and HLL registers,
  * ``pmin``  — min aggregates and theta hash minima.

  The merged buffer replicates (out_spec ``P()``); the per-chip pair
  buffer stays sharded (``P(SEGMENT_AXIS)``) so the unchanged unpack
  path sees chips exactly as the solo sharded executor does.
- ``merged_payload_bytes`` / ``collective_bytes`` statically account
  the interconnect traffic a dispatch will generate (the mesh lint
  pass forbids host-state writes inside shard bodies, so accounting is
  computed host-side from route metadata, never measured in-trace).
- ``PartialLedger`` tracks device-resident packed partial buffers
  across the double-buffered wave loop (``acquire_partials`` /
  ``release_partials`` — a registered sdlint leaks pair).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

from spark_druid_olap_tpu.ops import groupby as G
from spark_druid_olap_tpu.ops import kll as KLL
from spark_druid_olap_tpu.ops import theta as TH
from spark_druid_olap_tpu.parallel import cost as C
from spark_druid_olap_tpu.parallel import mesh as M
from spark_druid_olap_tpu.parallel import multihost as MH
from spark_druid_olap_tpu.parallel.mesh import SEGMENT_AXIS, shard_map
from spark_druid_olap_tpu.utils.config import (
    COST_MODEL_ENABLED,
    HLL_LOG2M,
    MESH_ENABLED,
    MESH_MIN_SEGMENTS,
    QUANTILE_LANES,
)


@dataclass(frozen=True)
class MeshDecision:
    """Outcome of the static precheck. ``reason`` is one of the
    fallback-matrix rows in docs/MESH.md (or ``"sharded"`` /
    ``"cost-sharded"`` when the wave shards)."""
    sharded: bool
    n_dev: int
    reason: str

    def sig_fields(self) -> Tuple:
        """The fields that shape the traced program (folded into the
        fused compile signature — sdlint K1: a config flip or device-
        count change must re-key the executable)."""
        return (self.sharded, self.n_dev)


SINGLE = MeshDecision(False, 1, "no-mesh")


def decide(eng, ds, lanes, n_segments: int) -> MeshDecision:
    """Static mesh-eligibility precheck for one fused group.

    Single-device on ANY disqualifying condition — the fused tier never
    errors because of the mesh; it just declines it. Reasons:

    - ``no-mesh``       engine has no mesh / one device
    - ``disabled``      sdot.mesh.enabled is False (kill switch)
    - ``multihost``     jax.process_count() > 1 — the fused tier binds
                        process-local arrays; the cross-process plane
                        stays the solo executor's multihost path
    - ``partial-store`` datasource rows live across the pod
    - ``few-segments``  fewer selected segments than
                        sdot.mesh.min.segments (a 1-segment-per-device
                        split pays collective latency for nothing)
    - ``cost-single``   the cost model priced the merge above the scan
                        win (parallel/cost.py mesh_estimate)
    """
    n = M.mesh_size(eng.mesh)
    if n <= 1:
        return SINGLE
    if not bool(eng.config.get(MESH_ENABLED)):
        return MeshDecision(False, 1, "disabled")
    if MH.is_multihost():
        return MeshDecision(False, 1, "multihost")
    if getattr(ds, "is_partial", False):
        return MeshDecision(False, 1, "partial-store")
    if n_segments < max(2, int(eng.config.get(MESH_MIN_SEGMENTS))):
        return MeshDecision(False, 1, "few-segments")
    if not bool(eng.config.get(COST_MODEL_ENABLED)):
        return MeshDecision(True, n, "sharded")
    try:
        est = C.mesh_estimate(
            eng.config, n_dev=n, rows=int(ds.num_rows),
            groups=max(lp.n_keys for lp in lanes),
            n_aggs=sum(len(lp.agg_plans) for lp in lanes),
            merge_bytes=collective_bytes(eng, lanes, n))
    except Exception:   # noqa: BLE001 — cost must never fail a query
        return MeshDecision(True, n, "sharded")
    if not est.recommend_sharded:
        return MeshDecision(False, 1, "cost-single")
    return MeshDecision(True, n, "cost-sharded")


# -- static interconnect accounting -------------------------------------------

def merged_payload_bytes(eng, lanes) -> int:
    """Size of the replicated (collective-merged) output buffers for one
    dispatch, computed from route metadata exactly the way
    ``_agg_meta_packers`` lays the merged buffer out: merged routes +
    rows route + HLL register blocks + theta lane blocks + KLL survivor
    blocks, at the packed buffer itemsize (i64 on x64 backends, i32
    otherwise)."""
    m = 1 << int(eng.config.get(HLL_LOG2M))
    kll_w = KLL.width(int(eng.config.get(QUANTILE_LANES)))
    widths = {"hll": m, "theta": TH.K_LANES, "kll": kll_w}
    itemsize = 8 if G._x64() else 4
    elems = 0
    for lp in lanes:
        sketch = {p.spec.name: p.kind for p in lp.agg_plans
                  if p.kind in ("hll", "theta", "kll")}
        for name, r in lp.routes.items():
            if name in sketch or not r.merged:
                continue
            elems += sum(size for _, size, _ in r.outputs(lp.n_keys))
        for name, kind in sketch.items():
            elems += lp.n_keys * widths[kind]
    return elems * itemsize


def collective_bytes(eng, lanes, n_dev: int) -> int:
    """Interconnect bytes one sharded dispatch moves: every device
    contributes its merged-payload partial to an all-reduce, so the
    reduction ships ``payload x (n_dev - 1)`` across the links (the
    ring-all-reduce convention; documented in docs/MESH.md and priced
    by parallel/cost.py)."""
    return merged_payload_bytes(eng, lanes) * max(0, int(n_dev) - 1)


# -- the sharded program wrapper ----------------------------------------------

def build_sharded_program(eng, lane_outs_fn: Callable, lanes,
                          packers: Sequence[Tuple]):
    """Wrap ``lane_outs_fn`` (arrays -> per-lane route-conformant output
    dicts; either the jaxpr-fused core or the Pallas wave mega-kernel)
    in ``shard_map`` over the engine mesh.

    Inside the body each device runs the UNCHANGED inner loop over its
    ``S / n_dev`` segment slice, then every lane's partials fold with
    ``ops.groupby.merge_lane_partials`` — psum / pmin / pmax per the
    route's declared algebra, sketch registers per ``AGG_CLOSURE.merge``
    — before packing. Merged buffers replicate; per-chip Neumaier /
    theta-lane pair buffers stay sharded for the host's exact f64
    combine. Returns a jitted callable with the same signature and
    output pytree as the single-device program, so dispatch, unpack and
    decode are byte-for-byte shared."""
    mesh = eng.mesh
    sketch_kinds = [
        {p.spec.name: p.kind for p in lp.agg_plans
         if p.kind in ("hll", "theta", "kll")}
        for lp in lanes]

    def sharded_lanes(arrays):
        outs = lane_outs_fn(arrays)
        packed = []
        for lp, out, (pack, _), sk in zip(lanes, outs, packers,
                                          sketch_kinds):
            merged = G.merge_lane_partials(out, lp.routes, sk,
                                           SEGMENT_AXIS)
            packed.append(pack(merged))
        return tuple(packed)

    smfn = shard_map(
        sharded_lanes, mesh=mesh,
        in_specs=(P(SEGMENT_AXIS, None),),
        out_specs=tuple((P(), P(SEGMENT_AXIS)) for _ in lanes),
        check_vma=False)
    return jax.jit(lambda arrays: smfn(arrays))


# -- device-resident partial-buffer ledger ------------------------------------

class _PartialToken:
    __slots__ = ("nbytes", "released")

    def __init__(self, nbytes: int):
        self.nbytes = int(nbytes)
        self.released = False


class PartialLedger:
    """Accounting for packed per-device partial buffers while a
    double-buffered wave loop holds them on device (between dispatch
    and host unpack). ``acquire_partials``/``release_partials`` are a
    registered sdlint leaks pair — every acquire must release on all
    paths, so a crashed wave loop can never strand phantom device
    bytes in the gauge."""

    def __init__(self):
        self._lock = threading.Lock()
        self.outstanding_bytes = 0
        self.peak_bytes = 0
        self.acquires = 0

    def acquire_partials(self, nbytes: int) -> _PartialToken:
        tok = _PartialToken(nbytes)
        with self._lock:
            self.acquires += 1
            self.outstanding_bytes += tok.nbytes
            self.peak_bytes = max(self.peak_bytes, self.outstanding_bytes)
        return tok

    def release_partials(self, tok: _PartialToken) -> None:
        with self._lock:
            if not tok.released:
                tok.released = True
                self.outstanding_bytes -= tok.nbytes

    def stats(self) -> dict:
        with self._lock:
            return {"outstanding_bytes": self.outstanding_bytes,
                    "peak_bytes": self.peak_bytes,
                    "acquires": self.acquires}


#: process-wide gauge (stats surface: wlm.stats()["sharedscan"]["mesh"])
LEDGER = PartialLedger()
