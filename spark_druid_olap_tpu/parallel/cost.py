"""Query cost model: single-chip vs mesh-sharded execution.

≈ ``DruidQueryCostModel.scala`` (872 LoC), which decides broker vs direct
historical queries and segments-per-query from input/output estimates:
``estimateInput:660-677`` (filter selectivity), ``estimateOutputCardinality
:691-716`` (dim cardinality product × selectivity), per-query-type cost
classes summing historical processing + merge + transport costs over
scheduling "waves". The TPU translation: the 'historicals' are mesh chips,
'broker merge' is the ICI collective, 'transport' is host<->device + DCN, and
a TPU-specific compile-amortization term replaces Spark scheduling cost.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.parallel.mesh import mesh_size
from spark_druid_olap_tpu.utils.config import (
    COST_COMPILE,
    COST_MODEL_ENABLED,
    COST_PER_BYTE_TRANSPORT,
    COST_PER_ROW_MERGE,
    COST_PER_ROW_SCAN,
)


@dataclasses.dataclass
class CostEstimate:
    rows: int                      # rows scanned after interval pruning
    selectivity: float             # estimated filter selectivity
    output_groups: int             # estimated result cardinality
    single_cost: float
    sharded_cost: float
    n_devices: int
    recommend_sharded: bool

    def table(self) -> str:
        return (f"rows={self.rows:,} sel={self.selectivity:.3f} "
                f"est_groups={self.output_groups:,}\n"
                f"single-chip cost={self.single_cost:.4g}  "
                f"sharded({self.n_devices})={self.sharded_cost:.4g}  "
                f"-> {'SHARDED' if self.recommend_sharded else 'SINGLE'}")


def _filter_selectivity(f: Optional[S.FilterSpec], ds) -> float:
    """≈ the reference's per-filter selectivity heuristics."""
    if f is None:
        return 1.0
    if isinstance(f, S.SelectorFilter):
        card = ds.cardinality(f.dimension) or 100
        return 1.0 / max(card, 1)
    if isinstance(f, S.BoundFilter):
        both = f.lower is not None and f.upper is not None
        return 0.25 if both else 0.5
    if isinstance(f, S.InFilter):
        card = ds.cardinality(f.dimension) or 100
        return min(1.0, len(f.values) / max(card, 1))
    if isinstance(f, S.PatternFilter):
        return 0.25
    if isinstance(f, S.NullFilter):
        return 0.9 if f.negated else 0.1
    if isinstance(f, S.LogicalFilter):
        sels = [_filter_selectivity(x, ds) for x in f.fields]
        if f.op == "and":
            out = 1.0
            for s_ in sels:
                out *= s_
            return out
        if f.op == "or":
            return min(1.0, sum(sels))
        return max(0.0, 1.0 - (sels[0] if sels else 0.0))
    return 0.5  # ExprFilter: unknown


def _output_groups(q: S.QuerySpec, ds) -> int:
    dims = S.query_dimensions(q)
    out = 1
    for d in dims:
        if d.extraction is None:
            out *= max(1, ds.cardinality(d.dimension) or 100)
        elif isinstance(d.extraction, S.TimeExtraction):
            out *= 32
        else:
            out *= 100
    gran = getattr(q, "granularity", S.GRAN_ALL)
    if gran is not None and not gran.is_all():
        lo, hi = ds.interval()
        buckets = {"year": 3.2e10, "quarter": 8e9, "month": 2.6e9,
                   "week": 6.05e8, "day": 8.64e7, "hour": 3.6e6,
                   "minute": 6e4}.get(gran.kind, 8.64e7)
        out *= max(1, int((hi - lo) / buckets))
    return out


def estimate(ctx_or_engine, q: S.QuerySpec) -> CostEstimate:
    engine = getattr(ctx_or_engine, "engine", ctx_or_engine)
    ds = engine.store.get(q.datasource)
    conf = engine.config
    seg_idx = ds.prune_segments(getattr(q, "intervals", None))
    if ds.num_segments:
        rows = int(ds.num_rows * len(seg_idx) / ds.num_segments)
    else:
        rows = 0
    sel = _filter_selectivity(getattr(q, "filter", None), ds)
    groups = min(_output_groups(q, ds), max(1, int(rows * sel)) or 1)

    scan_c = conf.get(COST_PER_ROW_SCAN)
    merge_c = conf.get(COST_PER_ROW_MERGE)
    byte_c = conf.get(COST_PER_BYTE_TRANSPORT)
    compile_c = conf.get(COST_COMPILE)

    n_dev = mesh_size(engine.mesh)
    # single chip: scan everything + decode output
    single = rows * scan_c + groups * byte_c * 16
    # sharded: scan split across devices + ICI merge of [K] partials per agg
    n_aggs = max(1, len(S.query_aggregations(q)))
    sharded = (rows / max(n_dev, 1)) * scan_c \
        + groups * n_aggs * merge_c \
        + groups * byte_c * 16 \
        + compile_c * 0.1  # sharded programs compile slower
    recommend = n_dev > 1 and sharded < single
    if not conf.get(COST_MODEL_ENABLED):
        recommend = n_dev > 1
    return CostEstimate(rows, sel, groups, single, sharded, n_dev, recommend)


def explain_cost(ctx, q: S.QuerySpec) -> str:
    try:
        return estimate(ctx, q).table()
    except Exception as e:  # cost must never break explain
        return f"cost: unavailable ({e})"
