"""Query cost model: single-chip vs mesh-sharded execution.

≈ ``DruidQueryCostModel.scala`` (872 LoC), which decides broker vs direct
historical queries and segments-per-query from input/output estimates:
``estimateInput:660-677`` (filter selectivity), ``estimateOutputCardinality
:691-716`` (dim cardinality product × selectivity), per-query-type cost
classes summing historical processing + merge + transport costs over
scheduling "waves". The TPU translation: the 'historicals' are mesh chips,
'broker merge' is the ICI collective, 'transport' is host<->device + DCN, and
a TPU-specific compile-amortization term replaces Spark scheduling cost.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.parallel.mesh import mesh_size
from spark_druid_olap_tpu.utils.config import (
    COST_COMPILE,
    COST_MODEL_ENABLED,
    COST_PER_BYTE_INTERCONNECT,
    COST_PER_BYTE_TRANSPORT,
    COST_PER_ROW_MERGE,
    COST_PER_ROW_SCAN,
    COST_SHARD_EFFICIENCY,
)


@dataclasses.dataclass
class CostEstimate:
    rows: int                      # rows scanned after interval pruning
    selectivity: float             # estimated filter selectivity
    output_groups: int             # estimated result cardinality
    single_cost: float
    sharded_cost: float
    n_devices: int
    recommend_sharded: bool
    scan_bytes: int = 0            # est. device bytes the scan binds
    segments_per_wave: int = 0     # 0 = everything in one wave
    n_waves: int = 1
    xhost_bytes: int = 0           # est. cross-host result replication
    host_xhost_bytes: int = 0      # est. host-tier column reassembly bytes
    ici_bytes: int = 0             # est. intra-host interconnect merge bytes

    def table(self) -> str:
        wave = "" if self.n_waves <= 1 else \
            f"  waves={self.n_waves}x{self.segments_per_wave}seg"
        xh = "" if not self.xhost_bytes else \
            f" xhost_bytes={self.xhost_bytes:,}"
        if self.host_xhost_bytes:
            xh += f" host_xhost_bytes={self.host_xhost_bytes:,}"
        return (f"rows={self.rows:,} sel={self.selectivity:.3f} "
                f"est_groups={self.output_groups:,} "
                f"scan_bytes={self.scan_bytes:,}{xh}\n"
                f"single-chip cost={self.single_cost:.4g}  "
                f"sharded({self.n_devices})={self.sharded_cost:.4g}  "
                f"-> {'SHARDED' if self.recommend_sharded else 'SINGLE'}"
                + wave)


def _filter_selectivity(f: Optional[S.FilterSpec], ds) -> float:
    """≈ the reference's per-filter selectivity heuristics."""
    if f is None:
        return 1.0
    if isinstance(f, S.SelectorFilter):
        card = ds.cardinality(f.dimension) or 100
        return 1.0 / max(card, 1)
    if isinstance(f, S.BoundFilter):
        frac = _bound_overlap_fraction(f, ds)
        if frac is not None:
            return frac
        both = f.lower is not None and f.upper is not None
        return 0.25 if both else 0.5
    if isinstance(f, S.InFilter):
        card = ds.cardinality(f.dimension) or 100
        return min(1.0, len(f.values) / max(card, 1))
    if isinstance(f, S.PatternFilter):
        frac = _pattern_fraction(f, ds)
        return frac if frac is not None else 0.25
    if isinstance(f, S.NullFilter):
        return 0.9 if f.negated else 0.1
    if isinstance(f, S.LogicalFilter):
        sels = [_filter_selectivity(x, ds) for x in f.fields]
        if f.op == "and":
            out = 1.0
            for s_ in sels:
                out *= s_
            return out
        if f.op == "or":
            return min(1.0, sum(sels))
        return max(0.0, 1.0 - (sels[0] if sels else 0.0))
    return 0.5  # ExprFilter: unknown


_PATTERN_FRAC_BOUND = 256


# CPU-fallback measured unit costs (round-3 probe workbench): consulted
# when the config still carries the v5e-measured DEFAULT on a cpu
# backend, so the perf gates are measurement-driven on BOTH backends out
# of the box. tools/calibrate.calibrate_primitives refits either backend
# in place (an explicitly-set config value always wins).
_CPU_MEASURED = {
    "sdot.querycostmodel.sort.seconds.per.row": 3.0e-7,
    "sdot.querycostmodel.sort.payload.seconds.per.row": 1.0e-7,
    "sdot.querycostmodel.scatter.seconds.per.update": 4.0e-9,
    "sdot.querycostmodel.scatter.big.seconds.per.update": 1.5e-7,
    "sdot.querycostmodel.gather.seconds.per.probe": 2.0e-9,
}


def unit_cost(config, entry) -> float:
    """Per-backend unit cost: the configured value when EXPLICITLY set
    (even to the default — config.is_set, not value equality), else the
    CPU-measured table on cpu backends, else the TPU-measured default."""
    import jax
    if config.is_set(entry):
        return float(config.get(entry))
    if jax.default_backend() == "cpu":
        return float(_CPU_MEASURED.get(entry.key, float(entry.default)))
    return float(entry.default)


def _pattern_fraction(f: S.PatternFilter, ds) -> Optional[float]:
    """Matching-dictionary fraction as the pattern's selectivity
    (uniform-frequency assumption). One regex pass over the dictionary,
    cached on the datasource — the filter lowering pays the same pass at
    trace time, and the late-materialization budget needs the real
    fraction (LIKE '%green%' over p_name is ~5%, not the 0.25 blanket)."""
    import re as _re
    from spark_druid_olap_tpu.ops import expr_compile as EC
    dim = getattr(ds, "dims", {}).get(f.dimension)
    if dim is None:
        return None
    from collections import OrderedDict
    cache = getattr(ds, "_pattern_frac_cache", None)
    if cache is None:
        cache = ds._pattern_frac_cache = OrderedDict()
    key = (f.dimension, f.kind, f.pattern)
    hit = cache.get(key)
    if hit is not None:
        cache.move_to_end(key)
        return hit
    vals = dim.dictionary
    n = len(vals)
    if n == 0:
        return None
    try:
        if f.kind == "like":
            rx = _re.compile(EC.like_to_regex(f.pattern))
            cnt = sum(1 for s in vals if rx.match(s))
        elif f.kind == "regex":
            rx = _re.compile(f.pattern)
            cnt = sum(1 for s in vals if rx.search(s))
        elif f.kind == "contains":
            cnt = sum(1 for s in vals if f.pattern in s)
        else:
            return None
    except _re.error:
        return None
    frac = max(cnt / n, 1.0 / (2 * n))
    cache[key] = frac
    # LRU-bounded like the session result caches: ad-hoc dashboards /
    # fuzzers emit unbounded distinct patterns (ADVICE r3)
    while len(cache) > _PATTERN_FRAC_BOUND:
        cache.popitem(last=False)
    return frac


def _bound_overlap_fraction(f: S.BoundFilter, ds) -> Optional[float]:
    """Range-overlap selectivity from column min/max metadata (DATE /
    LONG / DOUBLE metrics): |bound ∩ [min, max]| / |[min, max]|, assuming
    uniform density. Far better than the blanket 0.25 for the BI-typical
    date-quarter predicates (TPC-H q10-class: a 3-month window over 7
    years is ~0.036, not 0.25) — and the late-materialization budget
    depends on it."""
    from spark_druid_olap_tpu.ops import time_ops
    from spark_druid_olap_tpu.segment.column import ColumnKind
    try:
        kind = ds.column_kind(f.dimension)
    except KeyError:
        return None
    if kind not in (ColumnKind.DATE, ColumnKind.LONG, ColumnKind.DOUBLE):
        return None
    m = ds.metrics.get(f.dimension)
    if m is None:
        return None
    mn, mx = m.min, m.max              # uncached O(n) properties: bind once
    if mn is None or mx is None:
        return None
    lo_col, hi_col = float(mn), float(mx)
    if not (hi_col > lo_col):            # also rejects NaN bounds
        return None
    unit = 0.0 if kind == ColumnKind.DOUBLE else 1.0

    def conv(v):
        if v is None:
            return None
        if kind == ColumnKind.DATE:
            return float(time_ops.date_literal_to_days(v))
        return float(v)

    try:
        lo = conv(f.lower)
        hi = conv(f.upper)
    except (TypeError, ValueError):
        return None
    # half-open [lo_eff, hi_eff) over the column's [min, max + unit):
    # integer/date inclusive bounds widen by one unit; strict bounds
    # shift by one unit (measure-zero for DOUBLE, where unit = 0)
    lo = lo_col if lo is None else (lo + (unit if f.lower_strict else 0.0))
    hi = (hi_col + unit) if hi is None \
        else (hi + (0.0 if f.upper_strict else unit))
    lo = max(lo, lo_col)
    hi = min(hi, hi_col + unit)
    width = hi_col + unit - lo_col
    if width <= 0:
        return None
    return max(0.0, min(1.0, (hi - lo) / width))


def _output_groups(q: S.QuerySpec, ds) -> int:
    dims = S.query_dimensions(q)
    out = 1
    for d in dims:
        if d.extraction is None:
            out *= max(1, ds.cardinality(d.dimension) or 100)
        elif isinstance(d.extraction, S.TimeExtraction):
            out *= 32
        else:
            out *= 100
    gran = getattr(q, "granularity", S.GRAN_ALL)
    if gran is not None and not gran.is_all():
        lo, hi = ds.interval()
        buckets = {"year": 3.2e10, "quarter": 8e9, "month": 2.6e9,
                   "week": 6.05e8, "day": 8.64e7, "hour": 3.6e6,
                   "minute": 6e4}.get(gran.kind, 8.64e7)
        out *= max(1, int((hi - lo) / buckets))
    return out


def array_itemsize(ds, key: str) -> int:
    """Host itemsize of one stacked array (device canonicalization can only
    shrink f64->f32, so this bounds device bytes from above)."""
    from spark_druid_olap_tpu.ops.scan import (
        NULL_VALID_PREFIX, ROW_VALID_KEY, TIME_MS_KEY)
    if key == ROW_VALID_KEY or key.startswith(NULL_VALID_PREFIX):
        return 1
    if key == TIME_MS_KEY:
        return int(ds.time.ms_dtype().itemsize)
    if key in ds.dims:
        return int(ds.dims[key].data_dtype().itemsize)
    if key in ds.metrics:
        return int(ds.metrics[key].data_dtype().itemsize)
    if ds.time is not None and key == ds.time.name:
        return int(ds.time.data_dtype().itemsize)
    return 4


def bytes_per_segment(ds, names) -> int:
    return int(ds.padded_rows) * sum(array_itemsize(ds, k) for k in names)


def wave_tile_itemsize(ds, key: str) -> int:
    """Per-row VMEM bytes of one union array inside the wave mega-kernel
    (ops/pallas_wave.py) AFTER its input prep: validity masks ship as i8
    (1 byte), narrow integer codes widen to i32 on the host side of the
    kernel (uniform Mosaic tiling), wide types keep their itemsize."""
    from spark_druid_olap_tpu.ops.scan import NULL_VALID_PREFIX, ROW_VALID_KEY
    if key == ROW_VALID_KEY or key.startswith(NULL_VALID_PREFIX):
        return 1
    return max(4, array_itemsize(ds, key))


def pallas_tile_budget_bytes(conf) -> int:
    """VMEM byte budget the wave mega-kernel's tile planner
    (planner/fusion.py:plan_wave_tiles) fits the double-buffered input
    tiles plus the resident scratch block into."""
    from spark_druid_olap_tpu.utils.config import PALLAS_WAVE_TILE_BYTES
    return int(conf.get(PALLAS_WAVE_TILE_BYTES))


def wave_budget_bytes(conf) -> Optional[int]:
    """Per-device byte budget for one wave's scan arrays. Config override,
    else 60% of the device's reported HBM limit, else None (single wave)."""
    from spark_druid_olap_tpu.utils.config import WAVE_MAX_BYTES
    b = conf.get(WAVE_MAX_BYTES)
    if b:
        return int(b)
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit * 0.6)
    except Exception:  # noqa: BLE001 - CPU/interpret backends have no stats
        pass
    return None


def tier_io_budget(ds, conf) -> Optional[int]:
    """Per-wave host-I/O byte cap for a tiered (cold) datasource, or
    None on an in-memory store. A cold scan in one giant wave serializes
    the entire fault traffic ahead of the first dispatch; capping wave
    bytes at ``sdot.tier.wave.io.bytes`` forces enough waves that the
    prefetcher can hide wave i+1's loads behind wave i's compute."""
    if getattr(ds, "tier", None) is None:
        return None
    from spark_druid_olap_tpu.utils.config import TIER_WAVE_IO_BYTES
    b = int(conf.get(TIER_WAVE_IO_BYTES))
    return b if b > 0 else None


def tier_io_seg_bytes(ds, names) -> Optional[int]:
    """Per-segment HOST bytes one wave actually faults for the named
    scan keys — COMPRESSED bytes on an encoded tiered store
    (``TieredDatasource.host_bytes_per_segment``), None elsewhere. This
    is the divisor for the cold-tier io cap: an encoded store moves
    ratio× fewer bytes per segment, so the same ``sdot.tier.wave.io.
    bytes`` admits ratio× more segments per wave. The HBM-budget term
    keeps using the LOGICAL ``bytes_per_segment`` — chunks decode
    before device binding, so device bytes are unchanged by encoding."""
    fn = getattr(ds, "host_bytes_per_segment", None)
    if fn is None:
        return None
    b = int(fn(names))
    return b if b > 0 else None


def plan_waves(n_segments: int, n_dev: int, seg_bytes: int,
               budget: Optional[int], conf, output_groups: int,
               n_aggs: int, io_budget: Optional[int] = None,
               io_seg_bytes: Optional[int] = None) -> tuple:
    """Min-cost search over segments-per-wave (≈ the reference's
    ``druidQueryMethod`` searching 1..histSegsPerQueryLimit,
    DruidQueryCostModel.scala:343-414). Each wave costs a dispatch plus a
    host-side merge of the wave's [K] partials; each wave's scan arrays for
    one device must fit ``budget`` bytes. ``io_budget`` additionally caps
    one WAVE's total host bytes (all devices) — the cold-tier I/O term
    (``tier_io_budget``) that keeps load-behind-compute overlap full.
    ``io_seg_bytes`` is the per-segment divisor for that I/O term when the
    faulted bytes differ from the device bytes (encoded tiered stores,
    ``tier_io_seg_bytes``); it defaults to ``seg_bytes``.

    Returns (segments_per_wave, n_waves); segments_per_wave is a multiple of
    n_dev.
    """
    n_dev = max(1, n_dev)
    if n_segments <= 0:
        return n_dev, 1
    # every wave costs a dispatch plus a host merge of its [K] partials while
    # scan + transport totals are wave-count invariant, so the min-cost
    # segments-per-wave is simply the largest n_dev multiple under the HBM
    # budget (the reference's search space has a per-wave scheduling term
    # with the same monotone structure). Unbounded scans round UP to one
    # wave — segment padding covers the tail.
    cap = -(-n_segments // n_dev) * n_dev
    if budget is not None and seg_bytes > 0:
        per_dev = int(budget // seg_bytes)
        cap = min(cap, max(1, per_dev) * n_dev)
    io_div = io_seg_bytes if io_seg_bytes is not None else seg_bytes
    if io_budget is not None and io_div > 0:
        per_wave = max(1, int(io_budget // io_div))
        cap = min(cap, -(-per_wave // n_dev) * n_dev)
    return cap, -(-n_segments // cap)


def estimate(ctx_or_engine, q: S.QuerySpec) -> CostEstimate:
    engine = getattr(ctx_or_engine, "engine", ctx_or_engine)
    ds = engine.store.get(q.datasource)
    conf = engine.config
    seg_idx = ds.prune_segments(getattr(q, "intervals", None))
    if ds.num_segments:
        rows = int(ds.num_rows * len(seg_idx) / ds.num_segments)
    else:
        rows = 0
    sel = _filter_selectivity(getattr(q, "filter", None), ds)
    groups = min(_output_groups(q, ds), max(1, int(rows * sel)) or 1)

    scan_c = conf.get(COST_PER_ROW_SCAN)
    merge_c = conf.get(COST_PER_ROW_MERGE)
    byte_c = conf.get(COST_PER_BYTE_TRANSPORT)
    compile_c = conf.get(COST_COMPILE)

    n_dev = mesh_size(engine.mesh)
    eff = max(1e-3, min(1.0, float(conf.get(COST_SHARD_EFFICIENCY))))
    # single chip: scan everything + decode output
    single = rows * scan_c + groups * byte_c * 16
    # sharded: scan split across devices (at the CALIBRATED parallel
    # efficiency — a virtual mesh on shared cores splits nothing) + ICI
    # merge of [K] partials per agg
    n_aggs = max(1, len(S.query_aggregations(q)))
    # cross-host replication bytes (multi-host pods only): result rows
    # travel DCN/ICI once per peer host so every process can fetch the
    # replicated merge — O(groups x n_aggs), the two-dispatch compacted
    # transfer (VERDICT r4 item 3; the full-[T]-table gather this
    # replaced would be O(slots x n_aggs))
    import jax as _jax
    try:
        n_hosts = _jax.process_count()
    except Exception:   # noqa: BLE001 — uninitialized backend
        n_hosts = 1
    xhost_bytes = groups * n_aggs * 8 * max(0, n_hosts - 1) \
        if n_hosts > 1 else 0
    # intra-host interconnect merge bytes: each device contributes its
    # merged [K x n_aggs] partial block to the all-reduce, so the
    # reduction moves payload x (n_dev - 1) over the links (ring
    # convention; parallel/meshexec.py accounts dispatches identically)
    ici_bytes = groups * n_aggs * 8 * max(0, n_dev - 1)
    sharded = (rows / max(n_dev * eff, 1e-9)) * scan_c \
        + groups * n_aggs * merge_c \
        + groups * byte_c * 16 \
        + xhost_bytes * byte_c \
        + ici_bytes * conf.get(COST_PER_BYTE_INTERCONNECT) \
        + compile_c * 0.1  # sharded programs compile slower
    recommend = n_dev > 1 and sharded < single
    if not conf.get(COST_MODEL_ENABLED):
        recommend = n_dev > 1

    # approximate scan footprint + wave plan (exact names are executor-side;
    # this mirrors them closely enough for explain)
    names = set()
    for d in S.query_dimensions(q):
        names.add(d.dimension)
    for a in S.query_aggregations(q):
        if a.field:
            names.add(a.field)
    from spark_druid_olap_tpu.ops.filters import columns_of_filter
    names |= columns_of_filter(getattr(q, "filter", None))
    names = {c for c in names if c in ds.dims or c in ds.metrics
             or (ds.time is not None and c == ds.time.name)}
    seg_bytes = bytes_per_segment(
        ds, list(names) + ["__rows__"]) if ds.num_segments else 0
    scan_bytes = seg_bytes * len(seg_idx)
    # host-tier reassembly term (multi-host partial stores): a statement
    # shape that drops to the host fallback must rebuild each needed
    # column via the paged allgather — O(rows x column bytes), dwarfing
    # the engine path's O(groups) replication above. Surfaced so explain
    # shows WHY the engine path is worth keeping on a partial store.
    host_xhost = 0
    if getattr(ds, "is_partial", False) and ds.host_assignment is not None \
            and len(ds.host_assignment):
        ds_hosts = int(ds.host_assignment.max()) + 1
        if ds_hosts > 1:
            host_xhost = int(ds.num_rows) * \
                sum(array_itemsize(ds, k) for k in names)
    eff_dev = n_dev if recommend else 1
    spw, waves = plan_waves(len(seg_idx), eff_dev, seg_bytes,
                            wave_budget_bytes(conf), conf, groups, n_aggs)
    return CostEstimate(rows, sel, groups, single, sharded, n_dev, recommend,
                        scan_bytes=scan_bytes, segments_per_wave=spw,
                        n_waves=waves, xhost_bytes=int(xhost_bytes),
                        host_xhost_bytes=int(host_xhost),
                        ici_bytes=int(ici_bytes))


@dataclasses.dataclass
class MeshEstimate:
    """Mesh-or-single pricing for one fused shared-scan group
    (parallel/meshexec.py:decide). The solo path's ``estimate`` prices a
    whole query spec; the fused tier already holds planned lanes, so
    this variant takes the resolved quantities directly — including the
    EXACT merged-payload byte count the packers will ship across the
    interconnect, not a heuristic."""
    single_cost: float
    sharded_cost: float
    n_devices: int
    merge_bytes: int
    recommend_sharded: bool


def mesh_estimate(conf, *, n_dev: int, rows: int, groups: int,
                  n_aggs: int, merge_bytes: int) -> MeshEstimate:
    """Price one fused dispatch single-device vs sharded over ``n_dev``
    devices. Same unit costs as ``estimate`` — scan splits across the
    mesh at the calibrated parallel efficiency; the merge adds a
    per-row collective term plus the interconnect transport of the
    merged partial payload (``merge_bytes``, already x(n_dev - 1))."""
    scan_c = conf.get(COST_PER_ROW_SCAN)
    merge_c = conf.get(COST_PER_ROW_MERGE)
    byte_c = conf.get(COST_PER_BYTE_TRANSPORT)
    compile_c = conf.get(COST_COMPILE)
    icx_c = conf.get(COST_PER_BYTE_INTERCONNECT)
    eff = max(1e-3, min(1.0, float(conf.get(COST_SHARD_EFFICIENCY))))
    n_dev = max(1, int(n_dev))
    single = rows * scan_c + groups * byte_c * 16
    sharded = (rows / max(n_dev * eff, 1e-9)) * scan_c \
        + groups * n_aggs * merge_c \
        + groups * byte_c * 16 \
        + merge_bytes * icx_c \
        + compile_c * 0.1
    recommend = n_dev > 1 and sharded < single
    if not conf.get(COST_MODEL_ENABLED):
        recommend = n_dev > 1
    return MeshEstimate(single, sharded, n_dev, int(merge_bytes),
                        recommend)


def explain_cost(ctx, q: S.QuerySpec) -> str:
    try:
        out = estimate(ctx, q).table()
    except Exception as e:  # cost must never break explain
        return f"cost: unavailable ({e})"
    try:
        out += _explain_scan_plan(ctx, q)
    except Exception:   # noqa: BLE001 — advisory detail only
        pass
    return out


def _explain_scan_plan(ctx, q: S.QuerySpec) -> str:
    """Physical scan decisions: late-materialization budget and staged
    (post-compaction) filter conjuncts — the explain surface for the
    compact-then-aggregate path."""
    eng = ctx.engine
    f = getattr(q, "filter", None)
    ds = eng.store.get(q.datasource)
    seg_idx = ds.prune_segments(getattr(q, "intervals", None), f)
    cheap, exp = eng._split_filter_staged(f)
    m = eng._plan_compact_m(ds, seg_idx, cheap, sharded=False)
    if m is None:
        return ""
    # ESTIMATE: the execution-time decision additionally sees the agg
    # routes ('ffl' Pallas ceiling), sharding, and overflow memory —
    # none of which exist at explain time (ADVICE r3)
    line = f"\nscan: late-materialize to [{m:,}] survivors (estimate)"
    if exp is not None:
        n_exp = len(exp.fields) if isinstance(exp, S.LogicalFilter) \
            and exp.op == "and" else 1
        line += f" (+{n_exp} gather-heavy conjunct(s) staged after)"
    return line


# =============================================================================
# general join tier pricing (planner/joinplan.py)
# =============================================================================

@dataclasses.dataclass
class JoinEstimate:
    """Broadcast-vs-partitioned pricing for one recognized join.

    ``build_bytes``/``probe_bytes`` are host-row upper bounds over the
    columns the join actually touches; ``shuffle_bytes`` estimates the
    partition exchange (both sides cross the wire twice: shard -> broker
    -> aligned node), priced at the interconnect byte rate like the mesh
    tier's merge traffic."""
    mode: str                  # 'broadcast' | 'partitioned' | 'host'
    probe_bytes: int
    build_bytes: int
    shuffle_bytes: int
    broadcast_cost: float
    partitioned_cost: float
    reason: str

    def table(self) -> str:
        return (f"join: build_bytes={self.build_bytes:,} "
                f"probe_bytes={self.probe_bytes:,} "
                f"shuffle_bytes={self.shuffle_bytes:,} "
                f"broadcast={self.broadcast_cost:.4g} "
                f"partitioned={self.partitioned_cost:.4g} "
                f"-> {self.mode.upper()} ({self.reason})")


def join_side_bytes(ds, cols) -> int:
    """Upper-bound host bytes of one join side restricted to ``cols``."""
    return int(ds.num_rows) * int(sum(array_itemsize(ds, c)
                                      for c in cols))


def join_estimate(config, *, probe_ds, build_ds, probe_cols, build_cols,
                  cluster_nodes: int = 0) -> JoinEstimate:
    """Pick the join tier. ``sdot.join.mode`` forces a tier; in auto
    mode the broadcast byte cap gates eligibility and the cheaper
    estimate wins when both tiers are available."""
    from spark_druid_olap_tpu.utils.config import (
        JOIN_BROADCAST_MAX_BYTES, JOIN_MODE)
    build_bytes = join_side_bytes(build_ds, build_cols)
    probe_bytes = join_side_bytes(probe_ds, probe_cols)
    cap = int(config.get(JOIN_BROADCAST_MAX_BYTES))
    scan_c = config.get(COST_PER_ROW_SCAN)
    byte_c = config.get(COST_PER_BYTE_TRANSPORT)
    icx_c = config.get(COST_PER_BYTE_INTERCONNECT)
    # broadcast: replicate the build table once, stream the probe scan
    bc_cost = build_bytes * byte_c + probe_ds.num_rows * scan_c
    # partitioned: both sides ship twice over the exchange; each node
    # scans 1/N of the probe rows
    shuffle = 2 * (probe_bytes + build_bytes)
    n = max(1, int(cluster_nodes))
    pt_cost = shuffle * icx_c + (probe_ds.num_rows / n) * scan_c
    forced = str(config.get(JOIN_MODE)).lower()
    if forced in ("broadcast", "partitioned", "host"):
        return JoinEstimate(forced, probe_bytes, build_bytes, shuffle,
                            bc_cost, pt_cost, "forced by sdot.join.mode")
    can_bc = build_bytes <= cap
    can_pt = cluster_nodes > 1
    if can_bc and (not can_pt or bc_cost <= pt_cost):
        return JoinEstimate("broadcast", probe_bytes, build_bytes,
                            shuffle, bc_cost, pt_cost,
                            f"build fits cap ({build_bytes:,} <= {cap:,})")
    if can_pt:
        why = "build exceeds broadcast cap" if not can_bc \
            else "exchange prices cheaper"
        return JoinEstimate("partitioned", probe_bytes, build_bytes,
                            shuffle, bc_cost, pt_cost, why)
    if can_bc:
        return JoinEstimate("broadcast", probe_bytes, build_bytes,
                            shuffle, bc_cost, pt_cost, "no cluster")
    return JoinEstimate("host", probe_bytes, build_bytes, shuffle,
                        bc_cost, pt_cost,
                        "build exceeds broadcast cap; no cluster")
