"""Device mesh helpers.

The reference's cluster topology plane (ZooKeeper discovery via
``CuratorConnection.scala``, historical-server assignment in
``DruidMetadataCache.historicalServers:105-148``) collapses, on TPU, into the
JAX device runtime: ``jax.devices()`` *is* the discovery service, and a 1-D
``Mesh`` over the chips is the scan-parallel axis (segments shard across it
the way segments spread across historicals). Multi-host pods extend the same
mesh over ICI/DCN via ``jax.distributed`` — no new code path.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEGMENT_AXIS = "shards"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over (the first n) local devices; the single axis is the
    segment-scan axis."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SEGMENT_AXIS,))


def segment_sharding(mesh: Mesh) -> NamedSharding:
    """[S, R] arrays shard along the segment axis."""
    return NamedSharding(mesh, P(SEGMENT_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_size(mesh: Optional[Mesh]) -> int:
    return 1 if mesh is None else int(np.prod(list(mesh.shape.values())))


def mesh_subset(mesh: Mesh, n_devices: int) -> Mesh:
    """1-D sub-mesh over the first ``n_devices`` of an existing mesh —
    the bench/loadtest A-B legs scale the SAME device population down
    (1, 2, 4, ...) instead of constructing meshes from scratch, so every
    leg shards over a prefix of one device order."""
    devs = list(np.asarray(mesh.devices).reshape(-1))
    n = max(1, min(int(n_devices), len(devs)))
    return Mesh(np.array(devs[:n]), (SEGMENT_AXIS,))


_EMULATED_RE = re.compile(
    r"--xla_force_host_platform_device_count=(\d+)")


def emulated_host_devices() -> Optional[int]:
    """Device count of the CPU-emulated mesh when this process was
    launched with ``--xla_force_host_platform_device_count=N`` (the
    chipless-CI recipe, tests/conftest.py / docs/MESH.md), else None.
    Purely an observability hint — the mesh itself always comes from
    ``jax.devices()``."""
    m = _EMULATED_RE.search(os.environ.get("XLA_FLAGS", ""))
    return int(m.group(1)) if m else None


def shard_map(fn, *, mesh: Mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions. Newer jax exposes it at top
    level (with ``check_vma``); 0.4.x only ships
    ``jax.experimental.shard_map`` (same semantics, ``check_rep``). Every
    engine shard_map site routes through here so the collective paths run
    on whichever jax the host has — this is what keeps the CPU-emulated
    8-device mesh (tests/conftest.py) a live surface rather than an
    AttributeError."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm  # jax < 0.5
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=check_vma)
