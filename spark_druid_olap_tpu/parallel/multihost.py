"""Multi-host execution: one JAX process per host, one global mesh.

≈ the reference's genuinely distributed plane: segments are assigned to
historical servers by priority and least-load
(``DruidMetadataCache.assignHistoricalServers``,
``metadata/DruidMetadataCache.scala:105-148``) and a scan fans out one
Spark partition per (server × segment group)
(``DruidRDD.getPartitions:244-277``). The TPU translation:

- ``jax.distributed.initialize`` joins every host's process into one
  runtime; ``jax.devices()`` then lists EVERY chip in the pod and the
  1-D segment mesh (``mesh.make_mesh``) spans them. ICI/DCN collectives
  (psum / all_gather inside ``shard_map``) replace the broker merge.
- **Host-level segment ownership** (``assign_segments_to_hosts``):
  contiguous time-blocks balanced by rows — contiguity keeps interval
  pruning host-aligned, the balance term is the least-load analog. Each
  process materializes ONLY its own segments' column data
  (``Datasource.local_seg_ids``); global metadata (segment bounds,
  dictionaries from the streamer's pass A) is replicated everywhere, so
  planning stays deterministic across processes.
- **Transfers provide only local shards**: a globally-sharded array is
  assembled with ``jax.make_array_from_callback`` — the callback is
  invoked per locally-addressable device and reads the local store
  block (``layout_segments`` fixes the segment→device order so every
  host's devices carry exactly that host's segments; no cross-host
  traffic at bind time).
- Sharded programs whose outputs stayed per-chip in single-process mode
  (the hashed tier's slot tables) gain an in-mesh ``all_gather`` so the
  result is replicated and every process can fetch it (the executor's
  ``_shard_wrap``).

Every *planning* decision (pruning, slot sizing, wave split, compaction
budgets) runs on metadata that is identical on every process — a
divergent decision would deadlock the mesh, so zone-map pruning (which
reads per-host column data) is disabled for partial datasources
(``store.Datasource._filter_keep_mask``).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

import jax


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_count: Optional[int] = None) -> None:
    """Join this process into the multi-host JAX runtime. Call before any
    other JAX use (backend initialization pins the topology).

    ``local_device_count`` forces N virtual CPU devices per process — the
    test rig for multi-host sharding without N real chips (the same trick
    as the single-process virtual mesh, conftest.py)."""
    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={local_device_count}"
        if want not in flags:
            os.environ["XLA_FLAGS"] = f"{flags} {want}".strip()
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def is_multihost() -> bool:
    try:
        return jax.process_count() > 1
    except Exception:   # noqa: BLE001 — uninitialized backend
        return False


def assign_segments_to_hosts(row_counts: np.ndarray,
                             n_hosts: int) -> np.ndarray:
    """[S] -> host id. Contiguous time-blocks balanced by rows.

    Segments are time-ordered, so contiguous blocks keep a host's data one
    time range (interval pruning then prunes whole hosts, the way Druid's
    time-chunk assignment does); the row-balance objective is the
    least-load term of ``assignHistoricalServers``. Greedy split at the
    ideal cumulative boundaries — deterministic, metadata-only (every
    process computes the identical assignment)."""
    rows = np.asarray(row_counts, dtype=np.int64)
    s = len(rows)
    if n_hosts <= 1 or s == 0:
        return np.zeros(s, dtype=np.int32)
    cum = np.cumsum(rows)
    total = int(cum[-1])
    out = np.zeros(s, dtype=np.int32)
    # boundary h sits where cumulative rows pass h/n of the total
    targets = total * np.arange(1, n_hosts) / n_hosts
    cuts = np.searchsorted(cum - rows / 2.0, targets)
    prev = 0
    for h, c in enumerate(np.clip(cuts, 0, s)):
        out[prev:c] = h
        prev = max(prev, int(c))
    out[prev:] = n_hosts - 1
    return out


def host_blocks(mesh) -> Tuple[int, int]:
    """(n_hosts, devices_per_host) of the 1-D segment mesh. Requires the
    homogeneous-pod shape (same chip count per host) — the only topology
    ``jax.distributed`` + a dense Mesh supports cleanly."""
    n_proc = jax.process_count()
    n_dev = int(np.prod(list(mesh.shape.values())))
    if n_dev % n_proc:
        raise ValueError(
            f"mesh of {n_dev} devices over {n_proc} processes is not "
            f"host-homogeneous")
    return n_proc, n_dev // n_proc


def layout_segments(assignment: np.ndarray, seg_idx: np.ndarray,
                    n_hosts: int, devs_per_host: int):
    """Fix the segment→device order for a (pruned) selection so each
    host's devices scan exactly that host's segments.

    Returns ``(ordered, s_pad)``: ``ordered`` is a [n_hosts * per_host]
    int64 array of global segment ids with ``-1`` padding slots (empty,
    row-validity False), ``per_host`` padded to a common multiple of
    ``devs_per_host`` so the global segment axis divides evenly. Every
    process computes this identically from global metadata — it is the
    multi-host replacement for the executor's contiguous ``_pad_segments``
    split."""
    seg_idx = np.asarray(seg_idx, dtype=np.int64)
    per_host_lists = [seg_idx[assignment[seg_idx] == h]
                      for h in range(n_hosts)]
    longest = max((len(x) for x in per_host_lists), default=0)
    longest = max(longest, 1)
    per_host = -(-longest // devs_per_host) * devs_per_host
    ordered = np.full(n_hosts * per_host, -1, dtype=np.int64)
    for h, lst in enumerate(per_host_lists):
        ordered[h * per_host: h * per_host + len(lst)] = lst
    return ordered, per_host


def layout_segments_waves(assignment: np.ndarray, seg_idx: np.ndarray,
                          n_hosts: int, devs_per_host: int, n_waves: int,
                          seg_bytes: int = 0, wave_budget: int = 0):
    """Wave-mode variant of ``layout_segments`` (VERDICT r4 item 2: waves
    must compose with multi-host — SF100's overflow valve).

    Each WAVE is itself a host-blocked layout: wave ``w`` holds the
    ``w``-th chunk of every host's pruned segment list, padded to a common
    per-host-per-wave count that divides ``devs_per_host``. Returns
    ``(ordered, spw)``: ``ordered`` is [n_waves_eff * spw] with ``-1``
    padding; contiguous ``spw``-slices of it are exactly the per-wave
    layouts the executor's wave loop already slices, so ``_run_waves``
    needs no multi-host awareness beyond the shard-aware bind. Every
    process computes this identically from global metadata."""
    seg_idx = np.asarray(seg_idx, dtype=np.int64)
    per_host_lists = [seg_idx[assignment[seg_idx] == h]
                      for h in range(n_hosts)]
    longest = max((len(x) for x in per_host_lists), default=0)
    longest = max(longest, 1)
    n_waves = max(1, min(int(n_waves), longest))
    phw = -(-longest // n_waves)                   # per host per wave
    phw = -(-phw // devs_per_host) * devs_per_host
    if seg_bytes and wave_budget:
        # cap per-host-per-wave from the byte budget DIRECTLY: the
        # caller's n_waves assumed a balanced assignment, so a host
        # owning more than its share would bind phw/devs_per_host
        # segments past the per-device budget (the HBM-overflow valve)
        phw_budget = max(1, int(wave_budget) // int(seg_bytes)) \
            * devs_per_host
        phw = min(phw, max(phw_budget, devs_per_host))
    n_waves_eff = -(-longest // phw)
    spw = n_hosts * phw
    ordered = np.full(n_waves_eff * spw, -1, dtype=np.int64)
    for h, lst in enumerate(per_host_lists):
        for w in range(n_waves_eff):
            blk = lst[w * phw: (w + 1) * phw]
            base = w * spw + h * phw
            ordered[base: base + len(blk)] = blk
    return ordered, spw


def exchange_block(local: np.ndarray):
    """All-gather a VARIABLE-LENGTH per-process numpy array; returns one
    array per process (ascending process id). The cross-process host-data
    exchange under select paging, search counts, and the host-tier
    gather on partial stores (≈ the reference's Spark-side fallback scan
    pulling rows off historicals, ``DruidRDD.getPartitions:244-277``).

    Works on numeric/bool arrays only (dimensions travel as dictionary
    CODES and decode against the replicated global dictionary). int64
    payloads travel as (2x int32) words so the exchange survives non-x64
    backends, where jnp silently canonicalizes int64 to int32."""
    from jax.experimental import multihost_utils as mhu
    local = np.ascontiguousarray(local)
    n_proc = jax.process_count()
    if n_proc <= 1:
        return [local]
    orig_dtype = local.dtype
    orig_trailing = local.shape[1:]
    if orig_dtype == np.bool_:
        local = local.astype(np.uint8)
    elif orig_dtype in (np.dtype(np.int64), np.dtype(np.uint64),
                        np.dtype(np.float64)) \
            and not jax.config.jax_enable_x64:
        local = local.view(np.int32).reshape(local.shape + (2,))
    sizes = np.asarray(mhu.process_allgather(
        np.asarray([local.shape[0]], np.int32))).reshape(-1)
    m = int(sizes.max()) if sizes.size else 0
    if m == 0:
        return [np.empty((0,) + orig_trailing, orig_dtype)
                for _ in range(n_proc)]
    if local.shape[0] < m:
        pad = np.zeros((m - local.shape[0],) + local.shape[1:],
                       local.dtype)
        local = np.concatenate([local, pad], axis=0)
    out = np.asarray(mhu.process_allgather(local))   # [P, m, ...]
    blocks = []
    for p in range(out.shape[0]):
        blk = out[p, : int(sizes[p])]
        if orig_dtype == np.bool_:
            blk = blk.astype(np.bool_)
        elif blk.dtype != orig_dtype and blk.shape[-1:] == (2,):
            blk = np.ascontiguousarray(blk).view(orig_dtype) \
                .reshape(blk.shape[:-1])
        blocks.append(blk)
    return blocks


def put_sharded_blocks(build_block, ordered: np.ndarray, row_dim: int,
                       dtype, sharding) -> jax.Array:
    """Assemble the global [len(ordered), row_dim] device array, providing
    only locally-addressable shards. ``build_block(segment_ids)`` returns
    the host rows for a block of the ``ordered`` layout (padding ids (-1)
    and non-local ids must yield zero rows — callers use
    ``ops.scan.build_array_blocks`` which enforces that)."""
    gshape = (len(ordered), row_dim)

    def cb(index):
        sl = index[0] if index else slice(None)
        return build_block(ordered[sl])

    return jax.make_array_from_callback(gshape, sharding, cb)
