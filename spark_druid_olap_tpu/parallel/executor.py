"""Query executor: lowers a QuerySpec onto compiled XLA scan programs and runs
them single-chip or sharded over a device mesh.

This layer merges three reference components, re-seamed for TPU:

- ``DruidRDD`` (``DruidRDD.scala:152-277``): partitioning the scan across
  historicals/segments -> here, the segment axis of the stacked tensors,
  sharded over the mesh by ``shard_map``;
- the broker/historical scatter-gather + Spark-side final aggregate
  (``DruidStrategy.scala:349-360``, ``PostAggregate``): -> ICI collectives
  (psum/pmin/pmax) inside the compiled program;
- result-row materialization (``DruidRDD.scala:235-241`` value transforms):
  -> host-side group decoding through the global dictionaries.

Compile model: one XLA program per (query structure, padded shapes) — cached,
so repeated dashboard-style queries hit a warm executable (the reference's
analog is Druid's own query planning being stateless but fast; our compile
cost is front-loaded and amortized, tracked by the cost model's compile-cost
knob).
"""

from __future__ import annotations

import dataclasses
import os as _os
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.parallel import multihost as MH
from spark_druid_olap_tpu.ops import expr_compile as EC
from spark_druid_olap_tpu.ops import filters as F
from spark_druid_olap_tpu.ops import groupby as G
from spark_druid_olap_tpu.ops import hash_groupby as H
from spark_druid_olap_tpu.ops import hll as HLL
from spark_druid_olap_tpu.ops import kll as KLL
from spark_druid_olap_tpu.ops import pallas_groupby as PG_tpu
from spark_druid_olap_tpu.ops import sorted_groupby as SG
from spark_druid_olap_tpu.ops import theta as TH
from spark_druid_olap_tpu.ops import time_ops as T
from spark_druid_olap_tpu.ops import timezone as TZ
from spark_druid_olap_tpu.ops.scan import (
    CompactScanContext,
    ScanContext,
    array_dtype,
    array_names,
    build_array,
    build_array_blocks,
    ROW_VALID_KEY,
    NULL_VALID_PREFIX,
    TIME_MS_KEY,
)
from spark_druid_olap_tpu.parallel import cost as C
from spark_druid_olap_tpu.parallel.mesh import (SEGMENT_AXIS, mesh_size,
                                                 shard_map)
from spark_druid_olap_tpu.planner import fusion as FU
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.segment.column import ColumnKind
from spark_druid_olap_tpu.segment.store import (Datasource, Segment,
                                                SegmentStore)
from spark_druid_olap_tpu.utils import host_eval
from spark_druid_olap_tpu.utils import phases as PH
from spark_druid_olap_tpu.utils.config import (
    Config,
    TZ_ID,
    BACKEND_RETRY_SECONDS,
    DEVICE_CACHE_BYTES,
    ENCODE_ENABLED,
    GROUPBY_DENSE_MAX_KEYS,
    SCAN_COMPACT,
    SCAN_COMPACT_MIN_ROWS,
    GROUPBY_HASH_COMPACT_MIN,
    GROUPBY_HASH_MAX_SLOTS,
    GROUPBY_HASH_SORTED,
    GROUPBY_HASH_SLOTS,
    GROUPBY_MATMUL_MAX_KEYS,
    GROUPBY_PALLAS_MAX_KEYS,
    HAVING_DEVICE_MIN_KEYS,
    HLL_LOG2M,
    QUANTILE_LANES,
    SELECT_DEVICE_MIN_ROWS,
    SHAREDSCAN_FUSION_ENABLED,
    TOPN_DEVICE_MIN_KEYS,
)


_STAGE_TIMING = _os.environ.get("SDOT_STAGE_TIMING", "") == "1"
# SDOT_PROFILE_DISPATCH=N: amortized true-device-time measurement — the
# dispatch sites re-run the compiled program N extra times back-to-back
# and record (sync-to-sync time)/N as last_stats['profile_device_ms'],
# factoring out the tunnel RTT jitter a single dispatch+sync includes
try:
    _PROFILE_N = int(_os.environ.get("SDOT_PROFILE_DISPATCH", "0"))
except ValueError:
    _PROFILE_N = 0


def set_profile_dispatch(n: Optional[int]) -> None:
    """Runtime override of SDOT_PROFILE_DISPATCH (None restores the env
    value) — bench.py profiles one rep per query this way so scan GB/s is
    denominated in measured device time, not RTT-contaminated wall."""
    global _PROFILE_N
    if n is None:
        try:
            n = int(_os.environ.get("SDOT_PROFILE_DISPATCH", "0"))
        except ValueError:
            n = 0
    _PROFILE_N = int(n)


class EngineFallback(Exception):
    """Query (or part) can't run on the device path; planner must evaluate a
    host residual instead. ≈ the reference leaving unpushable predicates
    above the Druid scan (``ProjectFilterTransfom.addUnpushedAttributes``)."""


class QueryCancelled(RuntimeError):
    """Raised when a registered query id is cancelled mid-flight.

    ≈ the reference's cooperative cancellation: Spark task interruption
    relayed to abort the in-flight Druid HTTP call (``TaskCancelHandler``
    ``DruidRDD.scala:428-491``, ``CancellableHolder``
    ``DruidClient.scala:82-124``). A dispatched XLA program itself is not
    interruptible (neither was Druid's in-progress segment scan) — the check
    fires at stage boundaries: before dispatch, after the device round-trip,
    and per select page."""


class QueryTimeout(RuntimeError):
    """Raised when QueryContext.timeout_millis elapses at a stage boundary."""


# =============================================================================
# dimension planning (host side; card/decode known before tracing)
# =============================================================================

@dataclasses.dataclass
class DimPlan:
    output_name: str
    card: int
    build: object            # ctx -> int32 codes in [0, card)
    decode: object           # np.ndarray[int] -> np.ndarray of output values
    source_cols: tuple


def _with_null_slot(build, decode, card, name, nullable):
    """Nullable grouping columns get slot 0 = the null group (Druid emits a
    null group for null dimension values); non-null codes shift by one."""
    if not nullable:
        return build, decode, card

    def build2(ctx):
        nv = ctx.null_valid(name)
        codes = build(ctx)
        if nv is None:
            return codes + 1
        return jnp.where(nv, codes + 1, 0)

    def decode2(idx):
        idx = np.asarray(idx, np.int64)
        vals = decode(np.maximum(idx - 1, 0))
        out = np.empty(len(idx), dtype=object)
        out[:] = [None if i == 0 else v for i, v in zip(idx, vals)]
        return out

    return build2, decode2, card + 1


def _plan_plain(name: str, ds: Datasource, out: str, min_day, max_day) -> DimPlan:
    kind = ds.column_kind(name)
    if kind == ColumnKind.DIM:
        col = ds.dims[name]
        build, decode, card = _with_null_slot(
            lambda ctx: ctx.col(name),
            lambda idx: col.dictionary[np.asarray(idx, np.int64)],
            col.cardinality, name, col.validity is not None)
        return DimPlan(out, card, build, decode, (name,))
    if kind == ColumnKind.DATE:
        m = ds.metrics[name]
        lo = int(m.min) if m.min is not None else 0
        hi = int(m.max) if m.max is not None else 0
        build, decode, card = _with_null_slot(
            lambda ctx: ctx.col(name) - lo,
            lambda idx: (np.asarray(idx, np.int64) + lo)
            .astype("datetime64[D]"),
            hi - lo + 1, name, m.validity is not None)
        return DimPlan(out, card, build, decode, (name,))
    if kind == ColumnKind.LONG:
        m = ds.metrics[name]
        lo = int(m.min) if m.min is not None else 0
        hi = int(m.max) if m.max is not None else 0
        if hi - lo + 1 >= H.PART_LIMIT:
            # beyond one int32 key part even alone; hashed path can't pack it
            raise EngineFallback(f"grouping on wide-range long {name}")
        build, decode, card = _with_null_slot(
            lambda ctx: ctx.col(name) - lo,
            lambda idx: np.asarray(idx, np.int64) + lo,
            hi - lo + 1, name, m.validity is not None)
        return DimPlan(out, card, build, decode, (name,))
    if kind == ColumnKind.TIME:
        # raw-time grouping only supported at day grain via extraction
        raise EngineFallback("group by raw time column; use an extraction")
    raise EngineFallback(f"group by {kind}")


_FIELD_CARDS = {"month": (1, 12), "quarter": (1, 4), "day": (1, 31),
                "dow": (1, 7), "doy": (1, 366), "hour": (0, 23),
                "minute": (0, 59), "second": (0, 59)}


def _plan_time_extraction(dspec: S.DimensionSpec, ds: Datasource,
                          min_day: int, max_day: int,
                          tz: str = "UTC") -> DimPlan:
    ex = dspec.extraction
    assert isinstance(ex, S.TimeExtraction)
    name = dspec.dimension
    kind = ds.column_kind(name)
    if kind not in (ColumnKind.TIME, ColumnKind.DATE, ColumnKind.DIM):
        raise EngineFallback(f"time extraction over {kind}")
    if kind == ColumnKind.DIM:
        # date-string dim: convert through host LUT then treat as days
        # (calendar dates — timezone-independent)
        col = ds.dims[name]
        lut = np.array([T.date_literal_to_days(s) if s else 0
                        for s in col.dictionary], dtype=np.int32)
        day_build = lambda ctx: EC._take_lut(lut, ctx.col(name))
        lo_day, hi_day = int(lut.min()), int(lut.max())
    elif kind == ColumnKind.DATE:
        # calendar dates — timezone-independent
        m = ds.metrics[name]
        lo_day = int(m.min) if m.min is not None else 0
        hi_day = int(m.max) if m.max is not None else 0
        day_build = lambda ctx: ctx.col(name)
    elif not TZ.is_utc(tz):
        # instants: shift to session-local wall-clock before extraction
        lo_day, hi_day = min_day - 1, max_day + 1
        _tzlut = TZ.day_offset_lut(tz, lo_day, hi_day)

        def dt_build(ctx):
            return TZ.shift_days_ms(ctx.col(name), ctx.time_ms(), _tzlut,
                                    lo_day)

        day_build = lambda ctx: dt_build(ctx)[0]
    else:
        lo_day, hi_day = min_day, max_day
        day_build = lambda ctx: ctx.col(name)
    if kind == ColumnKind.TIME and not TZ.is_utc(tz):
        ms_build = lambda ctx: dt_build(ctx)[1]
    elif kind == ColumnKind.TIME:
        ms_build = lambda ctx: ctx.time_ms()
    else:
        ms_build = lambda ctx: None

    field = ex.field
    if field.startswith("trunc_"):
        grain = field[len("trunc_"):]
        def build(ctx, grain=grain):
            days = day_build(ctx)
            b, _, _ = T.bucket_and_cardinality(grain, days, ms_build(ctx),
                                               lo_day, hi_day)
            return b
        _, card, decode1 = T.bucket_and_cardinality(
            grain, np.zeros(1, np.int32), np.zeros(1, np.int32),
            lo_day, hi_day)
        decode = lambda idx: np.array([decode1(i) for i in np.asarray(idx)],
                                      dtype="datetime64[ms]")
        return DimPlan(dspec.output_name, card, build, decode, (name,))
    if field == "year":
        y_lo = host_eval._civil(np.array([lo_day]))[0][0]
        y_hi = host_eval._civil(np.array([hi_day]))[0][0]
        card = int(y_hi - y_lo + 1)
        def build(ctx):
            days = day_build(ctx)
            return T.extract_field("year", days) - int(y_lo)
        return DimPlan(dspec.output_name, card, build,
                       lambda idx: np.asarray(idx, np.int64) + int(y_lo),
                       (name,))
    if field == "week":
        lo = (lo_day + 3) // 7
        hi = (hi_day + 3) // 7
        def build(ctx):
            return T.extract_field("week", day_build(ctx)) - lo
        return DimPlan(dspec.output_name, hi - lo + 1, build,
                       lambda idx: ((np.asarray(idx, np.int64) + lo) * 7 - 3)
                       .astype("datetime64[D]"), (name,))
    if field in _FIELD_CARDS:
        f_lo, f_hi = _FIELD_CARDS[field]
        needs_ms = field in ("hour", "minute", "second")
        if needs_ms and kind != ColumnKind.TIME:
            raise EngineFallback(f"{field} of a date column")
        def build(ctx, field=field, f_lo=f_lo):
            return T.extract_field(field, day_build(ctx),
                                   ms_build(ctx)) - f_lo
        return DimPlan(dspec.output_name, f_hi - f_lo + 1, build,
                       lambda idx: np.asarray(idx, np.int64) + f_lo, (name,))
    raise EngineFallback(f"time extraction field {field}")


def plan_granularity_dim(gran: S.Granularity, ds: Datasource, min_day: int,
                         max_day: int, tz: str = "UTC") -> DimPlan:
    """Granularity bucketing as a leading group dimension named 'timestamp'
    (Druid result rows' timestamp field). Uses absolute time buckets for
    every grain incl. hour/minute/duration. Non-UTC sessions bucket in
    LOCAL wall-clock time and label buckets with their local start."""
    if ds.time is None:
        raise EngineFallback("granularity on time-less datasource")
    tname = ds.time.name
    kind = gran.kind
    if kind == "none":
        raise EngineFallback("'none' granularity (row-grain) on agg path")
    shift = not TZ.is_utc(tz)
    lo_day, hi_day = (min_day - 1, max_day + 1) if shift \
        else (min_day, max_day)
    tzlut = TZ.day_offset_lut(tz, lo_day, hi_day) if shift else None
    try:
        _, card, decode1 = T.bucket_and_cardinality(
            kind, np.zeros(1, np.int32), np.zeros(1, np.int32),
            lo_day, hi_day, gran.duration_millis)
    except ValueError as e:
        raise EngineFallback(str(e))

    def build(ctx):
        days, ms = ctx.col(tname), ctx.time_ms()
        if shift:
            days, ms = TZ.shift_days_ms(days, ms, tzlut, lo_day)
        b, _, _ = T.bucket_and_cardinality(
            kind, days, ms, lo_day, hi_day, gran.duration_millis)
        return b

    decode = lambda idx: np.array([decode1(i) for i in np.asarray(idx)],
                                  dtype="datetime64[ms]")
    return DimPlan("timestamp", card, build, decode, (tname,))


def _plan_expr_extraction(dspec: S.DimensionSpec, ds: Datasource,
                          min_day: int, max_day: int) -> DimPlan:
    ex = dspec.extraction
    assert isinstance(ex, S.ExprExtraction)
    cols = sorted(E.columns_in(ex.expr))
    # single string-dim expression: evaluate over the dictionary domain on
    # host, factorize, remap codes through a LUT (dictionary-functional path)
    if len(cols) == 1 and cols[0] in ds.dims:
        dim = ds.dims[cols[0]]
        try:
            vals = host_eval.eval_expr(ex.expr, {cols[0]: dim.dictionary})
        except host_eval.HostEvalError as e:
            raise EngineFallback(str(e))
        vals = np.asarray(vals)
        if vals.shape != dim.dictionary.shape:
            raise EngineFallback("non-elementwise dim expression")
        uniq, remap = np.unique(vals.astype(object) if vals.dtype == object
                                else vals, return_inverse=True)
        lut = remap.astype(np.int32)
        name = cols[0]
        return DimPlan(dspec.output_name, len(uniq),
                       lambda ctx: EC._take_lut(lut, ctx.col(name)),
                       lambda idx: uniq[np.asarray(idx, np.int64)],
                       (name,))
    # general expression: compile to device; needs a declared or derivable
    # small integer range
    card = ex.cardinality
    if card is None:
        raise EngineFallback(
            "expression dimension without cardinality bound "
            f"({E.to_sql(ex.expr)})")

    def build(ctx):
        v = EC.compile_expr(ex.expr, ctx)
        if isinstance(v, EC.BoolValue):
            return v.arr.astype(jnp.int32)
        if isinstance(v, EC.NumValue) and not v.is_float:
            return jnp.clip(v.arr, 0, card - 1)
        raise EC.Unsupported("expression dimension must be int/bool")

    return DimPlan(dspec.output_name, card, build,
                   lambda idx: np.asarray(idx, np.int64), tuple(cols))


def _plan_dict_transform(dspec: S.DimensionSpec, ds: Datasource,
                         vals_fn) -> DimPlan:
    """Dictionary-functional extraction: apply ``vals_fn`` to the dim's
    dictionary on host (may yield None entries = null), factorize, and remap
    codes through a constant LUT on device. Null output (and null input
    rows) land in slot 0."""
    name = dspec.dimension
    if ds.column_kind(name) != ColumnKind.DIM:
        raise EngineFallback("lookup/regex extraction over non-string column")
    dim = ds.dims[name]
    vals = vals_fn(dim.dictionary)
    null_mask = np.array([v is None for v in vals], dtype=bool)
    uniq = np.unique(np.asarray(
        [str(v) for v, nm in zip(vals, null_mask) if not nm], dtype=object)) \
        if (~null_mask).any() else np.empty(0, dtype=object)
    pos = {v: j for j, v in enumerate(uniq)}
    lut = np.array([0 if nm else 1 + pos[str(v)]
                    for v, nm in zip(vals, null_mask)], dtype=np.int32)
    has_nulls = dim.validity is not None

    def build(ctx):
        mapped = EC._take_lut(lut, ctx.col(name))
        if has_nulls:
            nv = ctx.null_valid(name)
            mapped = jnp.where(nv, mapped, 0)
        return mapped

    def decode(idx):
        idx = np.asarray(idx, np.int64)
        out = np.empty(len(idx), dtype=object)
        out[:] = [None if i == 0 else uniq[i - 1] for i in idx]
        return out

    return DimPlan(dspec.output_name, len(uniq) + 1, build, decode, (name,))


def _lookup_vals_fn(ex: S.LookupExtraction):
    table = dict(ex.lookup)

    def vals_fn(dictionary):
        out = []
        for s in dictionary:
            if s in table:
                out.append(table[s])
            elif ex.retain_missing:
                out.append(s)
            else:
                out.append(ex.replace_missing_with)
        return out
    return vals_fn


def _regex_vals_fn(ex: S.RegexExtraction):
    import re as _re
    rx = _re.compile(ex.pattern)

    def vals_fn(dictionary):
        out = []
        for s in dictionary:
            m = rx.search(s) if s is not None else None
            if m is not None:
                out.append(m.group(ex.index))
            elif ex.replace_missing:
                out.append(ex.replace_missing_with)
            else:
                out.append(s)
        return out
    return vals_fn


def plan_dimension(dspec: S.DimensionSpec, ds: Datasource, min_day: int,
                   max_day: int, tz: str = "UTC") -> DimPlan:
    try:
        if dspec.extraction is None:
            return _plan_plain(dspec.dimension, ds, dspec.output_name,
                               min_day, max_day)
        if isinstance(dspec.extraction, S.TimeExtraction):
            return _plan_time_extraction(dspec, ds, min_day, max_day, tz)
        if isinstance(dspec.extraction, S.LookupExtraction):
            return _plan_dict_transform(dspec, ds,
                                        _lookup_vals_fn(dspec.extraction))
        if isinstance(dspec.extraction, S.RegexExtraction):
            return _plan_dict_transform(dspec, ds,
                                        _regex_vals_fn(dspec.extraction))
        if isinstance(dspec.extraction, S.ExprExtraction):
            return _plan_expr_extraction(dspec, ds, min_day, max_day)
    except EC.Unsupported as e:
        raise EngineFallback(str(e))
    raise EngineFallback(f"extraction {type(dspec.extraction).__name__}")


# =============================================================================
# aggregation planning
# =============================================================================

@dataclasses.dataclass
class AggPlan:
    spec: S.AggregationSpec
    kind: str                    # 'count'|'sum'|'min'|'max'|'hll'
    out_dtype: object
    source_cols: tuple
    is_int: bool = False         # integer-exact device lanes (i32 storage)
    maxabs: Optional[float] = None   # static |value| bound (col metadata)
    dim_codes: bool = False      # min/max over a NON-numeric string dim:
    #   aggregate the dictionary CODES (the global dictionary is sorted
    #   ascending, segment/column.py:46, so code order IS lexicographic
    #   order) and decode the extremum code to its string at output

    def build_values(self, ctx: ScanContext):
        a = self.spec
        if a.kind == "anyvalue":
            # FD-demoted grouping column: any row's value works (max); dims
            # contribute their dictionary code, decoded at output
            return ctx.col(a.field)
        if a.field is not None:
            k = ctx.kind(a.field)
            if self.kind in ("hll", "theta"):
                if k == ColumnKind.DIM:
                    return ctx.col(a.field)
                if k in (ColumnKind.LONG, ColumnKind.DATE):
                    return ctx.col(a.field)
                if k == ColumnKind.DOUBLE:
                    return ctx.col(a.field).view(jnp.int32) \
                        if hasattr(ctx.col(a.field), "view") else \
                        jax.lax.bitcast_convert_type(ctx.col(a.field),
                                                     jnp.int32)
                raise EngineFallback(f"cardinality over {k}")
            if self.kind == "kll":
                # quantile domain: the actual numeric values (canonical
                # f32 inside kll_registers so every tier sees one bit
                # pattern per value)
                if k in (ColumnKind.LONG, ColumnKind.DOUBLE):
                    return ctx.col(a.field)
                raise EngineFallback(f"quantile over {k}")
            if k in (ColumnKind.LONG, ColumnKind.DOUBLE, ColumnKind.DATE):
                return ctx.col(a.field)
            if k == ColumnKind.DIM and self.dim_codes:
                return ctx.col(a.field)          # sorted-dict codes
            if k == ColumnKind.DIM and self.kind in ("min", "max", "sum"):
                # numeric-parsed dim (Druid coerces); host LUT
                lut = np.array([host_eval_try_float(s)
                                for s in ctx.dictionary(a.field)],
                               dtype=np.float32)
                return EC._take_lut(lut, ctx.col(a.field))
            raise EngineFallback(f"aggregate {a.kind} over {k}")
        if a.expr is not None:
            v = EC.compile_expr(a.expr, ctx)
            n = EC._as_num(v, ctx)
            return n.arr
        return None

    def build_mask(self, ctx: ScanContext, cse=None):
        """``cse`` (planner.fusion.CSECache, bound to ``ctx``) memoizes
        the filter lowering so aggregation filters repeated within a
        query — or across fused shared-scan lanes — lower once."""
        a = self.spec
        masks = []
        if a.filter is not None:
            m = cse.lower(a.filter) if cse is not None \
                else F.lower_filter(a.filter, ctx)
            if m is not None:
                masks.append(m)
        if a.field is not None:
            nv = ctx.null_valid(a.field)
            if nv is not None:
                masks.append(nv)
        if a.expr is not None:
            for c in E.columns_in(a.expr):
                nv = ctx.null_valid(c)
                if nv is not None:
                    masks.append(nv)
        if not masks:
            return None
        out = masks[0]
        for m in masks[1:]:
            out = out & m
        return out


def host_eval_try_float(s):
    try:
        return float(s)
    except (TypeError, ValueError):
        return np.nan


_AGG_KIND = {"count": ("count", np.int64), "longsum": ("sum", np.int64),
             "doublesum": ("sum", np.float64), "longmin": ("min", np.int64),
             "longmax": ("max", np.int64), "doublemin": ("min", np.float64),
             "doublemax": ("max", np.float64),
             "cardinality": ("hll", np.int64),
             "thetasketch": ("theta", np.int64),
             "quantile": ("kll", np.float64),
             "anyvalue": ("max", np.float64)}


def _identity_row(kinds_by_name) -> Dict[str, np.ndarray]:
    """The one identity row of a GLOBAL aggregate over zero rows — SQL
    semantics (and Druid's default timeseries behavior, minus its sum-is-0
    quirk): count/hll -> 0, sum/min/max -> NULL."""
    return {name: (np.array([0], dtype=np.int64)
                   if kind in ("count", "hll", "theta")
                   else np.array([np.nan]))
            for name, kind in kinds_by_name.items()}


def _col_bounds(ds: Datasource, name: str):
    """(is_int, maxabs) of a column's device representation (i32 codes/days/
    longs are integer-exact; DOUBLE is f32)."""
    kind = ds.column_kind(name)
    if kind == ColumnKind.DIM:
        return True, float(max(ds.dims[name].cardinality, 1))
    m = ds.metrics.get(name)
    if m is None:
        if ds.time is not None and name == ds.time.name:
            return True, float(2**31)
        return False, None
    lo = float(m.min) if m.min is not None else None
    hi = float(m.max) if m.max is not None else None
    maxabs = max(abs(lo), abs(hi)) if lo is not None and hi is not None \
        else None
    return kind in (ColumnKind.LONG, ColumnKind.DATE), maxabs


def _expr_bounds(e: E.Expr, ds: Datasource):
    """Conservative static (is_int, maxabs) of an expression's compiled
    device value — drives the exact-integer route for pushed-down
    ``sum(case when ...)``-style aggregates. Returns (False, None) when it
    can't tell."""
    if isinstance(e, E.Literal):
        v = e.value
        if isinstance(v, bool):
            return True, 1.0
        if isinstance(v, int):
            return True, float(abs(v))
        if isinstance(v, float):
            return False, float(abs(v))
        return False, None
    if isinstance(e, E.Column):
        # DIM columns lower to f32 parsed-LUT values in expressions (codes
        # are only integer-exact on the direct anyvalue/field path)
        if ds.column_kind(e.name) == ColumnKind.DIM:
            return False, None
        return _col_bounds(ds, e.name)
    if isinstance(e, E.Cast):
        i, m = _expr_bounds(e.child, ds)
        if e.to in ("int", "long", "integer", "bigint"):
            return True, m
        return i, m
    if isinstance(e, E.BinaryOp):
        li, lm = _expr_bounds(e.left, ds)
        ri, rm = _expr_bounds(e.right, ds)
        both = lm is not None and rm is not None
        if e.op in ("+", "-"):
            return li and ri, (lm + rm) if both else None
        if e.op == "*":
            return li and ri, (lm * rm) if both else None
        return False, None
    if isinstance(e, E.Case):
        is_int, maxabs = True, 0.0
        branches = [v for _, v in e.branches] + \
            ([e.otherwise] if e.otherwise is not None else [])
        for b in branches:
            bi, bm = _expr_bounds(b, ds)
            is_int &= bi
            if bm is None or maxabs is None:
                maxabs = None
            else:
                maxabs = max(maxabs, bm)
        return is_int, maxabs
    if isinstance(e, (E.Comparison, E.And, E.Or, E.Not, E.IsNull, E.InList,
                      E.Between, E.Like)):
        return True, 1.0
    return False, None


def plan_aggregation(a: S.AggregationSpec, ds: Datasource) -> AggPlan:
    if a.kind not in _AGG_KIND:
        raise EngineFallback(f"aggregation kind {a.kind}")
    kind, dtype = _AGG_KIND[a.kind]
    cols = set()
    is_int, maxabs = False, None
    if a.kind == "count":
        is_int, maxabs = True, 1.0
    elif a.field is not None:
        cols.add(a.field)
        ck = ds.column_kind(a.field)
        if kind == "kll" and ds.time is not None:
            cols.add(ds.time.name)   # content salt for the sampled set
        if a.kind == "anyvalue" or kind in ("hll", "theta", "kll"):
            is_int, maxabs = _col_bounds(ds, a.field)
            if ck == ColumnKind.DOUBLE:
                is_int = False
        elif ck == ColumnKind.DIM:
            if kind in ("min", "max") and not _dim_parses_numeric(
                    ds, a.field):
                # lexicographic min/max of a string dim = min/max of its
                # sorted-dictionary codes, decoded at output
                is_int, maxabs = _col_bounds(ds, a.field)
                cols |= F.columns_of_filter(a.filter)
                return AggPlan(a, kind, dtype, tuple(sorted(cols)),
                               is_int, maxabs, dim_codes=True)
            # numeric-parsed dim rides an f32 LUT
            is_int, maxabs = False, None
        else:
            is_int, maxabs = _col_bounds(ds, a.field)
    if a.expr is not None:
        cols |= E.columns_in(a.expr)
        is_int, maxabs = _expr_bounds(a.expr, ds)
    cols |= F.columns_of_filter(a.filter)
    return AggPlan(a, kind, dtype, tuple(sorted(cols)), is_int, maxabs)


def _dim_parses_numeric(ds: Datasource, field: str) -> bool:
    """Whether EVERY dictionary entry of a string dim parses as a number
    (then Druid's numeric-coercion semantics apply to min/max/sum over
    it); cached per datasource column — dictionaries can be large."""
    cache = getattr(ds, "_dim_numeric_cache", None)
    if cache is None:
        try:
            cache = ds._dim_numeric_cache = {}
        except AttributeError:           # frozen datasource: no cache
            cache = {}
    r = cache.get(field)
    if r is None:
        d = ds.dims[field].dictionary
        r = bool(len(d)) and not np.isnan(np.array(
            [host_eval_try_float(s) for s in d], dtype=np.float64)).any()
        cache[field] = r
    return r


# =============================================================================
# the engine
# =============================================================================

class QueryEngine:
    def __init__(self, store: SegmentStore, config: Optional[Config] = None,
                 mesh: Optional[Mesh] = None):
        self.store = store
        self.config = config or Config()
        self.mesh = mesh
        self._programs: Dict[tuple, object] = {}   # compile cache
        self._compiling: Dict[tuple, object] = {}  # sig -> in-flight Event
        self._compact_overflowed: set = set()      # shapes whose budget blew
        self._device_arrays: Dict[tuple, object] = {}
        self._device_bytes = 0
        self._cancel_flags: Dict[str, object] = {}
        self._cancel_refs: Dict[str, int] = {}
        self._cancel_lock = __import__("threading").Lock()
        # concurrency: queries execute in parallel (threading server); only
        # compile-cache population is serialized, and per-query stats are
        # thread-local so concurrent sessions don't trample each other
        self._compile_lock = __import__("threading").RLock()
        self._tls = __import__("threading").local()
        # device-loss state (≈ the reference's ZK-watch topology
        # invalidation, CuratorConnection.scala:77-136): when the backend
        # dies mid-session, statements demote to the host tier and a
        # bounded re-attach probe runs at most once per cooldown window
        self._backend_lost_at: Optional[float] = None
        self._backend_retry_at: float = 0.0
        # semantic result cache (cache/): exact + subsumption reuse of
        # materialized aggregate results, keyed on the per-datasource
        # ingest version (structural invalidation, no TTL)
        from spark_druid_olap_tpu.cache.result_cache import SemanticResultCache
        self.result_cache = SemanticResultCache(self.config)
        # workload management (wlm/): lane admission + tenant quotas in
        # front of every spec this engine executes; shed queries raise
        # AdmissionRejected here and never reach planning/dispatch
        from spark_druid_olap_tpu.metadata.history import InflightRegistry
        from spark_druid_olap_tpu.wlm.admit import WorkloadManager
        self.wlm = WorkloadManager(self.config)
        self.inflight = InflightRegistry()
        # shared-scan tier (parallel/sharedscan.py): concurrent eligible
        # queries on one datasource coalesce into a single fused program
        # with a shared column-union bind; gated by
        # sdot.sharedscan.enabled (off by default)
        from spark_druid_olap_tpu.parallel.sharedscan import (
            SharedScanCoalescer)
        self.sharedscan = SharedScanCoalescer(self)
        self.wlm.sharedscan = self.sharedscan
        # deterministic fault injection (fault/, docs/CHAOS.md): None
        # unless sdot.fault.plan is set, and every site guards on None
        # so the un-injected hot path pays nothing. The WLM site is
        # wired here; broker / persist / tier pick the injector up from
        # this attribute in their own constructors.
        from spark_druid_olap_tpu.fault import FaultInjector
        self.fault = FaultInjector.from_config(self.config)
        self.wlm.fault = self.fault
        # distributed serving tier (cluster/): on a broker this is the
        # scatter/merge client (cluster/broker.py:ClusterClient) wired
        # in by Context; None on single-process engines and historicals
        self.cluster = None
        # historical-node mode (cluster/historical.py): sketch
        # aggregates emit RAW register blocks instead of finalized
        # estimates, so the broker can merge registers across shards
        # and finalize the estimate exactly once
        self.partial_sketches = False

    @property
    def last_stats(self) -> Dict[str, object]:
        d = getattr(self._tls, "stats", None)
        if d is None:
            d = self._tls.stats = {}
        return d

    @property
    def dispatch_counts(self):
        """Thread-local MONOTONE [program_dispatches, host_transfers,
        wave_kernel_launches] counters (never reset by execute);
        statement layers diff them around a statement to report device
        round trips. On the tunneled chip each round trip costs the
        dispatch floor (~80ms), so this is the per-query wall-time
        budget made visible. Slot 2 counts hand-scheduled Pallas wave
        mega-kernel launches (parallel/sharedscan.py wave path) — a
        subset-annotation of slot 0, surfaced as ``kernel_launches`` in
        statement stats."""
        c = getattr(self._tls, "dcount", None)
        if c is None or len(c) < 3:
            c = self._tls.dcount = [0, 0, 0]
        return c

    def _tick(self, kind: int = 0, n: int = 1):
        self.dispatch_counts[kind] += n

    def _profile_dispatch(self, fn, args):
        """See _PROFILE_N: amortized device time of one compiled program.

        Syncs are data-dependent fetches, not ``block_until_ready`` — the
        tunneled axon plugin's block can return before the dispatch
        retires (see docs/bench/README.md), which would charge ~0ms to
        arbitrarily expensive programs."""
        if _PROFILE_N <= 0:
            return

        def sync(r):
            # first NON-EMPTY leaf: a zero-length leaf (multihost
            # zero-size per-chip buffer) would not block on the dispatch
            # and charge ~0ms (ADVICE r4)
            leaves = jax.tree_util.tree_leaves(r)
            for leaf in leaves:
                if getattr(leaf, "size", 0):
                    np.asarray(jax.numpy.ravel(leaf)[:1])
                    return
            jax.block_until_ready(leaves)

        sync(fn(args))
        t0 = _time.perf_counter()
        r = None
        for _ in range(_PROFILE_N):
            r = fn(args)
        sync(r)
        st = self.last_stats
        st["profile_device_ms"] = round(
            st.get("profile_device_ms", 0.0)
            + (_time.perf_counter() - t0) / _PROFILE_N * 1000, 2)

    def _stamp(self, key: str, t_start: float):
        """SDOT_STAGE_TIMING=1 diagnostic: accumulate per-stage wall ms
        into last_stats (plan/bind/device/decode splits for latency
        work). Off by default — the device stamp forces a block at the
        dispatch boundary, which costs overlap."""
        if _STAGE_TIMING:
            st = self.last_stats
            st[key] = round(st.get(key, 0.0)
                            + (_time.perf_counter() - t_start) * 1000, 2)

    # -- cancellation / timeout ----------------------------------------------
    def register_query(self, query_id: str) -> None:
        """Register a cancellable id BEFORE planning starts, so a cancel
        arriving at any point in the query's life is honored (≈ the
        reference registering the Druid query id with TaskCancelHandler
        before the HTTP call, DruidRDD.scala:175). Registrations are
        refcounted: statements sharing an id (one cancel scope, like
        Druid's queryId) stay cancellable until the LAST one releases."""
        import threading
        with self._cancel_lock:
            self._cancel_flags.setdefault(query_id, threading.Event())
            self._cancel_refs[query_id] = \
                self._cancel_refs.get(query_id, 0) + 1

    def release_query(self, query_id: str) -> None:
        with self._cancel_lock:
            n = self._cancel_refs.get(query_id, 1) - 1
            if n <= 0:
                self._cancel_refs.pop(query_id, None)
                self._cancel_flags.pop(query_id, None)
            else:
                self._cancel_refs[query_id] = n

    def cancel(self, query_id: str) -> bool:
        """Mark a registered query id cancelled (cooperative; takes effect at
        the next stage boundary)."""
        ev = self._cancel_flags.get(query_id)
        if ev is None:
            return False
        ev.set()
        return True

    def _stage_check(self, q, t0: float):
        ctxq = getattr(q, "context", None)
        if ctxq is None:
            return
        if ctxq.query_id is not None:
            ev = self._cancel_flags.get(ctxq.query_id)
            if ev is not None and ev.is_set():
                raise QueryCancelled(f"query {ctxq.query_id} cancelled")
        if ctxq.timeout_millis is not None:
            if (_time.perf_counter() - t0) * 1000 > ctxq.timeout_millis:
                raise QueryTimeout(
                    f"query exceeded {ctxq.timeout_millis}ms")

    # -- public ---------------------------------------------------------------
    def execute(self, q: S.QuerySpec) -> QueryResult:
        t0 = _time.perf_counter()
        self.last_stats.clear()   # per-thread; no cross-query leakage
        qid = getattr(getattr(q, "context", None), "query_id", None)
        if qid is not None:
            # refcounted: session-registered ids (and ids shared by
            # concurrent statements) stay cancellable until the LAST
            # holder releases
            self.register_query(qid)
        tier = pin_tok = None
        try:
            # tiered cold storage: pin every hot chunk this query faults
            # for its whole lifetime — eviction under budget pressure
            # must never pull a column out from under an in-flight scan.
            # acquire/release is a checked pair (sdlint leaks registry,
            # "tier-pin").
            tier_ds = self.store._datasources.get(
                getattr(q, "datasource", None))
            tier = getattr(tier_ds, "tier", None)
            pin_tok = tier.acquire_pins() if tier is not None else None
            tok = self.inflight.begin(qid, getattr(q, "datasource", None),
                                      type(q).__name__)
            try:
                # visible to the shared-scan coalescer (joined on this
                # thread): the group leader annotates every constituent's
                # sys_queries row with the coalesced-group id
                self._tls.inflight_tok = tok
                ticket = None
                try:
                    if self.wlm.enabled:
                        # admission BEFORE any planning/cache/dispatch
                        # work: a shed query must cost nothing, and queue
                        # wait counts against the deadline (t0 is already
                        # ticking). Specs of one statement admit
                        # sequentially (never hold-and-wait), so nested
                        # plans cannot deadlock on lane slots.
                        cancel_ev = self._cancel_flags.get(qid) \
                            if qid is not None else None
                        ticket = self.wlm.admit(self, q, t0, cancel_ev)
                        if ticket.timeout_millis is not None \
                                and getattr(q.context, "timeout_millis",
                                            None) is None:
                            # lane default timeout rides the spec so every
                            # downstream _stage_check honors it (context
                            # is stripped from cache keys and compile
                            # signatures, so the replace is cache-neutral)
                            import dataclasses as _dc
                            q = _dc.replace(q, context=_dc.replace(
                                q.context or S.QueryContext(),
                                timeout_millis=ticket.timeout_millis))
                        self.last_stats["wlm"] = ticket.stats()
                        self.inflight.running(tok, lane=ticket.lane,
                                              tenant=ticket.tenant,
                                              queued_ms=ticket.queued_ms)
                    else:
                        self.inflight.running(tok)
                    return self._execute_admitted(q, t0)
                finally:
                    self._tls.inflight_tok = None
                    if ticket is not None:
                        self.wlm.release(ticket)
            finally:
                self.inflight.done(tok)
        finally:
            try:
                if pin_tok is not None:
                    tier.release_pins(pin_tok)
                    self.last_stats["tier"] = tier.stats_snapshot()
                    enc_info = getattr(tier_ds, "encoding_info", None)
                    if enc_info is not None:
                        self.last_stats["encoding"] = enc_info()
            finally:
                if qid is not None:
                    self.release_query(qid)
            # after the releases: a failing stats snapshot must not be
            # able to strand the pin or the cancel flag
            if self.fault is not None:
                self.last_stats["fault"] = self.fault.stats()

    def _execute_admitted(self, q: S.QuerySpec, t0: float) -> QueryResult:
        try:
            pinfo = self.store.recovery_info.get(
                getattr(q, "datasource", None))
            if pinfo is not None:
                # the datasource was rebuilt from deep storage this
                # session — surface where it came from (snapshot / wal /
                # both) and what checksum verification cost
                self.last_stats["persist"] = dict(pinfo)
            cache = self.result_cache
            use_cache = cache.enabled and cache.cacheable(q)
            if use_cache:
                # lookup precedes the backend-loss gate on purpose: a
                # cached answer needs no device, so hits keep serving at
                # full speed while the host tier covers the misses
                ds_version = self.store.datasource_version(q.datasource)
                served, status = cache.lookup(q, ds_version)
                if served is not None:
                    self.last_stats["cache"] = status
                    self.last_stats["datasource"] = q.datasource
                    self.last_stats["total_ms"] = \
                        (_time.perf_counter() - t0) * 1000
                    return served
            if self.cluster is not None and self.cluster.should_distribute(q):
                # broker path: scatter per-shard subqueries to the
                # historicals and merge partials. Sits UNDER the cache
                # (hits never leave this process) and ABOVE the
                # backend-loss gate (the scatter needs no local device).
                # None = the client declined mid-flight (serde gap, node
                # EngineFallback, replicas exhausted with local fallback
                # enabled) — fall through to ordinary local execution.
                r = self.cluster.execute(q, t0)
                if r is not None:
                    # degraded (partial-results) answers must NEVER enter
                    # the result cache: a later healthy run would serve
                    # the hole forever
                    if use_cache and r.degraded is None:
                        cache.put(q, ds_version, r)
                        self.last_stats["cache"] = "miss"
                    return r
            if self._backend_lost_at is not None \
                    and not self._try_reattach():
                self.last_stats["backend_lost"] = True
                raise EngineFallback(
                    "backend_lost (device unreachable; host tier serving)")
            if self.sharedscan.should_try(q):
                # coalesce with concurrent eligible queries on the same
                # datasource; sits UNDER the cache layer so each
                # constituent still populates its own canonical key
                r = self.sharedscan.run(q, t0)
            else:
                r = self._execute_inner(q, t0)
            if use_cache:
                cache.put(q, ds_version, r)
                self.last_stats["cache"] = "miss"
            return r
        except EC.Unsupported as e:
            # expression/filter compilation is lazy (trace time), so an
            # unsupported node can surface only here — demote it to the
            # fallback signal the session layer handles
            raise EngineFallback(str(e)) from e
        except Exception as e:  # noqa: BLE001 — classify device loss
            if _is_backend_loss(e):
                self._mark_backend_lost()
                raise EngineFallback(
                    f"backend_lost ({type(e).__name__}: "
                    f"{str(e)[:120]})") from e
            raise

    def _mark_backend_lost(self):
        """Invalidate everything referencing dead device buffers; the
        host tier serves until a re-attach probe succeeds."""
        with self._compile_lock:
            self._backend_lost_at = _time.time()
            self._backend_retry_at = self._backend_lost_at \
                + float(self.config.get(BACKEND_RETRY_SECONDS))
            self._programs.clear()
            self._device_arrays.clear()
            self._device_bytes = 0
        self.last_stats["backend_lost"] = True

    def _try_reattach(self) -> bool:
        """At most one bounded device probe per cooldown window. The probe
        runs in a daemon thread with a hard deadline — a dispatch to a
        dead tunnel can hang, and an in-process hang would otherwise take
        the session down with it.

        A successful re-attach RESHARDS onto the now-live device set when
        its size changed (chips lost or restored) — the analog of the
        reference re-planning against ZooKeeper's changed server list
        (``CuratorConnection.scala:77-136``) instead of requiring the
        original topology back."""
        now = _time.time()
        with self._compile_lock:
            if now < self._backend_retry_at:
                return False
            # claim this window under the lock so concurrent statements
            # don't pile probes onto a dead backend
            self._backend_retry_at = now \
                + float(self.config.get(BACKEND_RETRY_SECONDS))
        if _probe_device_alive():
            with self._compile_lock:
                self._backend_lost_at = None
            if self.mesh is not None:
                try:
                    live = len(jax.devices())
                except Exception:   # noqa: BLE001 — treat as still down
                    return True
                if live != mesh_size(self.mesh):
                    self.reshard()
            return True
        return False

    def reshard(self, devices=None) -> None:
        """Rebuild the segment mesh over the CURRENTLY live devices (or an
        explicit subset) and drop every mesh-shaped artifact: compiled
        programs (their s_pad/shard split encodes the old device count)
        and device-resident arrays (their sharding references old
        devices). The store itself is host-resident, so the next
        statement re-binds onto the new mesh — segments re-spread the way
        Druid re-balances onto the surviving historicals."""
        from spark_druid_olap_tpu.parallel.mesh import make_mesh
        devs = list(devices) if devices is not None else jax.devices()
        with self._compile_lock:
            self.mesh = make_mesh(devices=devs) if len(devs) > 1 else None
            self._programs.clear()
            self._compact_overflowed.clear()
            self._device_arrays.clear()
            self._device_bytes = 0
        self.last_stats["resharded_to"] = len(devs)

    def _execute_inner(self, q: S.QuerySpec, t0: float) -> QueryResult:
        self._stage_check(q, t0)
        if isinstance(q, S.GroupByQuerySpec):
            r = self._run_agg(q, list(q.dimensions), q.aggregations,
                              q.post_aggregations, q.having, q.limit,
                              q.granularity, q.filter, q.intervals, t0)
        elif isinstance(q, S.TimeseriesQuerySpec):
            r = self._run_agg(q, [], q.aggregations, q.post_aggregations,
                              None, None, q.granularity, q.filter,
                              q.intervals, t0)
        elif isinstance(q, S.TopNQuerySpec):
            limit = S.topn_limit(q)
            r = self._run_agg(q, [q.dimension], q.aggregations,
                              q.post_aggregations, None, limit,
                              q.granularity, q.filter, q.intervals, t0)
        elif isinstance(q, S.SelectQuerySpec):
            r = self._run_select(q)
        elif isinstance(q, S.SearchQuerySpec):
            r = self._run_search(q)
        else:
            raise EngineFallback(f"query type {type(q).__name__}")
        self.last_stats["total_ms"] = (_time.perf_counter() - t0) * 1000
        return r

    # -- aggregation path -----------------------------------------------------
    def _run_agg(self, q, dimensions: List[S.DimensionSpec], aggregations,
                 post_aggregations, having, limit, granularity, filter_spec,
                 intervals, t0: Optional[float] = None,
                 no_topk: bool = False) -> QueryResult:
        ds = self.store.get(q.datasource)
        seg_idx = ds.prune_segments(intervals, filter_spec)
        gran_kind = granularity.kind if granularity else "all"

        if ds.num_rows == 0 or len(seg_idx) == 0:
            names = (["timestamp"] if gran_kind != "all" else [])
            names += [d.output_name for d in dimensions]
            names += [a.name for a in aggregations]
            names += [p.name for p in post_aggregations]
            if not dimensions and gran_kind == "all":
                # global aggregate over an empty/pruned scan still yields the
                # one identity row (same semantics as the global_empty path
                # below)
                data = _identity_row(
                    {a.name: _AGG_KIND.get(a.kind, ("sum", None))[0]
                     for a in aggregations})
                for p in post_aggregations:
                    v = np.asarray(host_eval.eval_expr(p.expr, data))
                    data[p.name] = np.broadcast_to(v, (1,)) if v.ndim == 0 \
                        else v
                if having is not None:
                    keep = host_eval.eval_pred3(having.expr, data)
                    data = {k: v[keep] for k, v in data.items()}
                self.last_stats.update({
                    "datasource": ds.name, "segments": 0, "sharded": False,
                    "groups": int(len(next(iter(data.values()))))
                    if data else 0, "rows_scanned": 0})
                return QueryResult(names, data)
            return QueryResult.empty(names)

        _tp = _time.perf_counter()
        all_dim_plans, agg_plans, min_day, max_day, n_keys, names, routes = \
            self._plan_agg(ds, seg_idx, dimensions, aggregations,
                           granularity, filter_spec, intervals)
        self._stamp("plan_ms", _tp)
        cards = [p.card for p in all_dim_plans]

        if bool(self.config.get(SHAREDSCAN_FUSION_ENABLED)):
            # solo-path CSE accounting, at PLAN time so warm program-
            # cache runs still tick the deterministic counters (the
            # trace-time cache in _make_core/_hash_core does the actual
            # sharing; this mirrors its hit count)
            try:
                tot, distinct = FU.analyze_query(
                    filter_spec, intervals,
                    [a.filter for a in aggregations])
                if tot > distinct:
                    self.sharedscan.note_solo_cse(tot - distinct, tot)
                elif tot:
                    self.sharedscan.note_solo_cse(0, tot)
            except Exception:  # noqa: BLE001 — accounting never fails a query
                pass

        route_hashed = n_keys > self.config.get(GROUPBY_DENSE_MAX_KEYS)
        if not route_hashed:
            # medium-K reroute (VERDICT r3 item 3): at K past the onehot
            # crossover, the sorted-run tier's one sort + payload scans
            # beat the dense matmul's N*K HBM onehot traffic — the SAME
            # gate as the sorted-run tier itself (its 'off' kill-switch
            # must kill the reroute too, or medium-K queries would land
            # on the hashed SCATTER tier the reroute exists to avoid)
            from spark_druid_olap_tpu.utils import config as CF
            min_k = int(self.config.get(CF.GROUPBY_SORTED_MIN_KEYS))
            if min_k > 0 and n_keys >= min_k \
                    and not any(p.kind in ("hll", "theta", "kll")
                                for p in agg_plans) \
                    and self._sorted_run_wanted():
                route_hashed = True
        if route_hashed:
            return self._run_agg_hashed(
                q, ds, seg_idx, all_dim_plans, agg_plans, names, min_day,
                max_day, post_aggregations, having, limit, filter_spec,
                intervals, t0, no_topk=no_topk)

        sharded = self._should_shard(q, ds, seg_idx)
        n_dev = mesh_size(self.mesh) if sharded else 1
        seg_bytes = C.bytes_per_segment(ds, names)
        spw, n_waves = C.plan_waves(
            len(seg_idx), n_dev, seg_bytes,
            C.wave_budget_bytes(self.config), self.config, n_keys,
            len(agg_plans),
            io_budget=C.tier_io_budget(ds, self.config),
            io_seg_bytes=C.tier_io_seg_bytes(ds, names))
        s_pad = spw if n_waves > 1 else _pad_segments(len(seg_idx), n_dev)
        n_seg_sel = len(seg_idx)
        multihost = sharded and MH.is_multihost()
        if multihost:
            seg_idx, s_pad, spw, n_waves = self._multihost_layout(
                ds, seg_idx, n_waves, seg_bytes)
        sketch_plans = [p for p in agg_plans
                        if p.kind in ("hll", "theta", "kll")]
        topk = self._plan_device_topk(limit, having, agg_plans, n_keys) \
            if n_waves == 1 and not no_topk else None
        having_dev = self._plan_device_having(having, routes, agg_plans,
                                              n_keys, topk, n_waves) \
            if not multihost else None
        # (multi-host: the having/table-resident two-dispatch path keeps
        # finals per-chip — the host HAVING epilogue over the replicated
        # merge is correct and cheap; revisit if profiling says otherwise)
        n_out = topk[1] if topk else n_keys

        top_idx = None
        base_sig = (ds.name, id(ds), _cache_repr(q), s_pad, ds.padded_rows,
                    min_day, max_day, sharded, n_dev, tuple(names),
                    self.config.get(TZ_ID),
                    self.config.get(GROUPBY_MATMUL_MAX_KEYS),
                    self.config.get(HLL_LOG2M),
                    self.config.get(QUANTILE_LANES),
                    bool(self.config.get(ENCODE_ENABLED)),
                    jax.default_backend(),
                    bool(jax.config.jax_enable_x64),
                    bool(self.config.get(SHAREDSCAN_FUSION_ENABLED)))
        if having_dev:
            # two dispatches: finals stay device-resident, only the mask
            # count then the passing groups travel
            sigA = ("aggtable", base_sig, having_dev)
            progA = self._cached_program(
                sigA, lambda: self._build_agg_table_program(
                    ds, all_dim_plans, agg_plans, filter_spec, intervals,
                    min_day, max_day, n_keys, sharded, routes,
                    having_dev))
            dev_arrays = self._bind_arrays(ds, names, seg_idx, s_pad,
                                           sharded)
            if t0 is not None:
                self._stage_check(q, t0)
            self._tick()
            _td = _time.perf_counter()
            table = dict(progA(dev_arrays))
            PH.add("dispatch", _time.perf_counter() - _td)
            cnt = int(np.asarray(table.pop("__stats__"))[0])
            n_out = min(n_keys,
                        1 << max(6, (max(cnt, 1) - 1).bit_length()))
            # most groups pass: the [n_keys] top_k sort costs more than
            # the transfer it saves — take the sort-free full gather
            full = n_out * 2 >= n_keys
            if full:
                n_out = n_keys
            gfn, unpackB = self._cached_program(
                (sigA, "gather", n_out, full),
                lambda: self._build_agg_gather_program(
                    agg_plans, routes, n_out, n_keys, sharded, full=full))
            self._tick()
            _td = _time.perf_counter()
            out = unpackB(gfn(table))
            PH.add("dispatch", _time.perf_counter() - _td)
            if t0 is not None:
                self._stage_check(q, t0)
            finals = _finals_from_out(out, routes, n_out, sketch_plans)
            if not full:
                top_idx = np.asarray(out["__topk_idx__"]) \
                    .astype(np.int64)
            # full mode: rows travel in key order — decode's identity
            # path (top_idx None) already maps sel -> key ids
        elif n_waves == 1:
            # budget from the CHEAP conjuncts only: staged gather-heavy
            # conjuncts apply after compaction and don't shrink what the
            # prefix must hold
            cheap_f0, _ = self._split_filter_staged(filter_spec)
            compact_m = self._plan_compact_m(ds, seg_idx, cheap_f0,
                                             sharded, routes=routes,
                                             n_dev=n_dev,
                                             allow_sharded=True,
                                             n_keys=n_keys)
            if compact_m and ("agg", base_sig, topk) \
                    in self._compact_overflowed:
                compact_m = None     # this shape overflowed before: the
                # estimate is structurally off for it, don't re-pay the
                # double execution on every warm run
            for cm in ((compact_m, None) if compact_m else (None,)):
                _tc = _time.perf_counter()
                prog_fn, unpack = self._cached_program(
                    ("agg", base_sig, topk, cm),
                    lambda cm=cm: self._build_agg_program(
                        ds, all_dim_plans, agg_plans, filter_spec,
                        intervals, min_day, max_day, n_keys, sharded,
                        routes, topk=topk, compact_m=cm))
                self._stamp("compile_ms", _tc)
                _tb = _time.perf_counter()
                dev_arrays = self._bind_arrays(ds, names, seg_idx, s_pad,
                                               sharded)
                self._stamp("bind_ms", _tb)
                if t0 is not None:
                    self._stage_check(q, t0)  # pre-dispatch boundary
                self._tick()
                self._profile_dispatch(prog_fn, dev_arrays)
                _td = _time.perf_counter()
                bufs = prog_fn(dev_arrays)
                if _STAGE_TIMING:
                    jax.block_until_ready(bufs)
                    self._stamp("device_ms", _td)
                out = unpack(bufs)
                self._stamp("fetch_ms", _td)
                PH.add("dispatch", _time.perf_counter() - _td)
                if t0 is not None:
                    self._stage_check(q, t0)  # post-device boundary
                over = out.pop("__over__", None)
                if over is None or int(np.asarray(over).reshape(-1)[0]) == 0:
                    if cm:
                        self.last_stats["compact_m"] = int(cm)
                    break
                # est. selectivity too optimistic: retry uncompacted and
                # remember this program shape so warm runs skip straight
                # to the uncompacted program
                self.last_stats["compact_overflow"] = \
                    int(np.asarray(over).reshape(-1)[0])
                self._compact_overflowed.add(("agg", base_sig, topk))
            finals = _finals_from_out(out, routes, n_out, sketch_plans)
            if topk:
                top_idx = np.asarray(out["__topk_idx__"]).astype(np.int64)
        else:
            # wave-mode late materialization (VERDICT r3 item 9): the
            # same compact block runs INSIDE each wave's program with a
            # per-wave survivor budget (first wave's rows stand in for
            # all — waves are equal-sized splits); any wave overflowing
            # its budget folds into '__over__' and the whole scan
            # re-runs uncompacted, exactly the single-wave protocol
            cheap_f0, _ = self._split_filter_staged(filter_spec)
            compact_m = self._plan_compact_m(
                ds, seg_idx[:spw], cheap_f0, sharded, routes=routes,
                n_dev=n_dev, allow_sharded=True, n_keys=n_keys)
            if compact_m and ("aggw", base_sig) in self._compact_overflowed:
                compact_m = None
            for cm in ((compact_m, None) if compact_m else (None,)):
                prog_fn, unpack = self._cached_program(
                    ("agg", base_sig, None, cm),
                    lambda cm=cm: self._build_agg_program(
                        ds, all_dim_plans, agg_plans, filter_spec,
                        intervals, min_day, max_day, n_keys, sharded,
                        routes, topk=None, compact_m=cm))
                finals, wave_over = self._run_waves(
                    q, ds, names, seg_idx, spw, sharded, prog_fn, unpack,
                    routes, n_keys, sketch_plans, t0)
                if not wave_over:
                    if cm:
                        self.last_stats["compact_m"] = int(cm)
                    break
                self.last_stats["compact_overflow"] = int(wave_over)
                self._compact_overflowed.add(("aggw", base_sig))

        # --- decode -----------------------------------------------------------
        _tdec = _time.perf_counter()
        rows = finals["__rows__"]
        sel = np.nonzero(rows > 0)[0]
        # a GLOBAL aggregate (no dims, no time bucketing) over zero matching
        # rows yields ONE identity row — SQL semantics (and Druid's default
        # timeseries behavior, minus its sum-is-0 quirk: we emit NULL sums)
        global_empty = (not all_dim_plans and gran_kind == "all"
                        and len(sel) == 0)
        if global_empty:
            sel = np.zeros(1, dtype=np.int64)
        data: Dict[str, np.ndarray] = {}
        columns: List[str] = []
        if all_dim_plans:
            key_ids = top_idx[sel] if top_idx is not None else sel
            code_lists = G.unfuse_key(key_ids, cards)
            for p, codes in zip(all_dim_plans, code_lists):
                data[p.output_name] = p.decode(codes)
                columns.append(p.output_name)
        for p in agg_plans:
            name = p.spec.name
            if p.kind in ("hll", "theta", "kll"):
                regs = finals[name]
                if self.partial_sketches:
                    # cluster historical mode: ship the raw [G, m]
                    # register block; the broker merges registers
                    # across shards (max/min/minsum) and finalizes the
                    # estimate once (cluster/merge.py) — that is what
                    # makes the distributed estimate EQUAL the
                    # single-engine one, not merely close
                    data[name] = np.asarray(regs)[sel]
                    columns.append(name)
                    continue
                if p.kind == "kll":
                    data[name] = KLL.estimate(
                        regs, p.spec.fraction or 0.5)[sel]
                    columns.append(name)
                    continue
                est = (HLL.estimate(regs) if p.kind == "hll"
                       else TH.estimate(regs))[sel]
                data[name] = np.round(est).astype(np.int64)
                columns.append(name)
                continue
            r = routes[name]
            v = finals[name][sel]
            data[name] = _decode_agg_value(ds, p, r, v)
            columns.append(name)
        if global_empty:
            data.update(_identity_row(
                {p.spec.name: p.kind for p in agg_plans
                 if p.kind in ("sum", "min", "max")}))

        data = self._agg_epilogue(data, columns, post_aggregations, having,
                                  limit)

        if topk and not isinstance(q, S.TopNQuerySpec):
            # exact-contract GroupBy: the candidate selection is
            # f32-approximate — prove the boundary row clears the cutoff
            # or re-run with the full-table transfer (ADVICE r2)
            scores = np.asarray(out["__topk_score__"], np.float64)
            if not _topk_selection_exact(limit, topk, routes[topk[0]],
                                         scores, data):
                return self._run_agg(q, dimensions, aggregations,
                                     post_aggregations, having, limit,
                                     granularity, filter_spec, intervals,
                                     t0, no_topk=True)

        self._stamp("decode_ms", _tdec)
        self.last_stats.update({
            "datasource": ds.name, "segments": int(n_seg_sel),
            "sharded": sharded, "groups": int(len(sel)),
            "rows_scanned": int(ds.num_rows), "waves": int(n_waves),
            "segments_per_wave": int(spw),
            "bytes_scanned": int(seg_bytes) * int(n_seg_sel),
            "topk_device": int(topk[1]) if topk else 0,
            "having_device": int(n_out) if having_dev else 0})
        return QueryResult(columns, data)

    @staticmethod
    def _split_filter_staged(f):
        """(cheap, expensive) for staged filter evaluation under
        compaction: top-level AND conjuncts whose lowering must GATHER
        (large frozen-int membership, keyed-lookup expressions — the
        decorrelated-EXISTS machinery) evaluate after compaction, on the
        survivors of the cheap conjuncts only. A 6M-probe gather costs
        ~40ms on v5e; post-compaction it costs ~M/6M of that."""
        def expr_has_gather(e):
            found = [False]

            def visit(n):
                if isinstance(n, (E.KeyedLookup, E.KeyedLookup2)):
                    found[0] = True
                if isinstance(n, E.InList) \
                        and isinstance(n.values, E.FrozenIntSet) \
                        and not EC.int_set_lowers_to_chain(n.values.array):
                    found[0] = True
                return n
            E.transform(e, visit)
            return found[0]

        def is_expensive(x):
            if isinstance(x, S.InFilter) \
                    and isinstance(x.values, E.FrozenIntSet) \
                    and not EC.int_set_lowers_to_chain(x.values.array):
                return True
            if isinstance(x, S.ExprFilter):
                return expr_has_gather(x.expr)
            if isinstance(x, S.LogicalFilter) and x.op == "not":
                return is_expensive(x.fields[0])
            return False

        if f is None:
            return None, None
        conj = list(f.fields) if isinstance(f, S.LogicalFilter) \
            and f.op == "and" else [f]
        cheap = [x for x in conj if not is_expensive(x)]
        exp = [x for x in conj if is_expensive(x)]
        if not exp:
            return f, None

        def rejoin(parts):
            if not parts:
                return None
            if len(parts) == 1:
                return parts[0]
            return S.LogicalFilter("and", tuple(parts))

        return rejoin(cheap), rejoin(exp)

    def _plan_compact_m(self, ds, seg_idx, filter_spec, sharded,
                        routes=None, n_dev=1, allow_sharded=False,
                        n_keys=None, n_ops=None):
        """Static survivor budget for late materialization (None = don't
        compact). Uses the cost model's filter-selectivity estimate with
        a 2x safety margin; a wrong estimate is caught by the program's
        '__over__' output and retried uncompacted. Sharded (dense path
        only): the budget is PER SHARD — the compact block runs on each
        shard's local arrays under shard_map, and overflow counts psum
        before travelling.

        Gate (VERDICT r3 weak 6 — calibrated constants, not literals): the
        compaction sort costs ``rows * sort_c``; it saves the downstream
        per-row aggregation work — scatter updates (or the fused kernel's
        streamed pass under an 'ffl' route) on the rows it removes — and
        re-buys ``m`` gather probes per touched column. All unit costs are
        per-backend measurements (``cost.unit_cost``; tools/calibrate.py
        refits them on the live backend). On TPU sort ≈ scatter/30 so the
        gate engages for any selective filter; on the CPU fallback the
        x64 sort only pays once the un-compacted table would scatter in
        the past-LLC thrash regime (the measured SF10 crossover).
        ``min.rows == 0`` is the explicit test/config override."""
        if filter_spec is None or (sharded and not allow_sharded):
            return None
        if not self.config.get(SCAN_COMPACT):
            return None
        min_rows = int(self.config.get(SCAN_COMPACT_MIN_ROWS))
        rows = int(sum(ds.segments[int(si)].num_rows for si in seg_idx
                       if si >= 0))   # -1 = multihost padding slot
        rows //= max(int(n_dev) if sharded else 1, 1)   # per-shard budget
        if min_rows > 0 and rows < min_rows:
            return None                  # small scans: the sort wins nothing
        sel = C._filter_selectivity(filter_spec, ds)
        est = rows * sel * 2.0           # safety margin before retry
        m = 1 << max(6, int(np.ceil(np.log2(max(est, 1.0)))))
        m = max(m, 1 << 15) if rows >= (1 << 21) else m
        if m > rows // 2:
            return None                  # unselective: nothing to remove
        if min_rows > 0:
            from spark_druid_olap_tpu.utils import config as CF
            if n_ops is None:
                n_ops = max(1, len(routes)) if routes is not None else 4
            n_ops = min(int(n_ops), 8)
            sort_s = rows * C.unit_cost(self.config, CF.COST_SORT_ROW)
            gather_s = m * n_ops * C.unit_cost(self.config,
                                               CF.COST_GATHER_PROBE)
            if routes is not None and any(
                    getattr(r, "tag", None) == "ffl"
                    for r in routes.values()):
                # fused single streamed pass: the only saving is the
                # kernel's per-row cost on removed rows
                saved = (rows - m) * C.unit_cost(self.config,
                                                 CF.COST_FUSED_ROW)
            else:
                per_key = 4 * (sum(
                    sz for r in routes.values()
                    for _, sz, _ in r.outputs(1)) if routes else n_ops)
                tbl_bytes = (int(n_keys) if n_keys else 1 << 16) * per_key
                big = tbl_bytes > int(self.config.get(
                    CF.COST_TABLE_CACHE_BYTES))
                sc = C.unit_cost(
                    self.config, CF.COST_SCATTER_UPDATE_BIG if big
                    else CF.COST_SCATTER_UPDATE)
                saved = (rows - m) * sc * n_ops
            if sort_s + gather_s >= saved:
                return None
        return int(m)

    def _plan_device_topk(self, limit, having, agg_plans, n_keys):
        """Decide whether the ordered-limit epilogue can run on device:
        select ``k_sel`` candidate keys by an f32 score over the merged
        partials (ops.groupby.route_score) and transfer only those rows.
        ≈ Druid's topN engine (per-key-space top-k on the data node instead
        of shipping the full groupBy result to the broker). Returns
        (metric, k_sel, ascending) or None.

        The candidate *selection* is f32-approximate with ``k_sel - limit``
        slack; the final ordering of candidates is exact (host combine).
        NULL-metric groups: min/max sentinels are detected on device and
        ranked after every real score (nulls-last, matching the host
        epilogue); a NULL *sum* scores as 0 (indistinguishable from a true
        zero), so it can displace a candidate only when the true top-k
        sits below 0 AND >slack NULL-sum groups exist — still tighter
        than Druid's documented topN approximation.
        Skipped under HAVING (it may filter an unbounded prefix) and in
        wave mode (waves merge by key; candidate sets differ per wave)."""
        if having is not None or limit is None or limit.limit is None:
            return None
        if not limit.columns:
            return None
        if n_keys < self.config.get(TOPN_DEVICE_MIN_KEYS):
            return None
        oc = limit.columns[0]
        mplan = next((p for p in agg_plans if p.spec.name == oc.name), None)
        if mplan is None or mplan.kind in ("hll", "theta", "kll"):
            return None
        if mplan.dim_codes:
            # string min/max decodes to text: the exactness proof can't
            # score it (float(str)), so the epilogue would always re-run
            return None
        k_sel = min(n_keys, _topk_slack(limit))
        if k_sel * 4 >= n_keys:
            return None              # full transfer is already cheap
        return (oc.name, k_sel, bool(oc.ascending))

    def _agg_epilogue(self, data, columns, post_aggregations, having, limit):
        """Host epilogue shared by the dense and hashed agg paths: post
        aggregations, HAVING, ORDER BY + LIMIT (≈ the Spark-side Project /
        Filter / Sort the reference leaves above the Druid scan)."""
        for pa in post_aggregations:
            data[pa.name] = np.asarray(host_eval.eval_expr(pa.expr, data))
            columns.append(pa.name)
        if having is not None:
            keep = host_eval.eval_pred3(having.expr, data)
            data = {k: v[keep] for k, v in data.items()}
        if limit is not None and limit.columns:
            order_keys = []
            for oc in reversed(limit.columns):
                k = data[oc.name]
                if k.dtype == object and all(
                        v is None or isinstance(v, (int, np.integer))
                        for v in k):
                    # wide-int min/max columns with empty groups: exact
                    # int64 sort (f64 would collapse values past 2^53),
                    # nulls last via a more-significant null flag
                    nulls = np.array([v is None for v in k])
                    vals = np.array([0 if v is None else int(v) for v in k],
                                    dtype=np.int64)
                    order_keys.append(vals if oc.ascending else -vals)
                    order_keys.append(nulls)
                    continue
                if k.dtype == object:
                    k = k.astype(str)
                order_keys.append(k if oc.ascending else _neg_key(k))
            idx = np.lexsort(order_keys)
            if limit.limit is not None:
                idx = idx[: limit.limit]
            data = {k: v[idx] for k, v in data.items()}
        elif limit is not None and limit.limit is not None:
            data = {k: v[: limit.limit] for k, v in data.items()}
        return data

    # -- hashed high-cardinality aggregation path -----------------------------
    def _run_agg_hashed(self, q, ds, seg_idx, dim_plans, agg_plans, names,
                        min_day, max_day, post_aggregations, having, limit,
                        filter_spec, intervals, t0, no_topk: bool = False):
        """Group-by above the dense key-space ceiling: fixed-size device hash
        table per chip/wave (ops/hash_groupby.py), partials merged by *key*
        on host. Table overflow retries at 4x slots, then falls back.
        ≈ Druid groupBy v2 never refusing on cardinality
        (DruidQuerySpec.scala:558-571)."""
        if any(p.kind in ("hll", "theta", "kll") for p in agg_plans):
            raise EngineFallback(
                "sketch aggregation over hashed group-by")
        cards = [p.card for p in dim_plans]
        try:
            parts = H.split_parts(cards)
        except H.KeySpaceTooWide as e:
            raise EngineFallback(str(e)) from e

        # EXACT selected-row count: initial_slots sizes the table straight
        # to min(key space, rows), which is only a true upper bound on the
        # group count when this is not an average-based estimate (a skewed
        # segment selection could undershoot an average and trigger a
        # spurious 4x-retry recompile)
        rows_sel = int(sum(ds.segments[int(si)].num_rows
                           for si in seg_idx))
        max_slots = int(self.config.get(GROUPBY_HASH_MAX_SLOTS))
        if not PG_tpu._tpu_backend():
            # the 16M-slot ceiling is TPU economics (400MB of HBM table
            # buffers, ~sort+scatter in hundreds of ms); on the CPU
            # fallback x64 scatters into a 16M-slot table thrash cache so
            # badly that the host pandas tier is ~3x faster (measured
            # q18-inner SF10: 530s engine vs 193s host) — CPU gets its
            # own configurable ceiling (default 8M, from that measurement)
            from spark_druid_olap_tpu.utils.config import (
                GROUPBY_HASH_MAX_SLOTS_CPU)
            max_slots = min(max_slots, int(self.config.get(
                GROUPBY_HASH_MAX_SLOTS_CPU)))
        n_keys_total = 1
        for c in cards:
            n_keys_total *= int(c)
        T = int(self.config.get(GROUPBY_HASH_SLOTS)) or H.initial_slots(
            min(n_keys_total, rows_sel), hi=max_slots)

        sharded = self._should_shard(q, ds, seg_idx)
        n_dev = mesh_size(self.mesh) if sharded else 1
        seg_bytes = C.bytes_per_segment(ds, names)
        spw, n_waves = C.plan_waves(
            len(seg_idx), n_dev, seg_bytes,
            C.wave_budget_bytes(self.config), self.config,
            min(rows_sel, T), len(agg_plans),
            io_budget=C.tier_io_budget(ds, self.config),
            io_seg_bytes=C.tier_io_seg_bytes(ds, names))
        s_pad = spw if n_waves > 1 else _pad_segments(len(seg_idx), n_dev)
        n_seg_sel = len(seg_idx)
        multihost = sharded and MH.is_multihost()
        if multihost:
            seg_idx, s_pad, spw, n_waves = self._multihost_layout(
                ds, seg_idx, n_waves, seg_bytes)
        wave_segs = [seg_idx[i: i + s_pad]
                     for i in range(0, len(seg_idx), s_pad)]
        sharding = NamedSharding(self.mesh, P(SEGMENT_AXIS, None)) \
            if sharded else None

        # no '__rows__' occupancy count here: occupied slots are read off
        # the key table (khi != EMPTY) directly
        metas = [G.AggInput(p.spec.name, p.kind, is_int=p.is_int,
                            maxabs=p.maxabs) for p in agg_plans]
        topk_plan = self._plan_device_topk_hashed(limit, having, agg_plans,
                                                  n_dev, n_waves) \
            if not no_topk else None
        exch_plan = None
        if topk_plan is None and n_dev > 1 and n_waves == 1:
            # multi-host included: the exchange is pure in-mesh
            # collectives (candidate all_gather + psum/pmin/pmax); its
            # O(k_sel) output replicates for cross-process fetch
            exch_plan = self._plan_hash_topk_exchange(q, limit, having,
                                                      agg_plans)

        kg_used = 0
        tk_scores = None
        # late materialization (shared with the dense path): the key
        # build + scatter aggregation shrink to O(survivors); a budget
        # overflow folds into '__unres__' and the first retry disables it
        cheap_f0, _ = self._split_filter_staged(filter_spec)
        lm = self._plan_compact_m(ds, seg_idx, cheap_f0, sharded,
                                  n_keys=T,
                                  n_ops=len(agg_plans) + 2) \
            if n_waves == 1 else None
        if lm and ("hashlm", ds.name, _cache_repr(q)) \
                in self._compact_overflowed:
            lm = None
        while True:
            # k_sel*4 <= T also bounds k_sel < T, so no clamp is needed
            topk = topk_plan if topk_plan and topk_plan[1] * 4 <= T \
                else None
            exch = exch_plan if exch_plan and exch_plan[1] * 4 <= T \
                else None
            compact = (topk is None and exch is None
                       and T >= self.config.get(GROUPBY_HASH_COMPACT_MIN))
            # multi-host: the [T] slot tables stay DEVICE-RESIDENT
            # sharded between the two dispatches (_shard_wrap
            # gather_only) — only '__stats__' and the kg compacted slots
            # cross hosts, O(groups-out) instead of O(T x n_aggs)
            # (VERDICT r4 item 3)
            k_out = topk[1] if topk else T
            n_rows_dev = int(ds.padded_rows) * int(ds.num_segments)
            sorted_run = False
            if self._sorted_run_wanted():
                sroutes = SG.plan_sorted_routes(metas, n_rows=n_rows_dev)
                if sroutes is not None:
                    routes = sroutes
                    sorted_run = True
            if not sorted_run:
                routes = G.plan_routes(
                    metas, T, self.config.get(GROUPBY_MATMUL_MAX_KEYS),
                    n_rows=n_rows_dev)
            sig = ("hashagg", ds.name, id(ds), _cache_repr(q), s_pad,
                   ds.padded_rows, min_day, max_day, sharded, n_dev, T,
                   tuple(names), topk, compact, lm, sorted_run,
                   self.config.get(TZ_ID),
                   self.config.get(GROUPBY_MATMUL_MAX_KEYS),
                   self.config.get(HLL_LOG2M),
                   bool(self.config.get(ENCODE_ENABLED)),
                   jax.default_backend(), bool(jax.config.jax_enable_x64),
                   bool(self.config.get(SHAREDSCAN_FUSION_ENABLED)))

            def build(lm=lm):
                if compact or exch:
                    return self._build_hash_table_program(
                        ds, dim_plans, parts, agg_plans, filter_spec,
                        intervals, min_day, max_day, T, sharded, routes,
                        compact_m=lm, sorted_run=sorted_run)
                return self._build_hash_program(
                    ds, dim_plans, parts, agg_plans, filter_spec,
                    intervals, min_day, max_day, T, sharded, routes,
                    topk=topk, compact_m=lm, sorted_run=sorted_run)

            prog = self._cached_program(sig, build)

            partials, unresolved = [], 0

            def bind(i):
                return self._bind_wave(ds, names, wave_segs[i], s_pad,
                                       sharding, multihost)

            # cold tier: start loading wave 1's chunks while wave 0
            # binds and computes (load-behind-compute)
            self._tier_prefetch(ds, names, wave_segs, 1)
            cur = self._bind_arrays(ds, names, seg_idx, s_pad, sharded) \
                if n_waves == 1 else bind(0)
            for i in range(len(wave_segs)):
                if t0 is not None:
                    self._stage_check(q, t0)
                if compact or exch:
                    self._tick()
                    self._profile_dispatch(lambda a: dict(prog(a)), cur)
                    _td = _time.perf_counter()
                    table = dict(prog(cur))         # table stays on device
                    if _STAGE_TIMING:
                        jax.block_until_ready(table)
                        self._stamp("device_ms", _td)
                    # wave i+2's cold chunks load behind wave i's compute
                    # and wave i+1's (synchronous) bind
                    self._tier_prefetch(ds, names, wave_segs, i + 2)
                    nxt = bind(i + 1) if i + 1 < len(wave_segs) else None
                    stats = np.asarray(
                        table.pop("__stats__")).reshape(-1, 2)
                    cur = nxt
                    unresolved += int(stats[:, 0].sum())
                    if unresolved:
                        break
                    if exch:
                        metric, k_sel, ascending = exch
                        # sums need wider per-chip candidate lists (a
                        # key large in total can rank lower locally);
                        # min/max are exact with k_sel alone
                        mplan = next(p for p in agg_plans
                                     if p.spec.name == metric)
                        k_cand = k_sel if mplan.kind in ("min", "max") \
                            else min(T, max(4 * k_sel, 1024))
                        kg_used = max(kg_used, k_sel)
                        gfn, unpackB = self._cached_program(
                            (sig, "exchange", exch, k_cand),
                            lambda: self._build_hash_topk_exchange_program(
                                agg_plans, routes, metric, ascending,
                                k_cand, k_sel, T))
                        self._tick()
                        self._profile_dispatch(gfn, table)
                        _tf = _time.perf_counter()
                        raw = unpackB(gfn(table))
                        self._stamp("fetch_ms", _tf)
                        PH.add("dispatch", _time.perf_counter() - _tf)
                        partials.extend(
                            _hash_chip_partials(raw, routes, k_sel, n_dev))
                        continue
                    occ_max = max(1, int(stats[:, 1].max()))
                    kg = min(T, 1 << max(6, (occ_max - 1).bit_length()))
                    kg_used = max(kg_used, kg)
                    gfn, unpackB = self._cached_program(
                        (sig, "gather", kg),
                        lambda kg=kg: self._build_hash_gather_program(
                            agg_plans, routes, kg, T, sharded))
                    self._tick()
                    self._profile_dispatch(gfn, table)
                    _tf = _time.perf_counter()
                    raw = unpackB(gfn(table))
                    self._stamp("fetch_ms", _tf)
                    PH.add("dispatch", _time.perf_counter() - _tf)
                    partials.extend(
                        _hash_chip_partials(raw, routes, kg, n_dev))
                else:
                    prog_fn, unpack = prog
                    self._tick()
                    self._profile_dispatch(prog_fn, cur)
                    _td = _time.perf_counter()
                    buf = prog_fn(cur)              # async dispatch
                    if _STAGE_TIMING:
                        jax.block_until_ready(buf)
                        self._stamp("device_ms", _td)
                    # double buffer: next wave's transfer overlaps compute
                    self._tier_prefetch(ds, names, wave_segs, i + 2)
                    nxt = bind(i + 1) if i + 1 < len(wave_segs) else None
                    _tf = _time.perf_counter()
                    raw = unpack(buf)
                    self._stamp("fetch_ms", _tf)
                    # overlapped prefetch/bind charged to their own
                    # phases; the rest of this interval is device work
                    PH.add("dispatch", _time.perf_counter() - _td)
                    cur = nxt
                    unresolved += int(raw.pop("__unres__").sum())
                    if unresolved:
                        break
                    if topk:
                        tk_scores = raw.pop("__topk_score__")
                    partials.extend(
                        _hash_chip_partials(raw, routes, k_out, n_dev))
            if not unresolved:
                if lm:
                    self.last_stats["compact_m"] = int(lm)
                break
            if lm:
                # the late-materialization budget may be what overflowed
                # (it folds into '__unres__'): disable it at the SAME T
                # first; only a second failure means true table overflow
                self.last_stats["compact_overflow"] = int(unresolved)
                self._compact_overflowed.add(
                    ("hashlm", ds.name, _cache_repr(q)))
                lm = None
                continue
            T *= 4
            if T > max_slots:
                raise EngineFallback(
                    f"hashed group-by exceeded {max_slots} table slots")
        if t0 is not None:
            self._stage_check(q, t0)

        _tm = _time.perf_counter()
        keys, merged = _merge_hash_partials(partials, routes)
        self._stamp("merge_ms", _tm)
        _tdec = _time.perf_counter()
        data: Dict[str, np.ndarray] = {}
        columns: List[str] = []
        khi, klo = H.unpack_key(keys)
        part_vals = [khi, klo]
        dim_codes: Dict[int, np.ndarray] = {}
        for pi, idxs in enumerate(parts):
            for i, c in zip(idxs, H.unfuse_part(part_vals[pi], cards, idxs)):
                dim_codes[i] = c
        for i, p in enumerate(dim_plans):
            data[p.output_name] = p.decode(dim_codes[i])
            columns.append(p.output_name)
        for p in agg_plans:
            name = p.spec.name
            data[name] = _decode_agg_value(ds, p, routes[name], merged[name])
            columns.append(name)

        data = self._agg_epilogue(data, columns, post_aggregations, having,
                                  limit)
        self._stamp("decode_ms", _tdec)

        if topk and tk_scores is not None \
                and not isinstance(q, S.TopNQuerySpec):
            # exact-contract GroupBy over the hashed tier: same proof as
            # the dense epilogue (ADVICE r2); single-chip single-wave by
            # _plan_device_topk_hashed, so the slot scores are global
            scores = np.sort(np.asarray(tk_scores, np.float64))[::-1]
            if not _topk_selection_exact(limit, topk, routes[topk[0]],
                                         scores, data):
                return self._run_agg_hashed(
                    q, ds, seg_idx, dim_plans, agg_plans, names, min_day,
                    max_day, post_aggregations, having, limit, filter_spec,
                    intervals, t0, no_topk=True)

        self.last_stats.update({
            "datasource": ds.name, "segments": int(n_seg_sel),
            "sharded": sharded, "groups": int(len(keys)),
            "rows_scanned": int(ds.num_rows), "waves": int(len(wave_segs)),
            "bytes_scanned": int(seg_bytes) * int(n_seg_sel),
            "segments_per_wave": int(s_pad), "hashed": True,
            "hash_slots": int(T), "hash_compact_k": int(kg_used),
            "topk_device": int(topk[1]) if topk
            else (int(exch[1]) if exch else 0),
            "topk_exchange": bool(exch)})
        return QueryResult(columns, data)

    def _plan_device_topk_hashed(self, limit, having, agg_plans, n_dev,
                                 n_waves):
        """Device top-k over the hash table: transfer only the best
        ``k_sel`` SLOTS per chip/wave instead of the full [T] table.

        Single-chip single-wave ONLY: there the table is complete, so
        per-slot scores are global and selection is exact (modulo the f32
        score + slack, like the dense epilogue). Multi-chip/wave a key's
        partials are split across per-chip tables — per-chip top-k both
        misses globally-large keys AND under-counts any key selected on
        one chip but not another (Druid's topN accepts exactly this
        skew; we keep the full-table key-wise merge instead and stay
        exact)."""
        if having is not None or limit is None or limit.limit is None:
            return None
        if not limit.columns:
            return None
        oc = limit.columns[0]
        mplan = next((p for p in agg_plans if p.spec.name == oc.name), None)
        if mplan is None or mplan.dim_codes:
            return None
        if n_dev != 1 or n_waves != 1:
            return None
        return (oc.name, _topk_slack(limit), bool(oc.ascending))

    def _hash_core(self, ds, dim_plans, parts, agg_plans, filter_spec,
                   intervals, min_day, max_day, T, routes,
                   compact_m=None, sorted_run=False):
        """The shared hash scan body: scan -> filter -> per-dim codes ->
        two-part key -> slot claim -> exact scatter aggregation into [T]
        buffers. Returns the raw out dict incl. '__tkhi__'/'__tklo__' key
        tables and '__unres__' (shape [1]). With ``compact_m``, late
        materialization (same machinery as the dense path) runs the key
        build + aggregation at O(survivors); a budget overflow folds into
        '__unres__' (the host first retries uncompacted, then grows T)."""
        matmul_max = self.config.get(GROUPBY_MATMUL_MAX_KEYS)
        cards = [p.card for p in dim_plans]
        cheap_f, exp_f = (self._split_filter_staged(filter_spec)
                          if compact_m else (filter_spec, None))
        fuse_cse = bool(self.config.get(SHAREDSCAN_FUSION_ENABLED))

        def core(arrays):
            ctx = ScanContext(ds, arrays, min_day, max_day,
                              tz=self.config.get(TZ_ID))
            # same trace-time predicate CSE as the dense core
            cse = FU.CSECache(ctx) if fuse_cse else None
            base = ctx.row_valid()
            fm = cse.lower(cheap_f) if cse is not None \
                else F.lower_filter(cheap_f, ctx)
            if fm is not None:
                base = base & fm
            im = F.interval_mask(intervals, ctx)
            if im is not None:
                base = base & im
            n_over = None
            if compact_m:
                flat = base.reshape(-1)
                ridx = jnp.arange(flat.shape[0], dtype=jnp.int32)
                okey = jnp.where(flat, jnp.int32(0), jnp.int32(1))
                _, sidx = jax.lax.sort((okey, ridx), num_keys=1)
                keep = jax.lax.slice_in_dim(sidx, 0, compact_m)
                n_live = jnp.sum(flat.astype(jnp.int32))
                n_over = jnp.maximum(
                    n_live - jnp.int32(compact_m), 0).astype(jnp.int32)
                ctx = CompactScanContext(ds, arrays, min_day, max_day,
                                         self.config.get(TZ_ID), keep=keep)
                cse = FU.CSECache(ctx) if fuse_cse else None
                base = flat[keep]
                if exp_f is not None:
                    em = cse.lower(exp_f) if cse is not None \
                        else F.lower_filter(exp_f, ctx)
                    if em is not None:
                        base = base & em
            codes = [p.build(ctx) for p in dim_plans]
            khi = H.fuse_part(codes, cards, parts[0])
            klo = H.fuse_part(codes, cards, parts[1]) if len(parts) > 1 \
                else jnp.zeros_like(khi)
            inputs = []
            for p in agg_plans:
                inputs.append(G.AggInput(p.spec.name, p.kind,
                                         p.build_values(ctx),
                                         p.build_mask(ctx, cse=cse),
                                         is_int=p.is_int, maxabs=p.maxabs))
            if sorted_run:
                # sorted-run tier: the slot sort rides the agg values as
                # payloads; prefix scans + run-boundary reads replace
                # every per-agg scatter (ops/sorted_groupby.py)
                out = SG.sorted_hash_groupby(khi, klo, base, T, inputs,
                                             routes)
            else:
                slot, tk_hi, tk_lo, unresolved = H.build_slots(
                    khi, klo, base, T)
                out = G.dense_groupby(slot, base, T, inputs, routes,
                                      matmul_max)
                out["__tkhi__"] = tk_hi
                out["__tklo__"] = tk_lo
                out["__unres__"] = unresolved.reshape(1)
            if n_over is not None:
                out["__unres__"] = (out["__unres__"].reshape(-1)[0]
                                    + n_over).reshape(1)
            return out

        return core

    def _hash_packers(self, agg_plans, routes, k_out, with_unres: bool,
                      with_score: bool = False):
        """(pack, unpack) over the hash outputs: ONE flat buffer — a
        tunneled/remote chip charges a full RTT per device->host transfer,
        so the table must not travel as 8-10 separate arrays (same packing
        contract as the dense path)."""
        x64 = G._x64()
        meta = ([("__unres__", 1, "i32")] if with_unres else []) \
            + [("__tkhi__", k_out, "i32"), ("__tklo__", k_out, "i32")]
        if with_score:
            meta.append(("__topk_score__", k_out, "f64" if x64 else "f32"))
        for p in agg_plans:
            meta.extend(routes[p.spec.name].outputs(k_out))
        total = sum(m[1] for m in meta)

        def pack(out):
            return jnp.concatenate([_encode_buf(out[oname], dt, x64)
                                    for oname, _, dt in meta])

        def unpack(buf):
            """-> {name: [n_chips*size] chip-major} (incl. '__unres__')."""
            flat = np.asarray(buf)
            chips = flat.reshape(-1, total)
            out = {}
            off = 0
            for oname, size, dt in meta:
                chunk = np.ascontiguousarray(
                    chips[:, off: off + size]).reshape(-1)
                off += size
                out[oname] = _decode_buf(chunk, dt, x64)
            return out

        return pack, unpack

    def _sorted_run_wanted(self) -> bool:
        """The ONE gate for the sorted-run tier (and the medium-K
        reroute onto it): config 'on'/'off' wins; 'auto' engages when
        riding a payload through the already-paid slot sort beats one
        scatter pass — per-backend calibrated constants, true on TPU,
        false on the CPU fallback unless calibration says otherwise."""
        sr_mode = str(self.config.get(GROUPBY_HASH_SORTED))
        if sr_mode == "off":
            return False
        if sr_mode == "on":
            return True
        from spark_druid_olap_tpu.utils import config as CF
        return C.unit_cost(self.config, CF.COST_SORT_PAYLOAD_ROW) \
            < C.unit_cost(self.config, CF.COST_SCATTER_UPDATE)

    def _multihost_layout(self, ds, seg_idx, n_waves, seg_bytes: int = 0):
        """Re-order a (pruned) segment selection into per-host blocks so
        each host's devices scan exactly the segments that host stores
        (parallel/multihost.layout_segments). Returns the executor-shape
        tuple ``(ordered_seg_idx, s_pad, spw, n_waves)`` — ordered may
        contain ``-1`` padding slots (zero rows, validity False). With
        ``n_waves > 1`` each contiguous ``spw``-slice of the returned
        layout is itself host-blocked (multihost.layout_segments_waves),
        so the wave loops compose with multi-host unchanged — SF100's
        overflow valve works on partial stores (VERDICT r4 item 2)."""
        n_hosts, dph = MH.host_blocks(self.mesh)
        assignment = ds.host_assignment
        if assignment is None:
            # complete (replicated) datasource: derive the same contiguous
            # row-balanced split every process computes from metadata
            rows = np.array([s.num_rows for s in ds.segments], np.int64)
            assignment = MH.assign_segments_to_hosts(rows, n_hosts)
        if n_waves > 1:
            # pass the byte budget down so a skewed assignment (one host
            # owning most of the pruned segments) cannot overshoot the
            # per-device wave budget the caller's n_waves assumed
            ordered, spw = MH.layout_segments_waves(
                assignment, seg_idx, n_hosts, dph, n_waves,
                seg_bytes=int(seg_bytes),
                wave_budget=int(C.wave_budget_bytes(self.config) or 0))
            return ordered, spw, spw, len(ordered) // spw
        ordered, _ = MH.layout_segments(assignment, seg_idx, n_hosts, dph)
        return ordered, len(ordered), len(ordered), 1

    def _shard_wrap(self, fn, in_spec, out_spec, gather_only=None):
        """``gather_only``: multi-host, dict-shaped outputs — all_gather
        (replicate for host fetch) ONLY these keys; the rest stay
        per-chip DEVICE-RESIDENT sharded arrays (the hashed tier's [T]
        slot tables, consumed by the gather dispatch without ever
        crossing hosts — VERDICT r4 item 3's transfer diet)."""
        if self.mesh is None:
            return jax.jit(fn)
        if MH.is_multihost() and out_spec == P(SEGMENT_AXIS):
            inner = fn
            if gather_only is None:
                # per-chip outputs are not fetchable across processes: an
                # in-mesh all_gather replicates them (chips-major, exactly
                # the layout the host-side key-wise merge already expects)
                def fn(x):
                    out = inner(x)
                    return jax.tree.map(
                        lambda y: jax.lax.all_gather(y, SEGMENT_AXIS,
                                                     tiled=True), out)
                out_spec = P()
            else:
                def fn2(x):
                    out = dict(inner(x))
                    gathered = {k: jax.lax.all_gather(
                        out.pop(k), SEGMENT_AXIS, tiled=True)
                        for k in tuple(gather_only) if k in out}
                    return gathered, out
                smfn = shard_map(
                    fn2, mesh=self.mesh, in_specs=(in_spec,),
                    out_specs=(P(), P(SEGMENT_AXIS)), check_vma=False)
                jfn = jax.jit(smfn)

                def wrapped(x):
                    g, rest = jfn(x)
                    return {**g, **rest}
                return wrapped
        smfn = shard_map(fn, mesh=self.mesh, in_specs=(in_spec,),
                             out_specs=out_spec, check_vma=False)
        return jax.jit(smfn)

    def _build_hash_program(self, ds, dim_plans, parts, agg_plans,
                            filter_spec, intervals, min_day, max_day, T,
                            sharded, routes, topk=None, compact_m=None,
                            sorted_run=False):
        """Single-dispatch hash program (full-table or topk-gathered
        transfer). Outputs stay per-chip in sharded mode (slot layouts
        differ per chip; the key-wise merge is host-side). With ``topk``
        only the top-scored ``k_sel`` slots per chip travel (see
        _plan_device_topk_hashed)."""
        core = self._hash_core(ds, dim_plans, parts, agg_plans, filter_spec,
                               intervals, min_day, max_day, T, routes,
                               compact_m=compact_m, sorted_run=sorted_run)
        k_out = topk[1] if topk else T
        pack, unpack = self._hash_packers(agg_plans, routes, k_out, True,
                                          with_score=bool(topk))

        def run(arrays):
            out = core(arrays)
            if topk:
                unres = out.pop("__unres__")
                out = _hash_topk_gather(out, routes, topk, T)
                out["__unres__"] = unres
            return pack(out)

        if not sharded:
            return jax.jit(run), unpack
        return self._shard_wrap(run, P(SEGMENT_AXIS, None),
                                P(SEGMENT_AXIS)), unpack

    def _build_hash_table_program(self, ds, dim_plans, parts, agg_plans,
                                  filter_spec, intervals, min_day, max_day,
                                  T, sharded, routes, compact_m=None,
                                  sorted_run=False):
        """Compaction dispatch 1 of 2: build the table, leave it DEVICE-
        RESIDENT, transfer only '__stats__' = [unresolved, occupied] per
        chip. The host sizes the gather dispatch from the occupancy."""
        core = self._hash_core(ds, dim_plans, parts, agg_plans, filter_spec,
                               intervals, min_day, max_day, T, routes,
                               compact_m=compact_m, sorted_run=sorted_run)

        def run(arrays):
            out = core(arrays)
            unres = out.pop("__unres__")
            occ = jnp.sum(out["__tkhi__"] != H.EMPTY).astype(jnp.int32)
            out["__stats__"] = jnp.concatenate(
                [unres.astype(jnp.int32), occ.reshape(1)])
            return out

        if not sharded:
            return jax.jit(run)
        return self._shard_wrap(run, P(SEGMENT_AXIS, None), P(SEGMENT_AXIS),
                                gather_only=("__stats__",))

    def _plan_hash_topk_exchange(self, q, limit, having, agg_plans):
        """Gate for the multi-chip candidate-exchange ordered limit (see
        _build_hash_topk_exchange_program). min/max metrics are EXACT under
        the exchange; sum/count metrics carry Druid's topN union skew, so
        they engage only for TopNQuerySpec (whose contract is approximate)
        — exact GroupBy keeps the full-table key-wise merge."""
        if having is not None or limit is None or limit.limit is None:
            return None
        if not limit.columns:
            return None
        oc = limit.columns[0]
        plan = next((p for p in agg_plans if p.spec.name == oc.name), None)
        if plan is None:
            return None
        if plan.kind not in ("min", "max") \
                and not isinstance(q, S.TopNQuerySpec):
            return None
        return (oc.name, _topk_slack(limit), bool(oc.ascending))

    def _build_hash_topk_exchange_program(self, agg_plans, routes, metric,
                                          ascending, k_cand, k_sel, T):
        """Multi-chip hashed ordered-limit WITHOUT shipping the tables:
        each chip nominates its local top-``k_cand`` keys, the candidate
        lists all_gather over ICI, every chip probes its OWN table for
        every candidate, and the per-chip metric contributions combine
        with psum/pmin/pmax into EXACT global scores. The global
        top-``k_sel`` candidates' rows then travel per chip (a key a chip
        doesn't hold contributes an EMPTY row the host merge drops).

        Exact for min/max metrics (a global top-k key's global extremum
        is attained on some chip, where it ranks locally at least as high
        — the candidate union must contain it, given slack for ties).
        For sum metrics the union can miss a key that is mediocre on
        every chip yet large in total — Druid's topN accepts exactly this
        skew, and values here are still exact for every returned key
        (never under-counted, unlike Druid's merge)."""
        pack, unpack = self._hash_packers(agg_plans, routes, k_sel, False)
        r = routes[metric]

        def run(table):
            table = dict(table)
            table.pop("__stats__", None)
            tkhi = table["__tkhi__"]
            tklo = table["__tklo__"]
            occ = tkhi != H.EMPTY
            local_sc = _topk_score(r, table, T, ascending, occ)
            _, lidx = jax.lax.top_k(local_sc, k_cand)
            cand_hi = jnp.where(occ[lidx], tkhi[lidx], H.EMPTY)
            cand_lo = jnp.where(occ[lidx], tklo[lidx], H.EMPTY)
            cand_hi = jax.lax.all_gather(cand_hi, SEGMENT_AXIS,
                                         tiled=True)
            cand_lo = jax.lax.all_gather(cand_lo, SEGMENT_AXIS,
                                         tiled=True)
            C = cand_hi.shape[0]
            slot, found = H.probe_slots(tkhi, tklo, cand_hi, cand_lo)
            # exact global metric per candidate from per-chip
            # contributions (identity where this chip lacks the key)
            mvals = {}
            for oname, _, _ in r.outputs(1):
                flat = table[oname].reshape(-1)
                width = flat.shape[0] // T
                if width == 1:
                    mvals[oname] = flat[slot]
                else:
                    mvals[oname] = flat.reshape(T, width)[slot] \
                        .reshape(-1)
            v = G.route_score(r, mvals, C)
            if r.kind == "min":
                # +/-inf identity: strictly above every value AND every
                # NULL sentinel (f64's sentinel IS inf), so absent chips
                # can never mask a NULL-metric group's nulls-last rank
                v = jnp.where(found, v, jnp.asarray(jnp.inf, v.dtype))
                v = jax.lax.pmin(v, SEGMENT_AXIS)
            elif r.kind == "max":
                v = jnp.where(found, v, jnp.asarray(-jnp.inf, v.dtype))
                v = jax.lax.pmax(v, SEGMENT_AXIS)
            else:
                v = jnp.where(found, v, jnp.zeros_like(v))
                v = jax.lax.psum(v, SEGMENT_AXIS)
            sc = -v if ascending else v
            big = jnp.finfo(sc.dtype).max
            if r.kind in ("min", "max"):
                # NULL group = every chip HOLDING the key has the
                # sentinel, detected on the RAW per-chip values BEFORE
                # the float cast (a legitimate i32/i64 extremum within
                # one f32 ulp of the sentinel must not be misclassified
                # as NULL — ADVICE r2), combined across chips
                local_null = G.route_null_mask(r, mvals)
                has_real = jax.lax.psum(
                    (found & jnp.logical_not(local_null))
                    .astype(jnp.int32), SEGMENT_AXIS) > 0
                sc = jnp.where(has_real, sc, jnp.asarray(-big, sc.dtype))
            # duplicates (one key nominated by several chips) keep only
            # their first occurrence; padding/absent keys rank last
            order = jnp.lexsort((cand_lo, cand_hi))
            sh = cand_hi[order]
            sl = cand_lo[order]
            dup_sorted = jnp.concatenate(
                [jnp.zeros((1,), bool),
                 (sh[1:] == sh[:-1]) & (sl[1:] == sl[:-1])])
            dup = jnp.zeros_like(dup_sorted).at[order].set(dup_sorted)
            exists = jax.lax.psum(found.astype(jnp.int32),
                                  SEGMENT_AXIS) > 0
            sc = jnp.where(dup | ~exists | (cand_hi == H.EMPTY),
                           jnp.asarray(-jnp.inf, sc.dtype), sc)
            _, cidx = jax.lax.top_k(sc, k_sel)
            sel_slot = slot[cidx]
            sel_found = found[cidx]
            out = {}
            for name, arr in table.items():
                flat = arr.reshape(-1)
                width = flat.shape[0] // T
                if width == 1:
                    out[name] = flat[sel_slot]
                else:
                    out[name] = flat.reshape(T, width)[sel_slot] \
                        .reshape(-1)
            # a chip without the key contributes an EMPTY row (dropped by
            # the host occupancy filter), so absent values never pollute
            # the key-wise merge
            out["__tkhi__"] = jnp.where(sel_found, cand_hi[cidx], H.EMPTY)
            out["__tklo__"] = jnp.where(sel_found, cand_lo[cidx], H.EMPTY)
            return pack(out)

        in_specs = {"__tkhi__": P(SEGMENT_AXIS),
                    "__tklo__": P(SEGMENT_AXIS)}
        for p in agg_plans:
            for oname, _, _ in routes[p.spec.name].outputs(1):
                in_specs[oname] = P(SEGMENT_AXIS)
        out_spec = P(SEGMENT_AXIS)
        if MH.is_multihost():
            # per-chip candidate rows replicate in-mesh so every process
            # fetches the same O(k_sel) buffer — the tables never move
            inner_run = run

            def run(table):   # noqa: F811 — multihost wrapper
                return jax.tree.map(
                    lambda y: jax.lax.all_gather(y, SEGMENT_AXIS,
                                                 tiled=True),
                    inner_run(table))
            out_spec = P()
        smfn = shard_map(run, mesh=self.mesh, in_specs=(in_specs,),
                             out_specs=out_spec, check_vma=False)
        return jax.jit(lambda table: smfn(table)), unpack

    def _build_hash_gather_program(self, agg_plans, routes, k_gather, T,
                                   sharded):
        """Compaction dispatch 2 of 2: gather the ``k_gather`` occupied
        slots from the resident table (per chip) and pack them into one
        transfer buffer — transfer scales with the ACTUAL group count, not
        the table size (a conservatively-sized table costs HBM, not
        wire)."""
        pack, unpack = self._hash_packers(agg_plans, routes, k_gather,
                                          False)

        def run(table):
            occ = (table["__tkhi__"] != H.EMPTY).astype(jnp.float32)
            _, idx = jax.lax.top_k(occ, k_gather)
            return pack(_gather_rows(table, idx, T))

        if not sharded:
            return jax.jit(run), unpack
        return self._shard_wrap(run, P(SEGMENT_AXIS),
                                P(SEGMENT_AXIS)), unpack

    def _run_waves(self, q, ds, names, seg_idx, spw, sharded, prog_fn,
                   unpack, routes, n_keys, sketch_plans, t0):
        """Execute the scan in bounded segment waves (double-buffered: the
        next wave's host->device transfer overlaps the current wave's
        compute), merging each wave's [K] finals on host. ≈ the reference's
        cost-model "waves" of segments-per-query bounding per-historical
        work (DruidQueryCostModel.scala:309-314,444)."""
        sharding = NamedSharding(self.mesh, P(SEGMENT_AXIS, None)) \
            if sharded else None
        multihost = sharded and MH.is_multihost()
        wave_segs = [seg_idx[i: i + spw]
                     for i in range(0, len(seg_idx), spw)]

        def bind(w):
            # no caching: wave mode exists because the scan exceeds HBM
            return self._bind_wave(ds, names, w, spw, sharding, multihost)

        finals = None
        # cold tier: wave 1's chunks load while wave 0 binds + computes
        self._tier_prefetch(ds, names, wave_segs, 1)
        cur = bind(wave_segs[0])
        for i in range(len(wave_segs)):
            if t0 is not None:
                self._stage_check(q, t0)   # per-wave boundary
            self._tick()
            _td = _time.perf_counter()
            bufs = prog_fn(cur)            # async dispatch
            # wave i+2's cold chunks load behind wave i's compute and
            # wave i+1's (synchronous) bind
            self._tier_prefetch(ds, names, wave_segs, i + 2)
            nxt = bind(wave_segs[i + 1]) if i + 1 < len(wave_segs) else None
            out = unpack(bufs)             # blocks on the device round-trip
            # the overlapped prefetch/bind above charge to their own
            # phases; what's left of this interval is device round-trip
            PH.add("dispatch", _time.perf_counter() - _td)
            over = out.pop("__over__", None)
            if over is not None:
                n_over = int(np.asarray(over).reshape(-1)[0])
                if n_over:
                    # this wave's compaction budget lied: stop burning
                    # waves, the caller re-runs the scan uncompacted
                    return None, n_over
            f = _finals_from_out(out, routes, n_keys, sketch_plans)
            finals = f if finals is None \
                else _merge_wave_finals(finals, f, routes, sketch_plans)
            cur = nxt
        return finals, 0

    def _plan_agg(self, ds, seg_idx, dimensions, aggregations, granularity,
                  filter_spec, intervals):
        """Shared planning for agg queries (used by both the execution path
        and build_core). Raises EngineFallback on unsupported/oversized.
        Returns (dim_plans incl. granularity, agg_plans, min_day, max_day,
        n_keys, array names)."""
        gran_kind = granularity.kind if granularity else "all"
        if len(seg_idx) == 0 or ds.num_rows == 0:
            raise EngineFallback("no segments match the query intervals")
        mins, maxs = ds.segment_time_bounds()
        min_day = int(mins[seg_idx].min() // T.MILLIS_PER_DAY)
        max_day = int(maxs[seg_idx].max() // T.MILLIS_PER_DAY)
        tz = self.config.get(TZ_ID)
        dim_plans = [plan_dimension(d, ds, min_day, max_day, tz)
                     for d in dimensions]
        if gran_kind != "all":
            dim_plans = [plan_granularity_dim(granularity, ds, min_day,
                                              max_day, tz)] + dim_plans
        agg_plans = [plan_aggregation(a, ds) for a in aggregations]
        n_keys = 1
        for p in dim_plans:
            n_keys *= p.card
        # no cap here: callers route n_keys above the dense limit to the
        # hashed path (build_core enforces its own dense-only cap)
        needed = set()
        for p in dim_plans:
            needed |= set(p.source_cols)
        for p in agg_plans:
            needed |= set(p.source_cols)
        needed |= F.columns_of_filter(filter_spec)
        time_in_play = ds.time is not None and (
            intervals is not None or gran_kind != "all"
            or ds.time.name in needed)
        if time_in_play:
            needed.add(ds.time.name)
        names = array_names(ds, sorted(needed), time_in_play)
        routes = self._plan_routes(agg_plans, n_keys, ds)
        return dim_plans, agg_plans, min_day, max_day, n_keys, names, routes

    def _plan_routes(self, agg_plans, n_keys, ds):
        """Static numeric routes for the dense (non-HLL) aggregations plus
        the '__rows__' group-occupancy count."""
        metas = [G.AggInput(p.spec.name, p.kind, is_int=p.is_int,
                            maxabs=p.maxabs)
                 for p in agg_plans if p.kind not in ("hll", "theta", "kll")]
        metas.append(G.AggInput("__rows__", "count", is_int=True, maxabs=1.0))
        return G.plan_routes(
            metas, n_keys, self.config.get(GROUPBY_MATMUL_MAX_KEYS),
            pallas_max=self.config.get(GROUPBY_PALLAS_MAX_KEYS),
            n_rows=int(ds.padded_rows) * int(ds.num_segments))

    def build_core(self, q: S.QuerySpec):
        """Build the *unjitted* scan-aggregate program for an agg query plus
        its input arrays — the compile-check surface (flagship forward step).
        Returns (fn, arrays) with fn pure and jittable."""
        if isinstance(q, S.TimeseriesQuerySpec):
            dims, aggs, gran = [], q.aggregations, q.granularity
        elif isinstance(q, S.GroupByQuerySpec):
            dims, aggs, gran = list(q.dimensions), q.aggregations, \
                q.granularity
        else:
            raise EngineFallback("core build supports groupby/timeseries")
        ds = self.store.get(q.datasource)
        seg_idx = ds.prune_segments(q.intervals, q.filter)
        dim_plans, agg_plans, min_day, max_day, n_keys, names, routes = \
            self._plan_agg(ds, seg_idx, dims, aggs, gran, q.filter,
                           q.intervals)
        if n_keys > self.config.get(GROUPBY_DENSE_MAX_KEYS):
            raise EngineFallback(
                f"core build is dense-only (key cardinality {n_keys})")
        n_dev = mesh_size(self.mesh)
        s_pad = _pad_segments(len(seg_idx), n_dev)
        arrays = {k: _build_array_checked(ds, k, seg_idx, s_pad)
                  for k in names}
        fn = self._make_core(ds, dim_plans, agg_plans, q.filter, q.intervals,
                             min_day, max_day, n_keys, routes)
        return fn, arrays

    def _make_core(self, ds, dim_plans, agg_plans, filter_spec,
                   intervals, min_day, max_day, n_keys, routes,
                   compact_m=None):
        matmul_max = self.config.get(GROUPBY_MATMUL_MAX_KEYS)
        log2m = self.config.get(HLL_LOG2M)
        kll_lanes = self.config.get(QUANTILE_LANES)
        hll_plans = [p for p in agg_plans if p.kind == "hll"]
        theta_plans = [p for p in agg_plans if p.kind == "theta"]
        kll_plans = [p for p in agg_plans if p.kind == "kll"]
        dense_plans = [p for p in agg_plans
                       if p.kind not in ("hll", "theta", "kll")]

        cheap_f, exp_f = (self._split_filter_staged(filter_spec)
                          if compact_m else (filter_spec, None))
        fuse_cse = bool(self.config.get(SHAREDSCAN_FUSION_ENABLED))

        def core(arrays):
            ctx = ScanContext(ds, arrays, min_day, max_day,
                              tz=self.config.get(TZ_ID))
            # trace-time predicate CSE: one query's tree can repeat
            # sub-predicates (OR-of-bounds over one column, a selector
            # shared by every filtered aggregation) — memoized lowering
            # emits each distinct sub-mask once, bit-identically
            cse = FU.CSECache(ctx) if fuse_cse else None
            base = ctx.row_valid()
            fm = cse.lower(cheap_f) if cse is not None \
                else F.lower_filter(cheap_f, ctx)
            if fm is not None:
                base = base & fm
            im = F.interval_mask(intervals, ctx)
            if im is not None:
                base = base & im
            n_over = None
            if compact_m:
                # late materialization: survivors sort to a static [M]
                # prefix; group keys / values / aggregation all run at
                # O(M). Overflow (est. selectivity too optimistic)
                # surfaces as '__over__' and the host retries without
                # compaction. A 2-operand sort is ~0.2ms/M rows on v5e
                # — far below one 6M-row scatter (~40ms).
                flat = base.reshape(-1)
                ridx = jnp.arange(flat.shape[0], dtype=jnp.int32)
                okey = jnp.where(flat, jnp.int32(0), jnp.int32(1))
                _, sidx = jax.lax.sort((okey, ridx), num_keys=1)
                keep = jax.lax.slice_in_dim(sidx, 0, compact_m)
                n_live = jnp.sum(flat.astype(jnp.int32))
                n_over = jnp.maximum(
                    n_live - jnp.int32(compact_m), 0).astype(jnp.int32)
                ctx = CompactScanContext(ds, arrays, min_day, max_day,
                                         self.config.get(TZ_ID), keep=keep)
                # the compacted context changes every mask's shape: the
                # full-width CSE entries must never leak past this point
                cse = FU.CSECache(ctx) if fuse_cse else None
                base = flat[keep]
                if exp_f is not None:
                    # staged: gather-heavy conjuncts (membership sets,
                    # keyed lookups) evaluate on the survivors only
                    em = cse.lower(exp_f) if cse is not None \
                        else F.lower_filter(exp_f, ctx)
                    if em is not None:
                        base = base & em
            if dim_plans:
                codes = [p.build(ctx) for p in dim_plans]
                key, _ = G.fuse_keys(codes, [p.card for p in dim_plans])
            else:
                key = jnp.zeros_like(base, dtype=jnp.int32)
            inputs = []
            for p in dense_plans:
                inputs.append(G.AggInput(p.spec.name, p.kind,
                                         p.build_values(ctx),
                                         p.build_mask(ctx, cse=cse),
                                         is_int=p.is_int, maxabs=p.maxabs))
            inputs.append(G.AggInput("__rows__", "count", is_int=True,
                                     maxabs=1.0))
            out = G.dense_groupby(key, base, n_keys, inputs, routes,
                                  matmul_max)
            for p in hll_plans:
                vals = p.build_values(ctx)
                am = p.build_mask(ctx, cse=cse)
                m = base if am is None else (base & am)
                out[p.spec.name] = HLL.hll_registers(
                    key, m, vals, n_keys, log2m)
            for p in theta_plans:
                vals = p.build_values(ctx)
                am = p.build_mask(ctx, cse=cse)
                m = base if am is None else (base & am)
                out[p.spec.name] = TH.theta_registers(key, m, vals, n_keys)
            for p in kll_plans:
                vals = p.build_values(ctx)
                am = p.build_mask(ctx, cse=cse)
                m = base if am is None else (base & am)
                # the time column joins the content salt so duplicate
                # values in distinct rows keep distinct survivor draws
                tcol = ctx.col(ds.time.name) if ds.time is not None else None
                out[p.spec.name] = KLL.kll_registers(
                    key, m, vals, tcol, n_keys, kll_lanes)
            if n_over is not None:
                out["__over__"] = n_over.reshape(1)
            return out

        return core

    def _build_agg_program(self, ds, dim_plans, agg_plans, filter_spec,
                           intervals, min_day, max_day, n_keys, sharded,
                           routes, topk=None, compact_m=None):
        """Returns (jit_fn, unpack).

        The program packs outputs into TWO flat device buffers so the host
        pays at most two device->host transfers (tunneled/remote chips
        charge full RTT per buffer): one for collective-merged outputs
        (limbs/min/max/HLL — replicated across chips), one for per-chip
        ff/lanes partial pairs (sharded along the segment axis; combined
        exactly in f64 on host, ≈ the reference's historical-mode
        Spark-side final aggregate). Packing is dtype-faithful: on f32
        backends floats travel bitcast inside an i32 buffer, never rounded.

        With ``topk=(metric, k_sel, ascending)`` a device top-k epilogue
        runs after the merge: candidate keys are selected by f32 score
        (``ops.groupby.route_score``), every output is gathered at those
        indices, and only ``[k_sel]``-sized buffers (plus the index map
        ``__topk_idx__``) travel to host — the TPU analog of Druid's topN
        engine answering from the data node instead of shipping the full
        groupBy result (reference rewrite gate:
        ``QuerySpecTransforms.scala`` topN + ``DruidQueryCostModel``
        topN threshold).
        """
        core = self._make_core(ds, dim_plans, agg_plans, filter_spec,
                               intervals, min_day, max_day, n_keys, routes,
                               compact_m=compact_m)
        hll_plans = [p for p in agg_plans if p.kind == "hll"]
        theta_plans = [p for p in agg_plans if p.kind == "theta"]
        kll_plans = [p for p in agg_plans if p.kind == "kll"]
        pack, unpack = self._agg_meta_packers(
            agg_plans, routes, topk[1] if topk else n_keys,
            with_idx=bool(topk), with_score=bool(topk),
            with_over=bool(compact_m))

        def topk_gather(out, axis_name=None):
            """Select k_sel candidate keys by score, gather every output."""
            metric, k_sel, ascending = topk
            rows_sc = G.route_score(routes["__rows__"], out, n_keys,
                                    axis_name)
            sc = _topk_score(routes[metric], out, n_keys, ascending,
                             rows_sc > 0.5, axis_name)
            vals, idx = jax.lax.top_k(sc, k_sel)
            idx = idx.astype(jnp.int32)
            g = _gather_rows(out, idx, n_keys)
            g["__topk_idx__"] = idx
            g["__topk_score__"] = vals
            return g

        if not sharded:
            def plain(arrays):
                out = core(arrays)
                if topk:
                    over = out.pop("__over__", None)
                    out = topk_gather(out)
                    if over is not None:
                        out["__over__"] = over
                return pack(out)

            fn = jax.jit(plain)
        else:
            mesh = self.mesh

            sketch_kinds = {p.spec.name: "hll" for p in hll_plans}
            sketch_kinds.update(
                {p.spec.name: "theta" for p in theta_plans})
            sketch_kinds.update(
                {p.spec.name: "kll" for p in kll_plans})

            def sharded_core(arrays):
                out = core(arrays)
                over = out.pop("__over__", None)
                # ONE mergeable-partial layout for every sharded program
                # (solo cores here, the fused mesh tier in
                # parallel/meshexec.py): psum / pmin / pmax per route
                # algebra, sketch registers per AGG_CLOSURE.merge
                merged = G.merge_lane_partials(out, routes, sketch_kinds,
                                               SEGMENT_AXIS)
                if topk:
                    merged = topk_gather(merged, SEGMENT_AXIS)
                if over is not None:
                    # any shard overflowing its local budget invalidates
                    # the run (those rows were dropped): psum so every
                    # chip's replicated buffer carries the global count
                    merged["__over__"] = jax.lax.psum(over, SEGMENT_AXIS)
                return pack(merged)

            if MH.is_multihost():
                # the per-chip partials buffer (ff/lanes pairs, host-side
                # lane combine) must replicate so every process can fetch;
                # fully-merged programs emit a ZERO-length one (all_gather
                # rejects zero-size dims — leave it, it decodes to nothing)
                inner_core = sharded_core

                def sharded_core(arrays):
                    rep, per_chip = inner_core(arrays)
                    if per_chip.size:
                        per_chip = jax.lax.all_gather(
                            per_chip, SEGMENT_AXIS, tiled=True)
                    return rep, per_chip
                out_specs = (P(), P())
            else:
                out_specs = (P(), P(SEGMENT_AXIS))
            smfn = shard_map(sharded_core, mesh=mesh,
                                 in_specs=(P(SEGMENT_AXIS, None),),
                                 out_specs=out_specs,
                                 check_vma=False)
            fn = jax.jit(lambda arrays: smfn(arrays))

        return fn, unpack

    def _cached_program(self, sig, build):
        """Program-cache fetch with PER-SIGNATURE compile ownership: warm
        queries never touch a lock, and two different programs compile
        CONCURRENTLY (XLA releases the GIL during compilation — and on a
        tunneled chip the compile largely happens server-side — so a
        threaded prewarm overlaps what a single lock would serialize;
        VERDICT r2 #10). A second thread wanting the SAME signature waits
        on the owner's event instead of compiling twice."""
        prog = self._programs.get(sig)
        while prog is None:
            with self._compile_lock:
                prog = self._programs.get(sig)
                if prog is not None:
                    break
                ev = self._compiling.get(sig)
                owner = ev is None
                if owner:
                    ev = self._compiling[sig] = \
                        __import__("threading").Event()
            if owner:
                try:
                    with PH.phase("compile"):
                        prog = build()
                    with self._compile_lock:
                        self._programs[sig] = prog
                finally:
                    with self._compile_lock:
                        self._compiling.pop(sig, None)
                    ev.set()
                break
            ev.wait()
            prog = self._programs.get(sig)
            # owner failed (exception): loop claims ownership and retries
        return prog

    def _plan_device_having(self, having, routes, agg_plans, n_keys,
                            topk, n_waves):
        """(agg_name, op, int_literal) when HAVING is a single comparison
        of an EXACT-on-device aggregate against an integer literal and the
        key space is big enough that shipping only passing groups pays
        (two dispatches: finals + having mask + count, then gather).
        Exactness: limb sums compare lexicographically at any magnitude;
        i32/i64/f64 min/max compare in their own domain. The host epilogue
        re-applies HAVING over the exact finals, so this is a transfer
        filter, never the source of truth."""
        if having is None or topk is not None or n_waves != 1:
            return None
        if n_keys < self.config.get(HAVING_DEVICE_MIN_KEYS):
            return None
        e = having.expr
        if not isinstance(e, E.Comparison):
            return None
        for a, b, op in ((e.left, e.right, e.op),
                         (e.right, e.left, E.FLIP_CMP.get(e.op, e.op))):
            if isinstance(a, E.Column) and isinstance(b, E.Literal) \
                    and isinstance(b.value, (int, np.integer)) \
                    and not isinstance(b.value, bool):
                r = routes.get(a.name)
                if r is None:
                    continue
                lit = int(b.value)
                # the literal must fit the route's comparable domain:
                # out-of-range casts would wrap/raise on device
                if r.tag == "i32" and not -2**31 <= lit < 2**31:
                    continue
                if r.tag in ("i64", "limbs") \
                        and not -2**62 <= lit < 2**62:
                    continue
                if r.tag in ("limbs", "i32", "i64", "f64"):
                    return (a.name, "!=" if op == "<>" else op, lit)
        return None

    def _having_mask(self, having_dev, out, routes, n_keys, axis_name):
        """Device bool [n_keys]: group occupied AND HAVING passes (exact;
        see _plan_device_having)."""
        name, op, lit = having_dev
        r = routes[name]
        rows_sc = G.route_score(routes["__rows__"], out, n_keys, axis_name)
        occ = rows_sc > 0.5
        if r.tag == "limbs":
            limbs = out[name + ".limbs"].reshape(n_keys, G.N_LIMBS)
            m = G.limbs_compare(limbs, lit, op)
        else:
            v = out[name]
            cmp = {"=": lambda a, b: a == b, "!=": lambda a, b: a != b,
                   "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
                   ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}
            m = cmp[op](v, jnp.asarray(lit, v.dtype))
            nm = G.route_null_mask(r, out)
            if nm is not None:          # NULL metric: UNKNOWN -> drop
                m = m & ~nm
        return m & occ

    def _build_agg_table_program(self, ds, dim_plans, agg_plans,
                                 filter_spec, intervals, min_day, max_day,
                                 n_keys, sharded, routes, having_dev):
        """HAVING-compaction dispatch 1 of 2: scan + merge, leave the
        finals DEVICE-RESIDENT, compute the exact having mask and transfer
        only its count. ≈ Druid evaluating HavingSpec on the data node
        instead of shipping every group to the broker."""
        core = self._make_core(ds, dim_plans, agg_plans, filter_spec,
                               intervals, min_day, max_day, n_keys, routes)
        hll_plans = [p for p in agg_plans if p.kind == "hll"]
        theta_plans = [p for p in agg_plans if p.kind == "theta"]
        kll_plans = [p for p in agg_plans if p.kind == "kll"]

        def finish(out, axis_name=None):
            out = dict(out)
            out["__hmask__"] = self._having_mask(having_dev, out, routes,
                                                 n_keys, axis_name)
            out["__stats__"] = jnp.sum(out["__hmask__"]) \
                .astype(jnp.int32).reshape(1)
            return out

        if not sharded:
            return jax.jit(lambda arrays: finish(core(arrays)))
        mesh = self.mesh

        sketch_kinds = {p.spec.name: "hll" for p in hll_plans}
        sketch_kinds.update({p.spec.name: "theta" for p in theta_plans})
        sketch_kinds.update({p.spec.name: "kll" for p in kll_plans})

        def sharded_core(arrays):
            out = core(arrays)
            # shared mergeable-partial layout (ops/groupby.py) — same
            # register algebra as the fused mesh tier
            merged = G.merge_lane_partials(out, routes, sketch_kinds,
                                           SEGMENT_AXIS)
            return finish(merged, SEGMENT_AXIS)

        out_specs = self._agg_out_specs(agg_plans, routes)
        smfn = shard_map(sharded_core, mesh=mesh,
                             in_specs=(P(SEGMENT_AXIS, None),),
                             out_specs=out_specs, check_vma=False)
        return jax.jit(lambda arrays: smfn(arrays))

    def _agg_out_specs(self, agg_plans, routes, with_stats=True):
        """Per-leaf shard specs of the post-merge finals dict: merged
        routes and sketches are replicated, ff/lanes partial pairs stay
        per-chip along the segment axis."""
        specs = {}
        for p in agg_plans:
            if p.kind in ("hll", "theta", "kll"):
                specs[p.spec.name] = P()
                continue
            r = routes[p.spec.name]
            for oname, _, _ in r.outputs(1):
                specs[oname] = P() if r.merged else P(SEGMENT_AXIS)
        r = routes["__rows__"]
        for oname, _, _ in r.outputs(1):
            specs[oname] = P() if r.merged else P(SEGMENT_AXIS)
        if with_stats:
            specs["__hmask__"] = P()
            specs["__stats__"] = P()
        return specs

    def _build_agg_gather_program(self, agg_plans, routes, k, n_keys,
                                  sharded, full=False):
        """HAVING-compaction dispatch 2 of 2: gather the passing groups
        (device mask from dispatch 1) and pack into the standard
        two-buffer transfer, sized [k] instead of [n_keys].

        ``full``: when the mask passes MOST groups, top_k compaction
        buys (n_keys - k) rows of transfer at the price of a [n_keys]
        sort — a measured 3.5s outlier at 1.5M keys on the CPU backend
        (VERDICT r4 weak 3). Instead the whole table travels in key
        order (no index map — decode's identity path applies) and the
        failing groups' occupancy counts are zeroed so the standard
        rows>0 decode drops them — no sort, same answer."""
        pack, unpack = self._agg_meta_packers(agg_plans, routes, k,
                                              with_idx=not full)

        def gather(table):
            table = dict(table)
            table.pop("__stats__", None)
            mask = table.pop("__hmask__")
            if full:
                idx = jnp.arange(n_keys, dtype=jnp.int32)
                g = _gather_rows(table, idx, n_keys)
                for oname, _, _ in routes["__rows__"].outputs(1):
                    flat = g[oname]
                    width = flat.shape[0] // n_keys
                    m = mask.astype(flat.dtype)
                    g[oname] = (flat.reshape(n_keys, width)
                                * m[:, None]).reshape(-1)
            else:
                _, idx = jax.lax.top_k(mask.astype(jnp.float32), k)
                idx = idx.astype(jnp.int32)
                g = _gather_rows(table, idx, n_keys)
                g["__topk_idx__"] = idx
            return pack(g)

        if not sharded:
            return jax.jit(gather), unpack
        # '__stats__' was already popped host-side after dispatch 1
        in_specs = self._agg_out_specs(agg_plans, routes, with_stats=False)
        in_specs["__hmask__"] = P()
        smfn = shard_map(gather, mesh=self.mesh, in_specs=(in_specs,),
                             out_specs=(P(), P(SEGMENT_AXIS)),
                             check_vma=False)
        return jax.jit(lambda table: smfn(table)), unpack

    def _agg_meta_packers(self, agg_plans, routes, n_out, with_idx,
                          with_score=False, with_over=False):
        """(pack, unpack) for the dense path's TWO-buffer transfer:
        collective-merged outputs in one replicated buffer, per-chip
        ff/lanes partial pairs in one segment-sharded buffer. ``n_out``
        is the per-key output length (n_keys, or the gather size when a
        top-k/having epilogue selected rows; then ``with_idx`` appends
        the '__topk_idx__' key map)."""
        hll_plans = [p for p in agg_plans if p.kind == "hll"]
        theta_plans = [p for p in agg_plans if p.kind == "theta"]
        kll_plans = [p for p in agg_plans if p.kind == "kll"]
        dense_plans = [p for p in agg_plans
                       if p.kind not in ("hll", "theta", "kll")]
        m = 1 << self.config.get(HLL_LOG2M)
        kll_w = KLL.width(self.config.get(QUANTILE_LANES))
        x64 = G._x64()
        # (out_name, flat_len, dtype_str, merged)
        meta = []
        for p in dense_plans:
            r = routes[p.spec.name]
            for oname, size, dt in r.outputs(n_out):
                meta.append((oname, size, dt, r.merged))
        r = routes["__rows__"]
        for oname, size, dt in r.outputs(n_out):
            meta.append((oname, size, dt, r.merged))
        meta += [(p.spec.name, n_out * m, "i32", True) for p in hll_plans]
        meta += [(p.spec.name, n_out * TH.K_LANES,
                  "f64" if x64 else "f32", True) for p in theta_plans]
        meta += [(p.spec.name, n_out * kll_w, "i32", True)
                 for p in kll_plans]
        if with_idx:
            meta.append(("__topk_idx__", n_out, "i32", True))
        if with_score:
            meta.append(("__topk_score__", n_out, "f64" if x64 else "f32",
                         True))
        if with_over:
            meta.append(("__over__", 1, "i32", True))
        merged_meta = [t for t in meta if t[3]]
        perchip_meta = [t for t in meta if not t[3]]
        buf_dtype = jnp.int64 if x64 else jnp.int32
        perchip_len = sum(t[1] for t in perchip_meta)

        def pack_group(out, metas):
            parts = [_encode_buf(out[oname], dt, x64)
                     for oname, _, dt, _ in metas]
            if not parts:
                return jnp.zeros((0,), buf_dtype)
            return jnp.concatenate(parts)

        def pack(out):
            return pack_group(out, merged_meta), \
                pack_group(out, perchip_meta)

        def unpack(bufs) -> Dict[str, np.ndarray]:
            for b in bufs:
                try:       # overlap the two device->host round trips
                    b.copy_to_host_async()
                except Exception:  # noqa: BLE001 — plain np inputs in tests
                    pass
            mflat = np.asarray(bufs[0])
            uflat = np.asarray(bufs[1])
            out = {}
            off = 0
            for oname, size, dt, _ in merged_meta:
                chunk = _decode_buf(mflat[off: off + size], dt, x64)
                off += size
                if any(oname == p.spec.name for p in hll_plans):
                    chunk = np.rint(chunk).astype(np.int32) \
                        .reshape(n_out, m)
                elif any(oname == p.spec.name for p in theta_plans):
                    chunk = np.asarray(chunk, np.float32) \
                        .reshape(n_out, TH.K_LANES)
                elif any(oname == p.spec.name for p in kll_plans):
                    chunk = np.rint(chunk).astype(np.int32) \
                        .reshape(n_out, kll_w)
                out[oname] = chunk
            if perchip_len:
                chips = uflat.reshape(-1, perchip_len)
                off = 0
                for oname, size, dt, _ in perchip_meta:
                    # [n_chips, size] -> flat chip-major (combine_route
                    # reshapes back)
                    out[oname] = _decode_buf(
                        np.ascontiguousarray(chips[:, off: off + size])
                        .reshape(-1), dt, x64)
                    off += size
            return out

        return pack, unpack

    # -- select path ----------------------------------------------------------
    def _run_select(self, q: S.SelectQuerySpec) -> QueryResult:
        ds = self.store.get(q.datasource)
        if ds.is_partial:
            # partial store: per-host mask + survivor/page exchange —
            # O(survivors + page) transfer, never the columns
            return self._run_select_multihost(q, ds)
        cols = list(q.columns) or ds.column_names()
        seg_idx = ds.prune_segments(q.intervals, q.filter)
        if len(seg_idx) == 0:
            return QueryResult.empty(cols)
        # filter on device when the scan is big enough to beat the
        # dispatch floor (compiled mask program, bit-packed transfer);
        # page materialization stays host-side — select is IO-bound
        # (≈ Druid Select paged through the broker)
        mask = None
        if (q.filter is not None or q.intervals is not None) \
                and ds.num_rows >= self.config.get(SELECT_DEVICE_MIN_ROWS):
            mask = self._device_mask(ds, q.filter, q.intervals, seg_idx)
        if mask is None:
            self.last_stats["select_filter"] = "host"
            mask = self._host_mask(ds, q.filter, q.intervals)
        idx = np.nonzero(mask)[0]
        if q.descending:
            idx = idx[::-1]
        page = idx[q.page_offset: q.page_offset + q.page_size]
        data = {}
        for c in cols:
            data[c] = _host_column_values(ds, c, page)
        self.last_stats.update({"datasource": ds.name,
                                "rows": int(len(page)),
                                "rows_scanned": int(ds.num_rows)})
        if self.last_stats.get("select_filter") != "host":
            # the device pass reads only the MASK's inputs (filter
            # columns); the page gather is host-side — sizing from the
            # output columns would overstate the roofline by orders
            mask_cols = set(F.columns_of_filter(q.filter))
            if q.intervals and ds.time is not None:
                mask_cols.add(ds.time.name)
            if mask_cols:
                self.last_stats["bytes_scanned"] = \
                    int(C.bytes_per_segment(ds, sorted(mask_cols))) \
                    * int(len(seg_idx))
        return QueryResult(cols, data)

    def _run_select_multihost(self, q: S.SelectQuerySpec,
                              ds: Datasource) -> QueryResult:
        """Select paging on a multi-host partial store (VERDICT r4
        item 2): every process runs the same query (SPMD); each host
        evaluates the filter over ITS local rows, hosts exchange the
        surviving GLOBAL row ids (O(survivors)), the page slice is
        computed identically everywhere, and only the page's raw values
        travel — dimensions as dictionary codes, decoded against the
        replicated global dictionary. ≈ Druid Select paging through the
        broker across historicals (the reference's paged select,
        ``DruidQuerySpec.scala`` SelectSpec result contract)."""
        import dataclasses as _dc
        if not MH.is_multihost():
            # single-process partial store (test rig): no peers to
            # exchange with — a local-only answer would be silently wrong
            ds.require_complete("select scan")
        cols = list(q.columns) or ds.column_names()
        seg_idx = ds.prune_segments(q.intervals, q.filter)
        if len(seg_idx) == 0:
            # metadata-deterministic: every process bails together
            return QueryResult.empty(cols)
        mask_local = self._host_mask(ds, q.filter, q.intervals,
                                     local=True)
        self.last_stats["select_filter"] = "host-local"
        gsur = ds.local_to_global_rows()[np.nonzero(mask_local)[0]]
        all_ids = np.concatenate(MH.exchange_block(gsur))
        all_ids.sort()
        if q.descending:
            all_ids = all_ids[::-1]
        page = all_ids[q.page_offset: q.page_offset + q.page_size]
        owner = ds.owner_of_rows(page)
        mine = np.nonzero(owner == ds.host_id)[0].astype(np.int64)
        lidx = ds.global_to_local_rows(page[mine])
        n_page = len(page)
        pos_blocks = MH.exchange_block(mine)

        def assemble(local_vals):
            """Exchange each host's page rows; place at page positions."""
            blocks = MH.exchange_block(local_vals)
            out = np.zeros((n_page,) + local_vals.shape[1:],
                           local_vals.dtype)
            for pb, blk in zip(pos_blocks, blocks):
                out[pb] = blk
            return out

        # a page-sized COMPLETE datasource clone: raw storage arrays are
        # exchanged (numeric only), then the standard host decode runs
        # unchanged (_host_column_values semantics cannot diverge)
        dims, mets = {}, {}
        time = None
        for c in cols:
            if c in ds.dims:
                col = ds.dims[c]
                dims[c] = _dc.replace(
                    col, codes=assemble(col.codes[lidx]),
                    validity=(assemble(col.validity[lidx])
                              if col.validity is not None else None))
            elif c in ds.metrics:
                m = ds.metrics[c]
                mm = _dc.replace(
                    m, values=assemble(m.values[lidx]),
                    validity=(assemble(m.validity[lidx])
                              if m.validity is not None else None))
                mm._bounds_cache = (m.min, m.max)
                mets[c] = mm
            elif ds.time is not None and c == ds.time.name:
                time = _dc.replace(ds.time,
                                   days=assemble(ds.time.days[lidx]),
                                   ms_in_day=assemble(
                                       ds.time.ms_in_day[lidx]))
        page_ds = Datasource(name=ds.name, time=time, dims=dims,
                             metrics=mets,
                             segments=[Segment("page", 0, n_page, 0, 0)])
        data = {c: _host_column_values(page_ds, c, None) for c in cols}
        self.last_stats.update({"datasource": ds.name,
                                "rows": int(n_page),
                                "rows_scanned": int(ds.num_rows),
                                "n_transfer": int(len(all_ids) + n_page)})
        return QueryResult(cols, data)

    def _run_search(self, q: S.SearchQuerySpec) -> QueryResult:
        ds = self.store.get(q.datasource)
        # host-side dictionary-occurrence counting; on a partial store
        # each host counts ITS rows and the per-code counts are summed
        # across processes (O(cardinality) transfer, never the columns)
        partial = ds.is_partial
        if partial and not MH.is_multihost():
            ds.require_complete("search scan")
        mask = self._host_mask(ds, q.filter, q.intervals, local=partial)
        needle = q.query if q.case_sensitive else q.query.lower()
        dims_out, vals_out, counts_out = [], [], []
        for dname in q.dimensions:
            dim = ds.dims[dname]
            cand = [i for i, s in enumerate(dim.dictionary)
                    if needle in (s if q.case_sensitive else s.lower())]
            if not cand:
                continue
            codes = dim.codes
            eff = mask if mask is not None \
                else np.ones(len(codes), dtype=bool)
            if dim.validity is not None:
                # NULL rows are encoded at code 0; they are not occurrences
                # of dictionary[0]
                eff = eff & dim.validity
            sub = codes[eff]
            counts = np.bincount(sub, minlength=dim.cardinality)
            if partial:
                counts = np.sum(MH.exchange_block(
                    counts.astype(np.int64)), axis=0)
            for c in cand:
                if counts[c] > 0:
                    dims_out.append(dname)
                    vals_out.append(dim.dictionary[c])
                    counts_out.append(int(counts[c]))
        if q.limit is not None:
            dims_out = dims_out[: q.limit]
            vals_out = vals_out[: q.limit]
            counts_out = counts_out[: q.limit]
        self.last_stats.update({"datasource": ds.name,
                                "search_values": len(vals_out)})
        if q.value_output is not None:
            # rewritten from a group-by: project to its output shape
            return QueryResult(
                [q.value_output, q.count_output],
                {q.value_output: np.array(vals_out, dtype=object),
                 q.count_output: np.array(counts_out, dtype=np.int64)})
        return QueryResult(
            ["dimension", "value", "count"],
            {"dimension": np.array(dims_out, dtype=object),
             "value": np.array(vals_out, dtype=object),
             "count": np.array(counts_out, dtype=np.int64)})

    # -- helpers --------------------------------------------------------------
    def _device_mask(self, ds: Datasource, filter_spec, intervals,
                     seg_idx) -> Optional[np.ndarray]:
        """Evaluate the select filter on device: one compiled program
        lowers the filter + interval mask over the pruned stacked scan and
        returns a 32x bit-packed word array ([S, R/32] uint32) — the same
        compiled filter tier aggregations use (dictionary compares, spatial,
        regex-via-dictionary, compiled expressions), so select filters can
        never diverge from aggregate filters. Returns the global [num_rows]
        bool mask, or None when the filter doesn't lower (host fallback)."""
        mins, maxs = ds.segment_time_bounds()
        if len(seg_idx) == 0 or ds.time is None:
            min_day = max_day = 0
        else:
            min_day = int(mins[seg_idx].min() // T.MILLIS_PER_DAY)
            max_day = int(maxs[seg_idx].max() // T.MILLIS_PER_DAY)
        needed = F.columns_of_filter(filter_spec)
        time_in_play = ds.time is not None and (
            intervals is not None or ds.time.name in needed)
        if time_in_play:
            needed.add(ds.time.name)
        names = array_names(ds, sorted(needed), time_in_play)
        # pad like the single-device agg path so the bound arrays SHARE
        # the device cache entries aggregations already made resident
        s_pad = _pad_segments(len(seg_idx), 1)
        sig = ("selmask", ds.name, id(ds), repr(filter_spec),
               repr(intervals), s_pad, ds.padded_rows, min_day, max_day,
               tuple(names), self.config.get(TZ_ID),
               jax.default_backend())
        prog = self._programs.get(sig)
        if prog is None:
            R = ds.padded_rows

            def core(arrays):
                ctx = ScanContext(ds, arrays, min_day, max_day,
                                  tz=self.config.get(TZ_ID))
                base = ctx.row_valid()
                fm = F.lower_filter(filter_spec, ctx)
                if fm is not None:
                    base = base & fm
                im = F.interval_mask(intervals, ctx)
                if im is not None:
                    base = base & im
                bits = base.reshape(s_pad, R // 32, 32).astype(jnp.uint32)
                weights = jnp.left_shift(
                    jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
                return (bits * weights[None, None, :]).sum(
                    axis=-1, dtype=jnp.uint32)

            with self._compile_lock:
                prog = self._programs.get(sig)
                if prog is None:
                    prog = jax.jit(core)
                    self._programs[sig] = prog
        try:
            # cached device bindings: a repeated (dashboard/paging) select
            # re-runs the mask program against resident arrays instead of
            # re-uploading the filter columns every call
            arrays = self._bind_arrays(ds, names, seg_idx, s_pad, False)
            self._tick()
            words = np.asarray(prog(arrays))
        except (EngineFallback, EC.Unsupported):
            return None
        shifts = np.arange(32, dtype=np.uint32)
        bits = ((words[:, :, None] >> shifts) & 1).astype(bool) \
            .reshape(s_pad, ds.padded_rows)
        mask = np.zeros(ds.num_rows, dtype=bool)
        for i, si in enumerate(seg_idx):
            s = ds.segments[int(si)]
            mask[s.start_row: s.end_row] = bits[i, : s.num_rows]
        self.last_stats["select_filter"] = "device"
        return mask

    def _host_mask(self, ds: Datasource, filter_spec, intervals,
                   local: bool = False):
        """Row mask evaluated host-side. ``local=True`` evaluates over
        THIS host's rows only (a partial store's local arrays) — the
        multi-host select/search paths merge per-host results instead of
        gathering columns."""
        n = ds.local_num_rows if local else ds.num_rows
        mask = np.ones(n, dtype=bool)
        if intervals is not None and ds.time is not None:
            ms = ds.time.millis if local \
                else ds.complete(columns=()).time.millis
            im = np.zeros(n, dtype=bool)
            for lo, hi in intervals:
                im |= (ms >= lo) & (ms < hi)
            mask &= im
        if filter_spec is not None:
            env = {}
            # SORTED: on a partial store each column gathers via a
            # cross-process collective — set iteration order differs
            # per process (hash randomization) and would deadlock
            for c in sorted(_filter_columns_all(filter_spec)):
                env[c] = _host_column_values(ds, c, None, local_ok=local)
            expr = filter_to_expr(filter_spec)
            mask &= host_eval.eval_pred3(expr, env)
        return mask

    def _should_shard(self, q, ds, seg_idx) -> bool:
        if ds.is_partial:
            # a partial store's data exists only across the pod: the
            # sharded path is the ONLY path (host/single-device would
            # need remote rows)
            if self.mesh is None or mesh_size(self.mesh) <= 1:
                raise RuntimeError(
                    f"partial datasource {ds.name!r} requires a multi-host "
                    f"mesh (engine has {mesh_size(self.mesh)} device(s))")
            self.last_stats["shard_decision"] = "partial-store"
            return True
        if self.mesh is None or mesh_size(self.mesh) <= 1:
            return False
        pref = q.context.prefer_sharded if hasattr(q, "context") else None
        if pref is not None:
            self.last_stats["shard_decision"] = "context"
            return bool(pref)
        try:
            est = C.estimate(self, q)
        except Exception:   # noqa: BLE001 — cost must never fail a query
            self.last_stats["shard_decision"] = "default"
            return len(seg_idx) >= 1
        self.last_stats["shard_decision"] = (
            f"cost:{'sharded' if est.recommend_sharded else 'single'}")
        self.last_stats["cost_single"] = est.single_cost
        self.last_stats["cost_sharded"] = est.sharded_cost
        return est.recommend_sharded

    def _bind_wave(self, ds, names, w, s_pad, sharding, multihost):
        """Uncached per-wave bind (wave mode exists because the scan
        exceeds the device budget). Multi-host: each process provides only
        the shards its devices own — the wave layout is host-blocked
        (multihost.layout_segments_waves), so a block's non-local segment
        ids never reach this process's builder."""
        with PH.phase("bind"):
            self._tick(1, len(names))
            if multihost:
                out = {}
                for k in names:
                    dt = array_dtype(ds, k)
                    if dt == np.int64 and not G._x64():
                        raise EngineFallback(
                            f"wide integer column {k!r} on a 32-bit backend")
                    out[k] = MH.put_sharded_blocks(
                        lambda ids, k=k: build_array_blocks(ds, k, ids),
                        w, ds.padded_rows, dt, sharding)
                return out
            return {k: _device_put_retry(
                _build_array_checked(ds, k, w, s_pad), sharding)
                for k in names}

    def _bind_arrays(self, ds, names, seg_idx, s_pad, sharded):
        """Fetch-or-build the device arrays a program binds. Cached per
        (datasource, array, segment selection, layout) so repeated dashboard
        queries never re-upload host data (≈ segments staying resident on
        Druid historicals between queries).

        Multi-host: ``seg_idx`` is the per-host block layout (global ids
        with -1 padding) and each process provides only the shards its
        devices own — ``jax.make_array_from_callback`` invokes the block
        builder per locally-addressable device, so no process ever
        materializes (or ships) another host's rows."""
        with PH.phase("bind"):
            return self._bind_arrays_inner(ds, names, seg_idx, s_pad,
                                           sharded)

    def _bind_arrays_inner(self, ds, names, seg_idx, s_pad, sharded):
        sharding = NamedSharding(self.mesh, P(SEGMENT_AXIS, None)) \
            if sharded else None
        multihost = sharded and MH.is_multihost()
        seg_sig = (len(seg_idx), hash(seg_idx.tobytes()))
        out = {}
        for k in names:
            key = (id(ds), k, s_pad, seg_sig, bool(sharded), multihost)
            dev = self._device_arrays.get(key)   # lock-free warm path
            if dev is None:
                with self._compile_lock:
                    dev = self._device_arrays.get(key)
                    if dev is None:
                        if multihost:
                            dt = array_dtype(ds, k)
                            if dt == np.int64 and not G._x64():
                                raise EngineFallback(
                                    f"wide integer column {k!r} on a "
                                    f"32-bit backend")
                            # account what THIS process holds (its own
                            # devices' shards), not the global array
                            nbytes = len(seg_idx) * ds.padded_rows \
                                * np.dtype(dt).itemsize \
                                // max(jax.process_count(), 1)
                            host = None
                        else:
                            host = _build_array_checked(ds, k, seg_idx,
                                                        s_pad)
                            nbytes = int(host.nbytes)
                        # bound device residency: distinct segment
                        # selections (paged selects, shifting intervals)
                        # would otherwise pin fresh copies until OOM.
                        # Evict BEFORE the upload so peak residency never
                        # exceeds cap + one array.
                        cap = int(self.config.get(DEVICE_CACHE_BYTES))
                        if self._device_bytes + nbytes > cap \
                                and self._device_arrays:
                            self._device_arrays.clear()
                            self._device_bytes = 0
                        self._tick(1)
                        if multihost:
                            dev = MH.put_sharded_blocks(
                                lambda ids, k=k: build_array_blocks(
                                    ds, k, ids),
                                seg_idx, ds.padded_rows, dt, sharding)
                        else:
                            dev = _device_put_retry(host, sharding)
                        self._device_arrays[key] = dev
                        self._device_bytes += nbytes
            out[k] = dev
        return out

    def _tier_prefetch(self, ds, names, wave_segs, i):
        """Enqueue wave ``i``'s cold-tier chunks on the prefetcher so
        they load behind the current wave's device compute. No-op on
        in-memory datasources or past the last wave."""
        pf = getattr(ds, "tier_prefetch", None)
        if pf is not None and i < len(wave_segs):
            pf(names, wave_segs[i])

    def clear_caches(self):
        # under the compile lock: the backend-lost recovery thread calls
        # this concurrently with query threads populating the same dicts
        # in _cached_program/_device_tables (sdlint locks/unguarded-write)
        with self._compile_lock:
            self._programs.clear()
            self._compact_overflowed.clear()
            self._device_arrays.clear()
            self._device_bytes = 0
        self.result_cache.clear()


_LOST_MARKERS = ("unavailable", "deadline_exceeded", "deadline exceeded",
                 "connection", "socket", "transport", "unreachable",
                 "device or resource busy", "premature end")


def _cache_repr(q) -> str:
    """repr(q) with the per-request QueryContext stripped: query_id /
    timeout never shape the compiled program, and leaving them in the
    signature would recompile EVERY server statement (each request
    carries a fresh query id — a 3-45s compile per request on a TPU)."""
    try:
        return repr(dataclasses.replace(q, context=None))
    except Exception:  # noqa: BLE001 — non-dataclass/frozen edge
        return repr(q)


def _is_backend_loss(e: BaseException) -> bool:
    """Heuristic classification of a permanently-dead device backend
    (tunneled-TPU failure mode: transfers/dispatches raise UNAVAILABLE /
    connection errors after _device_put_retry exhausts its backoff)."""
    if isinstance(e, EngineFallback) \
            or not isinstance(e, (RuntimeError, OSError)):
        return False
    from spark_druid_olap_tpu.cluster.broker import ClusterError
    if isinstance(e, ClusterError):
        # a shard unreachable over the NETWORK says nothing about the
        # local device backend — strict mode must surface it, not demote
        # it to a host fallback
        return False
    s = str(e).lower()
    return any(m in s for m in _LOST_MARKERS)


def _probe_device_alive(timeout_s: float = 10.0) -> bool:
    """Whether the default backend answers a trivial dispatch within the
    deadline, probed from a daemon thread (a hung dispatch must never
    hang the session)."""
    result = []

    def work():
        try:
            r = jax.device_put(np.arange(8, dtype=np.int32))
            result.append(int(jnp.sum(r)) == 28)
        except Exception:  # noqa: BLE001
            result.append(False)

    th = __import__("threading").Thread(target=work, daemon=True)
    th.start()
    th.join(timeout_s)
    return bool(result and result[0])


def _device_put_retry(host, sharding=None):
    """device_put with backoff on transient backend errors — the tunneled
    TPU's transfers can hiccup with UNAVAILABLE (≈ the reference wrapping
    Druid HTTP calls in RetryUtils.retryOnError)."""
    from spark_druid_olap_tpu.utils.retry import retry_on_error

    def transient(e):
        s = str(e)
        return "UNAVAILABLE" in s or "DEADLINE_EXCEEDED" in s \
            or "RESOURCE_EXHAUSTED" in s

    return retry_on_error(lambda: jax.device_put(host, sharding),
                          tries=3, start=0.5, retryable=transient)


def _build_array_checked(ds, key, seg_idx, s_pad) -> np.ndarray:
    """build_array + the wide-integer gate: a 32-bit device backend cannot
    carry int64 values without silently wrapping, so queries binding a wide
    LONG column demote to the host tier there (x64 backends carry them in
    f64 routes, exact to 2^53)."""
    arr = build_array(ds, key, seg_idx, s_pad)
    if arr.dtype == np.int64 and not G._x64():
        raise EngineFallback(
            f"wide integer column {key!r} on a 32-bit backend")
    return arr


def _decode_agg_value(ds, p, r, v) -> np.ndarray:
    """Final per-group route values -> output column (dtype-faithful; min/max
    empty-group sentinels become nulls, like Druid)."""
    if p.kind in ("min", "max"):
        if r.tag == "i32":
            sent = G.I32_MAX if p.kind == "min" else G.I32_MIN
            empty = v == np.int64(sent)
        elif r.tag == "i64":
            sent = G.I64_MAX if p.kind == "min" else G.I64_MIN
            empty = v == sent
        else:
            empty = np.abs(v) >= 3.0e38
        if p.spec.kind == "anyvalue":
            return _decode_anyvalue(ds, p.spec.field, v, empty)
        if p.dim_codes:
            # extremum CODE of the sorted dictionary -> its string (the
            # same decode contract as FD-demoted grouping columns)
            return _decode_anyvalue(ds, p.spec.field, v, empty)
        if empty.any():
            if r.tag == "i64" and \
                    np.abs(np.where(empty, 0, v)).max(initial=0) >= 2**53:
                # f64 NaN-nulls would round these; keep exact ints + None
                out = v.astype(object)
                out[empty] = None
                return out
            return np.where(empty, np.nan, v).astype(np.float64)
        if np.issubdtype(p.out_dtype, np.integer) and r.tag in ("i32", "i64"):
            return v.astype(np.int64)
        if np.issubdtype(p.out_dtype, np.integer):
            return np.round(v).astype(np.int64)
        return v.astype(np.float64)
    if np.issubdtype(p.out_dtype, np.integer):
        # sum/count int routes combine exactly (lanes/limbs/ff/i64);
        # np.rint would detour int64 through f64 and round past 2^53
        if np.issubdtype(v.dtype, np.integer):
            return v.astype(np.int64)
        return np.rint(v).astype(np.int64)
    return v.astype(np.float64)


def _encode_buf(a, dt: str, x64: bool):
    """Dtype-faithful packing of one flat program output into the int lane
    of the single transfer buffer: floats travel BITCAST inside the int
    buffer, never rounded (the packing contract shared by the dense and
    hashed programs)."""
    a = a.reshape(-1)
    if x64:
        if dt == "f64":
            return jax.lax.bitcast_convert_type(
                a.astype(jnp.float64), jnp.int64)
        if dt == "f32":
            # ffl pairs are f32 even on x64 backends: bitcast into the
            # low lane (astype would TRUNCATE the fraction)
            return jax.lax.bitcast_convert_type(
                a.astype(jnp.float32), jnp.int32).astype(jnp.int64)
        return a.astype(jnp.int64)
    if dt == "f32":
        return jax.lax.bitcast_convert_type(a.astype(jnp.float32), jnp.int32)
    return a.astype(jnp.int32)


def _decode_buf(chunk: np.ndarray, dt: str, x64: bool) -> np.ndarray:
    """Host inverse of _encode_buf (chunk must be contiguous for the
    bitcast view)."""
    if x64 and dt == "f64":
        return chunk.view(np.float64)
    if dt == "f32":
        if x64:
            return chunk.astype(np.int32).view(np.float32)
        return chunk.view(np.float32)
    return chunk


def _gather_rows(out, idx, n_keys):
    """Gather every per-key output at ``idx``: each output is flat
    [n_keys*width] key-major; rows of the [n_keys, width] view are kept."""
    g = {}
    for name, arr in out.items():
        flat = arr.reshape(-1)
        width = flat.shape[0] // n_keys
        if width == 1:
            g[name] = flat[idx]
        else:
            g[name] = flat.reshape(n_keys, width)[idx].reshape(-1)
    return g


def _topk_score(route, out, n_keys, ascending, valid, axis_name=None):
    """The shared selection-score pipeline of the dense and hashed top-k
    epilogues. Rank order must match the host epilogue's: real scores,
    then occupied groups whose metric is NULL (min/max sentinel — under
    ascending negation it would otherwise rank FIRST), then invalid
    (unoccupied) keys at -inf so NULL-metric groups still fill an
    under-subscribed LIMIT (nulls-last)."""
    sc = G.route_score(route, out, n_keys, axis_name)
    if ascending:
        sc = -sc
    nm = G.route_null_mask(route, out)
    if nm is not None:
        big = jnp.finfo(sc.dtype).max
        sc = jnp.where(nm, jnp.asarray(-big, sc.dtype), sc)
    return jnp.where(valid, sc, jnp.asarray(-jnp.inf, sc.dtype))


def _score_cast_exact(route, x64: bool, vlo: float, vhi: float) -> bool:
    """True when route_score is bit-exact for every metric value in
    [vlo, vhi] AND no value OUTSIDE that range can round onto a value
    inside it (so a boundary tie in score space is a true value tie).
    Bounds are therefore STRICT: at an inclusive 2^24 cutoff, an
    excluded i32 key at 2^24+1 rounds ties-to-even DOWN onto the
    cutoff and a tie-accept would certify a wrong result."""
    t = route.tag
    if t == "f32":
        return True                  # the score IS the device value
    if t == "f64":
        return x64
    if t == "i64":
        return x64 and -(2.0 ** 53) < vlo and vhi < 2.0 ** 53
    if t == "i32":
        return -(2.0 ** 24) < vlo and vhi < 2.0 ** 24
    if t in ("limbs", "lanes"):
        # nonnegative values below the first carry boundary reconstruct
        # as a sum of two exactly-representable f32 terms; values past
        # 2^24 round by at most 1 ulp and cannot reach below 2^23
        return 0.0 <= vlo and vhi < 2.0 ** 23
    return False                     # ff compensated pairs


def _topk_selection_exact(limit, topk, route, scores, data) -> bool:
    """True when the f32-approximate device candidate selection PROVABLY
    contains the exact ordered-limit result. Exact-contract GroupBy
    re-runs without the device epilogue when this returns False;
    TopNQuerySpec never checks (its contract is approximate, like
    Druid's topN engine — reference TopNQuerySpec semantics,
    DruidQuerySpec.scala:767-822).

    Soundness: the device transfers the best ``k_sel`` keys by a
    possibly-rounded score; every non-transferred key's device score is
    <= the k_sel-th best ("cutoff"), and its EXACT value can exceed its
    own device score only by the score-reconstruction error. So the
    result is exact whenever the LIMIT-th emitted row's exact value
    clears the cutoff by more than that error bound — excluded keys
    then cannot rank above (or tie with) any emitted row, which also
    makes secondary ORDER BY columns moot at the boundary."""
    metric, k_sel, ascending = topk
    cutoff = float(scores[-1]) if len(scores) else float("-inf")
    if cutoff != cutoff:
        return False                       # NaN scores: cannot reason
    if cutoff == float("-inf"):
        # an unoccupied (-inf) slot made the candidate set: every
        # occupied key was transferred, so the selection is complete
        return True
    n = int(limit.limit)
    if n <= 0:
        return True
    vals = data.get(metric)
    if vals is None:
        return False
    vals = np.asarray(vals)
    if len(vals) < n:
        # occupied keys were excluded (finite cutoff) yet the LIMIT is
        # under-subscribed — an excluded key might belong in the result
        return False
    v_k = vals[n - 1]
    if v_k is None or (isinstance(v_k, float) and v_k != v_k):
        return False      # NULL boundary row: excluded NULLs could tie
    try:
        s_k = float(v_k)
    except (TypeError, ValueError):
        return False
    if ascending:
        s_k = -s_k
    x64 = bool(jax.config.jax_enable_x64)
    c_val = -cutoff if ascending else cutoff        # cutoff in VALUE domain
    vlo = min(s_k if not ascending else -s_k, c_val)
    vhi = max(s_k if not ascending else -s_k, c_val)
    if _score_cast_exact(route, x64, vlo, vhi):
        # scores near the boundary are bit-exact: strictly-better is
        # always safe, and an exact TIE is safe when the primary metric
        # is the only order column (excluded tying keys are
        # interchangeable answers under SQL's unspecified tie order)
        return s_k > cutoff \
            or (s_k == cutoff and len(limit.columns) == 1)
    # Error bound for an excluded key's route_score reconstruction: a
    # few ulps relative to the magnitudes involved. The split integer
    # routes (limbs/lanes) renormalize through ~2^48-scale positive
    # intermediates that cancel for negative values, so near a
    # non-positive value the ABSOLUTE error is that scale's ulp.
    base = max(abs(cutoff), abs(s_k), 1.0)
    if route.tag in ("limbs", "lanes") and vlo <= 0:
        base = max(base, float(2 ** 50))
    f32_score = route.tag in ("limbs", "lanes", "ff", "ffl", "i32",
                              "f32") or not x64
    eps = float(np.spacing(np.float32(base))) if f32_score \
        else float(np.spacing(np.float64(base)))
    return (s_k - cutoff) > 64.0 * eps


def _topk_slack(limit: S.LimitSpec) -> int:
    """Candidate count for a device top-k selection. Secondary order
    columns (e.g. TPC-H q3/q18 'ORDER BY revenue DESC, o_orderdate') only
    reorder ties in the PRIMARY metric, so they widen the slack (selection
    stays exact unless >slack keys tie exactly at the cutoff value);
    single-column selection errors additionally require f32 rounding to
    cross a gap at the cutoff."""
    if len(limit.columns) == 1:
        return int(max(2 * limit.limit, limit.limit + 64))
    return int(max(4 * limit.limit, limit.limit + 256))


def _hash_topk_gather(out, routes, topk, T):
    """Per-chip top-k over hash-table slots: score occupied slots, keep the
    best k_sel (unoccupied slots at -inf fill any remainder and are
    dropped by the host occupancy filter)."""
    metric, k_sel, ascending = topk
    occ = out["__tkhi__"] != H.EMPTY
    sc = _topk_score(routes[metric], out, T, ascending, occ)
    vals, idx = jax.lax.top_k(sc, k_sel)
    g = _gather_rows(out, idx, T)
    g["__topk_score__"] = vals
    return g


def _hash_chip_partials(raw, routes, T, n_dev):
    """Split a hash program's stacked outputs into per-chip (packed-key,
    finals) partials, dropping unoccupied slots."""
    parts = []
    for c in range(n_dev):
        out_c = {}
        for name, arr in raw.items():
            if name == "__unres__":
                continue
            size = arr.size // n_dev
            out_c[name] = arr[c * size: (c + 1) * size]
        khi = out_c.pop("__tkhi__")
        klo = out_c.pop("__tklo__")
        occ = khi != H.EMPTY
        if not occ.any():
            continue
        finals = {name: np.asarray(G.combine_route(r, out_c, T))[occ]
                  for name, r in routes.items()}
        parts.append((H.pack_key(khi[occ], klo[occ]), finals))
    return parts


def _merge_hash_partials(parts, routes):
    """Merge per-chip/per-wave hash-table partials by key on host (≈ the
    broker-side merge of historical partials). Sums/counts add exactly
    (i64/f64 finals), min/max keep sentinels."""
    if not parts:
        empty = {name: np.zeros(0, np.float64) for name in routes}
        return np.zeros(0, np.int64), empty
    keys = np.concatenate([k for k, _ in parts])
    uniq, inv = np.unique(keys, return_inverse=True)
    merged = {}
    for name, r in routes.items():
        segs = np.concatenate([f[name] for _, f in parts])
        int_tag = r.tag in ("i32", "i64")
        if r.kind == "min":
            sent = {"i32": np.int64(G.I32_MAX),
                    "i64": G.I64_MAX}.get(r.tag, np.float64(np.inf))
            acc = np.full(len(uniq), sent,
                          dtype=np.int64 if int_tag else np.float64)
            np.minimum.at(acc, inv, segs)
        elif r.kind == "max":
            sent = {"i32": np.int64(G.I32_MIN),
                    "i64": G.I64_MIN}.get(r.tag, np.float64(-np.inf))
            acc = np.full(len(uniq), sent,
                          dtype=np.int64 if int_tag else np.float64)
            np.maximum.at(acc, inv, segs)
        else:
            dt = np.int64 if segs.dtype == np.int64 else np.float64
            acc = np.zeros(len(uniq), dtype=dt)
            np.add.at(acc, inv, segs.astype(dt))
        merged[name] = acc
    return uniq, merged


def _finals_from_out(out, routes, n_keys, sketch_plans):
    """Route outputs -> exact final [n_keys] arrays per aggregation (plus
    raw sketch registers), the unit that waves merge over."""
    finals = {name: np.asarray(G.combine_route(r, out, n_keys))
              for name, r in routes.items()}
    for p in sketch_plans:
        finals[p.spec.name] = np.asarray(out[p.spec.name])
    return finals


def _merge_wave_finals(acc, new, routes, sketch_plans=()):
    """Cross-wave merge: sums/counts add exactly (i64 or f64 finals), min/max
    keep their empty-group sentinels, sketch registers take their union
    (HLL: elementwise max; theta k-mins: elementwise min; KLL: lex-min
    survivor + exact count sum — ops/kll.py merge)."""
    theta_names = {p.spec.name for p in sketch_plans
                   if p.kind == "theta"}
    kll_names = {p.spec.name for p in sketch_plans if p.kind == "kll"}
    for name, v in new.items():
        r = routes.get(name)
        if r is None:                       # sketch registers
            if name in kll_names:
                acc[name] = KLL.merge(acc[name], v)
            else:
                acc[name] = np.minimum(acc[name], v) \
                    if name in theta_names else np.maximum(acc[name], v)
        elif r.kind == "min":
            acc[name] = np.minimum(acc[name], v)
        elif r.kind == "max":
            acc[name] = np.maximum(acc[name], v)
        else:
            acc[name] = acc[name] + v
    return acc


def _decode_anyvalue(ds: Datasource, field: str, v: np.ndarray,
                     empty: np.ndarray) -> np.ndarray:
    """Decode an FD-demoted grouping column from its max-aggregated device
    representation (dictionary code for dims, days for dates — exact i32
    lanes, never an f32 round-trip)."""
    kind = ds.column_kind(field)
    if kind == ColumnKind.DIM:
        codes = np.where(empty, 0, v).astype(np.int64)
        vals = ds.dims[field].dictionary[
            np.clip(codes, 0, max(ds.dims[field].cardinality - 1, 0))]
        if empty.any():
            vals = np.where(empty, None, vals)
        return vals
    if kind == ColumnKind.DATE:
        days = np.where(empty, 0, v).astype(np.int64)
        out = days.astype("datetime64[D]")
        if empty.any():
            out = np.where(empty, np.datetime64("NaT"), out)
        return out
    if kind == ColumnKind.LONG:
        if empty.any():
            return np.where(empty, np.nan, v).astype(np.float64)
        return np.rint(v).astype(np.int64)
    return np.where(empty, np.nan, v).astype(np.float64)


def _neg_key(k: np.ndarray):
    if np.issubdtype(k.dtype, np.number):
        return -k
    if np.issubdtype(k.dtype, np.datetime64):
        return -(k.astype(np.int64))
    # descending strings: invert via negated rank
    uniq, inv = np.unique(k, return_inverse=True)
    return -inv


def _pad_segments(s: int, n_dev: int) -> int:
    p = 1
    while p < s:
        p <<= 1
    p = max(p, n_dev)
    if p % n_dev:
        p = -(-p // n_dev) * n_dev
    return p


def _host_column_values(ds: Datasource, name: str,
                        idx: Optional[np.ndarray], *,
                        local_ok: bool = False):
    """Decoded host values of a column (optionally row-subset).

    On a multi-host partial store the columns are assembled by a
    cross-process gather (``Datasource.complete``) — the host fallback
    tier then serves any query shape, at O(table) transfer once.
    ``local_ok`` reads THIS host's rows only (local row indices) — the
    multi-host select/search paths that exchange results instead of
    columns."""
    if not local_ok:
        ds = ds.complete(columns=(name,))
    if name in ds.dims:
        col = ds.dims[name]
        codes = col.codes if idx is None else col.codes[idx]
        vals = col.dictionary[codes.astype(np.int64)]
        if col.validity is not None:
            v = col.validity if idx is None else col.validity[idx]
            vals = np.where(v, vals, None)
        return vals
    if name in ds.metrics:
        m = ds.metrics[name]
        vals = m.values if idx is None else m.values[idx]
        if m.kind == ColumnKind.DATE:
            return vals.astype("datetime64[D]")
        if m.kind == ColumnKind.LONG:
            out = vals.astype(np.int64)
            if m.validity is not None:
                v = m.validity if idx is None else m.validity[idx]
                out = np.where(v, out.astype(np.float64), np.nan)
            return out
        # keep f32 (storage dtype): python-float literals then compare
        # under NumPy weak promotion in f32, matching the device path's
        # comparison semantics at representation boundaries (e.g.
        # x >= 0.05 over a stored f32 0.05); np.nan fill preserves f32
        out = vals
        if m.validity is not None:
            v = m.validity if idx is None else m.validity[idx]
            out = np.where(v, out, np.float32(np.nan))
        return out
    if ds.time is not None and name == ds.time.name:
        ms = ds.time.millis if idx is None else ds.time.millis[idx]
        return ms.astype("datetime64[ms]")
    raise KeyError(name)


def _filter_columns_all(f: S.FilterSpec):
    return F.columns_of_filter(f)


def filter_to_expr(f: S.FilterSpec) -> E.Expr:
    """FilterSpec -> Expr (for host-side evaluation)."""
    if isinstance(f, S.SelectorFilter):
        if f.value is None:
            return E.IsNull(E.Column(f.dimension))
        return E.Comparison("=", E.Column(f.dimension), E.Literal(f.value))
    if isinstance(f, S.BoundFilter):
        parts = []
        c = E.Column(f.dimension)
        if f.lower is not None:
            parts.append(E.Comparison(">" if f.lower_strict else ">=", c,
                                      E.Literal(f.lower)))
        if f.upper is not None:
            parts.append(E.Comparison("<" if f.upper_strict else "<=", c,
                                      E.Literal(f.upper)))
        return E.And(tuple(parts)) if len(parts) != 1 else parts[0]
    if isinstance(f, S.InFilter):
        return E.InList(E.Column(f.dimension), tuple(f.values))
    if isinstance(f, S.PatternFilter):
        if f.kind == "like":
            return E.Like(E.Column(f.dimension), f.pattern)
        if f.kind == "contains":
            return E.Like(E.Column(f.dimension), f"%{f.pattern}%")
        raise EngineFallback("regex filter on host path")
    if isinstance(f, S.NullFilter):
        return E.IsNull(E.Column(f.dimension), negated=f.negated)
    if isinstance(f, S.LogicalFilter):
        subs = tuple(filter_to_expr(x) for x in f.fields)
        if f.op == "and":
            return E.And(subs) if subs else E.Literal(True)
        if f.op == "or":
            return E.Or(subs)
        return E.Not(subs[0])
    if isinstance(f, S.ExprFilter):
        return f.expr
    if isinstance(f, S.SpatialFilter):
        import math
        parts = []
        for ax, lo, hi in zip(f.axes, f.min_coords, f.max_coords):
            c = E.Column(ax)
            if lo is not None and math.isfinite(lo):
                parts.append(E.Comparison(">=", c, E.Literal(lo)))
            if hi is not None and math.isfinite(hi):
                parts.append(E.Comparison("<=", c, E.Literal(hi)))
        return E.And(tuple(parts)) if len(parts) != 1 else (
            parts[0] if parts else E.Literal(True))
    raise EngineFallback(f"filter {type(f).__name__}")
