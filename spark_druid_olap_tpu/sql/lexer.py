"""SQL lexer (hand-rolled; no third-party parser deps in the image).

≈ the lexical layer of ``AbstractSparkSQLParser.scala`` (the reference uses
Scala parser combinators with a ``SqlLexical``)."""

from __future__ import annotations

import dataclasses
from typing import List


class SqlSyntaxError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str       # 'ident' | 'number' | 'string' | 'op' | 'kw' | 'eof'
    value: str
    pos: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "join", "inner", "left",
    "right", "outer", "cross", "on", "distinct", "exists", "asc", "desc",
    "interval", "date", "timestamp", "extract", "union", "all", "grouping",
    "sets", "cube", "rollup", "true", "false", "explain", "rewrite", "clear",
    "metadata", "execute", "query", "using", "datasource", "druiddatasource",
    "substring", "for", "approx", "with", "offset", "create", "drop",
    "refresh",
}

_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", "||"}
_ONE_CHAR_OPS = set("+-*/%(),.<>=")


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    n = len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise SqlSyntaxError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise SqlSyntaxError(f"unterminated string at {i}")
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":
            close = c
            j = sql.find(close, i + 1)
            if j < 0:
                raise SqlSyntaxError(f"unterminated quoted identifier at {i}")
            out.append(Token("ident", sql[i + 1: j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_e = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_e:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_e and j > i:
                    seen_e = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            out.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            kind = "kw" if word.lower() in KEYWORDS else "ident"
            out.append(Token(kind, word.lower() if kind == "kw" else word, i))
            i = j
            continue
        if sql[i:i + 2] in _TWO_CHAR_OPS:
            out.append(Token("op", sql[i:i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            out.append(Token("op", c, i))
            i += 1
            continue
        if c == ";":
            i += 1
            continue
        raise SqlSyntaxError(f"unexpected character {c!r} at {i}")
    out.append(Token("eof", "", n))
    return out
