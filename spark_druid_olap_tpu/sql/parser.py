"""Recursive-descent SQL parser for the analytic subset the engine rewrites.

≈ the reference's parser layer: Spark's SQL parser for queries plus
``SparklineDataParser.scala:105-124`` for the extension commands (``CLEAR
METADATA``, ``EXPLAIN REWRITE <sql>``, ``ON DATASOURCE ds EXECUTE QUERY
<json>``). Covers the TPC-H dialect: joins (ANSI + comma), scalar/IN/EXISTS
subqueries, derived tables, CASE, CAST, EXTRACT, SUBSTRING, BETWEEN, LIKE,
IN, date/timestamp/interval literals and arithmetic, grouping sets / cube /
rollup, count(distinct), approx_count_distinct.

Qualified column names are stored unqualified (``l.l_quantity`` ->
``l_quantity``): the engine requires globally-unique column names across a
star schema, exactly like the reference (``StarSchemaInfo.scala:127-165``).
Table aliases are tracked on the relations themselves.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A
from spark_druid_olap_tpu.sql.lexer import SqlSyntaxError, Token, tokenize

AGG_FUNCS = {"sum", "min", "max", "avg", "count"}


def _substitute_ctes(node, ctes):
    """Replace TableRef(name) with SubqueryRef(cte_query) everywhere a CTE
    name is referenced — relations, derived tables, and subqueries in
    expressions (≈ Spark's CTESubstitution)."""
    if not ctes:
        return node
    import dataclasses

    def sub_rel(rel):
        if rel is None:
            return None
        if isinstance(rel, A.TableRef):
            q = ctes.get(rel.name)
            if q is not None:
                return A.SubqueryRef(q, rel.alias or rel.name)
            return rel
        if isinstance(rel, A.SubqueryRef):
            return dataclasses.replace(rel, query=sub_stmt(rel.query))
        if isinstance(rel, A.Join):
            return dataclasses.replace(rel, left=sub_rel(rel.left),
                                       right=sub_rel(rel.right),
                                       condition=sub_expr(rel.condition))
        return rel

    def sub_expr(e):
        if e is None or isinstance(e, str):
            return e

        def rep(n):
            if isinstance(n, (A.ScalarSubquery, A.Exists, A.InSubquery)):
                return dataclasses.replace(n, query=sub_stmt(n.query))
            return n

        return E.transform(e, rep)

    def sub_stmt(st):
        if isinstance(st, A.UnionAll):
            return dataclasses.replace(
                st, parts=tuple(sub_stmt(p) for p in st.parts))
        items = tuple(it if it.expr == "*"
                      else dataclasses.replace(it, expr=sub_expr(it.expr))
                      for it in st.items)
        gb = st.group_by
        if isinstance(gb, tuple):
            gb = tuple(sub_expr(g) for g in gb)
        ob = tuple(dataclasses.replace(o, expr=sub_expr(o.expr))
                   for o in st.order_by)
        return dataclasses.replace(
            st, items=items, relation=sub_rel(st.relation),
            where=sub_expr(st.where), having=sub_expr(st.having),
            group_by=gb, order_by=ob)

    return sub_stmt(node)


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks: List[Token] = tokenize(sql)
        self.i = 0

    # -- token helpers --------------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.eat_kw(kw):
            t = self.peek()
            raise SqlSyntaxError(
                f"expected {kw.upper()} at {t.pos}, got {t.value!r}")

    def expect_op(self, op: str):
        if not self.at_op(op):
            t = self.peek()
            raise SqlSyntaxError(
                f"expected {op!r} at {t.pos}, got {t.value!r}")
        self.next()

    # -- statements -----------------------------------------------------------
    def parse_statement(self) -> A.Statement:
        if self.at_kw("explain"):
            self.next()
            self.eat_kw("rewrite")
            rest_pos = self.peek().pos
            q = self.parse_with() if self.at_kw("with") \
                else self.parse_select_or_union()
            self._expect_eof()
            return A.ExplainRewrite(q, self.sql[rest_pos:])
        if self.at_kw("clear"):
            self.next()
            self.expect_kw("metadata")
            ds = None
            purge = False
            if self.peek().kind == "ident":
                w = self.next().value
                # trailing soft word PURGE also deletes on-disk snapshots;
                # a datasource literally named "purge" must be cleared via
                # CLEAR METADATA purge PURGE
                if w.lower() == "purge" and self.peek().kind == "eof":
                    purge = True
                else:
                    ds = w
                    if self._at_word("purge"):
                        self.next()
                        purge = True
            self._expect_eof()
            return A.ClearMetadata(ds, purge=purge)
        if self._at_word("checkpoint") or self._at_word("restore"):
            # soft-word-led persist commands (persist/): CHECKPOINT and
            # RESTORE stay valid identifiers everywhere else
            word = self.next().value.lower()
            ds = None
            if self.peek().kind != "eof":
                ds = self._ident()
            self._expect_eof()
            return A.Checkpoint(ds) if word == "checkpoint" \
                else A.Restore(ds)
        if self.at_kw("create"):
            self.next()
            self.expect_kw("rollup")
            name = self._ident()
            self.expect_kw("on")
            base = self._ident()
            self._expect_word("dimensions")
            dims = self._parse_paren_ident_list()
            self._expect_word("aggregations")
            aggs = self._parse_paren_expr_list()
            gran = None
            if self._at_word("granularity"):
                self.next()
                gran = self._ident().lower()
            self._expect_eof()
            return A.CreateRollup(name, base, dims, aggs, gran)
        if self.at_kw("drop"):
            self.next()
            self.expect_kw("rollup")
            name = self._ident()
            self._expect_eof()
            return A.DropRollup(name)
        if self.at_kw("refresh"):
            self.next()
            self.expect_kw("rollup")
            name = self._ident()
            self._expect_eof()
            return A.RefreshRollup(name)
        t = self.peek()
        if t.kind == "kw" and t.value == "with":
            q = self.parse_with()
            self._expect_eof()
            return q
        if (t.kind == "kw" and t.value == "select") or self.at_op("("):
            q = self.parse_select_or_union()
            self._expect_eof()
            return q
        raise SqlSyntaxError(f"cannot parse statement at {t.pos}: {t.value!r}")

    # -- rollup DDL helpers (DIMENSIONS/AGGREGATIONS/GRANULARITY are soft
    # words, not reserved keywords) -------------------------------------------
    def _at_word(self, word: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.value.lower() == word

    def _expect_word(self, word: str):
        if not self._at_word(word):
            t = self.peek()
            raise SqlSyntaxError(
                f"expected {word.upper()} at {t.pos}, got {t.value!r}")
        self.next()

    def _parse_paren_ident_list(self):
        self.expect_op("(")
        out = []
        if not self.at_op(")"):
            out.append(self._ident())
            while self.at_op(","):
                self.next()
                out.append(self._ident())
        self.expect_op(")")
        return tuple(out)

    def _parse_paren_expr_list(self):
        self.expect_op("(")
        out = []
        if not self.at_op(")"):
            out.append(self.parse_expr())
            while self.at_op(","):
                self.next()
                out.append(self.parse_expr())
        self.expect_op(")")
        return tuple(out)

    def parse_with(self):
        """WITH name AS (select), ... <select|union> — CTEs desugar to
        derived tables wherever their name is referenced (the existing
        view-merge / composite machinery then plans them; ≈ Spark's
        CTESubstitution rule)."""
        self.expect_kw("with")
        ctes: dict = {}
        while True:
            name = self._ident()
            self.expect_kw("as")
            self.expect_op("(")
            q = self.parse_select_or_union()
            self.expect_op(")")
            if name in ctes:
                raise SqlSyntaxError(f"duplicate CTE name {name!r}")
            # earlier CTEs are visible inside later ones
            ctes[name] = _substitute_ctes(q, ctes)
            if not self.at_op(","):
                break
            self.next()
        return _substitute_ctes(self.parse_select_or_union(), ctes)

    def parse_select_or_union(self):
        first_paren = self.at_op("(")
        q = self.parse_select()
        if not self.at_kw("union"):
            return q
        parts = [q]
        parens = [first_paren]
        last_paren = False
        while self.eat_kw("union"):
            if not self.eat_kw("all"):
                raise SqlSyntaxError(
                    "only UNION ALL is supported (use SELECT DISTINCT "
                    "over a derived union for UNION)")
            last_paren = self.at_op("(")
            parens.append(last_paren)
            parts.append(self.parse_select())
        for p, was_paren in zip(parts[:-1], parens[:-1]):
            # standard SQL binds trailing clauses to the whole union; a
            # bare non-final branch that consumed its own is ambiguous
            if not was_paren and (p.order_by or p.limit is not None
                                  or p.offset):
                raise SqlSyntaxError(
                    "ORDER BY/LIMIT/OFFSET on a non-final UNION ALL "
                    "branch: parenthesize the branch to scope them to it")
        if last_paren:
            # '(select ... limit n)' keeps its own clauses; the union's
            # trailing ORDER BY / LIMIT / OFFSET follow the parens
            ob, lim, off = self._parse_trailing_clauses()
        else:
            # a bare last SELECT consumed the trailing clauses, which
            # standard SQL binds to the WHOLE union — hoist them
            import dataclasses
            last = parts[-1]
            ob, lim, off = last.order_by, last.limit, last.offset
            parts[-1] = dataclasses.replace(last, order_by=(), limit=None,
                                            offset=0)
        return A.UnionAll(tuple(parts), ob, lim, off)

    def _parse_trailing_clauses(self):
        order_by: List[A.OrderItem] = []
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.at_op(","):
                self.next()
                order_by.append(self.parse_order_item())
        limit = None
        if self.eat_kw("limit"):
            t = self.next()
            if t.kind != "number":
                raise SqlSyntaxError(f"LIMIT expects a number at {t.pos}")
            limit = int(t.value)
        offset = 0
        if self.eat_kw("offset"):
            t = self.next()
            if t.kind != "number":
                raise SqlSyntaxError(f"OFFSET expects a number at {t.pos}")
            offset = int(t.value)
        return tuple(order_by), limit, offset

    def _expect_eof(self):
        t = self.peek()
        if t.kind != "eof":
            raise SqlSyntaxError(
                f"unexpected trailing input at {t.pos}: {t.value!r}")

    # -- select ---------------------------------------------------------------
    def parse_select(self) -> A.SelectStmt:
        if self.at_op("("):
            self.next()
            q = self.parse_select()
            self.expect_op(")")
            return q
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        self.eat_kw("all")
        items = [self.parse_select_item()]
        while self.at_op(","):
            self.next()
            items.append(self.parse_select_item())
        relation = None
        if self.eat_kw("from"):
            relation = self.parse_relation()
        where = None
        if self.eat_kw("where"):
            where = self.parse_expr()
        group_by = None
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            group_by = self.parse_group_by()
        having = None
        if self.eat_kw("having"):
            having = self.parse_expr()
        order_by, limit, offset = self._parse_trailing_clauses()
        return A.SelectStmt(tuple(items), relation, where, group_by, having,
                            order_by, limit, distinct, offset)

    def parse_select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.next()
            return A.SelectItem("*")
        e = self.parse_expr()
        alias = None
        if self.eat_kw("as"):
            alias = self._ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return A.SelectItem(e, alias)

    def parse_order_item(self) -> A.OrderItem:
        e = self.parse_expr()
        asc = True
        if self.eat_kw("desc"):
            asc = False
        else:
            self.eat_kw("asc")
        return A.OrderItem(e, asc)

    def parse_group_by(self):
        if self.at_kw("grouping"):
            self.next()
            self.expect_kw("sets")
            self.expect_op("(")
            sets = []
            while True:
                self.expect_op("(")
                exprs = []
                if not self.at_op(")"):
                    exprs.append(self.parse_expr())
                    while self.at_op(","):
                        self.next()
                        exprs.append(self.parse_expr())
                self.expect_op(")")
                sets.append(tuple(exprs))
                if self.at_op(","):
                    self.next()
                    continue
                break
            self.expect_op(")")
            return A.GroupingSets(tuple(sets))
        if self.at_kw("cube", "rollup"):
            kind = self.next().value
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.at_op(","):
                self.next()
                exprs.append(self.parse_expr())
            self.expect_op(")")
            if kind == "cube":
                sets = []
                for mask in range(1 << len(exprs)):
                    sets.append(tuple(e for j, e in enumerate(exprs)
                                      if mask & (1 << j)))
            else:  # rollup
                sets = [tuple(exprs[:k]) for k in range(len(exprs), -1, -1)]
            return A.GroupingSets(tuple(sets))
        exprs = [self.parse_expr()]
        while self.at_op(","):
            self.next()
            exprs.append(self.parse_expr())
        return tuple(exprs)

    # -- relations ------------------------------------------------------------
    def parse_relation(self) -> A.Relation:
        rel = self.parse_relation_primary()
        while True:
            if self.at_op(","):
                self.next()
                right = self.parse_relation_primary()
                rel = A.Join(rel, right, "cross", None)
                continue
            kind = None
            if self.at_kw("join"):
                kind = "inner"
                self.next()
            elif self.at_kw("inner"):
                self.next()
                self.expect_kw("join")
                kind = "inner"
            elif self.at_kw("left"):
                self.next()
                self.eat_kw("outer")
                self.expect_kw("join")
                kind = "left"
            elif self.at_kw("cross"):
                self.next()
                self.expect_kw("join")
                kind = "cross"
            if kind is None:
                return rel
            right = self.parse_relation_primary()
            cond = None
            if self.eat_kw("on"):
                cond = self.parse_expr()
            rel = A.Join(rel, right, kind, cond)

    def parse_relation_primary(self) -> A.Relation:
        if self.at_op("("):
            self.next()
            if self.at_kw("select", "with"):
                q = self.parse_with() if self.at_kw("with") \
                    else self.parse_select_or_union()
                self.expect_op(")")
                alias = self._alias_required()
                return A.SubqueryRef(q, alias)
            rel = self.parse_relation()
            self.expect_op(")")
            return rel
        name = self._ident()
        # schema-qualified datasource: 'db.table' (reference works across
        # non-default Hive databases, MultiDBTest.scala; here databases
        # are dotted namespaces in one store)
        while self.at_op("."):
            self.next()
            name = f"{name}.{self._ident()}"
        alias = None
        if self.eat_kw("as"):
            alias = self._ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return A.TableRef(name, alias)

    def _alias_required(self) -> str:
        self.eat_kw("as")
        t = self.peek()
        if t.kind != "ident":
            raise SqlSyntaxError(f"derived table needs an alias at {t.pos}")
        return self.next().value

    def _ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.next().value
        # permit non-reserved keywords as identifiers
        if t.kind == "kw" and t.value in ("date", "timestamp", "query",
                                          "metadata", "datasource"):
            return self.next().value
        raise SqlSyntaxError(f"expected identifier at {t.pos}, got {t.value!r}")

    # -- expressions (precedence climbing) ------------------------------------
    def parse_expr(self) -> E.Expr:
        return self.parse_or()

    def parse_or(self) -> E.Expr:
        left = self.parse_and()
        parts = [left]
        while self.eat_kw("or"):
            parts.append(self.parse_and())
        return parts[0] if len(parts) == 1 else E.Or(tuple(parts))

    def parse_and(self) -> E.Expr:
        left = self.parse_not()
        parts = [left]
        while self.at_kw("and"):
            self.next()
            parts.append(self.parse_not())
        return parts[0] if len(parts) == 1 else E.And(tuple(parts))

    def parse_not(self) -> E.Expr:
        if self.eat_kw("not"):
            return E.Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> E.Expr:
        left = self.parse_additive()
        while True:
            if self.at_op("=", "!=", "<>", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "<>":
                    op = "!="
                right = self.parse_additive()
                left = E.Comparison(op, left, right)
                continue
            if self.at_kw("is"):
                self.next()
                neg = self.eat_kw("not")
                self.expect_kw("null")
                left = E.IsNull(left, negated=neg)
                continue
            neg = False
            save = self.i
            if self.at_kw("not"):
                self.next()
                neg = True
            if self.at_kw("between"):
                self.next()
                lo = self.parse_additive()
                self.expect_kw("and")
                hi = self.parse_additive()
                left = E.Between(left, lo, hi, negated=neg)
                continue
            if self.at_kw("in"):
                self.next()
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self.parse_select()
                    self.expect_op(")")
                    left = A.InSubquery(left, q, negated=neg)
                else:
                    vals = [self._literal_value()]
                    while self.at_op(","):
                        self.next()
                        vals.append(self._literal_value())
                    self.expect_op(")")
                    left = E.InList(left, tuple(vals), negated=neg)
                continue
            if self.at_kw("like"):
                self.next()
                t = self.next()
                if t.kind != "string":
                    raise SqlSyntaxError(f"LIKE expects string at {t.pos}")
                left = E.Like(left, t.value, negated=neg)
                continue
            if neg:
                self.i = save
            break
        return left

    def _literal_value(self):
        e = self.parse_additive()
        if isinstance(e, E.Literal):
            return e.value
        raise SqlSyntaxError("IN list expects literal values")

    def parse_additive(self) -> E.Expr:
        left = self.parse_multiplicative()
        while self.at_op("+", "-", "||"):
            op = self.next().value
            right = self.parse_multiplicative()
            if op == "||":
                left = E.Func("concat", (left, right))
            else:
                left = self._fold_interval(op, left, right)
        return left

    def _fold_interval(self, op: str, left: E.Expr, right: E.Expr) -> E.Expr:
        """date +/- INTERVAL folding (TPC-H style constant arithmetic)."""
        if isinstance(right, E.Func) and right.name == "__interval__":
            n = right.args[0].value
            unit = right.args[1].value
            if op == "-":
                n = -n
            if unit == "day":
                return E.Func("date_add", (left, E.Literal(n)))
            return E.Func("add_months",
                          (left, E.Literal(n * (12 if unit == "year" else 1))))
        return E.BinaryOp(op, left, right)

    def parse_multiplicative(self) -> E.Expr:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            right = self.parse_unary()
            left = E.BinaryOp(op, left, right)
        return left

    def parse_unary(self) -> E.Expr:
        if self.at_op("-"):
            self.next()
            child = self.parse_unary()
            if isinstance(child, E.Literal) and isinstance(
                    child.value, (int, float)):
                return E.Literal(-child.value)
            return E.BinaryOp("-", E.Literal(0), child)
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> E.Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            v = float(t.value) if any(c in t.value for c in ".eE") \
                else int(t.value)
            return E.Literal(v)
        if t.kind == "string":
            self.next()
            return E.Literal(t.value)
        if self.at_kw("true"):
            self.next()
            return E.Literal(True)
        if self.at_kw("false"):
            self.next()
            return E.Literal(False)
        if self.at_kw("null"):
            self.next()
            return E.Literal(None)
        if self.at_kw("date", "timestamp"):
            kind = self.next().value
            nt = self.peek()
            if nt.kind == "string":
                self.next()
                import datetime as _dt
                s = nt.value
                if kind == "date":
                    y, m, d = (int(x) for x in s[:10].split("-"))
                    return E.Literal(_dt.date(y, m, d))
                return E.Literal(
                    _dt.datetime.fromisoformat(s.replace("Z", "+00:00")))
            # bare keyword used as identifier (e.g. a column named date)
            return E.Column(kind)
        if self.at_kw("interval"):
            self.next()
            t2 = self.next()
            if t2.kind == "string":
                n = int(t2.value)
            elif t2.kind == "number":
                n = int(t2.value)
            else:
                raise SqlSyntaxError(f"INTERVAL expects quantity at {t2.pos}")
            unit_t = self.next()
            unit = unit_t.value.lower().rstrip("s")
            if unit not in ("day", "month", "year"):
                raise SqlSyntaxError(f"unsupported interval unit {unit!r}")
            return E.Func("__interval__", (E.Literal(n), E.Literal(unit)))
        if self.at_kw("case"):
            return self.parse_case()
        if self.at_kw("cast"):
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            ty = self._type_name()
            self.expect_op(")")
            return E.Cast(e, ty)
        if self.at_kw("extract"):
            self.next()
            self.expect_op("(")
            field_t = self.next()
            field = field_t.value.lower()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_op(")")
            return E.Func(field, (e,))
        if self.at_kw("substring"):
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            if self.eat_kw("from"):
                start = self.parse_expr()
                ln = None
                if self.eat_kw("for"):
                    ln = self.parse_expr()
            else:
                self.expect_op(",")
                start = self.parse_expr()
                ln = None
                if self.at_op(","):
                    self.next()
                    ln = self.parse_expr()
            self.expect_op(")")
            args = (e, start) if ln is None else (e, start, ln)
            return E.Func("substr", args)
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.parse_select()
            self.expect_op(")")
            return A.Exists(q)
        if self.at_op("("):
            self.next()
            if self.at_kw("select"):
                q = self.parse_select()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "ident" or (t.kind == "kw" and t.value in
                                 ("query", "metadata", "datasource")):
            name = self.next().value
            # qualified name: the engine binds by GLOBALLY-UNIQUE bare
            # column names (≈ StarSchemaInfo.scala:127-165), but the
            # qualifier is retained as metadata so the alias-scoping
            # pass can resolve correlated self-references
            qual = None
            while self.at_op("."):
                self.next()
                nxt = self.peek()
                if nxt.kind in ("ident", "kw"):
                    qual = name
                    name = self.next().value
                elif nxt.kind == "op" and nxt.value == "*":
                    self.next()
                    return E.Column("*")
                else:
                    raise SqlSyntaxError(f"bad qualified name at {nxt.pos}")
            if self.at_op("("):
                call = self.parse_function_call(name)
                if self._at_word("over"):
                    return self.parse_over(call, name)
                return call
            return E.Column(name, qual=qual)
        raise SqlSyntaxError(
            f"unexpected token {t.value!r} at {t.pos}")

    def _type_name(self) -> str:
        t = self.next()
        name = t.value.lower()
        # decimal(p, s) etc.
        if self.at_op("("):
            self.next()
            while not self.at_op(")"):
                self.next()
            self.next()
        return name

    def parse_case(self) -> E.Expr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        branches = []
        while self.eat_kw("when"):
            cond = self.parse_expr()
            if operand is not None:
                cond = E.Comparison("=", operand, cond)
            self.expect_kw("then")
            val = self.parse_expr()
            branches.append((cond, val))
        otherwise = None
        if self.eat_kw("else"):
            otherwise = self.parse_expr()
        self.expect_kw("end")
        return E.Case(tuple(branches), otherwise)

    def parse_function_call(self, name: str) -> E.Expr:
        self.expect_op("(")
        lname = name.lower()
        distinct = False
        if self.eat_kw("distinct"):
            distinct = True
        if self.at_op("*"):
            self.next()
            self.expect_op(")")
            if lname == "count":
                return E.AggCall("count", None)
            raise SqlSyntaxError(f"{name}(*) unsupported")
        args: List[E.Expr] = []
        if not self.at_op(")"):
            args.append(self.parse_expr())
            while self.at_op(","):
                self.next()
                args.append(self.parse_expr())
        self.expect_op(")")
        if lname in AGG_FUNCS:
            if lname == "count" and distinct:
                return E.AggCall("count", args[0], distinct=True)
            return E.AggCall(lname, args[0], distinct=distinct)
        if lname in ("approx_count_distinct", "approx_distinct"):
            return E.AggCall("count", args[0], distinct=True, approx=True)
        if lname in ("approx_count_distinct_theta", "theta_sketch"):
            return E.AggCall("theta", args[0])
        if lname in ("percentile_approx", "approx_percentile",
                     "approx_quantile"):
            if len(args) != 2 or not isinstance(args[1], E.Literal) \
                    or isinstance(args[1].value, bool) \
                    or not isinstance(args[1].value, (int, float)):
                raise SqlSyntaxError(
                    f"{name}(value, fraction) expects a literal fraction")
            frac = float(args[1].value)
            if not 0.0 <= frac <= 1.0:
                raise SqlSyntaxError(
                    "percentile fraction must be in [0, 1]")
            return E.AggCall("percentile", args[0], fraction=frac)
        return E.Func(lname, tuple(args))

    # -- window functions (OVER / PARTITION / ROWS etc. are soft words;
    # ORDER, BY, BETWEEN, AND are real keywords) ------------------------------
    _WINDOW_FUNCS = {"rank", "dense_rank", "row_number", "lag", "lead",
                     "sum", "min", "max", "avg", "count"}

    def parse_over(self, call: E.Expr, name: str) -> E.Expr:
        self._expect_word("over")
        self.expect_op("(")
        partition: List[E.Expr] = []
        if self._at_word("partition"):
            self.next()
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.at_op(","):
                self.next()
                partition.append(self.parse_expr())
        order: List = []
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            o = self.parse_order_item()
            order.append((o.expr, o.ascending))
            while self.at_op(","):
                self.next()
                o = self.parse_order_item()
                order.append((o.expr, o.ascending))
        frame = None
        if self._at_word("rows"):
            self.next()
            if self.eat_kw("between"):
                lo = self._parse_frame_bound()
                self.expect_kw("and")
                hi = self._parse_frame_bound()
            else:
                lo = self._parse_frame_bound()
                hi = (0, 0)
            frame = (self._frame_side(lo, start=True),
                     self._frame_side(hi, start=False))
        self.expect_op(")")
        if isinstance(call, E.AggCall):
            if call.distinct or call.approx or call.fraction is not None:
                raise SqlSyntaxError(
                    f"{call.fn} OVER does not support this aggregate form")
            fn = call.fn
            args = () if call.arg is None else (call.arg,)
        elif isinstance(call, E.Func) and call.name in self._WINDOW_FUNCS:
            fn = call.name
            args = call.args
        else:
            raise SqlSyntaxError(f"{name} is not a window function")
        if fn in ("rank", "dense_rank") and not order:
            raise SqlSyntaxError(f"{fn}() OVER requires ORDER BY")
        if fn in ("lag", "lead"):
            if not 1 <= len(args) <= 3:
                raise SqlSyntaxError(f"{fn} expects 1 to 3 arguments")
            if not order:
                raise SqlSyntaxError(f"{fn}() OVER requires ORDER BY")
        return E.WindowCall(fn, tuple(args), tuple(partition), tuple(order),
                            frame)

    def _parse_frame_bound(self):
        if self._at_word("unbounded"):
            self.next()
            if self._at_word("preceding"):
                self.next()
                return ("unbounded", -1)
            self._expect_word("following")
            return ("unbounded", 1)
        if self._at_word("current"):
            self.next()
            self._expect_word("row")
            return (0, 0)
        t = self.next()
        if t.kind != "number":
            raise SqlSyntaxError(f"expected a ROWS frame bound at {t.pos}")
        n = int(t.value)
        if self._at_word("preceding"):
            self.next()
            return (n, -1)
        self._expect_word("following")
        return (n, 1)

    @staticmethod
    def _frame_side(bound, start: bool):
        kind, sign = bound
        if kind == "unbounded":
            if (start and sign > 0) or (not start and sign < 0):
                raise SqlSyntaxError("unsupported ROWS frame direction")
            return None
        if sign == 0:
            return 0
        if (start and sign > 0) or (not start and sign < 0):
            raise SqlSyntaxError("unsupported ROWS frame direction")
        return kind


def parse_statement(sql: str) -> A.Statement:
    p = Parser(sql)
    # handle ON DATASOURCE command before general statement parsing
    t0 = p.peek()
    if (t0.kind == "kw" and t0.value == "on") or \
            (t0.kind == "ident" and t0.value.lower() == "on"):
        p.next()
        if not p.eat_kw("datasource"):
            p.eat_kw("druiddatasource")
        ds = p._ident()
        sharded = False
        if p.eat_kw("using"):
            mode = p.next().value.lower()
            sharded = mode in ("sharded", "historical")
        p.expect_kw("execute")
        p.eat_kw("query")
        qt = p.next()
        if qt.kind != "string":
            raise SqlSyntaxError("EXECUTE QUERY expects a quoted JSON string")
        p._expect_eof()
        return A.ExecuteRawQuery(ds, qt.value, sharded)
    return p.parse_statement()


def parse_select(sql: str) -> A.SelectStmt:
    stmt = parse_statement(sql)
    if not isinstance(stmt, A.SelectStmt):
        raise SqlSyntaxError("expected a SELECT statement")
    return stmt
