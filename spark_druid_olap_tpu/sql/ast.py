"""SQL AST.

≈ the parsed-plan surface the reference gets from Spark's SQL parser plus its
own front parser (``SparklineDataParser.scala``). Expressions reuse
``ir.expr`` nodes directly (one expression currency end-to-end); this module
adds the relational shell: select statements, table refs, joins, subqueries,
grouping sets, and the command statements the reference's parser adds
(``CLEAR METADATA``, ``EXPLAIN REWRITE``, ``ON DATASOURCE ... EXECUTE
QUERY``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

from spark_druid_olap_tpu.ir import expr as E


# -- relations ----------------------------------------------------------------

class Relation:
    pass


@dataclasses.dataclass(frozen=True)
class TableRef(Relation):
    name: str
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SubqueryRef(Relation):
    query: "SelectStmt"
    alias: str


@dataclasses.dataclass(frozen=True)
class Join(Relation):
    left: Relation
    right: Relation
    kind: str                      # 'inner' | 'left' | 'cross'
    condition: Optional[E.Expr]    # None for cross/comma joins


# -- subquery-bearing expressions ---------------------------------------------

@dataclasses.dataclass(frozen=True)
class ScalarSubquery(E.Expr):
    query: "SelectStmt"


@dataclasses.dataclass(frozen=True)
class InSubquery(E.Expr):
    child: E.Expr
    query: "SelectStmt"
    negated: bool = False

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class Exists(E.Expr):
    query: "SelectStmt"
    negated: bool = False


# -- select -------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SelectItem:
    expr: Union[E.Expr, str]       # '*' for star
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class OrderItem:
    expr: E.Expr
    ascending: bool = True


@dataclasses.dataclass(frozen=True)
class GroupingSets:
    """GROUP BY GROUPING SETS / CUBE / ROLLUP (reference rewrites these via
    Spark's Expand; see AggregateTransform grouping-set handling)."""
    sets: Tuple[Tuple[E.Expr, ...], ...]


@dataclasses.dataclass(frozen=True)
class SelectStmt:
    items: Tuple[SelectItem, ...]
    relation: Optional[Relation]
    where: Optional[E.Expr] = None
    group_by: Optional[Union[Tuple[E.Expr, ...], GroupingSets]] = None
    having: Optional[E.Expr] = None
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    distinct: bool = False
    offset: int = 0


@dataclasses.dataclass(frozen=True)
class UnionAll:
    """``<select> UNION ALL <select> [...] [ORDER BY ..] [LIMIT n]
    [OFFSET m]`` — each branch plans independently (engine pushdown per
    branch, like Spark planning each child of a Union), rows concatenate
    positionally under the FIRST branch's column names, then the trailing
    ordering applies."""
    parts: Tuple[SelectStmt, ...]
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


# -- commands (≈ SparklineDataParser commands) --------------------------------

@dataclasses.dataclass(frozen=True)
class ExplainRewrite:
    query: SelectStmt
    sql: str


@dataclasses.dataclass(frozen=True)
class ClearMetadata:
    datasource: Optional[str] = None
    # PURGE: also delete the on-disk snapshots/WAL (deep storage) — a
    # plain clear drops only the in-memory store, and recovery would
    # resurrect persisted datasources on the next start
    purge: bool = False


@dataclasses.dataclass(frozen=True)
class Checkpoint:
    """``CHECKPOINT [<datasource>]`` — publish snapshot(s) to deep
    storage (persist/); no datasource = every complete one."""
    datasource: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Restore:
    """``RESTORE [<datasource>]`` — rewind in-memory state to the last
    published snapshot + committed WAL tail."""
    datasource: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ExecuteRawQuery:
    datasource: str
    query_json: str
    use_sharded: bool = False


# -- materialized rollup DDL (mv/) --------------------------------------------

@dataclasses.dataclass(frozen=True)
class CreateRollup:
    """``CREATE ROLLUP <name> ON <datasource> DIMENSIONS (..) AGGREGATIONS
    (..) [GRANULARITY <g>]`` — aggregations are parsed aggregate-call
    expressions (merge-closed kinds only; validated at build time)."""
    name: str
    base: str
    dimensions: Tuple[str, ...]
    aggregations: Tuple[E.Expr, ...]
    granularity: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class DropRollup:
    name: str


@dataclasses.dataclass(frozen=True)
class RefreshRollup:
    name: str


Statement = Union[SelectStmt, UnionAll, ExplainRewrite, ClearMetadata,
                  ExecuteRawQuery, CreateRollup, DropRollup, RefreshRollup,
                  Checkpoint, Restore]
