"""SQL session: parse -> plan -> execute.

≈ the reference's end-to-end statement path: ``SPLParser`` front commands +
Catalyst planning with ``DruidStrategy`` + falling back to plain Spark when no
rewrite applies. Here: pushdown builder first; :class:`PlanUnsupported` or a
runtime :class:`EngineFallback` routes to the pandas host executor.
"""

from __future__ import annotations

import functools as _functools
import time as _time
from typing import List, Optional

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.parallel.executor import EngineFallback
from spark_druid_olap_tpu.planner import builder as B
from spark_druid_olap_tpu.planner import host_exec
from spark_druid_olap_tpu.planner.plans import PlannedQuery, PlanUnsupported
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.sql import ast as A
from spark_druid_olap_tpu.sql.parser import parse_statement
from spark_druid_olap_tpu.utils import phases as PH

# per-thread count of subquery-channel cache hits (planner/decorrelate
# _cached_inner): statements diff it to annotate ``served_from`` when a
# warm rep legitimately reports zero device dispatches
_subq_tls = __import__("threading").local()


def _note_subquery_hit() -> None:
    """Called by the decorrelation passes when an inlined subquery is
    served from the gated subquery result cache."""
    _subq_tls.hits = getattr(_subq_tls, "hits", 0) + 1


def resolve_lookups(ctx, stmt: A.SelectStmt) -> A.SelectStmt:
    """Inline registered lookup tables: ``LOOKUP(col, 'name')`` becomes
    ``__lookup_pairs(col, <pairs literal>)`` so both the pushdown builder
    (-> LookupExtraction) and the host evaluator see a self-contained
    expression (≈ Druid resolving a registered lookup by name)."""
    if not getattr(ctx, "lookups", None) or not isinstance(stmt,
                                                           A.SelectStmt):
        return stmt
    import dataclasses

    def fix_expr(e):
        if e is None or e == "*":
            return e

        def rep(n):
            if isinstance(n, E.Func) and n.name.lower() == "lookup" \
                    and len(n.args) == 2 \
                    and isinstance(n.args[1], E.Literal) \
                    and isinstance(n.args[1].value, str):
                lname = n.args[1].value
                table = ctx.lookups.get(lname)
                if table is None:
                    raise KeyError(f"unknown lookup {lname!r}; registered: "
                                   f"{sorted(ctx.lookups)}")
                pairs = tuple(sorted(table.items()))
                return E.Func("__lookup_pairs", (n.args[0],
                                                 E.Literal(pairs)))
            if isinstance(n, (A.ScalarSubquery, A.Exists, A.InSubquery)):
                return dataclasses.replace(n,
                                           query=resolve_lookups(ctx,
                                                                 n.query))
            return n
        return E.transform(e, rep)

    def fix_rel(rel):
        if isinstance(rel, A.Join):
            return dataclasses.replace(
                rel, left=fix_rel(rel.left), right=fix_rel(rel.right),
                condition=fix_expr(rel.condition))
        if isinstance(rel, A.SubqueryRef):
            return dataclasses.replace(rel,
                                       query=resolve_lookups(ctx, rel.query))
        return rel

    gb = stmt.group_by
    if isinstance(gb, A.GroupingSets):
        gb = A.GroupingSets(tuple(tuple(fix_expr(g) for g in s)
                                  for s in gb.sets))
    elif gb is not None:
        gb = tuple(fix_expr(g) for g in gb)
    return dataclasses.replace(
        stmt,
        items=tuple(dataclasses.replace(it, expr=fix_expr(it.expr))
                    for it in stmt.items),
        relation=None if stmt.relation is None else fix_rel(stmt.relation),
        where=fix_expr(stmt.where), group_by=gb,
        having=fix_expr(stmt.having),
        order_by=tuple(dataclasses.replace(o, expr=fix_expr(o.expr))
                       for o in stmt.order_by))


class _NegativePlan:
    """Negative plan-cache entry: the builder deterministically rejects the
    statement under the current (store, config). A dedicated type — the
    old structural sentinel (a bare ('unsupported', msg) tuple) would
    silently misclassify any future tuple-shaped plan (ADVICE r3)."""

    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason


_UNSET = object()   # "this memo slot was never computed" (None is a value)


class _StmtMemo:
    """Planning-cascade memo for one canonical statement: every
    recognizer outcome along the select path, INCLUDING negative ones
    (window extraction found nothing, join recognizer declined, builder
    rejected). Keyed like the plan cache — (store version, config
    fingerprint, repr(stmt)) — plus a lookup-table fingerprint, so any
    ingest, config flip, CLEAR METADATA, rollup DDL (registry bumps the
    store version) or lookup registration re-plans from scratch. A warm
    repeated statement skips straight from key to cached plan."""

    __slots__ = ("window", "resolved", "pq", "join", "composite")

    def __init__(self):
        self.window = _UNSET      # None | (base_stmt, WindowPlan)
        self.resolved = _UNSET    # offset-stripped, fully resolved stmt
        self.pq = _UNSET          # PlannedQuery | _NegativePlan
        self.join = _UNSET        # JoinPlan | None (declined)
        self.composite = _UNSET   # CompositePlan | None (rejected)


def _lookups_fp(ctx) -> int:
    """Registered-lookup fingerprint for the memo key: lookup tables
    inline into the resolved statement WITHOUT bumping the store
    version, so re-registering one must miss the memo. Tables are
    dim-scale (the inlined-pairs literal already embeds them in plans),
    so hashing them per statement is noise next to the cascade."""
    lk = getattr(ctx, "lookups", None)
    if not lk:
        return 0
    return hash(tuple((n, tuple(sorted(t.items())))
                      for n, t in sorted(lk.items())))


def _memo_put(cache, key, val, bound: int) -> None:
    """LRU insert honoring sdot.plan.memo.entries (the shared
    result_cache_put has its own fixed bound)."""
    cache[key] = val
    cache.move_to_end(key)
    while len(cache) > max(1, bound):
        cache.popitem(last=False)


@_functools.lru_cache(maxsize=256)
def _parse_cached(sql: str):
    """Memoized parse (AST nodes are frozen dataclasses — safely
    shared). Timed INSIDE the miss path so ``stats['phases']['parse']``
    only appears when the parser actually ran."""
    t0 = _time.perf_counter()
    stmt = parse_statement(sql)
    PH.stash("parse", _time.perf_counter() - t0)
    return stmt


def run_sql(ctx, sql: str, query_id: Optional[str] = None,
            lane: Optional[str] = None, tenant: Optional[str] = None,
            priority: Optional[int] = None) -> QueryResult:
    if lane is not None or tenant is not None or priority is not None:
        # the request's lane/tenant/priority ride wlm thread-local state
        # down to every spec this statement executes (incl. subqueries
        # and composite sub-plans) — same channel as query_id below
        ctx.engine.wlm.push_request(lane, tenant, priority)
        try:
            return run_sql(ctx, sql, query_id=query_id)
        finally:
            ctx.engine.wlm.pop_request()
    if query_id is not None:
        # register BEFORE planning so a cancel landing at any point in the
        # statement's life is honored; current id rides thread-local state
        # down to every spec this statement executes (incl. subqueries)
        from spark_druid_olap_tpu.planner.host_exec import ctx_tls
        tls = ctx_tls(ctx)       # resolve BEFORE acquiring the refcount:
        ctx.engine.register_query(query_id)   # nothing between acquire
        try:                                  # and try may raise
            tls.query_id = query_id
            return _run_sql_inner(ctx, sql)
        finally:
            tls.query_id = None
            ctx.engine.release_query(query_id)
    return _run_sql_inner(ctx, sql)


def _run_sql_inner(ctx, sql: str) -> QueryResult:
    # module-contributed front commands (≈ SPLParser trying its command
    # grammar before the base parser)
    for handler in getattr(ctx, "statement_handlers", ()):
        r = handler(ctx, sql)
        if r is not None:
            return r
    # statement boundary: a previous statement's un-consumed parse time
    # must not leak into this one's accumulator
    PH.clear_stash()
    from spark_druid_olap_tpu.utils.config import PLAN_MEMO_ENABLED
    if ctx.config.get(PLAN_MEMO_ENABLED):
        stmt = _parse_cached(sql)
    else:
        _tp = _time.perf_counter()
        stmt = parse_statement(sql)
        PH.stash("parse", _time.perf_counter() - _tp)
    if isinstance(stmt, A.ClearMetadata):
        from spark_druid_olap_tpu.mv.registry import clear_rollups
        if stmt.datasource:
            ctx.store.drop(stmt.datasource)
            clear_rollups(ctx, stmt.datasource)
            # the drop bumps the datasource version (stale keys can never
            # hit again), but the entries themselves must not linger
            ctx.engine.result_cache.clear()
        else:
            clear_rollups(ctx)
            ctx.engine.clear_caches()  # includes the semantic result cache
        if stmt.purge and ctx.persist is not None:
            # PURGE extends the clear to deep storage — without it the
            # snapshots survive and recovery resurrects the datasources
            ctx.persist.purge(stmt.datasource)
        return QueryResult(["status"], {"status": np.array(["OK"],
                                                           dtype=object)})
    if isinstance(stmt, (A.Checkpoint, A.Restore)):
        return _run_persist_command(ctx, stmt)
    if isinstance(stmt, (A.CreateRollup, A.DropRollup, A.RefreshRollup)):
        from spark_druid_olap_tpu.mv.registry import handle_statement
        msg = handle_statement(ctx, stmt)
        return QueryResult(["status"], {"status": np.array([msg],
                                                           dtype=object)})
    if isinstance(stmt, A.ExecuteRawQuery):
        from spark_druid_olap_tpu.ir.serde import query_from_json
        q = query_from_json(stmt.query_json, default_ds=stmt.datasource)
        r = ctx.engine.execute(q)
        ctx.history.record(q, dict(ctx.engine.last_stats), sql=sql)
        return r
    if isinstance(stmt, A.ExplainRewrite):
        text = explain_text(ctx, stmt.query, stmt.sql)
        return QueryResult(["plan"],
                           {"plan": np.array(text.split("\n"), dtype=object)})
    return _run_select(ctx, stmt, sql)


def _run_persist_command(ctx, stmt) -> QueryResult:
    """``CHECKPOINT [ds]`` / ``RESTORE [ds]`` (persist/manager.py)."""
    if ctx.persist is None:
        raise RuntimeError(
            "persistence is disabled; set sdot.persist.path")
    if isinstance(stmt, A.Checkpoint):
        summaries = ctx.checkpoint(stmt.datasource)
        msgs = [f"checkpointed {s['datasource']} v{s['version']} "
                f"({s['rows']} rows, {s['bytes']} bytes)"
                for s in summaries] or ["nothing to checkpoint"]
        return QueryResult(["status"],
                           {"status": np.array(msgs, dtype=object)})
    report = ctx.persist.restore(stmt.datasource)
    # the restore rewinds ingest-version counters; cached results keyed
    # on the pre-restore versions could collide with post-restore keys,
    # so every derived cache drops
    ctx.engine.clear_caches()
    msgs = [f"restored {d['datasource']} from {d['source']}"
            for d in report["datasources"]] or ["nothing restored"]
    return QueryResult(["status"],
                       {"status": np.array(msgs, dtype=object)})


def explain_sql(ctx, sql: str) -> str:
    stmt = parse_statement(sql)
    if isinstance(stmt, A.ExplainRewrite):
        return explain_text(ctx, stmt.query, stmt.sql)
    if isinstance(stmt, (A.SelectStmt, A.UnionAll)):
        return explain_text(ctx, stmt, sql)
    return f"command: {type(stmt).__name__}"


def explain_text(ctx, stmt: A.SelectStmt, sql: str) -> str:
    """≈ ``ExplainDruidRewrite`` (reference DruidMetadataCommands.scala:49-78)
    — shows whether the query pushes down, the engine query specs, and the
    cost-model decision."""
    if isinstance(stmt, A.UnionAll):
        lines = [f"SQL: {sql.strip()}",
                 f"UNION ALL over {len(stmt.parts)} branches (each plans "
                 f"independently):"]
        for i, p in enumerate(stmt.parts):
            sub = explain_text(ctx, p, f"<branch {i}>")
            lines.append("  " + sub.replace("\n", "\n  "))
        return "\n".join(lines)
    lines = [f"SQL: {sql.strip()}"]
    from spark_druid_olap_tpu.planner.scoping import (resolve_alias_scopes,
                                                      resolve_databases)
    stmt = resolve_databases(ctx, stmt)
    stmt = resolve_alias_scopes(ctx, stmt)
    stmt = resolve_lookups(ctx, stmt)
    try:
        from spark_druid_olap_tpu.planner.decorrelate import (
            decorrelate_semijoins)
        from spark_druid_olap_tpu.planner.viewmerge import merge_derived
        stmt = decorrelate_semijoins(ctx, merge_derived(ctx, stmt))
        pq = B.build(ctx, stmt)
    except PlanUnsupported as e:
        from spark_druid_olap_tpu.planner import composite
        from spark_druid_olap_tpu.planner.decorrelate import (
            stmt_has_subqueries)
        try:
            # execute=False: explain must never dispatch engine queries
            # (the inlining passes RUN subqueries) or pollute the history
            cp = composite.build_composite(ctx, stmt, execute=False)
            lines.append("pushdown: COMPOSITE (engine derived tables + "
                         "host finish)")
            lines.append(composite.describe(cp, "  "))
            return "\n".join(lines)
        except Exception:  # noqa: BLE001 — explain must never fail
            pass
        if stmt_has_subqueries(stmt):
            lines.append(
                "pushdown: DEFERRED — subqueries inline at execution "
                "(inner queries run through the engine; correlated "
                "shapes become KeyedLookup broadcast joins / per-key "
                "min-max EXISTS, planner/decorrelate.py); remaining "
                "shapes run on the host tier")
            return "\n".join(lines)
        lines.append(f"pushdown: NO ({e})")
        lines.append("execution: host (pandas fallback)")
        return "\n".join(lines)
    lines.append(f"pushdown: YES -> datasource {pq.datasource!r}, "
                 f"{len(pq.specs)} engine quer"
                 f"{'y' if len(pq.specs) == 1 else 'ies'}")
    if pq.rollup is not None:
        lines.append(f"rollup rewrite: {pq.rollup} -> scans "
                     f"{pq.specs[0].datasource!r} instead of the base "
                     f"datasource")
    from spark_druid_olap_tpu.parallel.cost import explain_cost
    for i, q in enumerate(pq.specs):
        lines.append(f"  [{i}] {type(q).__name__}: dims="
                     f"{[d.output_name for d in S.query_dimensions(q)]} "
                     f"aggs={[a.name for a in S.query_aggregations(q)]} "
                     f"intervals={q.intervals}")
        lines.append("      " + explain_cost(ctx, q).replace("\n", "\n      "))
    if pq.distinct_phase2:
        lines.append(f"  phase2: exact count-distinct over "
                     f"{pq.distinct_phase2.group_cols}")
    from spark_druid_olap_tpu.utils.config import (SHAREDSCAN_ENABLED,
                                                   WLM_BATCH_WINDOW_MS)
    if ctx.config.get(SHAREDSCAN_ENABLED):
        from spark_druid_olap_tpu.cache.keys import cacheable
        n_elig = sum(1 for q in pq.specs if cacheable(q))
        lines.append(
            f"sharedscan: ON — {n_elig}/{len(pq.specs)} spec(s) eligible "
            f"to coalesce with concurrent queries on the same datasource "
            f"(hold window {ctx.config.get(WLM_BATCH_WINDOW_MS)}ms)")
    return "\n".join(lines)


def _run_select(ctx, stmt: A.SelectStmt, sql: str) -> QueryResult:
    from spark_druid_olap_tpu.utils.config import TZ_ID
    from spark_druid_olap_tpu.utils import host_eval as _he
    _tz_tok = _he.SESSION_TZ.set(ctx.config.get(TZ_ID))
    try:
        return _run_select_tz(ctx, stmt, sql)
    finally:
        _he.SESSION_TZ.reset(_tz_tok)


def _transform_tracer(ctx):
    """Per-statement rewrite tracing gated by ``sdot.debug.transformations``
    (≈ the reference's DruidTransforms debug tracing,
    ``DruidTransforms.scala:121-136``): logs each rewrite stage that
    CHANGED the statement, with O(1)-repr lookup tables."""
    from spark_druid_olap_tpu.utils.config import DEBUG_TRANSFORMATIONS
    if not ctx.config.get(DEBUG_TRANSFORMATIONS):
        return lambda name, before, after: after

    import reprlib
    import sys as _sys
    rl = reprlib.Repr()
    rl.maxstring = rl.maxother = 2000
    rl.maxtuple = rl.maxlist = rl.maxdict = 40

    def trace(name, before, after):
        if after is not before:
            print(f"[sdot.rewrite] {name}: {rl.repr(after)}",
                  file=_sys.stderr)
        return after

    return trace


def _run_select_tz(ctx, stmt, sql: str) -> QueryResult:
    if isinstance(stmt, A.UnionAll):
        return _run_union(ctx, stmt, sql)
    from spark_druid_olap_tpu.utils.config import (PHASES_ENABLED,
                                                   PLAN_MEMO_ENABLED,
                                                   PLAN_MEMO_ENTRIES)
    # nested entries (union branches, window base statements) get None
    # back and merge their phases into the outer statement's accumulator
    ph_tok = PH.begin(bool(ctx.config.get(PHASES_ENABLED)))
    try:
        memo = None
        memo_hit = None
        if ctx.config.get(PLAN_MEMO_ENABLED):
            with PH.phase("plan.memo"):
                _mcache, _mkey = host_exec.result_cache(ctx, "stmtmemo",
                                                        stmt)
                _mkey = _mkey + (_lookups_fp(ctx),)
                memo = _mcache.get(_mkey)
                memo_hit = memo is not None
                if memo_hit:
                    _mcache.move_to_end(_mkey)
                else:
                    memo = _StmtMemo()
                    _memo_put(_mcache, _mkey, memo,
                              int(ctx.config.get(PLAN_MEMO_ENTRIES)))
        if memo is not None and memo.window is not _UNSET:
            wp = memo.window
        else:
            with PH.phase("plan.window"):
                wp = _maybe_windows(ctx, stmt)
            if memo is not None:
                # WindowUnsupported propagates UNCACHED (slot stays
                # _UNSET): only deterministic outcomes memoize
                memo.window = wp
        if wp is not None:
            return _run_windowed(ctx, wp, sql, ph_tok)
        return _run_select_planned(ctx, stmt, sql, ph_tok, memo, memo_hit)
    finally:
        PH.end(ph_tok)   # idempotent: normally closed at stats assembly


def _run_select_planned(ctx, stmt, sql: str, ph_tok, memo,
                        memo_hit) -> QueryResult:
    t0 = _time.perf_counter()
    dc0 = list(ctx.engine.dispatch_counts)
    sq0 = getattr(_subq_tls, "hits", 0)
    _stage = __import__("os").environ.get("SDOT_STAGE_TIMING", "") == "1"
    _marks = {}

    def _mark(key, t_start):
        if _stage:
            _marks[key] = round(_marks.get(key, 0.0)
                                + (_time.perf_counter() - t_start) * 1000, 2)
    offset = stmt.offset
    if offset:
        # strip the offset before planning: the engine/host paths see an
        # extended LIMIT, the slice happens once here
        import dataclasses as _dc
        stmt = _dc.replace(stmt, offset=0,
                           limit=None if stmt.limit is None
                           else stmt.limit + offset)
    if memo is not None and memo.resolved is not _UNSET:
        stmt = memo.resolved
    else:
        with PH.phase("plan.resolve"):
            from spark_druid_olap_tpu.planner.scoping import (
                resolve_alias_scopes, resolve_databases)
            stmt = resolve_databases(ctx, stmt)
            stmt = resolve_alias_scopes(ctx, stmt)
            stmt = resolve_lookups(ctx, stmt)
        if memo is not None:
            memo.resolved = stmt
    trace = _transform_tracer(ctx)
    rollup_status = None  # engine path only: 'rollup:<name>' | 'base'
    try:
        from spark_druid_olap_tpu.planner.decorrelate import (
            decorrelate_semijoins, inline_correlated_scalars,
            inline_subqueries)
        from spark_druid_olap_tpu.planner.viewmerge import merge_derived
        # statement plan cache: the rewrite passes (subquery-inlining
        # AST transforms) and the pushdown build cost ~100-200ms of
        # host CPU per statement on deep trees (TPC-H q21-class); the
        # result is deterministic given (store version, config), both
        # folded into the key by result_cache. Inlined subquery RESULTS
        # embedded in the plan stay valid under the same key.
        from spark_druid_olap_tpu.utils.config import PLAN_CACHE_ENABLED
        plan_cached = False
        _pc_on = ctx.config.get(PLAN_CACHE_ENABLED)
        if memo is not None and memo.pq is not _UNSET:
            pq = memo.pq
            # the memo subsumes the plan cache (same key discipline:
            # store version + config fingerprint), so a memo-served
            # plan reports as a statement-cache hit when the plan
            # cache is on — stats["plan_cached"] keeps its contract
            plan_cached = bool(_pc_on)
            if isinstance(pq, _NegativePlan):
                raise PlanUnsupported(pq.reason)
        else:
            _pcache, _pkey = host_exec.result_cache(ctx, "plan", stmt)
            pq = _pcache.get(_pkey) if _pc_on else None
            plan_cached = pq is not None
            if plan_cached:
                _pcache.move_to_end(_pkey)
                if memo is not None:
                    memo.pq = pq
                if isinstance(pq, _NegativePlan):
                    # negative entry: the builder deterministically
                    # rejects this statement under the current
                    # store/config — skip straight to the
                    # composite/host tiers
                    raise PlanUnsupported(pq.reason)
            else:
                _tr = _time.perf_counter()
                with PH.phase("plan.rewrite"):
                    stmt2 = trace("merge_derived", stmt,
                                  merge_derived(ctx, stmt))
                    stmt2 = trace("decorrelate_semijoins", stmt2,
                                  decorrelate_semijoins(ctx, stmt2))
                    stmt2 = trace("inline_correlated_scalars", stmt2,
                                  inline_correlated_scalars(ctx, stmt2))
                    stmt2 = trace("inline_subqueries", stmt2,
                                  inline_subqueries(ctx, stmt2))
                _mark("stmt_rewrite_ms", _tr)
                _tb = _time.perf_counter()
                try:
                    with PH.phase("plan.build"):
                        pq = B.build(ctx, stmt2)
                except PlanUnsupported as pe:
                    neg = _NegativePlan(str(pe))
                    if _pc_on:
                        host_exec.result_cache_put(_pcache, _pkey, neg)
                    if memo is not None:
                        memo.pq = neg
                    raise
                _mark("stmt_build_ms", _tb)
                if _pc_on:
                    host_exec.result_cache_put(_pcache, _pkey, pq)
                if memo is not None:
                    memo.pq = pq
        _te = _time.perf_counter()
        df = execute_planned(ctx, pq)
        _mark("stmt_exec_ms", _te)
        mode = "engine"
        rollup_status = f"rollup:{pq.rollup}" if pq.rollup else "base"
    except (PlanUnsupported, EngineFallback) as e:
        df = mode = None
        if isinstance(e, PlanUnsupported):
            # general two-table joins (fact-to-fact, self-join funnel,
            # non-equi residual) on the device join tiers. Tried BEFORE
            # the composite planner: recognition is conservative (two
            # stored relations, >=1 equi key, plain aggregate shape),
            # and everything it accepts runs the probe inside the
            # device wave loop — strictly better than the composite
            # tier's gather-and-host-join finish for the same shape.
            # Any decline falls through unchanged.
            from spark_druid_olap_tpu.planner import joinplan
            from spark_druid_olap_tpu.utils.config import JOIN_ENABLED
            try:
                if memo is not None and memo.join is not _UNSET:
                    jp = memo.join
                else:
                    # recognition only (pure) — cost arbitration and the
                    # JOIN_ENABLED kill switch stay live in try_execute;
                    # JOIN_ENABLED is semantic (in the fingerprint), so
                    # a memoized decline can't outlive a flip
                    with PH.phase("plan.join"):
                        jp = (joinplan.try_plan(ctx, stmt)
                              if bool(ctx.config.get(JOIN_ENABLED))
                              else None)
                    if memo is not None:
                        memo.join = jp
                df = joinplan.try_execute(ctx, stmt, plan=jp)
            except joinplan.JoinUnsupported:
                df = None
            if df is not None:
                mode = "engine"
                rollup_status = "base"
        if df is None and isinstance(e, PlanUnsupported):
            # engine-planned derived tables + dim-scale host finish (the
            # reference's DruidQuery-scans-under-Spark-join shape)
            from spark_druid_olap_tpu.planner import composite
            try:
                # build from the PRE-inline statement: the inlining
                # passes execute subqueries away, and the composite
                # planner needs to SEE them (its dim-only-FROM gate) and
                # plan derived tables through its own chain. Same plan
                # cache contract as the pushdown path (store version +
                # config fingerprint in the key).
                from spark_druid_olap_tpu.utils.config import (
                    PLAN_CACHE_ENABLED)
                if memo is not None and memo.composite is not _UNSET:
                    cp = memo.composite
                    if cp is None:   # memoized deterministic rejection
                        raise PlanUnsupported("composite rejected (memo)")
                else:
                    _cc_on = ctx.config.get(PLAN_CACHE_ENABLED)
                    _ccache, _ckey = host_exec.result_cache(ctx, "cplan",
                                                            stmt)
                    cp = _ccache.get(_ckey) if _cc_on else None
                    if cp is not None:
                        _ccache.move_to_end(_ckey)
                    else:
                        try:
                            with PH.phase("plan.composite"):
                                cp = composite.build_composite(ctx, stmt)
                        except PlanUnsupported:
                            # deterministic rejection memoizes; runtime
                            # EngineFallback/HostExecError do NOT
                            if memo is not None:
                                memo.composite = None
                            raise
                        if _cc_on:
                            host_exec.result_cache_put(_ccache, _ckey, cp)
                    if memo is not None:
                        memo.composite = cp
                df = composite.execute_composite(ctx, cp)
                mode = "engine"
                rollup_status = "base"
            except (PlanUnsupported, EngineFallback,
                    host_exec.HostExecError):
                df = None
        if df is None:
            df = host_exec.execute_select(ctx, stmt)
            mode = f"host ({e})"
    if offset:
        df = df.iloc[offset:].reset_index(drop=True)
    stats = dict(ctx.engine.last_stats)
    stats["mode"] = mode
    if rollup_status is not None:
        stats["rollup"] = rollup_status
    stats["total_ms"] = (_time.perf_counter() - t0) * 1000
    dc1 = ctx.engine.dispatch_counts
    stats["n_dispatch"] = dc1[0] - dc0[0]
    stats["n_transfer"] = dc1[1] - dc0[1]
    # hand-scheduled Pallas wave mega-kernel launches (sharedscan wave
    # path) attributed to this statement's thread — a subset-annotation
    # of n_dispatch, 0 on the jaxpr path
    stats["kernel_launches"] = (dc1[2] - dc0[2]
                                if len(dc1) > 2 and len(dc0) > 2 else 0)
    # explicit provenance for LEGITIMATE zero-dispatch engine statements
    # (bench.py's zero_dispatch_engine guard exempts annotated ones and
    # flags the rest): a semantic result-cache hit, or a statement whose
    # decorrelated inners were served by the gated subquery channel and
    # whose residual plan needed no device work of its own
    if stats.get("cache") not in (None, "miss"):
        stats["served_from"] = "result_cache"
    elif mode == "engine" and stats["n_dispatch"] == 0 \
            and getattr(_subq_tls, "hits", 0) > sq0:
        stats["served_from"] = "subquery_cache"
    if plan_cached:
        stats["plan_cached"] = True
    if memo_hit is not None:
        stats["plan_memo"] = {"hit": bool(memo_hit)}
    phases = PH.end(ph_tok)
    if phases is not None:
        stats["phases"] = {k: round(v, 3) for k, v in phases.items()}
    stats.update(_marks)
    ctx.history.record(stmt, stats, sql=sql)
    res = QueryResult(list(df.columns),
                      {c: df[c].to_numpy() for c in df.columns})
    # partial-results mode: the degraded annotation survives the
    # DataFrame round trip (callers check r.degraded; degraded answers
    # are never cached, enforced engine-side). Host-mode statements
    # never scattered, so their stats snapshot may carry a STALE
    # cluster entry from the previous engine query — gate on mode.
    res.degraded = (stats.get("cluster") or {}).get("degraded") \
        if mode == "engine" else None
    return res


def _maybe_windows(ctx, stmt):
    """Strip ``OVER (...)`` calls BEFORE any planning (window/plan.py).
    Returns ``(base_stmt, WindowPlan)`` or None. Runs ahead of the plan
    cache on purpose: the base statement is what gets planned/cached,
    so a windowed statement and its base share cache entries."""
    from spark_druid_olap_tpu.window import plan as WPLAN
    return WPLAN.extract(ctx, stmt)


def _run_windowed(ctx, wp, sql: str, ph_tok=None) -> QueryResult:
    """Window post-pass: run the base statement through the normal
    tiers (engine pushdown / cluster scatter / composite / host), then
    compute the window columns on device over the merged result frame
    and apply the deferred ORDER BY / LIMIT / OFFSET
    (window/exec.py). Distribution composes for free: on a broker the
    base statement scatters and merges before the post-pass sees it.
    The base statement re-enters ``_run_select_tz`` with the phase
    accumulator already open, so its phases merge here and this
    statement's ``stats['phases']`` covers the whole pipeline."""
    from spark_druid_olap_tpu.window import exec as WEXEC
    base_stmt, plan = wp
    t0 = _time.perf_counter()
    base = _run_select_tz(ctx, base_stmt, f"{sql} <window base>")
    _tw = _time.perf_counter()
    with PH.phase("epilogue"):
        df = WEXEC.apply(ctx, plan, base.to_pandas())
    stats = dict(ctx.engine.last_stats)
    stats["mode"] = "engine+window"
    stats["window"] = {"n_windows": len(plan.windows),
                       "fns": sorted({w.fn for w in plan.windows}),
                       "window_ms": round(
                           (_time.perf_counter() - _tw) * 1000, 2)}
    stats["total_ms"] = (_time.perf_counter() - t0) * 1000
    phases = PH.end(ph_tok)
    if phases is not None:
        stats["phases"] = {k: round(v, 3) for k, v in phases.items()}
    ctx.history.record(base_stmt, stats, sql=sql)
    res = QueryResult(list(df.columns),
                      {c: df[c].to_numpy() for c in df.columns})
    res.degraded = base.degraded
    return res


def _run_union(ctx, u: A.UnionAll, sql: str) -> QueryResult:
    """UNION ALL: each branch plans independently (engine pushdown per
    branch, like Spark planning each Union child), rows concatenate
    positionally under the first branch's column names, then the trailing
    ORDER BY / OFFSET / LIMIT apply."""
    t0 = _time.perf_counter()
    frames = [
        _run_select_tz(ctx, part, f"{sql} <union branch {i}>").to_pandas()
        for i, part in enumerate(u.parts)]
    df = host_exec.finish_union(frames, u)
    ctx.history.record(u, {"mode": "union",
                           "branches": len(u.parts),
                           "total_ms": (_time.perf_counter() - t0) * 1000},
                       sql=sql)
    return QueryResult(list(df.columns),
                       {c: df[c].to_numpy() for c in df.columns})


def execute_planned(ctx, pq: PlannedQuery) -> pd.DataFrame:
    import dataclasses as _dc
    from spark_druid_olap_tpu.planner.host_exec import ctx_tls
    qid = getattr(ctx_tls(ctx), "query_id", None)
    frames: List[pd.DataFrame] = []
    degraded: List[dict] = []
    for q, set_dims in zip(pq.specs, pq.spec_dims):
        if qid is not None and getattr(q.context, "query_id", None) is None:
            qctx = q.context or S.QueryContext()
            q = _dc.replace(q, context=_dc.replace(qctx, query_id=qid))
        r = ctx.engine.execute(q)
        if r.degraded is not None:
            degraded.append(r.degraded)
        df = r.to_pandas()
        if "__count__" in df.columns and "__count__" not in pq.output_columns:
            df = df.drop(columns=["__count__"])
        # null-fill dims missing from this grouping set
        for d in pq.all_dims:
            if d not in df.columns:
                df[d] = None
        frames.append(df)
    df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]

    if pq.residual is not None:
        from spark_druid_olap_tpu.utils import host_eval
        env = {c: df[c].to_numpy() for c in df.columns}
        # WHERE-derived conjuncts: Kleene 3VL (UNKNOWN drops the row;
        # plain eval_expr would mis-handle NULL-bearing predicates and
        # can collapse to a scalar)
        mask = np.broadcast_to(
            np.asarray(host_eval.eval_pred3(pq.residual, env), dtype=bool),
            (len(df),))
        df = df[mask].reset_index(drop=True)

    if pq.distinct_phase2 is not None:
        df = _phase2_distinct(df, pq)
        from spark_druid_olap_tpu.utils import host_eval
        env = {c: df[c].to_numpy() for c in df.columns}
        for p in pq.deferred_posts:
            v = np.asarray(host_eval.eval_expr(p.expr, env))
            df[p.name] = np.broadcast_to(v, (len(df),)) if v.ndim == 0 else v
            env[p.name] = df[p.name].to_numpy()

    if pq.order_by and not pq.order_applied_in_spec:
        cols = [c for c, _ in pq.order_by]
        asc = [a for _, a in pq.order_by]
        df = df.sort_values(cols, ascending=asc, kind="mergesort")
    if pq.limit is not None and not pq.order_applied_in_spec:
        df = df.head(pq.limit)

    if pq.select_renames:
        df = df.rename(columns=pq.select_renames)
    missing = [c for c in pq.output_columns if c not in df.columns]
    if missing:
        raise EngineFallback(f"planned outputs missing: {missing}")
    if degraded:
        # engine.execute clears last_stats per spec, so a degraded
        # (partial-results) annotation from an earlier grouping set
        # would be lost — re-merge them where run_sql's stats snapshot
        # (and the final QueryResult) can see them
        merged = degraded[0] if len(degraded) == 1 else {
            "missing_shards": sorted(
                {s for d in degraded for s in d["missing_shards"]}),
            "coverage_rows": min(d["coverage_rows"] for d in degraded),
            "total_rows": max(d["total_rows"] for d in degraded)}
        ctx.engine.last_stats.setdefault("cluster", {})["degraded"] = merged
    return df[pq.output_columns].reset_index(drop=True)


def _phase2_distinct(df: pd.DataFrame, pq: PlannedQuery) -> pd.DataFrame:
    d2 = pq.distinct_phase2
    gcols = d2.group_cols
    # null arg values don't count toward count(distinct)
    nn = df[~df[d2.distinct_dim].isna()]
    if gcols:
        cnt = nn.groupby(gcols, dropna=False, as_index=False).agg(
            **{d2.distinct_out: (d2.distinct_dim, "nunique")})
    else:
        cnt = pd.DataFrame({d2.distinct_out: [nn[d2.distinct_dim].nunique()]})
    aggd = {}
    for col, fn in d2.other_aggs.items():
        aggd[col] = (col, fn)
    if gcols:
        if aggd:
            oth = df.groupby(gcols, dropna=False, as_index=False).agg(**aggd)
            out = oth.merge(cnt, on=gcols, how="left")
        else:
            out = cnt
    else:
        if aggd:
            oth = pd.DataFrame({c: [getattr(df[c], fn)()]
                                for c, (c2, fn) in aggd.items()})
            out = pd.concat([oth, cnt], axis=1)
        else:
            out = cnt
    out[d2.distinct_out] = out[d2.distinct_out].fillna(0).astype(np.int64)
    return out
