"""Canonical cache keys for the semantic result cache.

A key identifies the *answer* of an engine-level QuerySpec, so it must be
insensitive to representations that cannot change the result:

* ``QueryContext`` (query id, timeout, shard preference) is stripped.
* AND/OR filter trees are flattened, TRUE conjuncts dropped, and children
  sorted; IN value lists are deduped and sorted.
* Aggregations are sorted by output name (the hit path restores the
  query's column order from the spec itself).
* Intervals are sorted and merged via the same [lo, hi) millisecond
  convention as ``ir/intervals.py``; the full range folds to ``None``.

Dimension order is deliberately *kept*: it determines the engine's fused
group-key construction and therefore row order, and two queries that
differ only in dimension order must not alias to one entry if we want
cached results bit-identical to uncached execution.

The key also folds in the per-datasource ingest version
(:meth:`SegmentStore.datasource_version`) and ``Config.fingerprint()``,
so invalidation is structural — any re-ingest, stream append, drop or
config change moves subsequent queries to fresh keys (≈ Druid's segment
version in its result-cache keys).

Restart contract (persist/): recovery restores each datasource's ingest
version EXACTLY as it was at the last commit (``SegmentStore.restore``),
so version-keyed entries stay coherent across a process restart. An
in-session ``RESTORE`` instead *rewinds* versions — the session layer
clears this cache afterwards, since a rewound version could collide with
entries keyed under the same number but different data.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ir.intervals import MAX_MS, MIN_MS

# Engine-level spec types the semantic cache serves. Select is excluded
# (pagination state) and Search results are cheap scans over dictionaries.
CACHEABLE_TYPES = (S.GroupByQuerySpec, S.TimeseriesQuerySpec, S.TopNQuerySpec)


def cacheable(q) -> bool:
    return isinstance(q, CACHEABLE_TYPES)


def _sort_key(f) -> str:
    return repr(f)


def normalize_filter(f: Optional[S.FilterSpec]) -> Optional[S.FilterSpec]:
    """Return a canonical filter, or None for anything equivalent to TRUE."""
    if f is None:
        return None
    if isinstance(f, S.LogicalFilter):
        if f.op in ("and", "or"):
            parts = []
            for child in f.fields:
                nc = normalize_filter(child)
                if nc is None:
                    if f.op == "or":
                        return None  # TRUE branch absorbs the OR
                    continue  # TRUE conjunct drops from the AND
                if isinstance(nc, S.LogicalFilter) and nc.op == f.op:
                    parts.extend(nc.fields)
                else:
                    parts.append(nc)
            if not parts:
                # Empty AND is TRUE; empty OR is FALSE — keep the latter.
                return None if f.op == "and" else S.LogicalFilter("or", ())
            if len(parts) == 1:
                return parts[0]
            return S.LogicalFilter(f.op, tuple(sorted(parts, key=_sort_key)))
        if f.op == "not":
            kids = tuple(
                normalize_filter(c) if normalize_filter(c) is not None else S.TrueFilter
                for c in f.fields
            )
            return S.LogicalFilter("not", kids)
        return f
    if isinstance(f, S.InFilter):
        vals = tuple(sorted(set(f.values), key=lambda v: (v is None, v)))
        return dataclasses.replace(f, values=vals)
    return f


def normalize_intervals(
    intervals: Optional[Tuple[S.Interval, ...]],
) -> Optional[Tuple[S.Interval, ...]]:
    """Sort, drop empties, merge overlapping/adjacent; full range -> None."""
    if intervals is None:
        return None
    spans = sorted((int(lo), int(hi)) for lo, hi in intervals if int(lo) < int(hi))
    merged = []
    for lo, hi in spans:
        if merged and lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    out = tuple((lo, hi) for lo, hi in merged)
    if out == ((MIN_MS, MAX_MS),):
        return None
    return out


def normalize_aggs(
    aggs: Tuple[S.AggregationSpec, ...],
) -> Tuple[S.AggregationSpec, ...]:
    normed = tuple(
        dataclasses.replace(a, filter=normalize_filter(a.filter)) for a in aggs
    )
    return tuple(sorted(normed, key=lambda a: a.name))


#: Spec fields DELIBERATELY stripped from the canonical key even though
#: runtime code reads them (sdlint keys/K2 checks this list). Every entry
#: needs a result-neutrality argument:
#: - context: carries query_id / timeout / lane / tenant / priority —
#:   pure execution metadata. The planner and executor read it only for
#:   cancellation, deadlines, and admission routing; no field of
#:   QueryContext ever reaches an aggregation, filter, or output column,
#:   so two queries differing only in context MUST alias to one entry
#:   (that aliasing is the whole point of the result cache under
#:   per-request ids).
KEY_EXEMPT_FIELDS = ("context",)


def normalize_spec(q):
    """Canonical form of a cacheable spec: context stripped, filter/aggs/
    intervals normalized. The returned spec is only used for its repr."""
    kw = dict(
        context=S.QueryContext(),
        filter=normalize_filter(q.filter),
        intervals=normalize_intervals(q.intervals),
        aggregations=normalize_aggs(q.aggregations),
    )
    return dataclasses.replace(q, **kw)


def canonical_key(q, ds_version: int, config_fp) -> tuple:
    """Hashable key for one engine-level query answer."""
    return (
        type(q).__name__,
        q.datasource,
        int(ds_version),
        config_fp,
        repr(normalize_spec(q)),
    )


def expected_columns(q) -> Tuple[str, ...]:
    """Output column order the engine produces for ``q`` — used to restore
    the query's own order when serving from an agg-sorted cache entry."""
    cols = []
    gran = getattr(q, "granularity", None)
    if gran is not None and getattr(gran, "kind", None) != "all":
        cols.append("timestamp")
    for d in S.query_dimensions(q):
        cols.append(d.output_name)
    for a in S.query_aggregations(q):
        cols.append(a.name)
    for p in getattr(q, "post_aggregations", ()) or ():
        cols.append(p.name)
    return tuple(cols)
