"""Byte-budgeted LRU + the engine-facing semantic result cache.

:class:`ByteBudgetLRU` is generic infra (also bounds the partial-store
gather cache in ``segment/store.py``): an ordered map with a byte budget,
thread-safe, counting hits / misses / evictions / resident bytes.

:class:`SemanticResultCache` sits in ``QueryEngine.execute`` around
``_execute_inner``. On lookup it tries an exact canonical-key hit, then —
when ``sdot.cache.subsumption`` is on — probes the generalized specs from
``subsume.candidates`` and derives the answer on the host. Entries are
snapshotted on put and copied again on get, so cached arrays can never be
mutated by callers. Keys fold in the per-datasource ingest version, so a
re-ingest / stream append / drop invalidates without any eager sweep.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.cache import keys as K
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.utils import phases as PH


def nbytes_of(obj) -> int:
    """Approximate host bytes held by arrays / tuples of arrays."""
    if obj is None:
        return 0
    if isinstance(obj, np.ndarray):
        if obj.dtype == object:
            n = int(obj.size)
            if not n:
                return 0
            flat = obj.ravel()
            if n > 4096:
                # strided sample: exact counting is an O(n) Python loop,
                # minutes on multi-million-row gathered columns
                sample = flat[:: n // 4096 + 1]
                per = sum(len(str(x)) + 48 for x in sample) / len(sample)
                return int(per * n)
            return int(sum(len(str(x)) + 48 for x in flat))
        return int(obj.nbytes)
    if isinstance(obj, (tuple, list)):
        return sum(nbytes_of(x) for x in obj)
    if isinstance(obj, dict):
        return sum(nbytes_of(v) for v in obj.values())
    return 64


class ByteBudgetLRU:
    """Thread-safe LRU bounded by total payload bytes, not entry count."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[object, Tuple[object, int]]" = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key, count: bool = True):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                if count:
                    self.misses += 1
                return None
            self._entries.move_to_end(key)
            if count:
                self.hits += 1
            return hit[0]

    def put(self, key, value, nbytes: Optional[int] = None) -> bool:
        nb = int(nbytes_of(value) if nbytes is None else nbytes)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old[1]
            if nb > self.max_bytes:
                # Oversized payloads would immediately evict everything
                # else; refuse them rather than thrash the budget.
                return False
            self._entries[key] = (value, nb)
            self.bytes += nb
            while self.bytes > self.max_bytes and self._entries:
                _, (_, enb) = self._entries.popitem(last=False)
                self.bytes -= enb
                self.evictions += 1
            return True

    def pop(self, key) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old[1]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


def _snapshot(result: QueryResult):
    """Immutable-by-convention copy of a QueryResult's payload."""
    cols = tuple(result.columns)
    data = {c: np.array(result.data[c], copy=True) for c in cols}
    return (cols, data)


def _materialize(q, entry) -> QueryResult:
    """Fresh QueryResult in the query's own column order, arrays copied."""
    cols, data = entry
    want = K.expected_columns(q)
    order = list(want) if set(want) == set(cols) else list(cols)
    return QueryResult(order, {c: np.array(data[c], copy=True) for c in order})


class SemanticResultCache:
    """Engine-level result cache with subsumption reuse.

    Config is read live on every call, so toggling ``sdot.cache.enabled``
    (or resizing ``sdot.cache.max_bytes``) in a running session takes
    effect immediately; resized budgets apply on the next put.
    """

    def __init__(self, config):
        self.config = config
        self.lru = ByteBudgetLRU(int(config.get("sdot.cache.max_bytes")))
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.subsumed = 0
        self.puts = 0

    # -- config -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return bool(self.config.get("sdot.cache.enabled"))

    @property
    def subsumption(self) -> bool:
        return bool(self.config.get("sdot.cache.subsumption"))

    def cacheable(self, q) -> bool:
        return K.cacheable(q)

    # -- core -------------------------------------------------------------
    def _key(self, q, ds_version: int):
        return K.canonical_key(q, ds_version, self.config.fingerprint())

    def lookup(self, q, ds_version: int):
        """Return ``(QueryResult, 'hit'|'subsumed')`` or ``(None, 'miss')``.
        Probe time (subsumption derivation included) lands in the
        per-query phase profile as ``cache.lookup``."""
        with PH.phase("cache.lookup"):
            return self._lookup(q, ds_version)

    def _lookup(self, q, ds_version: int):
        entry = self.lru.get(self._key(q, ds_version), count=False)
        if entry is not None:
            with self._lock:
                self.hits += 1
            return _materialize(q, entry), "hit"
        if self.subsumption:
            from spark_druid_olap_tpu.cache import subsume

            tz = str(self.config.get("sdot.timezone") or "UTC")
            utc = tz.upper() in ("UTC", "ETC/UTC", "Z")
            for gen, derive in subsume.candidates(q, utc=utc):
                gentry = self.lru.get(self._key(gen, ds_version), count=False)
                if gentry is None:
                    continue
                derived = derive(q, gentry)
                if derived is None:
                    continue
                with self._lock:
                    self.subsumed += 1
                return derived, "subsumed"
        with self._lock:
            self.misses += 1
        return None, "miss"

    def put(self, q, ds_version: int, result: QueryResult) -> None:
        entry = _snapshot(result)
        self.lru.max_bytes = int(self.config.get("sdot.cache.max_bytes"))
        if self.lru.put(self._key(q, ds_version), entry, nbytes_of(entry[1])):
            with self._lock:
                self.puts += 1

    def clear(self) -> None:
        self.lru.clear()

    def stats(self) -> Dict[str, int]:
        out = self.lru.stats()
        # The LRU's own hit/miss counters track raw probes (exact +
        # subsumption); report query-level semantics alongside.
        out.pop("hits", None)
        out.pop("misses", None)
        with self._lock:
            out.update(
                {
                    "enabled": self.enabled,
                    "subsumption": self.subsumption,
                    "hits": self.hits,
                    "misses": self.misses,
                    "subsumed": self.subsumed,
                    "puts": self.puts,
                }
            )
        return out
