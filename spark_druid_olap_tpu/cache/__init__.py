"""Semantic query-result cache (≈ Druid's broker/historical result caches).

``keys.py``     canonical cache keys from normalized QuerySpecs + the
                per-datasource ingest version (structural invalidation).
``result_cache.py``  byte-budgeted LRU over materialized host results and
                the engine-facing :class:`SemanticResultCache`.
``subsume.py``  derivability rules answering a query from a *superset*
                cached entry without touching the device.
"""
