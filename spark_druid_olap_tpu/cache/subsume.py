"""Subsumption: answer a query from a *superset* cached entry.

Each rule yields ``(generalized_spec, derive_fn)`` pairs from
:func:`candidates`. The cache probes the generalized spec's canonical key
and, on a hit, calls ``derive_fn(q, entry)`` to re-shape the cached rows
on the host — no device work. ``derive_fn`` returns ``None`` whenever it
cannot prove the derivation exact, and the cache falls through to the
next candidate (ultimately a miss).

Rules (mirroring classic view-matching / Druid broker merge logic):

1. **Granularity rollup** — a coarser-granularity timeseries from a
   cached finer one, for aggregations whose partials merge losslessly
   (count/longsum/doublesum re-sum; min/min, max/max). UTC sessions
   only: non-UTC bucketing shifts wall-clock boundaries through the
   engine's TZ LUTs, which host-side re-bucketing does not replicate.
   ``week`` only coarsens to ``all`` (weeks straddle month bounds).
   Float re-summation is kept because the engine's own cross-bucket
   merge is the same left-to-right ordered reduction over ascending
   buckets.
2. **TopN from GroupBy** — an (exact) TopN answered by ordering and
   heading a cached unlimited GroupBy over the same dimension.
3. **Filtered GroupBy** — a GroupBy whose filter touches only its own
   plain (extraction-free) dimensions, answered by masking rows of the
   cached unfiltered GroupBy: every group is homogeneous in its own
   dims, so a dim-only row filter is exactly a group filter.
4. **Having/limit re-evaluation** — having, order-by-limit, and post
   aggregations re-applied on a cached unconstrained GroupBy, using the
   engine's own epilogue ordering so ties land identically.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from spark_druid_olap_tpu.cache import keys as K
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.utils import host_eval

MILLIS_PER_DAY = 86_400_000

# target granularity kind -> finer source kinds that nest inside it,
# coarsest (cheapest to merge) first
_SOURCES = {
    "all": ("year", "quarter", "month", "week", "day", "hour", "minute"),
    "year": ("quarter", "month", "day", "hour", "minute"),
    "quarter": ("month", "day", "hour", "minute"),
    "month": ("day", "hour", "minute"),
    "week": ("day", "hour", "minute"),
    "day": ("hour", "minute"),
    "hour": ("minute",),
}

# agg kind -> lossless partial-merge op (approximate sketches excluded)
_MERGE = {
    "count": "sum",
    "longsum": "sum",
    "doublesum": "sum",
    "longmin": "min",
    "doublemin": "min",
    "longmax": "max",
    "doublemax": "max",
}


def _ctx_stripped(q, **kw):
    return dataclasses.replace(q, context=S.QueryContext(), **kw)


def _post_variants(q) -> Tuple[Tuple[S.PostAggregationSpec, ...], ...]:
    """Probe both the cached-with-same-posts and cached-without-posts
    shapes; posts are always recomputed from aggs on derivation."""
    if getattr(q, "post_aggregations", ()):
        return (q.post_aggregations, ())
    return ((),)


# ---------------------------------------------------------------------------
# shared epilogue — must order ties exactly as the engine's _agg_epilogue
# ---------------------------------------------------------------------------

def _apply_epilogue(data: dict, post_aggregations, having, limit) -> dict:
    """Posts + HAVING + ORDER BY/LIMIT, byte-compatible with
    ``QueryEngine._agg_epilogue`` (same lexsort keys, same null order)."""
    from spark_druid_olap_tpu.parallel.executor import _neg_key

    for pa in post_aggregations:
        data[pa.name] = np.asarray(host_eval.eval_expr(pa.expr, data))
    if having is not None:
        keep = host_eval.eval_pred3(having.expr, data)
        data = {k: v[keep] for k, v in data.items()}
    if limit is not None and limit.columns:
        order_keys = []
        for oc in reversed(limit.columns):
            k = data[oc.name]
            if k.dtype == object and all(
                    v is None or isinstance(v, (int, np.integer)) for v in k):
                nulls = np.array([v is None for v in k])
                vals = np.array([0 if v is None else int(v) for v in k],
                                dtype=np.int64)
                order_keys.append(vals if oc.ascending else -vals)
                order_keys.append(nulls)
                continue
            if k.dtype == object:
                k = k.astype(str)
            order_keys.append(k if oc.ascending else _neg_key(k))
        idx = np.lexsort(order_keys)
        if limit.limit is not None:
            idx = idx[: limit.limit]
        data = {k: v[idx] for k, v in data.items()}
    elif limit is not None and limit.limit is not None:
        data = {k: v[: limit.limit] for k, v in data.items()}
    return data


def _finish(q, data: dict) -> Optional[QueryResult]:
    """Package ``data`` into the query's expected column order."""
    want = K.expected_columns(q)
    if any(c not in data for c in want):
        return None
    return QueryResult(
        list(want), {c: np.array(data[c], copy=True) for c in want})


# ---------------------------------------------------------------------------
# rule 1 — granularity rollup (timeseries)
# ---------------------------------------------------------------------------

def _bucket_start_ms(kind: str, ms: np.ndarray) -> np.ndarray:
    """Target bucket start per row, epoch ms UTC — mirrors the engine's
    ``ops/time_ops.bucket_and_cardinality`` decode math."""
    if kind == "all":
        return np.zeros_like(ms)
    if kind == "minute":
        return ms - (ms % 60_000)
    if kind == "hour":
        return ms - (ms % 3_600_000)
    if kind == "day":
        return ms - (ms % MILLIS_PER_DAY)
    if kind == "week":
        days = ms // MILLIS_PER_DAY
        wk = (days + 3) // 7  # Monday-aligned, epoch was a Thursday
        return (wk * 7 - 3) * MILLIS_PER_DAY
    dt = ms.astype("datetime64[ms]")
    if kind == "month":
        return dt.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    if kind == "quarter":
        m = dt.astype("datetime64[M]").astype(np.int64)
        return ((m // 3) * 3).astype("datetime64[M]") \
            .astype("datetime64[ms]").astype(np.int64)
    if kind == "year":
        return dt.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
    raise ValueError(f"unsupported rollup target granularity {kind!r}")


def _merge_column(vals: np.ndarray, inv: np.ndarray, n: int, how: str
                  ) -> Optional[np.ndarray]:
    if vals.dtype == object:
        # wide-int sums / min-max decode to Python ints with None for
        # empty groups; merge null-skipping in plain Python
        out = [None] * n
        for g, v in zip(inv, vals):
            if v is None:
                continue
            cur = out[g]
            if cur is None:
                out[g] = v
            elif how == "sum":
                out[g] = cur + v
            elif how == "min":
                out[g] = min(cur, v)
            else:
                out[g] = max(cur, v)
        return np.array(out, dtype=object)
    if np.issubdtype(vals.dtype, np.floating):
        valid = ~np.isnan(vals)
        cnt = np.zeros(n, dtype=np.int64)
        np.add.at(cnt, inv[valid], 1)
        if how == "sum":
            out = np.zeros(n, dtype=vals.dtype)
            np.add.at(out, inv[valid], vals[valid])
        elif how == "min":
            out = np.full(n, np.inf, dtype=vals.dtype)
            np.minimum.at(out, inv[valid], vals[valid])
        else:
            out = np.full(n, -np.inf, dtype=vals.dtype)
            np.maximum.at(out, inv[valid], vals[valid])
        out[cnt == 0] = np.nan
        return out
    if np.issubdtype(vals.dtype, np.integer):
        if how == "sum":
            out = np.zeros(n, dtype=vals.dtype)
            np.add.at(out, inv, vals)
        elif how == "min":
            out = np.full(n, np.iinfo(vals.dtype).max, dtype=vals.dtype)
            np.minimum.at(out, inv, vals)
        else:
            out = np.full(n, np.iinfo(vals.dtype).min, dtype=vals.dtype)
            np.maximum.at(out, inv, vals)
        return out
    return None


def _derive_rollup(q, entry) -> Optional[QueryResult]:
    cols, data = entry
    if "timestamp" not in data:
        return None
    ts = np.asarray(data["timestamp"])
    if not np.issubdtype(ts.dtype, np.datetime64):
        return None
    target = q.granularity.kind
    if len(ts) == 0:
        if target == "all":
            return None  # global aggregate over zero rows: identity-row
            # semantics the rollup cannot reproduce — execute normally
        return QueryResult.empty(list(K.expected_columns(q)))
    ms = ts.astype("datetime64[ms]").astype(np.int64)
    buckets = _bucket_start_ms(target, ms)
    uniq, inv = np.unique(buckets, return_inverse=True)
    n = len(uniq)
    out: Dict[str, np.ndarray] = {}
    if target != "all":
        out["timestamp"] = uniq.astype("datetime64[ms]")
    for a in q.aggregations:
        how = _MERGE.get(a.kind)
        src = data.get(a.name)
        if how is None or src is None:
            return None
        merged = _merge_column(np.asarray(src), inv, n, how)
        if merged is None:
            return None
        out[a.name] = merged
    out = _apply_epilogue(out, q.post_aggregations, None, None)
    return _finish(q, out)


# ---------------------------------------------------------------------------
# rule 3 helper — host evaluation of dim-only filters over decoded groups
# ---------------------------------------------------------------------------

_SIMPLE_FILTERS = (S.SelectorFilter, S.BoundFilter, S.InFilter,
                   S.PatternFilter, S.NullFilter)


def _filter_derivable(f: S.FilterSpec, dim_map: Dict[str, str]) -> bool:
    if isinstance(f, S.LogicalFilter):
        return all(_filter_derivable(c, dim_map) for c in f.fields)
    return isinstance(f, _SIMPLE_FILTERS) and f.dimension in dim_map


def _null_mask(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.array([v is None for v in col], dtype=bool)
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    return np.zeros(len(col), dtype=bool)


def _eval_filter(f, data: dict, dim_map: Dict[str, str]
                 ) -> Optional[np.ndarray]:
    """Boolean row mask of ``f`` over decoded group columns, or None when
    a comparison cannot be proven faithful to the engine's dictionary
    semantics (caller falls through to a miss)."""
    if isinstance(f, S.LogicalFilter):
        n = len(next(iter(data.values()))) if data else 0
        if f.op == "not":
            inner = _eval_filter(f.fields[0], data, dim_map) \
                if f.fields else None
            return None if inner is None else ~inner
        acc = np.full(n, f.op == "and", dtype=bool)
        for c in f.fields:
            m = _eval_filter(c, data, dim_map)
            if m is None:
                return None
            acc = (acc & m) if f.op == "and" else (acc | m)
        return acc
    col = np.asarray(data[dim_map[f.dimension]])
    null = _null_mask(col)
    if isinstance(f, S.NullFilter):
        return ~null if f.negated else null
    if col.dtype != object:
        # engine dim filters compare against string dictionary entries;
        # only derive over decoded string columns
        return None
    svals = np.array([("" if v is None else str(v)) for v in col])
    if isinstance(f, S.SelectorFilter):
        if f.value is None:
            return null
        return (svals == str(f.value)) & ~null
    if isinstance(f, S.InFilter):
        want = {str(v) for v in f.values if v is not None}
        mask = np.isin(svals, sorted(want)) & ~null
        if any(v is None for v in f.values):
            mask |= null
        return mask
    if isinstance(f, S.BoundFilter):
        if f.numeric:
            return None  # numeric coercion order differs from lexicographic
        mask = ~null
        if f.lower is not None:
            lo = str(f.lower)
            mask &= (svals > lo) if f.lower_strict else (svals >= lo)
        if f.upper is not None:
            hi = str(f.upper)
            mask &= (svals < hi) if f.upper_strict else (svals <= hi)
        return mask
    if isinstance(f, S.PatternFilter):
        if f.kind == "contains":
            pred = lambda s: f.pattern in s
        elif f.kind == "like":
            rx = re.compile(
                "^" + "".join(
                    ".*" if ch == "%" else "." if ch == "_"
                    else re.escape(ch) for ch in f.pattern) + "$",
                re.DOTALL)
            pred = lambda s: rx.match(s) is not None
        elif f.kind == "regex":
            rx = re.compile(f.pattern)
            pred = lambda s: rx.search(s) is not None
        else:
            return None
        return np.array([pred(s) for s in svals], dtype=bool) & ~null
    return None


def _make_derive_groupby(extra_filter: Optional[S.FilterSpec]):
    def derive(q, entry) -> Optional[QueryResult]:
        cols, data = entry
        data = dict(data)
        if extra_filter is not None:
            dim_map = {d.dimension: d.output_name for d in q.dimensions
                       if d.extraction is None}
            mask = _eval_filter(extra_filter, data, dim_map)
            if mask is None:
                return None
            data = {k: np.asarray(v)[mask] for k, v in data.items()}
        # posts always recomputed from the cached aggs
        data = {k: v for k, v in data.items()
                if k not in {p.name for p in q.post_aggregations}}
        data = _apply_epilogue(data, q.post_aggregations, q.having, q.limit)
        return _finish(q, data)

    return derive


def _derive_topn(q, entry) -> Optional[QueryResult]:
    cols, data = entry
    data = {k: v for k, v in dict(data).items()
            if k not in {p.name for p in q.post_aggregations}}
    limit = S.LimitSpec((S.OrderByColumn(q.metric, ascending=False),),
                        q.threshold)
    if q.metric not in data and q.metric not in {
            p.name for p in q.post_aggregations}:
        return None
    data = _apply_epilogue(data, q.post_aggregations, None, limit)
    return _finish(q, data)


# ---------------------------------------------------------------------------
# candidate enumeration
# ---------------------------------------------------------------------------

def candidates(q, utc: bool = True) -> Iterator[tuple]:
    """Yield ``(generalized_spec, derive_fn)`` pairs, best-first."""
    if isinstance(q, S.TimeseriesQuerySpec):
        gran = q.granularity or S.GRAN_ALL
        # malformed granularity (e.g. a bare string) falls through to the
        # engine so its own contract error surfaces, not a cache traceback
        gkind = getattr(gran, "kind", None)
        if utc and gkind and all(a.kind in _MERGE for a in q.aggregations):
            for src_kind in _SOURCES.get(gkind, ()):
                for pp in _post_variants(q):
                    yield (
                        _ctx_stripped(q, granularity=S.Granularity(src_kind),
                                      post_aggregations=pp),
                        _derive_rollup,
                    )
        return
    if isinstance(q, S.TopNQuerySpec):
        for pp in _post_variants(q):
            yield (
                S.GroupByQuerySpec(
                    datasource=q.datasource,
                    dimensions=(q.dimension,),
                    aggregations=q.aggregations,
                    post_aggregations=pp,
                    filter=q.filter,
                    having=None,
                    limit=None,
                    granularity=q.granularity,
                    intervals=q.intervals,
                ),
                _derive_topn,
            )
        return
    if isinstance(q, S.GroupByQuerySpec):
        variants = []
        if q.having is not None or q.limit is not None:
            variants.append((dict(having=None, limit=None), None))
        nf = K.normalize_filter(q.filter)
        if nf is not None:
            dim_map = {d.dimension: d.output_name for d in q.dimensions
                       if d.extraction is None}
            if _filter_derivable(nf, dim_map):
                variants.append(
                    (dict(filter=None, having=None, limit=None), nf))
        for kw, extra in variants:
            for pp in _post_variants(q):
                yield (
                    _ctx_stripped(q, post_aggregations=pp, **kw),
                    _make_derive_groupby(extra),
                )
