"""Versioned on-disk snapshot format for datasources (deep storage).

Layout under ``<root>/<datasource-dir>/``::

    CURRENT                   # JSON pointer {"version": N}, atomic replace
    v<NNNNNNNNNN>/            # one published snapshot (N = monotone
                              #   publish number; the ingest version it
                              #   captures lives in the manifest)
      manifest.json           # schema, segment map, versions, checksums
      time_days.bin ...       # per-column raw little-endian blobs
      dim_NNNN_dict.json      # sorted global dictionaries (NNNN = dim index)
    wal.log                   # stream-ingest journal (persist/wal.py)
    quarantine/               # checksum-failing versions moved aside

Publish protocol (≈ Druid's segment push to deep storage + metadata
commit): write every blob into a hidden temp dir, fsync each file, then
``os.replace`` the temp dir to its version name and atomically rewrite
CURRENT. The version name is a monotone per-datasource publish number
(max existing + 1), so a publish NEVER replaces an existing directory —
even a re-checkpoint of the same ingest version lands in a fresh dir,
and there is no instant at which CURRENT's directory is missing. A crash
at any point leaves either the old CURRENT (temp dirs are
garbage-collected on the next publish) or the new one — never a
half-published snapshot.

Every blob carries a CRC32 in the manifest; recovery verifies them
(``sdot.persist.verify.checksums``) and quarantines the version on any
mismatch instead of serving silently corrupt columns.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

FORMAT_VERSION = 1
MANIFEST = "manifest.json"
CURRENT = "CURRENT"
QUARANTINE_DIR = "quarantine"


def sanitize(name: str) -> str:
    """Datasource name -> filesystem-safe directory name (dotted database
    prefixes are fine; path separators and leading dots are not)."""
    out = name.replace(os.sep, "%2F").replace("/", "%2F")
    return "_" + out if out.startswith(".") else out


def fsync_dir(path: str) -> None:
    """Flush a directory inode: a rename-publish is only durable once
    the directory entry itself is synced (best-effort — some filesystems
    refuse O_RDONLY fsync on directories)."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


_fsync_dir = fsync_dir    # established internal spelling


def _write_blob(dirpath: str, rel: str, data: bytes,
                files: Dict[str, dict], meta: dict) -> None:
    with open(os.path.join(dirpath, rel), "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    files[rel] = {"crc": zlib.crc32(data), "bytes": len(data), **meta}


def _array_blob(dirpath: str, rel: str, arr: np.ndarray,
                files: Dict[str, dict]) -> None:
    _write_blob(dirpath, rel, arr.tobytes(),
                files, {"dtype": arr.dtype.str, "shape": list(arr.shape)})


def _encoded_blob(dirpath: str, rel: str, arr: np.ndarray, bounds,
                  files: Dict[str, dict], codec: str) -> None:
    """One column blob as concatenated per-SEGMENT encoded chunks, so a
    tiered store can fault any segment's byte range independently. The
    file meta grows a self-describing ``enc`` block — ``codec`` plus one
    ``[byte_off, byte_len, header]`` entry per segment (headers carry
    the chunk's codec, row count, params, and integer value bounds; see
    encode/codecs.py) — while ``dtype``/``shape`` keep describing the
    LOGICAL array, exactly as the raw format does. A chunk the codec
    fails to shrink stays raw inside the same file (encode_chunk's
    fallback), so encoding never inflates a segment."""
    from spark_druid_olap_tpu.encode import codecs as EN
    segs, parts, off = [], [], 0
    for s, e in bounds:
        payload, header = EN.encode_chunk(
            np.ascontiguousarray(arr[s:e]), codec)
        parts.append(payload)
        segs.append([off, len(payload), header])
        off += len(payload)
    _write_blob(dirpath, rel, b"".join(parts), files,
                {"dtype": arr.dtype.str, "shape": list(arr.shape),
                 "enc": {"codec": codec, "segments": segs}})


def version_dirname(version: int) -> str:
    return f"v{int(version):010d}"


def list_versions(ds_root: str) -> List[int]:
    try:
        names = os.listdir(ds_root)
    except OSError:
        return []
    out = []
    for n in names:
        if n.startswith("v") and n[1:].isdigit() \
                and os.path.isdir(os.path.join(ds_root, n)):
            out.append(int(n[1:]))
    return sorted(out)


def current_version(ds_root: str) -> Optional[int]:
    """The published version per CURRENT; falls back to the newest
    on-disk version dir when the pointer is missing or unreadable."""
    try:
        with open(os.path.join(ds_root, CURRENT)) as f:
            v = int(json.load(f)["version"])
        if os.path.isdir(os.path.join(ds_root, version_dirname(v))):
            return v
    except (OSError, ValueError, KeyError):
        pass
    versions = list_versions(ds_root)
    return versions[-1] if versions else None


def write_snapshot(ds_root: str, ds, ingest_version: int,
                   wal_seq: int, keep: int = 2, encode=None) -> dict:
    """Publish one snapshot of a COMPLETE datasource; returns the
    manifest. Atomic: temp dir -> rename -> CURRENT pointer swap. The
    on-disk version is allocated (max existing + 1), never reused: an
    in-place replace of an existing version dir would open a crash
    window with no directory behind CURRENT after the covering WAL
    records were already truncated.

    ``encode`` (an :class:`encode.chooser.EncodeOptions`, None = raw)
    turns on per-column compressed blobs: the chooser picks a codec per
    column, columns it declines stay raw, and the manifest's per-file
    ``enc`` blocks make the result self-describing — a reader that
    predates the encoding block only ever sees it on snapshots it never
    wrote, and readers here fall back to the raw path whenever the
    block is absent, so raw and encoded versions interoperate under one
    CURRENT pointer with zero manifest-format churn."""
    ds.require_complete("checkpoint")
    os.makedirs(ds_root, exist_ok=True)
    # collect temp dirs a crashed previous publish left behind
    for n in os.listdir(ds_root):
        if n.startswith(".tmp-"):
            shutil.rmtree(os.path.join(ds_root, n), ignore_errors=True)
    versions = list_versions(ds_root)
    publish_version = (versions[-1] + 1) if versions else 1
    tmp = os.path.join(ds_root, f".tmp-{os.getpid()}-{publish_version}")
    os.makedirs(tmp, exist_ok=True)
    try:
        return _fill_and_publish(ds_root, ds, ingest_version, wal_seq,
                                 keep, publish_version, tmp, encode)
    except BaseException:
        # a failed publish must not strand the temp dir until the next
        # write_snapshot's sweep — a crash-restart loop would otherwise
        # accumulate one orphan per attempt
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def _fill_and_publish(ds_root: str, ds, ingest_version: int, wal_seq: int,
                      keep: int, publish_version: int, tmp: str,
                      encode=None) -> dict:
    files: Dict[str, dict] = {}
    enc_cols: Dict[str, str] = {}
    enc_raw_bytes = 0

    def _column_blob(rel: str, arr: np.ndarray) -> None:
        # per-column codec choice at publish time: the chooser measures
        # the actual array (not the ingest-time hint) so a compaction
        # that re-sorts or widens a column re-chooses its codec; columns
        # the chooser declines (floats, high-entropy ints, ratio below
        # sdot.encode.min.ratio) stay raw in the SAME snapshot
        nonlocal enc_raw_bytes
        codec = None
        if encode is not None and getattr(encode, "enabled", False):
            from spark_druid_olap_tpu.encode import chooser as _chooser
            codec = _chooser.choose_codec(np.asarray(arr), encode)
        if codec is None:
            _array_blob(tmp, rel, arr, files)
        else:
            _encoded_blob(tmp, rel, arr,
                          [(s.start_row, s.end_row) for s in ds.segments],
                          files, codec)
            enc_cols[rel] = codec
            enc_raw_bytes += int(arr.nbytes)

    manifest = {
        "format": FORMAT_VERSION,
        "datasource": ds.name,
        "snapshot_version": int(publish_version),
        "ingest_version": int(ingest_version),
        "wal_seq": int(wal_seq),
        "num_rows": int(ds.num_rows),
        "created_at": time.time(),
        "segments": [[s.id, s.start_row, s.end_row,
                      s.min_millis, s.max_millis] for s in ds.segments],
        "spatial": {k: list(v) for k, v in ds.spatial.items()},
        "time": None,
        "dims": [],
        "metrics": [],
    }
    if ds.time is not None:
        _column_blob("time_days.bin", ds.time.days)
        _column_blob("time_ms.bin", ds.time.ms_in_day)
        manifest["time"] = {"name": ds.time.name,
                            "days": "time_days.bin", "ms": "time_ms.bin"}
    for i, (name, d) in enumerate(ds.dims.items()):
        codes_f = f"dim_{i:04d}_codes.bin"
        dict_f = f"dim_{i:04d}_dict.json"
        _column_blob(codes_f, d.codes)
        _write_blob(tmp, dict_f,
                    json.dumps([str(v) for v in d.dictionary]).encode(),
                    files, {"json": True})
        entry = {"name": name, "codes": codes_f, "dictionary": dict_f,
                 "validity": None}
        if d.validity is not None:
            vf = f"dim_{i:04d}_valid.bin"
            _column_blob(vf, d.validity)
            entry["validity"] = vf
        manifest["dims"].append(entry)
    for i, (name, m) in enumerate(ds.metrics.items()):
        vals_f = f"met_{i:04d}_values.bin"
        _column_blob(vals_f, m.values)
        # global (min, max) over valid rows: the cost model's
        # selectivity input. Publishing it keeps a TIERED recovery from
        # faulting a whole column just to plan (tier/loader.py injects
        # these as the column's bounds cache). Additive — format
        # version unchanged; old manifests simply lack the field.
        mn, mx = m.min, m.max
        entry = {"name": name, "kind": m.kind.value, "values": vals_f,
                 "validity": None,
                 "min": None if mn is None else float(mn),
                 "max": None if mx is None else float(mx)}
        # per-SEGMENT (min, max) zone maps, same additive contract as the
        # global pair above: tiered recovery injects them so broker /
        # planner pruning never faults a cold blob just to bound a
        # segment. None marks a segment with no valid rows (JSON has no
        # +/-inf), which prunes nothing — exactly the in-memory
        # semantics of an all-null segment's (inf, -inf) bounds.
        smin, smax = ds.segment_metric_bounds(name)
        entry["seg_bounds"] = [
            [float(lo), float(hi)] if np.isfinite(lo) and np.isfinite(hi)
            else None for lo, hi in zip(smin, smax)]
        if m.validity is not None:
            vf = f"met_{i:04d}_valid.bin"
            _column_blob(vf, m.validity)
            entry["validity"] = vf
        manifest["metrics"].append(entry)
    manifest["files"] = files
    manifest["bytes"] = sum(e["bytes"] for e in files.values())
    if enc_cols:
        from spark_druid_olap_tpu.encode import codecs as EN
        enc_bytes = sum(files[rel]["bytes"] for rel in enc_cols)
        manifest["encoding"] = {
            "version": EN.ENCODING_VERSION,
            "columns": enc_cols,
            "raw_bytes": int(enc_raw_bytes),
            "encoded_bytes": int(enc_bytes),
        }

    with open(os.path.join(tmp, MANIFEST), "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())

    final = os.path.join(ds_root, version_dirname(publish_version))
    os.replace(tmp, final)
    _fsync_dir(ds_root)
    _write_current(ds_root, int(publish_version))
    prune(ds_root, keep=keep, current=int(publish_version))
    return manifest


def _write_current(ds_root: str, version: int) -> None:
    tmp = os.path.join(ds_root, CURRENT + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"version": int(version)}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(ds_root, CURRENT))
    _fsync_dir(ds_root)


def prune(ds_root: str, keep: int, current: int) -> None:
    """Retain the newest ``keep`` versions (always including the current
    one); remove the rest."""
    keep = max(1, int(keep))
    versions = list_versions(ds_root)
    retained = set(sorted(versions)[-keep:]) | {int(current)}
    for v in versions:
        if v not in retained:
            shutil.rmtree(os.path.join(ds_root, version_dirname(v)),
                          ignore_errors=True)


def load_manifest(ds_root: str, version: int) -> dict:
    with open(os.path.join(ds_root, version_dirname(version),
                           MANIFEST)) as f:
        return json.load(f)


def datasource_manifests(root: str) -> Dict[str, dict]:
    """Deep-storage catalog scan: datasource name -> current published
    manifest. The cluster shard plan (cluster/assign.py) is a pure
    function of this scan, which is what makes deep storage the
    coordination substrate: every process pointed at the same root
    derives the same plan with no coordinator service. Datasources with
    WAL-only state (never checkpointed) have no manifest and are
    invisible here — the broker serves those locally."""
    out: Dict[str, dict] = {}
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return out
    for n in entries:
        p = os.path.join(root, n)
        if not os.path.isdir(p) or n.startswith("."):
            continue
        cur = current_version(p)
        if cur is None:
            continue
        try:
            m = load_manifest(p, cur)
        except (OSError, ValueError, KeyError):
            continue
        name = m.get("datasource")
        if name is not None:
            out[name] = m
    return out


class SnapshotCorrupt(Exception):
    """A snapshot file failed checksum / structural verification."""


def _read_blob(vdir: str, rel: str, files: dict, verify: bool) -> bytes:
    try:
        with open(os.path.join(vdir, rel), "rb") as f:
            data = f.read()
    except OSError as e:
        raise SnapshotCorrupt(f"missing blob {rel}: {e}") from e
    meta = files.get(rel)
    if meta is None:
        raise SnapshotCorrupt(f"blob {rel} not in manifest")
    if len(data) != int(meta["bytes"]):
        raise SnapshotCorrupt(
            f"blob {rel}: {len(data)} bytes, manifest says {meta['bytes']}")
    if verify and zlib.crc32(data) != int(meta["crc"]):
        raise SnapshotCorrupt(f"blob {rel}: CRC32 mismatch")
    return data


def _read_array(vdir: str, rel: str, files: dict, verify: bool) -> np.ndarray:
    data = _read_blob(vdir, rel, files, verify)
    meta = files[rel]
    enc = meta.get("enc")
    if enc is not None:
        # encoded blob: decode the per-segment chunks back to the
        # logical array (the eager recovery path; tiered recovery keeps
        # the bytes encoded and decodes on fault instead). Manifests
        # without an ``enc`` block — every pre-encoding snapshot —
        # never reach this branch, so the raw path below stays
        # byte-for-byte what it always was.
        from spark_druid_olap_tpu.encode import codecs as EN
        dt = np.dtype(meta["dtype"])
        mv = memoryview(data)
        parts = []
        try:
            for off, length, header in enc["segments"]:
                parts.append(EN.decode_array(mv[off:off + length], header))
        except (EN.EncodingError, KeyError, ValueError, TypeError) as e:
            raise SnapshotCorrupt(f"blob {rel}: bad encoded chunk: {e}") \
                from e
        arr = np.concatenate(parts) if parts else np.empty(0, dtype=dt)
        if arr.dtype != dt:
            raise SnapshotCorrupt(
                f"blob {rel}: decoded dtype {arr.dtype.str}, "
                f"manifest says {meta['dtype']}")
        try:
            arr = arr.reshape(meta.get("shape", [-1]))
        except ValueError as e:
            raise SnapshotCorrupt(
                f"blob {rel}: decoded {arr.size} elements, manifest "
                f"shape {meta.get('shape')}") from e
        if arr.size and not arr.flags.writeable:
            arr = arr.copy()
        return arr
    arr = np.frombuffer(data, dtype=np.dtype(meta["dtype"]))
    # writable copy: Datasource caches mutate nothing, but downstream
    # numpy ops (e.g. in-place sorts in tests) must not hit a read-only
    # frombuffer view
    return arr.reshape(meta.get("shape", [-1])).copy()


def load_snapshot(ds_root: str, version: int,
                  verify: bool = True) -> Tuple[object, dict, float]:
    """(Datasource, manifest, checksum_verify_ms). Raises
    :class:`SnapshotCorrupt` on any checksum/structure failure."""
    from spark_druid_olap_tpu.segment.column import (
        ColumnKind, DimColumn, MetricColumn, TimeColumn)
    from spark_druid_olap_tpu.segment.store import Datasource, Segment

    t0 = time.perf_counter()
    try:
        manifest = load_manifest(ds_root, version)
    except (OSError, ValueError) as e:
        raise SnapshotCorrupt(f"unreadable manifest: {e}") from e
    if int(manifest.get("format", -1)) != FORMAT_VERSION:
        raise SnapshotCorrupt(
            f"unknown snapshot format {manifest.get('format')!r}")
    vdir = os.path.join(ds_root, version_dirname(version))
    files = manifest.get("files", {})

    time_col = None
    if manifest["time"] is not None:
        t = manifest["time"]
        time_col = TimeColumn(
            name=t["name"],
            days=_read_array(vdir, t["days"], files, verify),
            ms_in_day=_read_array(vdir, t["ms"], files, verify))
    dims = {}
    for e in manifest["dims"]:
        dict_raw = _read_blob(vdir, e["dictionary"], files, verify)
        try:
            dictionary = np.asarray(json.loads(dict_raw.decode()),
                                    dtype=object)
        except ValueError as ex:
            raise SnapshotCorrupt(
                f"dictionary {e['dictionary']}: {ex}") from ex
        dims[e["name"]] = DimColumn(
            name=e["name"], dictionary=dictionary,
            codes=_read_array(vdir, e["codes"], files, verify),
            validity=None if e["validity"] is None
            else _read_array(vdir, e["validity"], files, verify))
    metrics = {}
    for e in manifest["metrics"]:
        metrics[e["name"]] = MetricColumn(
            name=e["name"],
            values=_read_array(vdir, e["values"], files, verify),
            validity=None if e["validity"] is None
            else _read_array(vdir, e["validity"], files, verify),
            kind=ColumnKind(e["kind"]))
    segments = [Segment(id=s[0], start_row=int(s[1]), end_row=int(s[2]),
                        min_millis=int(s[3]), max_millis=int(s[4]))
                for s in manifest["segments"]]
    ds = Datasource(name=manifest["datasource"], time=time_col, dims=dims,
                    metrics=metrics, segments=segments,
                    spatial={k: tuple(v)
                             for k, v in manifest["spatial"].items()})
    if ds.num_rows != int(manifest["num_rows"]):
        raise SnapshotCorrupt(
            f"segment map rows {ds.num_rows} != manifest "
            f"num_rows {manifest['num_rows']}")
    return ds, manifest, (time.perf_counter() - t0) * 1000.0


def quarantine_version(ds_root: str, version: int) -> Optional[str]:
    """Move a corrupt snapshot version aside (never deleted — an operator
    may want the evidence) and return its new path."""
    src = os.path.join(ds_root, version_dirname(version))
    if not os.path.isdir(src):
        return None
    qdir = os.path.join(ds_root, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dst = os.path.join(
        qdir, f"{int(time.time())}-{version_dirname(version)}")
    i = 0
    while os.path.exists(dst):
        i += 1
        dst = os.path.join(
            qdir, f"{int(time.time())}-{version_dirname(version)}.{i}")
    os.replace(src, dst)
    # the corrupt dir must STAY moved after a crash, or recovery retries
    # the same poisoned version forever
    _fsync_dir(ds_root)
    _fsync_dir(qdir)
    return dst
