"""Write-ahead journal for stream-ingest appends.

One journal file per datasource. A committed batch survives ``kill -9``:
the commit point is the journal append + fsync, which happens BEFORE the
in-memory store registers the new rows — crash after the fsync replays
the batch at recovery; crash before it loses only the uncommitted batch
(which the caller never saw acknowledged).

Record framing (little-endian):

    [4B magic 'SDWL'][4B u32 header_len][8B u64 body_len]
    [4B u32 crc32(header + body)][header JSON][body bytes]

The header is a small JSON dict (record seq, datasource, kind, ingest
kwargs); the body is the batch itself as an Arrow IPC stream. Replay
reads records until EOF and STOPS at the first short or checksum-failing
record — a torn tail from a crash mid-append is expected, not an error.
Everything before it is intact by CRC.
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Tuple

_MAGIC = b"SDWL"
_FRAME = struct.Struct("<4sIQI")


def _fsync_dir(path: str) -> None:
    # local copy of snapshot.fsync_dir — this module stays import-free
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def encode_batch(df) -> bytes:
    """pandas DataFrame -> Arrow IPC stream bytes (schema included)."""
    import pyarrow as pa
    table = pa.Table.from_pandas(df, preserve_index=False)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def decode_batch(body: bytes):
    """Arrow IPC stream bytes -> pandas DataFrame."""
    import pyarrow as pa
    with pa.ipc.open_stream(io.BytesIO(body)) as r:
        return r.read_all().to_pandas()


def _pack_record(header: dict, body: bytes) -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode()
    crc = zlib.crc32(hdr)
    crc = zlib.crc32(body, crc)
    return _FRAME.pack(_MAGIC, len(hdr), len(body), crc) + hdr + body


class _Ticket:
    """One producer's frame waiting in the group-commit queue."""

    __slots__ = ("header", "body", "event", "error")

    def __init__(self, header: dict, body: bytes):
        self.header = header
        self.body = body
        self.event = threading.Event()
        self.error: Optional[BaseException] = None


class WriteAheadLog:
    """Append-only framed journal with crash-tolerant replay.

    Two write paths share the same framing and durability contract:

    - :meth:`append` — one record, one fsync (the original path).
    - :meth:`append_group` — the record joins a shared commit queue; one
      producer becomes the flush leader, writes every queued frame in
      enqueue order, and a SINGLE fsync covers the whole batch. The ACK
      (the call returning) is released only after the covering fsync, so
      ACK-implies-durable holds exactly as on the single path — the
      fsync cost is just amortized across concurrent producers.

    Torn-tail semantics are identical on both paths: a frame that fails
    mid-write (fault-injected cut, real I/O error) is truncated back out
    so the journal stays appendable, and only THAT producer's append
    fails; a covering fsync that fails rolls the whole un-durable group
    back and fails every producer in it (none were acked).
    """

    def __init__(self, path: str, fsync: bool = True, fault=None):
        self.path = path
        self.fsync = fsync
        self.fault = fault      # fault injector (docs/CHAOS.md) or None
        self._f = None
        # group commit state: _q_lock guards the pending queue, _io_lock
        # serializes every file mutation (group flush, single append,
        # truncate_through, repair) so a journal rewrite can never race
        # a half-written group. LOCK ORDER: _io_lock before _q_lock.
        self._q_lock = threading.Lock()
        self._io_lock = threading.RLock()
        self._pending: List[_Ticket] = []
        self.group_commits = 0      # covering fsyncs issued
        self.group_frames = 0       # frames those fsyncs covered

    # -- write ----------------------------------------------------------------
    def _file(self):
        if self._f is None or self._f.closed:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._f = open(self.path, "ab")
        return self._f

    def append(self, header: dict, body: bytes) -> None:
        """Write one record; on return (with fsync on) it is durable.

        A failed append SELF-HEALS: any exception mid-write truncates
        the file back to the pre-append offset, so a torn or corrupt
        record left by the failure cannot poison later appends (replay
        stops at the first bad record — garbage in the middle would
        silently drop every durable record after it)."""
        rec = _pack_record(header, body)
        inj = self.fault
        with self._io_lock:
            f = self._file()
            pos = f.seek(0, os.SEEK_END)    # append-mode tell() may lag
            try:
                if inj is not None:
                    # chaos sites: "wal.append" truncate/flip corrupts the
                    # record (a torn write — the append FAILS, the batch is
                    # never acked), "wal.fsync" raises a simulated I/O error
                    cut = inj.mutate("wal.append", rec, key=self.path)
                    if cut is not rec:
                        f.write(cut)
                        f.flush()
                        raise OSError("fault-injected torn WAL append")
                    f.write(rec)
                    f.flush()
                    inj.fire("wal.fsync", key=self.path)
                else:
                    f.write(rec)
                    f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            except BaseException:
                # roll the partial record back so the journal stays
                # appendable
                try:
                    f.truncate(pos)
                    f.flush()
                    if self.fsync:
                        os.fsync(f.fileno())
                except OSError:
                    pass    # repair() at next recovery trims it instead
                raise

    # -- group commit ---------------------------------------------------------
    def enqueue(self, header: dict, body: bytes) -> _Ticket:
        """Stage one record on the shared commit queue and return its
        ticket (no blocking, no I/O). Enqueue order is preserved on
        disk, so callers that assign sequence numbers under their own
        lock and enqueue before releasing it get seq-ordered journals
        for free — and by the time any later-enqueued ticket resolves,
        every earlier ticket has resolved too (the leader drains the
        queue in order and settles a whole batch before releasing the
        io lock), which is what lets the persist manager excise a
        failed frame's build from the in-memory append chain before a
        successor registers on top of it."""
        t = _Ticket(header, body)
        with self._q_lock:
            self._pending.append(t)
        return t

    def commit(self, t: _Ticket) -> None:
        """Block until ``t``'s covering fsync made it durable, or raise
        its failure (an error means NOT acked, exactly like
        :meth:`append`)."""
        while not t.event.is_set():
            # leader election: whoever gets the io lock drains the queue
            # and commits the batch. A producer whose frame was covered
            # by a previous leader's fsync just wakes and returns.
            acquired = self._io_lock.acquire(timeout=0.02)
            if not acquired:
                continue
            try:
                if t.event.is_set():
                    break
                with self._q_lock:
                    batch, self._pending = self._pending, []
                if batch:
                    self._write_group(batch)
            finally:
                self._io_lock.release()
        if t.error is not None:
            raise t.error

    def append_group(self, header: dict, body: bytes) -> None:
        """:meth:`enqueue` + :meth:`commit` in one call, for callers
        with no ordering stake of their own."""
        self.commit(self.enqueue(header, body))

    def _write_group(self, batch: List[_Ticket]) -> None:
        """Write every frame in ``batch``, then one covering fsync.
        Called with the io lock held. Never raises: outcomes are
        delivered per-ticket. A frame that fails mid-write is truncated
        back out (that producer alone fails, the group continues); a
        failing covering fsync rolls the whole un-durable suffix back
        and fails every producer whose frame it covered."""
        inj = self.fault
        try:
            f = self._file()
            group_start = f.seek(0, os.SEEK_END)
        except OSError as e:
            for t in batch:
                t.error = e
                t.event.set()
            return
        pos = group_start
        wrote: List[_Ticket] = []
        for t in batch:
            rec = _pack_record(t.header, t.body)
            try:
                if inj is not None:
                    # same per-frame chaos semantics as append(): a
                    # mutate rule tears THIS frame only
                    cut = inj.mutate("wal.append", rec, key=self.path)
                    if cut is not rec:
                        f.write(cut)
                        f.flush()
                        raise OSError("fault-injected torn WAL append")
                f.write(rec)
            except BaseException as e:  # noqa: BLE001 — per-ticket fate
                try:
                    f.flush()
                    f.truncate(pos)
                    f.flush()
                except OSError:
                    pass    # repair() at next recovery trims it
                t.error = e
                t.event.set()
                continue
            pos += len(rec)
            wrote.append(t)
        try:
            f.flush()
            if inj is not None and wrote:
                # chaos sites: "wal.group_commit" models the covering
                # fsync failing (the WHOLE batch is un-acked and rolled
                # back), "wal.fsync" keeps its single-path meaning
                inj.fire("wal.group_commit", key=self.path)
                inj.fire("wal.fsync", key=self.path)
            if self.fsync:
                os.fsync(f.fileno())
        except BaseException as e:  # noqa: BLE001 — per-ticket fate
            # nothing past group_start is durable: roll it all back so
            # the journal stays appendable, and fail every producer (no
            # ACK was released, so ACK-implies-durable holds)
            try:
                f.truncate(group_start)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            except OSError:
                pass
            for t in wrote:
                t.error = e
                t.event.set()
            return
        self.group_commits += 1
        self.group_frames += len(wrote)
        for t in wrote:
            t.event.set()

    def close(self) -> None:
        with self._io_lock:
            if self._f is not None and not self._f.closed:
                self._f.close()

    # -- read -----------------------------------------------------------------
    def replay(self) -> Iterator[Tuple[dict, bytes]]:
        """Yield (header, body) for every INTACT record, stopping at the
        first torn/corrupt one (crash tail). Missing file = no records."""
        if not os.path.exists(self.path):
            return
        end = self.size_bytes()
        with open(self.path, "rb") as f:
            while True:
                frame = f.read(_FRAME.size)
                if len(frame) < _FRAME.size:
                    return                      # clean EOF or torn frame
                magic, hlen, blen, crc = _FRAME.unpack(frame)
                if magic != _MAGIC:
                    return                      # corrupt frame boundary
                if hlen + blen > end - f.tell():
                    # lengths from a torn frame can be garbage: bound by
                    # the actual file size before allocating the read
                    return
                hdr = f.read(hlen)
                body = f.read(blen)
                if len(hdr) < hlen or len(body) < blen:
                    return                      # torn tail
                c = zlib.crc32(hdr)
                if zlib.crc32(body, c) != crc:
                    return                      # bit rot / torn overwrite
                try:
                    header = json.loads(hdr.decode())
                except ValueError:
                    return
                yield header, body

    def records(self) -> List[Tuple[dict, bytes]]:
        return list(self.replay())

    def repair(self) -> int:
        """Trim a torn/corrupt tail left by a crash mid-append, so the
        journal is appendable again (a live append after un-trimmed
        garbage would be unreachable to replay). Returns bytes trimmed.
        Called at recovery, before any new appends."""
        if not os.path.exists(self.path):
            return 0
        end = self.size_bytes()
        good = 0
        with open(self.path, "rb") as f:
            while True:
                frame = f.read(_FRAME.size)
                if len(frame) < _FRAME.size:
                    break
                magic, hlen, blen, crc = _FRAME.unpack(frame)
                if magic != _MAGIC:
                    break
                if hlen + blen > end - f.tell():
                    break       # garbage lengths from a torn frame
                hdr = f.read(hlen)
                body = f.read(blen)
                if len(hdr) < hlen or len(body) < blen:
                    break
                c = zlib.crc32(hdr)
                if zlib.crc32(body, c) != crc:
                    break
                good += _FRAME.size + hlen + blen
        torn = self.size_bytes() - good
        if torn > 0:
            with self._io_lock:
                self.close()
                with open(self.path, "r+b") as f:
                    f.truncate(good)
                    f.flush()
                    os.fsync(f.fileno())
        return max(0, torn)

    # -- maintenance ----------------------------------------------------------
    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def truncate_through(self, seq: int) -> None:
        """Drop every intact record with ``header['seq'] <= seq`` (they
        are folded into a published snapshot) by atomically rewriting the
        journal with the surviving tail. The torn tail (if any) is
        discarded too — it was never committed."""
        # the io lock excludes an in-flight group flush: a rewrite under
        # a half-committed group would orphan its frames in the replaced
        # file (acked data lost through a dead fd)
        with self._io_lock:
            keep = [(h, b) for h, b in self.replay()
                    if int(h.get("seq", 0)) > seq]
            self.close()
            if not keep:
                try:
                    os.remove(self.path)
                except OSError:
                    pass
                return
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                for header, body in keep:
                    f.write(_pack_record(header, body))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            # the rewritten journal replaces records a snapshot already
            # owns; if the rename itself is lost on crash, replay
            # re-applies them — harmless for idempotent restores but the
            # dir entry must still be durable before the caller drops
            # the covering snapshot refs
            _fsync_dir(os.path.dirname(self.path) or ".")

    def last_seq(self) -> Optional[int]:
        last = None
        for h, _ in self.replay():
            last = int(h.get("seq", 0))
        return last
