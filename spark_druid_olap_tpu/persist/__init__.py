"""Durable segment persistence: deep storage, ingest WAL, crash recovery.

The in-tree replacement for the durability tier the reference delegates
to Druid (deep storage + segment publish/handoff + metadata store):

- :mod:`spark_druid_olap_tpu.persist.snapshot` — versioned on-disk
  snapshot format (per-column binary blobs + JSON manifest with schema,
  segment map, ingest version, per-file CRC32 checksums), published via
  atomic temp-dir + rename.
- :mod:`spark_druid_olap_tpu.persist.wal` — framed, checksummed
  write-ahead journal for ``stream_ingest`` appends (commit point =
  journal fsync), torn-tail tolerant replay.
- :mod:`spark_druid_olap_tpu.persist.manager` — checkpoint / recovery
  orchestration: background checkpointer, catalog + rollup-registry +
  ingest-version restore, corrupt-snapshot quarantine, history-driven
  warmup ordering.

Configured by the ``sdot.persist.*`` family (utils/config.py); disabled
entirely when ``sdot.persist.path`` is empty.
"""

from spark_druid_olap_tpu.persist.manager import PersistManager  # noqa: F401
