"""Background compaction: roll a stream-appended tail into
time-partitioned segments.

Streaming appends leave a datasource as many small realtime segments
(one or more per batch) whose time ranges interleave — correct, but scan
pruning degrades and per-segment overheads pile up, exactly the problem
Druid solves with its compaction tasks. The compactor rebuilds the
datasource at the COLUMN level: one stable argsort over the time column,
every dim/metric column permuted by it (dictionaries are already global
and sorted — the order-preserving append invariant — so codes permute
untouched), and fresh segment boundaries cut every ``target_rows`` rows.
The result holds bit-identical rows to the input, just globally
time-sorted and evenly partitioned.

Generation swap protocol (the crash-safety contract):

1. build the compacted Datasource value (outside any lock — racing
   appends are detected, not blocked);
2. publish it as a NEW snapshot version through the standard
   tmp + fsync + os.replace + dir-fsync discipline
   (persist/snapshot.py) — a crash at any instant leaves either the old
   or the new generation fully readable under ``CURRENT``, never both,
   never a torn one;
3. truncate the WAL records the new generation covers (only AFTER the
   publish is durable — sdlint ordering rules O4/O5 machine-check this
   file);
4. swap the in-memory value QUIETLY: same rows, same ingest version, so
   result caches stay valid and rollup staleness does not move — a
   rollup fresh before the swap is fresh after it, a stale one stays
   stale (the version-counter contract in persist/manager.py).

A live ``stream_ingest`` racing the build wins: the commit phase
re-checks the datasource identity + ingest version under the build lock
and retries the whole build against the new tail (bounded attempts; the
background cadence picks it up again later).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from spark_druid_olap_tpu.persist import snapshot as SNAP
from spark_druid_olap_tpu.segment.column import (DimColumn, MetricColumn,
                                                 TimeColumn)
from spark_druid_olap_tpu.segment.store import Datasource, Segment

_MS_PER_DAY = 86_400_000


def rebuild_time_partitioned(ds: Datasource,
                             target_rows: int = 1 << 20) -> Datasource:
    """A new Datasource with the same rows globally time-sorted and cut
    into segments of ``target_rows``. Pure value-level transform: ``ds``
    is untouched (immutable-columns contract)."""
    n = ds.num_rows
    if ds.time is not None:
        millis = (ds.time.days.astype(np.int64) * _MS_PER_DAY
                  + ds.time.ms_in_day.astype(np.int64))
        order = np.argsort(millis, kind="stable")
        identity = bool(np.array_equal(order, np.arange(n)))
    else:
        millis = np.zeros(n, dtype=np.int64)
        order = None
        identity = True

    def take(a):
        if a is None or identity:
            return a
        return a[order]

    time_col = None
    if ds.time is not None:
        time_col = TimeColumn(name=ds.time.name,
                              days=take(ds.time.days),
                              ms_in_day=take(ds.time.ms_in_day))
    dims = {k: DimColumn(name=d.name, dictionary=d.dictionary,
                         codes=take(d.codes), validity=take(d.validity))
            for k, d in ds.dims.items()}
    mets = {k: MetricColumn(name=m.name, values=take(m.values),
                            validity=take(m.validity), kind=m.kind)
            for k, m in ds.metrics.items()}
    if not identity:
        millis = millis[order]

    segments = []
    n_seg = max(1, -(-n // max(1, int(target_rows))))
    per = -(-n // n_seg) if n else 0
    for i in range(n_seg):
        s, e = i * per, min((i + 1) * per, n)
        if s >= e:
            break
        segments.append(Segment(
            id=f"{ds.name}_{i:05d}", start_row=s, end_row=e,
            min_millis=int(millis[s]), max_millis=int(millis[e - 1])))
    return Datasource(name=ds.name, time=time_col, dims=dims,
                      metrics=mets, segments=segments,
                      spatial=dict(ds.spatial))


def compact_datasource(manager, name: str, *,
                       target_rows: Optional[int] = None,
                       force: bool = False,
                       retries: int = 3) -> Optional[dict]:
    """Compact one datasource and atomically swap the new generation in.
    Returns a summary dict, or None when skipped (below the segment
    floor, partial, unknown, or starved out by live appends)."""
    store = manager.ctx.store
    if target_rows is None:
        from spark_druid_olap_tpu.utils.config import SEGMENT_ROWS
        target_rows = int(manager.ctx.config.get(SEGMENT_ROWS))
    for _ in range(max(1, retries)):
        with manager._ds_lock(name):
            ds = store._datasources.get(name)
            if ds is None or getattr(ds, "is_partial", False):
                return None
            if not force \
                    and len(ds.segments) < manager.compact_min_segments:
                return None
            if len(ds.segments) <= 1 or ds.num_rows == 0:
                return None     # nothing to roll up
            iv = store.datasource_version(name)
            src = ds
            if getattr(src, "tier", None) is not None:
                # same materialize-first doctrine as appends: the
                # rebuild reads every column, so fault the datasource
                # hot once instead of chunk-thrashing the cold tier
                src = src.materialize()
        # -- build outside the lock: live producers keep streaming --------
        new_ds = rebuild_time_partitioned(src, target_rows=target_rows)
        with manager._ds_lock(name):
            if store._datasources.get(name) is not ds \
                    or store.datasource_version(name) != iv \
                    or name in manager._tail_ds:
                # an append won the race (or its chain is still waiting
                # on a covering fsync) — swapping the base under an
                # in-flight chain could drop its rows from a later
                # build, so rebuild against the new tail instead
                continue
            return _publish_generation(manager, name, ds, new_ds, iv)
    return None


def _publish_generation(manager, name: str, old_ds, new_ds,
                        ingest_version: int) -> dict:
    """Commit phase. Caller holds the datasource build lock, so the
    registered state cannot move under us; the manager lock covers the
    shared bookkeeping."""
    with manager.lock:
        covered = manager._covered_seq(name)
        inj = manager.fault
        if inj is not None:
            # chaos site: a publish-time failure (disk full / fsync
            # error mid-swap). Fired BEFORE the swap starts, and
            # write_snapshot itself cleans up its tmp dir on failure —
            # either way the old generation stays fully readable and
            # the WAL is untouched.
            inj.fire("compact.publish", key=name)
        manifest = SNAP.write_snapshot(
            manager._ds_root(name), new_ds, ingest_version, covered,
            keep=manager.keep, encode=manager.encode)
        # the new generation is durable — only now may the journal
        # records it covers go (a crash here replays nothing onto it;
        # a crash before the replace recovers the old generation + WAL)
        manager._wal_for(name).truncate_through(covered)
        # quiet in-memory swap: identical rows under the SAME ingest
        # version — result caches stay valid and rollup staleness does
        # not move (store.restore pins the version; no register event,
        # no dirty mark)
        manager.ctx.store.restore(new_ds, ingest_version)
        manager._dirty.discard(name)
        if manager.tier is not None:
            manager.tier.drop_datasource(name)
        manager.counters["compactions"] += 1
        manager.counters["compacted_segments"] += max(
            0, len(old_ds.segments) - len(new_ds.segments))
        return {"datasource": name, "version": ingest_version,
                "segments_before": len(old_ds.segments),
                "segments_after": len(new_ds.segments),
                "rows": int(manifest["num_rows"]),
                "bytes": int(manifest["bytes"]),
                "snapshot_version": int(manifest["snapshot_version"])}
