"""Checkpoint / recovery orchestration for the persist subsystem.

One :class:`PersistManager` per Context (created when
``sdot.persist.path`` is set). It owns:

- **Durable stream ingest**: ``Context.stream_ingest`` routes here; the
  new Datasource value is BUILT first (which fully validates the batch —
  a rejected batch is never journaled), then the batch is journaled (WAL
  append + fsync = commit point), then the store registers it, so a
  ``kill -9`` at any instant loses at most the batch whose commit was
  never acknowledged — and a rejected batch can never poison replay of
  the committed ones behind it.
- **Checkpoints**: fold a datasource's current in-memory state into a
  published snapshot (persist/snapshot.py) and truncate the WAL records
  the snapshot now covers. Explicit (``CHECKPOINT`` SQL /
  ``Context.checkpoint()``) or via the background checkpointer
  (``sdot.persist.checkpoint.interval.seconds`` cadence,
  ``sdot.persist.checkpoint.max.bytes`` per-pass byte budget).
- **Recovery**: at Context creation (``sdot.persist.recover.on.start``),
  reload snapshots in history-driven warmup order (most recently queried
  first), verify checksums (quarantining corrupt versions and falling
  back to older ones), replay each WAL tail, and restore the catalog:
  star schemas, lookups, rollup definitions, and — critically — the
  per-datasource *ingest-version counters*, so result-cache invalidation
  and rollup staleness semantics are exactly what they were before the
  crash (a rollup stale at kill time is still stale, and bypassed, after
  recovery).

Version-restore contract: ``SegmentStore.restore`` pins the recovered
datasource's ingest version to the manifest's value and advances the
global counter to at least it; WAL-replayed appends then bump versions
normally. Consequences: (a) a rollup whose ``built_version`` equals the
manifest version is fresh again after recovery iff no later append
exists; (b) any WAL tail on the base makes it stale — never served.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

import pandas as pd

from spark_druid_olap_tpu.persist import snapshot as SNAP
from spark_druid_olap_tpu.persist import wal as WAL

CATALOG_FILE = "catalog.json"


def _ds_bytes(ds) -> int:
    # footprint accessors, not raw .nbytes: sizing a TIERED datasource
    # through the array properties would fault every column hot
    total = 0
    if ds.time is not None:
        total += ds.time.footprint_nbytes()
    for d in ds.dims.values():
        total += d.footprint_nbytes()
    for m in ds.metrics.values():
        total += m.footprint_nbytes()
    return total


class PersistManager:
    def __init__(self, ctx, root: str):
        from spark_druid_olap_tpu.utils.config import (
            PERSIST_APPEND_PARALLEL,
            PERSIST_CHECKPOINT_MAX_BYTES,
            PERSIST_CHECKPOINT_SECONDS,
            PERSIST_COMPACT_MIN_SEGMENTS,
            PERSIST_COMPACT_SECONDS,
            PERSIST_GROUP_COMMIT,
            PERSIST_KEEP_SNAPSHOTS,
            PERSIST_VERIFY_CHECKSUMS,
            PERSIST_WAL_FSYNC,
        )
        self.ctx = ctx
        self.root = os.path.abspath(root)
        # fault injector (fault/, docs/CHAOS.md): threaded into every
        # WAL, the snapshot publish path, and the cold tier below
        self.fault = getattr(ctx.engine, "fault", None)
        os.makedirs(self.root, exist_ok=True)
        # LOCK ORDER: a per-datasource build lock (serializing the
        # order-preserving append chain) comes BEFORE this manager lock,
        # which comes BEFORE QueryHistory._lock (docs/LINT.md; checkpoint
        # paths read the session query history under this lock). History
        # code must never call into persist, and nothing may acquire a
        # ds build lock while holding this lock.
        self.lock = threading.RLock()
        cfg = ctx.config
        self.wal_fsync = bool(cfg.get(PERSIST_WAL_FSYNC))
        self.keep = int(cfg.get(PERSIST_KEEP_SNAPSHOTS))
        self.verify = bool(cfg.get(PERSIST_VERIFY_CHECKSUMS))
        self.interval_s = float(cfg.get(PERSIST_CHECKPOINT_SECONDS))
        self.pass_budget = int(cfg.get(PERSIST_CHECKPOINT_MAX_BYTES))
        self.group_commit = bool(cfg.get(PERSIST_GROUP_COMMIT))
        self.append_parallel = bool(cfg.get(PERSIST_APPEND_PARALLEL))
        self.compact_interval_s = float(cfg.get(PERSIST_COMPACT_SECONDS))
        self.compact_min_segments = int(
            cfg.get(PERSIST_COMPACT_MIN_SEGMENTS))
        # checkpoint-time columnar encoding policy (sdot.encode.*),
        # resolved once here and threaded through every write_snapshot —
        # checkpoint and compaction publish with the same policy, WAL
        # tails stay raw rows by construction (the journal never goes
        # through the snapshot writer)
        from spark_druid_olap_tpu.encode.chooser import EncodeOptions
        self.encode = EncodeOptions.from_config(cfg)
        self._wals: Dict[str, WAL.WriteAheadLog] = {}
        self._wal_seq: Dict[str, int] = {}      # last seq ASSIGNED, per ds
        self._reg_seq: Dict[str, int] = {}      # last seq REGISTERED, per ds
        # name -> newest built-but-not-yet-registered Datasource value:
        # the base the next concurrent producer's append builds on, so
        # the order-preserving chain survives the build lock being
        # released before the covering group fsync lands
        self._tail_ds: Dict[str, object] = {}
        # name -> in-flight build chain, seq order: every entry is a
        # built-but-unregistered batch ({seq, ds, df, kwargs, ticket}).
        # Kept so a frame that FAILS its commit (torn write, failed
        # covering fsync) can be excised and its successors' builds —
        # which chained on the rejected rows — rebuilt before any of
        # them registers: rows never become queryable unless their
        # journal record is durable (guarded by the ds build lock)
        self._tail_chain: Dict[str, list] = {}
        self._ds_locks: Dict[str, threading.RLock] = {}
        self._dirty = set()                     # names needing a checkpoint
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_compact = 0.0
        self.counters = {"checkpoints": 0, "checkpoint_bytes": 0,
                         "wal_appends": 0, "wal_replayed": 0,
                         "wal_repaired": 0, "wal_repaired_bytes": 0,
                         "compactions": 0, "compacted_segments": 0,
                         "quarantined": 0, "errors": 0}
        self.recovery_report: Optional[dict] = None
        # out-of-core tiered storage: when enabled, recovery hands back
        # TieredDatasources whose columns fault from the snapshot blobs
        # through this byte-budgeted hot set (tier/store.py)
        from spark_druid_olap_tpu.utils.config import (
            TIER_BUDGET_BYTES, TIER_DECODED_CACHE_BYTES, TIER_ENABLED,
            TIER_PREFETCH_ENABLED, TIER_PREFETCH_THREADS,
            TIER_VERIFY_CHECKSUMS)
        self.tier = None
        if bool(cfg.get(TIER_ENABLED)):
            from spark_druid_olap_tpu.tier.store import TieredColumnStore
            self.tier = TieredColumnStore(
                int(cfg.get(TIER_BUDGET_BYTES)),
                verify=bool(cfg.get(TIER_VERIFY_CHECKSUMS)),
                popularity=self._tier_popularity,
                on_corrupt=self._on_tier_corrupt,
                decoded_budget=int(cfg.get(TIER_DECODED_CACHE_BYTES)))
            # .fault on the tier store is the demand-fault METHOD, so
            # the injector rides a different name there
            self.tier.chaos = self.fault
            if bool(cfg.get(TIER_PREFETCH_ENABLED)):
                self.tier.start_prefetcher(
                    int(cfg.get(TIER_PREFETCH_THREADS)))
        ctx.store.add_listener(self._on_store_event)

    # -- paths ----------------------------------------------------------------
    def _ds_root(self, name: str) -> str:
        return os.path.join(self.root, SNAP.sanitize(name))

    def _wal_for(self, name: str) -> WAL.WriteAheadLog:
        with self.lock:
            w = self._wals.get(name)
            if w is None:
                w = self._wals[name] = WAL.WriteAheadLog(
                    os.path.join(self._ds_root(name), "wal.log"),
                    fsync=self.wal_fsync, fault=self.fault)
            return w

    def _ds_lock(self, name: str) -> threading.RLock:
        """Per-datasource build lock (acquired BEFORE self.lock). It
        serializes the order-preserving append chain and the checkpoint/
        compact commit phases for one datasource without stalling
        producers on every other datasource."""
        with self.lock:
            lk = self._ds_locks.get(name)
            if lk is None:
                lk = self._ds_locks[name] = threading.RLock()
            return lk

    def _next_seq(self, name: str) -> int:
        seq = self._wal_seq.get(name)
        if seq is None:
            seq = self._wal_for(name).last_seq() or 0
            root = self._ds_root(name)
            cur = SNAP.current_version(root)
            if cur is not None:
                try:
                    seq = max(seq, int(SNAP.load_manifest(
                        root, cur).get("wal_seq", 0)))
                except (OSError, ValueError):
                    pass
            # everything journaled before this session's first append is
            # already folded into whatever state checkpoint would
            # snapshot — it is the registered watermark, NOT the
            # in-flight appends about to be assigned seqs past it
            self._reg_seq.setdefault(name, seq)
        seq += 1
        self._wal_seq[name] = seq
        return seq

    # -- store events ---------------------------------------------------------
    def _on_store_event(self, event: str, name: Optional[str]) -> None:
        # register (ingest / append / replay) marks dirty for the
        # background checkpointer; restore comes FROM disk and is clean
        if event == "register":
            self._dirty.add(name)
        elif event == "drop":
            self._dirty.discard(name)
            self._wal_seq.pop(name, None)
            self._reg_seq.pop(name, None)
            self._tail_ds.pop(name, None)
            self._tail_chain.pop(name, None)
            if self.tier is not None:
                self.tier.drop_datasource(name)
        elif event == "clear":
            self._dirty.clear()
            self._wal_seq.clear()
            self._reg_seq.clear()
            self._tail_ds.clear()
            self._tail_chain.clear()
            if self.tier is not None:
                self.tier.clear()

    # -- tier callbacks -------------------------------------------------------
    def _tier_popularity(self, ds_name: str, column: str) -> float:
        """Eviction score for one hot chunk's column: the session query
        history's per-column hit count (metadata/history.py). Called
        under the tier lock; QueryHistory never calls back into tier or
        persist, so the order tier.lock -> history.lock is safe."""
        hist = getattr(self.ctx, "history", None)
        if hist is None:
            return 0.0
        return hist.column_score(ds_name, column)

    def _on_tier_corrupt(self, ds_name: str, version_dir: str,
                         reason: str) -> None:
        """First-fault CRC mismatch on a cold blob: quarantine that
        snapshot version and re-recover the datasource from an older one
        (or the WAL alone) — the exact PERSIST recovery semantics, just
        triggered lazily. The faulting query still fails with
        SnapshotCorrupt; the NEXT query sees the fallback store. Invoked
        by the tier OUTSIDE its lock (docs/LINT.md lock order:
        PersistManager.lock before QueryHistory._lock; the tier lock is
        never held across this call)."""
        with self.lock:
            dirpath = os.path.dirname(os.path.abspath(version_dir))
            base = os.path.basename(version_dir)
            try:
                version = int(base.lstrip("v"))
            except ValueError:
                return
            qpath = SNAP.quarantine_version(dirpath, version)
            if qpath is None:
                return          # another fault already quarantined it
            self.counters["quarantined"] += 1
            if self.tier is not None:
                self.tier.drop_datasource(ds_name)
            # re-recover whichever datasource lives in that directory
            # (ds_name may be a shard namespace; the directory maps to
            # the parent datasource on disk)
            name = None
            for n, p in self._ds_dirs().items():
                if os.path.abspath(p) == dirpath:
                    name = n
                    break
            report = {"datasources": [], "quarantined": [
                {"datasource": name or ds_name, "version": version,
                 "reason": reason, "moved_to": qpath}], "errors": []}
            if name is not None:
                if self.tier is not None and name != ds_name:
                    self.tier.drop_datasource(name)
                info = self._recover_datasource(name, dirpath, report)
                recovery_info = dict(
                    getattr(self.ctx.store, "recovery_info", {}) or {})
                if info is not None:
                    recovery_info[name] = info
                self.ctx.store.recovery_info = recovery_info
            prev = self.recovery_report
            if prev is not None:
                prev.setdefault("quarantined", []).extend(
                    report["quarantined"])
                prev.setdefault("errors", []).extend(report["errors"])
            else:
                self.recovery_report = report

    # -- durable stream ingest ------------------------------------------------
    def stream_ingest(self, name: str, df: pd.DataFrame,
                      kwargs: dict):
        """Durable append, safe for concurrent producers.

        The per-datasource build lock is held only for the build + seq
        assignment + enqueue; the covering group fsync is awaited
        OUTSIDE it, so concurrent producers on one datasource share a
        single fsync (persist/wal.py group commit) instead of paying one
        each. Ordering survives the split: seqs are assigned and frames
        enqueued under the build lock (journal order == seq order), each
        build chains on the newest built tail (``_tail_chain``), and
        registration is monotone by seq — a later batch's Datasource is
        a superset of every earlier one's, so the highest-seq register
        wins and earlier producers just ACK.

        Failure resolution: a frame that fails its commit (torn write,
        failed covering fsync) is excised from the chain and every
        successor build — which chained on the rejected rows — is
        rebuilt from its surviving base before anything registers
        (``_excise_failed``). The WAL resolves tickets in enqueue
        order, so whichever producer reaches the lock first (the
        failed one's except path or a successor's ACK path) sees the
        failure and repairs the chain; no build containing un-durable
        rows can ever become queryable. With group commit OFF the
        append runs synchronously under the build lock (the original
        one-fsync-per-append path) and failure rollback is immediate.
        """
        from spark_druid_olap_tpu.segment.append import (
            append_dataframe, wal_kwargs_to_dict)
        from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
        store = self.ctx.store
        dslock = self._ds_lock(name)
        with dslock:
            existing = store._datasources.get(name)
            if existing is not None and len(df) == 0:
                return existing     # no-op: nothing to journal or apply
            base = self._tail_ds.get(name)
            if base is None:
                base = existing
                if base is None:
                    # new incarnation of this name: any on-disk state
                    # belongs to a previous one (dropped / cleared
                    # without PURGE) and recovery must never merge the
                    # two, so fence the old snapshot + WAL aside before
                    # journaling the create
                    self._fence_stale_state(name)
                elif SNAP.current_version(self._ds_root(name)) is None:
                    # first append to a datasource that was batch-
                    # ingested in memory only: a WAL replay needs a base
                    # to append onto, so publish one synchronously
                    # before journaling
                    self.checkpoint(name)
                if base is not None \
                        and getattr(base, "tier", None) is not None:
                    # appends mutate column arrays (dataclasses.replace
                    # + concatenate) — swap the tiered store for an
                    # eager copy first. Quiet swap: no version bump, no
                    # store events; the register below marks dirty as
                    # usual.
                    base = base.materialize()
                    store._datasources[name] = base
                    self.tier.drop_datasource(name)
            kind = "create" if base is None else "append"
            # Build the new Datasource value BEFORE journaling: the WAL
            # append is the commit point, and a batch the build rejects
            # (unknown column, missing time column, bad dtype) must never
            # be journaled — a journaled reject would deterministically
            # fail again on every replay, shadowing later committed
            # batches behind it.
            if base is None:
                new_ds = ingest_dataframe(name, df, **kwargs)
            else:
                new_ds = append_dataframe(
                    base, df,
                    target_rows=int(kwargs.get("target_rows")
                                    or (1 << 20)),
                    parallel=self.append_parallel)
            seq = self._next_seq(name)
            header = {"seq": seq, "datasource": name, "kind": kind,
                      "kwargs": wal_kwargs_to_dict(kwargs)}
            body = WAL.encode_batch(df)
            wal = self._wal_for(name)
            entry = {"seq": seq, "ds": new_ds, "df": df,
                     "kwargs": dict(kwargs), "ticket": None}
            self._tail_chain.setdefault(name, []).append(entry)
            self._tail_ds[name] = new_ds
            if not self.group_commit:
                # legacy path: one fsync per append, committed under
                # the build lock — serialized, so the chain is just
                # this entry and rollback is a pop
                try:
                    wal.append(header, body)
                except BaseException:
                    self._set_chain(name,
                                    self._tail_chain[name][:-1])
                    raise
                with self.lock:
                    self.counters["wal_appends"] += 1
                return self._register_through(name, seq)
            # enqueue while still holding the build lock: journal
            # order == seq order, and ticket resolution order follows
            entry["ticket"] = wal.enqueue(header, body)
        # -- commit point: outside the build lock so the fsync can cover
        # every frame concurrent producers queued meanwhile ------------------
        try:
            wal.commit(entry["ticket"])
        except BaseException:
            with dslock:
                self._excise_failed(name)
            raise
        with dslock:
            with self.lock:
                self.counters["wal_appends"] += 1
            # my ACK implies every earlier-enqueued frame has resolved:
            # drop any that failed (rebuilding their successors) before
            # registering, so torn rows never become queryable
            self._excise_failed(name)
            return self._register_through(name, seq)

    def _set_chain(self, name: str, entries: list) -> None:
        """Install the in-flight build chain for ``name`` (build lock
        held), keeping the newest-tail shortcut in lockstep."""
        if entries:
            self._tail_chain[name] = entries
            self._tail_ds[name] = entries[-1]["ds"]
        else:
            self._tail_chain.pop(name, None)
            self._tail_ds.pop(name, None)

    def _register_through(self, name: str, seq: int):
        """Register the chain entry carrying ``seq`` and drop every
        entry it covers (build lock held). Absent entry = a later
        producer's ACK already registered a superset and removed it —
        the rows are servable and durable, nothing to do."""
        chain = self._tail_chain.get(name) or []
        mine = next((e for e in chain if e["seq"] == seq), None)
        if mine is None:
            return self.ctx.store._datasources.get(name)
        if seq > self._reg_seq.get(name, -1):
            self._reg_seq[name] = seq
            self.ctx.store.register(mine["ds"])
        self._set_chain(name,
                        [e for e in chain if e["seq"] > seq])
        return mine["ds"]

    def _excise_failed(self, name: str) -> None:
        """Drop every chain entry whose commit FAILED (ticket resolved
        with an error) and rebuild the builds downstream of the first
        casualty — they chained on the rejected rows (build lock held).
        Rebuilds replay the surviving entries' own DataFrames in seq
        order from the last intact base, exactly what WAL replay does
        at recovery, so the live state and the journal stay one."""
        from spark_druid_olap_tpu.segment.append import append_dataframe
        from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
        chain = self._tail_chain.get(name) or []
        dead = {i for i, e in enumerate(chain)
                if e["ticket"] is not None and e["ticket"].event.is_set()
                and e["ticket"].error is not None}
        if not dead:
            return
        out, dirty = [], False
        cur = self.ctx.store._datasources.get(name)
        for i, e in enumerate(chain):
            if i in dead:
                dirty = True
                continue
            if not dirty:            # upstream of every failure: intact
                out.append(e)
                cur = e["ds"]
                continue
            if cur is None:
                # the journaled 'create' itself was rejected; replay
                # treats the first surviving append as the create
                # (segment/append.py apply_stream_ingest), so do the same
                e["ds"] = ingest_dataframe(name, e["df"], **e["kwargs"])
            else:
                e["ds"] = append_dataframe(
                    cur, e["df"],
                    target_rows=int(e["kwargs"].get("target_rows")
                                    or (1 << 20)),
                    parallel=self.append_parallel)
            cur = e["ds"]
            out.append(e)
        self._set_chain(name, out)

    def _fence_stale_state(self, name: str) -> None:
        """Move a previous incarnation's on-disk snapshot/WAL aside
        (under a dotted name recovery ignores — kept, not deleted, so an
        operator can still inspect it). Without the fence, a re-created
        datasource's 'create' record lands in the OLD journal with a seq
        past the stale snapshot's watermark, and recovery appends the
        new data onto the dropped incarnation's rows."""
        p = self._ds_root(name)
        if not os.path.isdir(p):
            return
        w = self._wals.pop(name, None)
        if w is not None:
            w.close()
        self._wal_seq.pop(name, None)
        self._reg_seq.pop(name, None)
        self._tail_ds.pop(name, None)
        self._tail_chain.pop(name, None)
        base = os.path.join(
            self.root,
            f".dropped-{int(time.time())}-{os.path.basename(p)}")
        dst, i = base, 0
        while os.path.exists(dst):
            i += 1
            dst = f"{base}.{i}"
        try:
            os.replace(p, dst)
            # the fence must survive a crash: if the rename is lost,
            # recovery resurrects the dropped incarnation and the new
            # create lands in its journal
            SNAP.fsync_dir(self.root)
        except OSError:
            shutil.rmtree(p, ignore_errors=True)

    # -- checkpoint -----------------------------------------------------------
    def _covered_seq(self, name: str) -> int:
        """Highest WAL seq the REGISTERED state reflects — the watermark
        a snapshot of that state may truncate through. Never the
        allocation watermark (``_wal_seq``): a seq assigned to an
        in-flight producer whose frame/register hasn't landed yet is NOT
        covered, and truncating through it would drop an acked batch.
        Callers already hold ``self.lock``; taken again (RLock) so the
        watermark read-modify-write is guarded in its own right."""
        with self.lock:
            seq = self._reg_seq.get(name)
            if seq is not None:
                return seq
            if name in self._wal_seq:
                # seqs were assigned this session but none registered:
                # only the pre-session journal (folded in at _next_seq
                # init) is covered — and that init seeded _reg_seq, so
                # reaching here means nothing is
                return 0
            seq = self._wal_for(name).last_seq() or 0
            self._reg_seq[name] = seq
            return seq

    def checkpoint(self, name: str) -> dict:
        """Publish one datasource's current state; returns a summary."""
        with self._ds_lock(name):
            with self.lock:
                ds = self.ctx.store.get(name)
                ds.require_complete("checkpoint")
                iv = self.ctx.store.datasource_version(name)
                wal_seq = self._covered_seq(name)
                if self.fault is not None:
                    # chaos site: a publish-time I/O error (fsync
                    # failure, disk full). The WAL is untouched, so
                    # nothing is lost — the datasource just stays dirty
                    # for the next pass.
                    self.fault.fire("snapshot.write", key=name)
                manifest = SNAP.write_snapshot(
                    self._ds_root(name), ds, iv, wal_seq, keep=self.keep,
                    encode=self.encode)
                # snapshot covers every journaled record at or below the
                # registered watermark — drop them (in-flight frames
                # past it survive the rewrite)
                self._wal_for(name).truncate_through(wal_seq)
                self._dirty.discard(name)
                self.counters["checkpoints"] += 1
                self.counters["checkpoint_bytes"] += int(
                    manifest["bytes"])
                self._write_catalog()
                return {"datasource": name, "version": iv,
                        "rows": manifest["num_rows"],
                        "bytes": manifest["bytes"]}

    def checkpoint_all(self, only_dirty: bool = False,
                       byte_budget: Optional[int] = None) -> List[dict]:
        """Checkpoint every (or every dirty) complete datasource; with a
        byte budget, snapshot in ascending size order until the pass
        would exceed it (the rest stay dirty for the next pass). The
        manager lock is held only to size the candidates and then
        per-datasource inside :meth:`checkpoint` — a background pass
        over many datasources never stalls streaming ingest for the
        whole sweep."""
        with self.lock:
            store = self.ctx.store
            names = [n for n in store.names()
                     if not only_dirty or n in self._dirty]
            sized = []
            for n in names:
                try:
                    ds = store.get(n)
                except KeyError:
                    continue
                if ds.is_partial:
                    continue        # multi-host partials never checkpoint
                sized.append((_ds_bytes(ds), n))
        sized.sort()
        out = []
        spent = 0
        for nbytes, n in sized:
            if byte_budget and out and spent + nbytes > byte_budget:
                break               # always make progress on >= 1 ds
            try:
                out.append(self.checkpoint(n))
                spent += nbytes
            except KeyError:
                continue            # dropped between the listing and now
            except Exception:       # noqa: BLE001 — one bad ds can't
                with self.lock:     # starve the rest; counter increments
                    self.counters["errors"] += 1   # are read-modify-write
        return out

    # -- catalog (stars / rollups / lookups / warmup) -------------------------
    def _warmup_map(self) -> Dict[str, float]:
        """datasource -> last-queried unix time, merged over the previous
        catalog file and this session's query history (drives recovery
        load order: hot datasources first)."""
        warm: Dict[str, float] = {}
        old = self._read_catalog()
        for k, v in (old.get("warmup") or {}).items():
            warm[k] = float(v)
        hist = getattr(self.ctx, "history", None)
        if hist is not None:
            for rec in hist.entries():
                # raw engine queries carry the datasource on the record;
                # SQL statements carry it in the engine stats they copied
                ds = rec.datasource or (rec.stats or {}).get("datasource")
                if isinstance(ds, str):
                    warm[ds] = max(warm.get(ds, 0.0), float(rec.started_at))
        return warm

    def _write_catalog(self) -> None:
        from spark_druid_olap_tpu.mv.registry import rollup_to_dict
        stars = [s.to_dict()
                 for s in self.ctx.catalog.star_schemas.values()]
        rollups = [rollup_to_dict(r)
                   for r in getattr(self.ctx, "rollups", {}).values()]
        doc = {"format": SNAP.FORMAT_VERSION,
               "stars": stars, "rollups": rollups,
               "lookups": dict(getattr(self.ctx, "lookups", {}) or {}),
               "warmup": self._warmup_map(),
               "written_at": time.time()}
        tmp = os.path.join(self.root, CATALOG_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, CATALOG_FILE))
        SNAP.fsync_dir(self.root)

    def _read_catalog(self) -> dict:
        try:
            with open(os.path.join(self.root, CATALOG_FILE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    # -- recovery -------------------------------------------------------------
    def _ds_dirs(self) -> Dict[str, str]:
        """datasource name -> directory, discovered from manifests (and
        WAL headers for never-checkpointed datasources)."""
        out = {}
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            return out
        for n in entries:
            p = os.path.join(self.root, n)
            if not os.path.isdir(p) or n.startswith("."):
                continue
            name = None
            cur = SNAP.current_version(p)
            if cur is not None:
                try:
                    name = SNAP.load_manifest(p, cur)["datasource"]
                except (OSError, ValueError, KeyError):
                    name = None
            if name is None:
                w = WAL.WriteAheadLog(os.path.join(p, "wal.log"))
                it = None
                try:
                    it = w.replay()
                    for h, _ in it:
                        name = h.get("datasource")
                        break
                finally:
                    # the break leaves the generator suspended inside
                    # its `with open(...)` — close it, or the read
                    # handle lives until GC (a real fd on every recovery
                    # scan, not just lint hygiene)
                    if it is not None:
                        it.close()
                    w.close()
            if name is not None:
                out[name] = p
        return out

    def _recover_datasource(self, name: str, dirpath: str,
                            report: dict) -> Optional[dict]:
        from spark_druid_olap_tpu.segment.append import (
            apply_stream_ingest, wal_kwargs_from_dict)
        manifest = None
        verify_ms = 0.0
        loaded_version = None
        versions = SNAP.list_versions(dirpath)
        cur = SNAP.current_version(dirpath)
        candidates = ([cur] if cur is not None else []) \
            + [v for v in sorted(versions, reverse=True) if v != cur]
        for v in candidates:
            try:
                if self.tier is not None:
                    # cold-tier recovery: O(manifest) structural check,
                    # columns fault on demand; blob CRCs verify on first
                    # fault (tier/loader.py)
                    from spark_druid_olap_tpu.tier.loader import (
                        load_tiered_snapshot)
                    ds, manifest, verify_ms = load_tiered_snapshot(
                        dirpath, v, self.tier, verify=self.verify)
                else:
                    ds, manifest, verify_ms = SNAP.load_snapshot(
                        dirpath, v, verify=self.verify)
                loaded_version = v
                break
            except SNAP.SnapshotCorrupt as e:
                qpath = SNAP.quarantine_version(dirpath, v)
                self.counters["quarantined"] += 1
                report["quarantined"].append(
                    {"datasource": name, "version": v,
                     "reason": str(e), "moved_to": qpath})
                manifest = None
        if manifest is not None:
            self.ctx.store.restore(ds, int(manifest["ingest_version"]))
        else:
            # WAL-only path: replay rebuilds from the journaled 'create'
            # record. An in-session RESTORE can reach here with the live
            # object still registered — drop it (directly, no store
            # events: the on-disk state must survive), or the create
            # batch would append on top of it, duplicating every row.
            self.ctx.store._datasources.pop(name, None)
        covered = int(manifest["wal_seq"]) if manifest is not None else 0
        replayed = 0
        wal = self._wal_for(name)
        # a crash mid-append leaves a torn tail; trim it NOW so live
        # appends after recovery land where replay can see them. The
        # self-heal is no longer silent: operators watching
        # GET /metadata/persist see how often crashes tear the journal.
        repaired_bytes = wal.repair()
        if repaired_bytes > 0:
            self.counters["wal_repaired"] += 1
            self.counters["wal_repaired_bytes"] += int(repaired_bytes)
            report.setdefault("repaired", []).append(
                {"datasource": name, "bytes": int(repaired_bytes)})
        for header, body in wal.replay():
            seq = int(header.get("seq", 0))
            if seq <= covered:
                continue
            # advance the seq watermark even past a failing record so a
            # later live append can never reuse its sequence number
            self._wal_seq[name] = max(self._wal_seq.get(name, 0), seq)
            if self.tier is not None:
                live = self.ctx.store._datasources.get(name)
                if getattr(live, "tier", None) is not None:
                    # a WAL tail past the snapshot must append onto an
                    # eager store (documented tier limitation: the tail
                    # materializes this datasource in RAM; the next
                    # checkpoint re-publishes and it loads tiered again)
                    self.ctx.store._datasources[name] = live.materialize()
                    self.tier.drop_datasource(name)
            try:
                df = WAL.decode_batch(body)
                kwargs = wal_kwargs_from_dict(header.get("kwargs") or {})
                apply_stream_ingest(self.ctx, name, df, kwargs)
            except Exception as e:  # noqa: BLE001 — recovery must finish
                self.counters["errors"] += 1
                report["errors"].append(
                    {"datasource": name, "seq": seq, "reason": str(e)})
                continue            # one bad record must not shadow the
                                    # committed batches behind it
            replayed += 1
        self.counters["wal_replayed"] += replayed
        # the registered state now reflects everything replayed (and the
        # allocation watermark, advanced past failing records above) —
        # that is the watermark a later checkpoint may truncate through
        self._reg_seq[name] = max(self._wal_seq.get(name, 0), covered,
                                  self._reg_seq.get(name, 0))
        if manifest is None and replayed == 0:
            return None
        source = ("snapshot+wal" if manifest is not None and replayed
                  else "snapshot" if manifest is not None else "wal")
        info = {"source": source,
                "snapshot_version": loaded_version,
                "checksum_verify_ms": round(verify_ms, 3),
                "wal_records": replayed,
                "wal_repaired_bytes": int(repaired_bytes)}
        report["datasources"].append({"datasource": name, **info})
        return info

    def recover(self) -> dict:
        """Reload every persisted datasource + the catalog; returns (and
        stores) a recovery report."""
        t0 = time.perf_counter()
        with self.lock:
            report = {"datasources": [], "quarantined": [], "errors": [],
                      "order": []}
            catalog = self._read_catalog()
            warm = {k: float(v)
                    for k, v in (catalog.get("warmup") or {}).items()}
            dirs = self._ds_dirs()
            # history-driven warmup: most recently queried first, then
            # rollup backings (queries hit them via rewrite), then name
            order = sorted(
                dirs, key=lambda n: (-warm.get(n, 0.0), n))
            report["order"] = list(order)
            recovery_info = {}
            for name in order:
                info = self._recover_datasource(name, dirs[name], report)
                if info is not None:
                    recovery_info[name] = info
            # catalog: lookups, star schemas, rollup definitions
            for lname, table in (catalog.get("lookups") or {}).items():
                self.ctx.lookups.setdefault(lname, table)
            from spark_druid_olap_tpu.metadata.star import StarSchema
            for sd in catalog.get("stars") or ():
                try:
                    star = StarSchema.from_dict(sd)
                    self.ctx.catalog.register_star_schema(star)
                except Exception as e:  # noqa: BLE001
                    report["errors"].append(
                        {"star": sd.get("factTable"), "reason": str(e)})
            from spark_druid_olap_tpu.mv.registry import rollup_from_dict
            for rd in catalog.get("rollups") or ():
                try:
                    r = rollup_from_dict(rd)
                except Exception as e:  # noqa: BLE001
                    report["errors"].append(
                        {"rollup": rd.get("name"), "reason": str(e)})
                    continue
                if r.backing in self.ctx.store._datasources:
                    self.ctx.rollups[r.name] = r
            self.ctx.store.recovery_info = recovery_info
            report["total_ms"] = round(
                (time.perf_counter() - t0) * 1000, 2)
            self.recovery_report = report
            return report

    def restore(self, name: Optional[str] = None) -> dict:
        """In-session ``RESTORE``: rewind the in-memory state to the last
        published snapshot (+ committed WAL tail). Ingest-version
        counters rewind with it, so every derived cache must drop — the
        session layer clears the engine caches after calling this."""
        with self.lock:
            dirs = self._ds_dirs()
            if name is not None:
                if name not in dirs:
                    raise KeyError(
                        f"no snapshot or WAL on disk for {name!r} "
                        f"under {self.root}")
                dirs = {name: dirs[name]}
            report = {"datasources": [], "quarantined": [], "errors": [],
                      "order": sorted(dirs)}
            recovery_info = dict(
                getattr(self.ctx.store, "recovery_info", {}) or {})
            for n in sorted(dirs):
                info = self._recover_datasource(n, dirs[n], report)
                if info is not None:
                    recovery_info[n] = info
            self.ctx.store.recovery_info = recovery_info
            self.recovery_report = report
            return report

    # -- purge ----------------------------------------------------------------
    def purge(self, name: Optional[str] = None) -> int:
        """Delete on-disk snapshots/WALs (CLEAR METADATA ... PURGE).
        Returns the number of datasource directories removed."""
        with self.lock:
            removed = 0
            if name is not None:
                p = self._ds_root(name)
                w = self._wals.pop(name, None)
                if w is not None:
                    w.close()
                self._wal_seq.pop(name, None)
                self._reg_seq.pop(name, None)
                self._tail_ds.pop(name, None)
                self._tail_chain.pop(name, None)
                self._dirty.discard(name)
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
                    removed = 1
                return removed
            for n, p in self._ds_dirs().items():
                shutil.rmtree(p, ignore_errors=True)
                removed += 1
            # fenced previous incarnations (.dropped-*) go too: PURGE
            # means "nothing of this root survives a restart"
            try:
                for n in os.listdir(self.root):
                    if n.startswith(".dropped-"):
                        shutil.rmtree(os.path.join(self.root, n),
                                      ignore_errors=True)
            except OSError:
                pass
            try:
                os.remove(os.path.join(self.root, CATALOG_FILE))
            except OSError:
                pass
            for w in self._wals.values():
                w.close()
            self._wals.clear()
            self._wal_seq.clear()
            self._reg_seq.clear()
            self._tail_ds.clear()
            self._tail_chain.clear()
            self._dirty.clear()
            return removed

    # -- compaction -----------------------------------------------------------
    def compact(self, name: Optional[str] = None,
                target_rows: Optional[int] = None) -> List[dict]:
        """Roll stream-appended tails into time-partitioned segments
        (persist/compact.py). With a name: force-compact that datasource;
        without: sweep every datasource past the segment-count floor."""
        from spark_druid_olap_tpu.persist.compact import compact_datasource
        out: List[dict] = []
        if name is not None:
            r = compact_datasource(self, name, target_rows=target_rows,
                                   force=True)
            return [r] if r else []
        for n in list(self.ctx.store.names()):
            try:
                r = compact_datasource(self, n, target_rows=target_rows)
            except Exception:  # noqa: BLE001 — one bad ds can't stop
                with self.lock:  # the sweep
                    self.counters["errors"] += 1
                continue
            if r:
                out.append(r)
        return out

    # -- background checkpointer / compactor ----------------------------------
    def start_background(self) -> None:
        periods = [p for p in (self.interval_s, self.compact_interval_s)
                   if p > 0]
        if not periods or self._thread is not None:
            return
        self._bg_period = min(periods)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._bg_loop, name="sdot-checkpointer", daemon=True)
        self._thread.start()

    def _bg_loop(self) -> None:
        last_ckpt = last_compact = time.monotonic()
        slack = self._bg_period * 0.05
        while not self._stop.wait(self._bg_period):
            now = time.monotonic()
            if self.interval_s > 0 \
                    and now - last_ckpt >= self.interval_s - slack:
                last_ckpt = now
                try:
                    self.checkpoint_all(
                        only_dirty=True,
                        byte_budget=self.pass_budget or None)
                except Exception:  # noqa: BLE001 — the loop must survive
                    with self.lock:
                        self.counters["errors"] += 1
            if self.compact_interval_s > 0 \
                    and now - last_compact >= self.compact_interval_s \
                    - slack:
                last_compact = now
                try:
                    self.compact()
                except Exception:  # noqa: BLE001 — the loop must survive
                    with self.lock:
                        self.counters["errors"] += 1

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if self.tier is not None:
            self.tier.stop()
        with self.lock:
            for w in self._wals.values():
                w.close()

    # -- observability --------------------------------------------------------
    def snapshots_view(self) -> pd.DataFrame:
        """``sys_snapshots``: one row per published snapshot version plus
        one per quarantined version."""
        rows = []
        with self.lock:
            for name, dirpath in sorted(self._ds_dirs().items()):
                cur = SNAP.current_version(dirpath)
                wal_bytes = self._wal_for(name).size_bytes()
                for v in SNAP.list_versions(dirpath):
                    try:
                        m = SNAP.load_manifest(dirpath, v)
                    except (OSError, ValueError):
                        m = {}
                    rows.append({
                        "datasource": name, "version": v,
                        "state": "published",
                        "current": bool(v == cur),
                        "rows": int(m.get("num_rows", 0)),
                        "bytes": int(m.get("bytes", 0)),
                        "wal_seq": int(m.get("wal_seq", 0)),
                        "wal_bytes": int(wal_bytes),
                        "dirty": name in self._dirty,
                        "created_at": float(m.get("created_at", 0.0)),
                    })
                qdir = os.path.join(dirpath, SNAP.QUARANTINE_DIR)
                if os.path.isdir(qdir):
                    for q in sorted(os.listdir(qdir)):
                        rows.append({
                            "datasource": name, "version": -1,
                            "state": f"quarantined:{q}",
                            "current": False, "rows": 0, "bytes": 0,
                            "wal_seq": 0, "wal_bytes": int(wal_bytes),
                            "dirty": name in self._dirty,
                            "created_at": 0.0})
        cols = ["datasource", "version", "state", "current", "rows",
                "bytes", "wal_seq", "wal_bytes", "dirty", "created_at"]
        return pd.DataFrame(rows, columns=cols)

    def stats(self) -> dict:
        """``GET /metadata/persist`` payload."""
        with self.lock:
            per_ds = {}
            for name, dirpath in self._ds_dirs().items():
                per_ds[name] = {
                    "currentVersion": SNAP.current_version(dirpath),
                    "versions": SNAP.list_versions(dirpath),
                    "walBytes": self._wal_for(name).size_bytes(),
                    "dirty": name in self._dirty,
                }
            return {
                "enabled": True,
                "path": self.root,
                "datasources": per_ds,
                "dirty": sorted(self._dirty),
                "counters": dict(self.counters),
                "groupCommit": {
                    "enabled": self.group_commit,
                    "commits": sum(w.group_commits
                                   for w in self._wals.values()),
                    "frames": sum(w.group_frames
                                  for w in self._wals.values()),
                },
                "background": {
                    "intervalSeconds": self.interval_s,
                    "passByteBudget": self.pass_budget,
                    "compactIntervalSeconds": self.compact_interval_s,
                    "running": self._thread is not None
                    and self._thread.is_alive(),
                },
                "recovery": self.recovery_report,
                "tier": None if self.tier is None
                else self.tier.stats_snapshot(),
            }
