"""Session context — the framework's entry point.

≈ the reference's session/extension layer: ``SPLSessionState`` +
``ModuleLoader`` (``SPLSessionState.scala:80-132``,
``SparklineDataModule.scala:70-87``) wire the parser, logical rules, and
physical strategy into a Spark session; here ``Context`` wires the SQL front
end, planner, engine, metadata catalog, and config into one object.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.result import QueryResult
from spark_druid_olap_tpu.segment.ingest import (
    ingest_csv,
    ingest_dataframe,
    ingest_parquet,
)
from spark_druid_olap_tpu.segment.store import SegmentStore
from spark_druid_olap_tpu.utils.config import Config


def _enable_x64_once():
    # On CPU, native 64-bit routes (i64 sums, f64 compares) are exact and
    # cheap. TPU backends must stay 32-bit (f64 unsupported, i64 emulated):
    # the lane/limb routes carry exactness there. SDOT_FORCE_32BIT=1 keeps
    # 32-bit even on CPU (TPU-dtype simulation/debugging).
    import os
    if os.environ.get("SDOT_FORCE_32BIT"):
        return
    try:
        if jax.default_backend() == "cpu":
            jax.config.update("jax_enable_x64", True)
    except Exception:
        pass


class Context:
    def __init__(self, config: Optional[Dict] = None, mesh=None,
                 auto_mesh: bool = False):
        _enable_x64_once()
        self.config = Config(config)
        self.store = SegmentStore()
        if mesh is None and len(jax.devices()) > 1:
            from spark_druid_olap_tpu.utils.config import MESH_AUTO
            if auto_mesh or bool(self.config.get(MESH_AUTO)):
                from spark_druid_olap_tpu.parallel.mesh import make_mesh
                mesh = make_mesh()
        self.mesh = mesh
        from spark_druid_olap_tpu.parallel.executor import QueryEngine
        self.engine = QueryEngine(self.store, self.config, mesh)
        from spark_druid_olap_tpu.metadata.catalog import Catalog
        self.catalog = Catalog(self.store)
        from spark_druid_olap_tpu.metadata.history import QueryHistory
        from spark_druid_olap_tpu.utils.config import (QUERY_HISTORY,
                                                       QUERY_HISTORY_SIZE)
        # disabled history keeps the registry but records nothing
        # (maxlen=0 deque): every record() call stays a cheap no-op
        self.history = QueryHistory(
            self.config.get(QUERY_HISTORY_SIZE)
            if self.config.get(QUERY_HISTORY) else 0)
        # named lookup tables for the SQL LOOKUP(col, 'name') function
        # (≈ Druid registered lookups backing the lookup extraction fn)
        self.lookups: Dict[str, Dict[str, Optional[str]]] = {}
        # materialized rollup registry: name -> mv.registry.RollupDef;
        # the planner consults it for automatic rewrite (mv/match.py)
        self.rollups: Dict[str, object] = {}
        # module extension points (≈ SparklineDataModule/ModuleLoader)
        from spark_druid_olap_tpu.utils import host_eval as _he
        self.functions = _he.EXTRA_FUNCTIONS
        self.spec_rules = []
        self.statement_handlers = []
        self.modules = []
        from spark_druid_olap_tpu.utils.config import MODULES
        mods_csv = self.config.get(MODULES)
        if mods_csv:
            from spark_druid_olap_tpu.utils.modules import install_from_config
            self.modules = install_from_config(self, mods_csv)
        # durable persistence (persist/): deep-storage snapshots + ingest
        # WAL + startup recovery; None when sdot.persist.path is unset
        self.persist = None
        from spark_druid_olap_tpu.utils.config import (
            PERSIST_ENABLED, PERSIST_PATH, PERSIST_RECOVER)
        ppath = self.config.get(PERSIST_PATH)
        if ppath and self.config.get(PERSIST_ENABLED):
            from spark_druid_olap_tpu.persist.manager import PersistManager
            self.persist = PersistManager(self, ppath)
            if self.config.get(PERSIST_RECOVER):
                self.persist.recover()
            self.persist.start_background()
        # distributed serving tier (cluster/): a broker attaches the
        # scatter/merge client to its engine; historicals are built by
        # cluster/historical.py (they set sdot.cluster.role=historical
        # and never attach a client — no recursive scatter)
        self.cluster = None
        from spark_druid_olap_tpu.utils.config import (
            CLUSTER_NODES, CLUSTER_ROLE)
        if self.config.get(CLUSTER_NODES) \
                and self.config.get(CLUSTER_ROLE) == "broker":
            from spark_druid_olap_tpu.cluster.broker import ClusterClient
            self.cluster = ClusterClient(self)
            self.engine.cluster = self.cluster

    def reshard(self, devices=None) -> None:
        """Rebuild the engine's device mesh over the currently-live (or
        given) devices — topology elasticity after chip loss/restore
        (≈ the reference re-planning on ZooKeeper server-list changes)."""
        self.engine.reshard(devices)
        self.mesh = self.engine.mesh

    def install_module(self, module) -> None:
        """Install an extension module programmatically (≈ adding to
        spark.sparklinedata.modules)."""
        module.install(self)
        self.modules.append(module)

    def register_lookup(self, name: str, mapping: Dict) -> None:
        """Register a named value-translation map usable as
        ``LOOKUP(col, 'name')`` in SQL (≈ Druid lookup registration)."""
        self.lookups[name] = {str(k): (None if v is None else str(v))
                              for k, v in mapping.items()}

    # -- ingest / registration ------------------------------------------------
    def _ingest_kwargs(self, kwargs):
        """Session default for segment sizing (sdot.segment.target.rows)
        when the caller doesn't pass target_rows explicitly."""
        if "target_rows" not in kwargs:
            from spark_druid_olap_tpu.utils.config import SEGMENT_ROWS
            kwargs = {**kwargs,
                      "target_rows": self.config.get(SEGMENT_ROWS)}
        return kwargs

    def ingest_dataframe(self, name, df, **kwargs):
        ds = ingest_dataframe(name, df, **self._ingest_kwargs(kwargs))
        self.store.register(ds)
        return ds

    def ingest_parquet(self, name, path, **kwargs):
        ds = ingest_parquet(name, path, **self._ingest_kwargs(kwargs))
        self.store.register(ds)
        return ds

    def ingest_csv(self, name, path, **kwargs):
        ds = ingest_csv(name, path, **self._ingest_kwargs(kwargs))
        self.store.register(ds)
        return ds

    def ingest_parquet_stream(self, name, path, **kwargs):
        """Out-of-core Parquet ingest (row-group streaming; see
        segment/stream_ingest.py) — for datasets whose raw pandas form
        would not fit in host memory."""
        from spark_druid_olap_tpu.segment.stream_ingest import (
            ingest_parquet_stream)
        ds = ingest_parquet_stream(name, path, **self._ingest_kwargs(kwargs))
        self.store.register(ds)
        return ds

    def stream_ingest(self, name, df, **kwargs):
        """Streaming append (≈ Druid realtime ingest): create the
        datasource on the first batch, append rows after. With
        persistence on (sdot.persist.path) each batch is journaled to
        the write-ahead log and fsynced BEFORE it becomes queryable, so
        a committed batch survives kill -9 (persist/wal.py). Returns the
        new immutable Datasource value.

        When an ``ingest`` WLM lane is configured, each batch takes a
        lane slot for its local apply — producers share the same
        admission fabric as queries instead of starving them. On a
        broker, an acked batch is additionally pushed to the
        time-matched shard's owners (cluster/broker.py) so distributed
        reads keep read-your-writes; the push is an optimization, never
        part of the durability or ACK path."""
        kwargs = self._ingest_kwargs(kwargs)
        wlm = getattr(self.engine, "wlm", None)
        ticket = wlm.admit_ingest() if wlm is not None else None
        cl = self.cluster
        token = cl.ingest_begin(name) if cl is not None else None
        acked_df = None
        try:
            if self.persist is not None:
                ds = self.persist.stream_ingest(name, df, kwargs)
            else:
                from spark_druid_olap_tpu.segment.append import (
                    apply_stream_ingest)
                ds = apply_stream_ingest(self, name, df, kwargs)
            acked_df = df
            return ds
        finally:
            if token is not None:
                cl.ingest_finish(token, name, acked_df, kwargs)
            if ticket is not None:
                wlm.release(ticket)

    def checkpoint(self, name: Optional[str] = None):
        """Publish snapshot(s) to deep storage (requires
        sdot.persist.path). ``name=None`` checkpoints every complete
        datasource. Returns the checkpoint summaries."""
        if self.persist is None:
            raise RuntimeError(
                "persistence is disabled; set sdot.persist.path")
        if name is not None:
            return [self.persist.checkpoint(name)]
        return self.persist.checkpoint_all()

    def close(self) -> None:
        """Stop background machinery (the persist checkpointer, the
        cluster client's prober + scatter pool). Safe to call more than
        once; the context remains usable for queries."""
        if self.persist is not None:
            self.persist.stop()
        if self.cluster is not None:
            self.cluster.close()
            self.cluster = None
            self.engine.cluster = None

    def register_star_schema(self, star_schema) -> None:
        self.catalog.register_star_schema(star_schema)

    # -- query ----------------------------------------------------------------
    def execute(self, q: S.QuerySpec) -> QueryResult:
        """Execute a raw engine QuerySpec (≈ ``ON DRUIDDATASOURCE ... EXECUTE
        QUERY <json>``, reference ``PlanUtil.logicalPlan:49-66``)."""
        r = self.engine.execute(q)
        self.history.record(q, dict(self.engine.last_stats))
        return r

    def sql(self, query: str, query_id: Optional[str] = None,
            lane: Optional[str] = None, tenant: Optional[str] = None,
            priority: Optional[int] = None) -> QueryResult:
        try:
            from spark_druid_olap_tpu.sql.session import run_sql
        except ImportError as e:
            raise NotImplementedError(
                "SQL front end not available in this build") from e
        return run_sql(self, query, query_id=query_id, lane=lane,
                       tenant=tenant, priority=priority)

    def explain(self, query: str) -> str:
        try:
            from spark_druid_olap_tpu.sql.session import explain_sql
        except ImportError as e:
            raise NotImplementedError(
                "SQL front end not available in this build") from e
        return explain_sql(self, query)
