"""Device-side window-function post-pass.

``plan.extract`` strips ``OVER (...)`` calls out of a SELECT statement so
the base query runs through the normal engine / cluster / mesh path
untouched; ``exec.apply`` then computes the window columns over the
(merged) result frame as segment-sorted jit kernels — partition-boundary
masks plus prefix scans, no host loop over rows. See docs/WINDOWS.md.
"""

from spark_druid_olap_tpu.window.plan import extract  # noqa: F401
from spark_druid_olap_tpu.window.exec import apply  # noqa: F401
