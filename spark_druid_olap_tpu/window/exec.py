"""Device-side window kernels over the base query's result frame.

Each window call lowers to ONE jit program over integer partition/order
codes plus the argument column: a ``jnp.lexsort`` groups rows into
segment runs, a partition-boundary mask derives per-row segment
start/end indices, and the function body is prefix scans (segmented via
``lax.associative_scan`` with reset flags) or frame gathers — no host
loop over rows. Results scatter back to the original row order through
the inverse permutation.

String/object columns participate through sorted factorized codes
(``pd.factorize(sort=True)``): code order equals value order, so
min/max/lag/lead over codes map back to values exactly.

Null semantics (shared with the pandas references in
tests/test_window.py): ORDER BY treats NULL as the LARGEST value (last
ascending, first descending); aggregate arguments skip NULLs
(all-null frame -> NULL); lag/lead return the stored value inside the
partition (NULL included) and the default only past its edge.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp

from spark_druid_olap_tpu.window.plan import (OFFSET_FNS, RANKING_FNS,
                                              WindowCol, WindowPlan,
                                              WindowUnsupported)

_I64MAX = np.int64(2 ** 62)     # in-band infinity for int min/max


# -- code building (host: factorize is inherently a host operation) ----------

def _order_key(col: pd.Series, ascending: bool) -> np.ndarray:
    """Integer sort key for one ORDER BY column: sorted factorize codes
    with NULL mapped past the largest code, negated for DESC."""
    codes, uniq = pd.factorize(col, sort=True, use_na_sentinel=True)
    key = np.where(codes < 0, len(uniq), codes).astype(np.int64)
    return key if ascending else -key


def _partition_ids(df: pd.DataFrame, cols: Tuple[str, ...]) -> np.ndarray:
    if not cols:
        return np.zeros(len(df), dtype=np.int64)
    mats = []
    for c in cols:
        codes, _ = pd.factorize(df[c], sort=False, use_na_sentinel=False)
        mats.append(codes.astype(np.int64))
    if len(mats) == 1:
        return mats[0]
    _, pid = np.unique(np.stack(mats, axis=1), axis=0, return_inverse=True)
    return pid.astype(np.int64)


def _prep_arg(col: pd.Series):
    """(values int64/float64, valid mask, decoder) for an argument
    column. The decoder maps kernel-space values + validity back to the
    column's domain (datetime ticks, factorized object codes)."""
    a = col.to_numpy()
    if a.dtype.kind == "M":
        iv = a.astype("datetime64[ns]").view(np.int64)
        vm = ~np.isnat(a)

        def dec(v, ok):
            out = v.astype(np.int64).view("datetime64[ns]").copy()
            out[~ok] = np.datetime64("NaT")
            return out
        return np.where(vm, iv, 0), vm, dec
    if a.dtype.kind == "f":
        vm = ~np.isnan(a)

        def dec(v, ok):
            return np.where(ok, v, np.nan).astype(np.float64)
        return a.astype(np.float64), vm, dec
    if a.dtype.kind in "iub":
        vm = np.ones(len(a), dtype=bool)

        def dec(v, ok):
            v = np.asarray(v)
            if ok.all():
                return v.astype(np.int64)
            return np.where(ok, v.astype(np.float64), np.nan)
        return a.astype(np.int64), vm, dec
    # object / strings: sorted codes so code order == value order
    codes, uniq = pd.factorize(col, sort=True, use_na_sentinel=True)
    vm = codes >= 0

    def dec(v, ok):
        out = np.empty(len(v), dtype=object)
        vv = np.asarray(v).astype(np.int64)
        for i in range(len(v)):
            out[i] = uniq[vv[i]] if ok[i] else None
        return out
    return np.where(vm, codes, 0).astype(np.int64), vm, dec


# -- jit kernels --------------------------------------------------------------

def _segments(pid, n):
    """(perm, sorted pid, boundary, seg_start, seg_end, iota) given the
    UNSORTED pid and the precomputed perm is folded in by callers."""
    iota = jnp.arange(n, dtype=jnp.int64)
    boundary = jnp.concatenate(
        [jnp.ones(1, dtype=bool), pid[1:] != pid[:-1]])
    seg_start = jax.lax.cummax(jnp.where(boundary, iota, 0))
    b_end = jnp.concatenate(
        [pid[:-1] != pid[1:], jnp.ones(1, dtype=bool)])
    start_rev = jax.lax.cummax(jnp.where(b_end[::-1], iota, 0))
    seg_end = (n - 1) - start_rev[::-1]
    return iota, boundary, seg_start, seg_end


def _segscan(op, vals, boundary, reverse=False):
    """Segmented inclusive scan: ``op`` accumulates within a segment and
    resets at each boundary flag."""
    if reverse:
        return _segscan(op, vals[::-1], boundary[::-1])[::-1]

    def combine(a, b):
        fa, va = a
        fb, vb = b
        return (fa | fb, jnp.where(fb, vb, op(va, vb)))
    _, out = jax.lax.associative_scan(combine, (boundary, vals))
    return out


def _shift(a, k, fill):
    n = a.shape[0]
    if k == 0:
        return a
    if abs(k) >= n:
        return jnp.full_like(a, fill)
    if k > 0:
        return jnp.concatenate([jnp.full(k, fill, a.dtype), a[:-k]])
    return jnp.concatenate([a[-k:], jnp.full(-k, fill, a.dtype)])


@functools.partial(jax.jit, static_argnames=("fn", "n_keys"))
def _rank_kernel(pid, keys, fn: str, n_keys: int):
    n = pid.shape[0]
    perm = jnp.lexsort(tuple(keys[::-1]) + (pid,))
    sp = pid[perm]
    iota, boundary, seg_start, _ = _segments(sp, n)
    if fn == "row_number":
        out_sorted = iota - seg_start + 1
    else:
        change = boundary
        for k in keys:
            sk = k[perm]
            change = change | jnp.concatenate(
                [jnp.ones(1, dtype=bool), sk[1:] != sk[:-1]])
        if fn == "rank":
            out_sorted = jax.lax.cummax(
                jnp.where(change, iota, 0)) - seg_start + 1
        else:                                   # dense_rank
            c = jnp.cumsum(change.astype(jnp.int64))
            c0 = jax.lax.cummax(jnp.where(boundary, c, 0))
            out_sorted = c - c0 + 1
    return jnp.zeros(n, out_sorted.dtype).at[perm].set(out_sorted)


@functools.partial(jax.jit, static_argnames=("k",))
def _offset_kernel(pid, keys, vals, vm, k: int):
    """lag (k>0) / lead (k<0): (value, in_partition, value_valid)."""
    n = pid.shape[0]
    perm = jnp.lexsort(tuple(keys[::-1]) + (pid,))
    sp = pid[perm]
    sv, svm = vals[perm], vm[perm]
    shifted = _shift(sv, k, jnp.zeros((), sv.dtype))
    pin = _shift(sp, k, jnp.full((), -1, sp.dtype)) == sp
    sok = _shift(svm, k, jnp.zeros((), bool))
    scatter = lambda a: jnp.zeros(n, a.dtype).at[perm].set(a)  # noqa: E731
    return scatter(shifted), scatter(pin), scatter(sok)


@functools.partial(jax.jit, static_argnames=("fn", "frame"))
def _agg_kernel(pid, keys, vals, vm, fn: str, frame):
    """Framed aggregate: returns (acc, cnt) — the op-accumulated value
    over the frame's valid rows and the count of valid rows, both in
    original row order."""
    n = pid.shape[0]
    perm = jnp.lexsort(tuple(keys[::-1]) + (pid,))
    sp = pid[perm]
    sv, svm = vals[perm], vm[perm]
    iota, boundary, seg_start, seg_end = _segments(sp, n)
    is_f = jnp.issubdtype(sv.dtype, jnp.floating)
    if fn in ("sum", "avg", "count"):
        op, identity = jnp.add, jnp.zeros((), sv.dtype)
    elif fn == "min":
        op = jnp.minimum
        identity = jnp.array(jnp.inf, sv.dtype) if is_f else _I64MAX
    else:
        op = jnp.maximum
        identity = jnp.array(-jnp.inf, sv.dtype) if is_f else -_I64MAX
    mv = jnp.where(svm, sv, identity)
    cm = svm.astype(jnp.int64)
    p, f = frame
    if p is None:
        fwd_v = _segscan(op, mv, boundary)
        fwd_c = _segscan(jnp.add, cm, boundary)
        hi = seg_end if f is None else jnp.minimum(iota + f, seg_end)
        acc, cnt = fwd_v[hi], fwd_c[hi]
    elif f is None:
        b_end = jnp.concatenate(
            [sp[:-1] != sp[1:], jnp.ones(1, dtype=bool)])
        rev_v = _segscan(op, mv, b_end, reverse=True)
        rev_c = _segscan(jnp.add, cm, b_end, reverse=True)
        lo = jnp.maximum(iota - p, seg_start)
        acc, cnt = rev_v[lo], rev_c[lo]
    elif op is jnp.add:
        fwd_v = _segscan(jnp.add, mv, boundary)
        fwd_c = _segscan(jnp.add, cm, boundary)
        hi = jnp.minimum(iota + f, seg_end)
        lo = jnp.maximum(iota - p, seg_start)
        base = jnp.maximum(lo - 1, 0)
        acc = fwd_v[hi] - jnp.where(lo > seg_start, fwd_v[base], 0)
        cnt = fwd_c[hi] - jnp.where(lo > seg_start, fwd_c[base], 0)
    else:
        # bounded min/max: the scan does not invert, so stack shifted
        # lanes across the frame (trace-time unroll, capped by
        # sdot.window.max.frame before the kernel is built)
        acc = jnp.full(n, identity, mv.dtype)
        cnt = jnp.zeros(n, jnp.int64)
        for k in range(-f, p + 1):
            skv = _shift(mv, k, identity)
            skc = _shift(cm, k, jnp.zeros((), jnp.int64))
            ok = _shift(sp, k, jnp.full((), -1, sp.dtype)) == sp
            acc = op(acc, jnp.where(ok, skv, identity))
            cnt = cnt + jnp.where(ok, skc, 0)
    scatter = lambda a: jnp.zeros(n, a.dtype).at[perm].set(a)  # noqa: E731
    return scatter(acc), scatter(cnt)


# -- per-call evaluation ------------------------------------------------------

def _compute(ctx, w: WindowCol, df: pd.DataFrame) -> np.ndarray:
    n = len(df)
    if n == 0:
        if w.fn in RANKING_FNS or w.fn == "count":
            return np.zeros(0, dtype=np.int64)
        return np.zeros(0, dtype=np.float64)
    pid = jnp.asarray(_partition_ids(df, w.part_cols))
    keys = tuple(jnp.asarray(_order_key(df[c], asc))
                 for c, asc in w.order_cols)

    if w.fn in RANKING_FNS:
        out = _rank_kernel(pid, keys, fn=w.fn, n_keys=len(keys))
        return np.asarray(out).astype(np.int64)

    if w.fn in OFFSET_FNS:
        vals, vm, dec = _prep_arg(df[w.arg_cols[0]])
        k = w.offset if w.fn == "lag" else -w.offset
        v, pin, ok = _offset_kernel(pid, keys, jnp.asarray(vals),
                                    jnp.asarray(vm), k=k)
        v, pin, ok = np.asarray(v), np.asarray(pin), np.asarray(ok)
        out = dec(v, pin & ok)
        if w.default is not None:
            edge = ~pin
            if out.dtype == object:
                out[edge] = w.default
            elif np.issubdtype(out.dtype, np.datetime64):
                out[edge] = np.datetime64(w.default)
            else:
                out = out.astype(np.float64) \
                    if isinstance(w.default, float) \
                    and out.dtype.kind != "f" else out
                out[edge] = w.default
        return out

    # framed aggregates
    frame = w.frame
    if frame is None:
        frame = (None, 0) if w.order_cols else (None, None)
    p, f = frame
    if p is not None and f is not None:
        from spark_druid_olap_tpu.utils.config import WINDOW_MAX_FRAME
        cap = int(ctx.config.get(WINDOW_MAX_FRAME))
        if p + f + 1 > cap:
            raise WindowUnsupported(
                f"ROWS frame spans {p + f + 1} rows; cap is "
                f"sdot.window.max.frame={cap}")
    if w.fn == "count" and not w.arg_cols:
        vals = np.ones(n, dtype=np.int64)
        vm = np.ones(n, dtype=bool)
        dec = None
    else:
        vals, vm, dec = _prep_arg(df[w.arg_cols[0]])
        if w.fn in ("sum", "avg") and df[w.arg_cols[0]].dtype == object:
            raise WindowUnsupported(
                f"window {w.fn}() over a non-numeric column")
    acc, cnt = _agg_kernel(pid, keys, jnp.asarray(vals), jnp.asarray(vm),
                           fn=w.fn, frame=frame)
    acc, cnt = np.asarray(acc), np.asarray(cnt)
    if w.fn == "count":
        return cnt.astype(np.int64)
    ok = cnt > 0
    if w.fn == "avg":
        return np.where(ok, acc.astype(np.float64)
                        / np.maximum(cnt, 1), np.nan)
    if w.fn == "sum":
        if acc.dtype.kind == "f" or not ok.all():
            return np.where(ok, acc.astype(np.float64), np.nan)
        return acc.astype(np.int64)
    # min / max map back through the argument decoder (datetime ticks,
    # object codes) so string and timestamp extremes round-trip exactly
    return dec(acc, ok)


# -- plan application ---------------------------------------------------------

def apply(ctx, plan: WindowPlan, df: pd.DataFrame) -> pd.DataFrame:
    """Compute the window columns over the base result frame and
    assemble the statement's output (deferred ORDER BY / LIMIT / OFFSET
    included)."""
    from spark_druid_olap_tpu.utils import host_eval
    env: Dict[str, np.ndarray] = {c: df[c].to_numpy() for c in df.columns}
    for w in plan.windows:
        env[w.slot] = _compute(ctx, w, df)

    from spark_druid_olap_tpu.ir import expr as E
    out = pd.DataFrame(index=df.index)
    helper = set(plan.aux_cols)
    base_cols = [c for c in df.columns if c not in helper]
    for it in plan.items:
        if it.expr == "*":
            for c in base_cols:
                out[c] = df[c]
            continue
        if isinstance(it.expr, E.Column) and it.expr.name in env:
            v = env[it.expr.name]
        else:
            v = np.asarray(host_eval.eval_expr(it.expr, env))
        out[it.name] = np.broadcast_to(v, (len(df),)) if v.ndim == 0 else v
        env[it.name] = out[it.name].to_numpy()

    if plan.order_by:
        out_cols = list(out.columns)
        skeys = []
        for i, (e, asc) in enumerate(plan.order_by):
            if isinstance(e, E.Literal) and isinstance(e.value, int):
                e = E.Column(out_cols[e.value - 1])      # ordinal
            v = np.asarray(host_eval.eval_expr(e, env))
            sk = f"__wsort{i}"
            out[sk] = np.broadcast_to(v, (len(out),)) if v.ndim == 0 else v
            skeys.append((sk, asc))
        out = out.sort_values([c for c, _ in skeys],
                              ascending=[a for _, a in skeys],
                              kind="mergesort")
        out = out.drop(columns=[c for c, _ in skeys])
    if plan.offset:
        out = out.iloc[plan.offset:]
    if plan.limit is not None:
        out = out.head(plan.limit)
    return out.reset_index(drop=True)
