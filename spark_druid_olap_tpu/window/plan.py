"""Window post-pass planning: strip ``OVER (...)`` calls from a SELECT.

The session calls :func:`extract` before anything else touches the
statement. When the statement carries window calls, the result is a
``(base_stmt, WindowPlan)`` pair:

- ``base_stmt`` is the statement with every window call removed and with
  auxiliary aliased items (``__w_p0``, ``__w_o0``, ``__w_a0``, ...)
  appended so the base execution — engine pushdown, cluster scatter,
  mesh, composite or host, whichever tier wins — materializes every
  partition key, order key and argument the window pass needs. The
  outer ORDER BY / LIMIT / OFFSET are stripped too: SQL evaluates
  window functions over the FULL result set, so the ordering epilogue
  must run after the post-pass, not inside the base query.
- ``WindowPlan`` records the window calls (deduplicated), how each
  output item rebuilds from base + window columns, and the deferred
  ordering epilogue.

This mirrors how the reference planner splits a windowed Spark plan
into a Druid-pushed aggregate plus a Spark ``Window`` operator on top —
except here the "operator on top" runs as jit device kernels
(``window/exec.py``) instead of a host sort-and-loop.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.sql import ast as A

#: window functions the post-pass lowers; anything else raises.
RANKING_FNS = ("rank", "dense_rank", "row_number")
OFFSET_FNS = ("lag", "lead")
AGG_FNS = ("sum", "min", "max", "avg", "count")
SUPPORTED_FNS = RANKING_FNS + OFFSET_FNS + AGG_FNS


class WindowUnsupported(ValueError):
    """A window shape the post-pass cannot lower. There is no fallback
    tier for window functions (the host evaluator rejects them too), so
    this surfaces to the caller as the statement's error."""


@dataclasses.dataclass(frozen=True)
class WindowCol:
    """One window call lowered to one computed column ``__w<i>``."""
    slot: str                       # output column name (__w0, __w1, ...)
    fn: str
    call: E.WindowCall              # original (for diagnostics / stats)
    part_cols: Tuple[str, ...]      # aux column names in the base frame
    order_cols: Tuple[Tuple[str, bool], ...]   # (aux name, ascending)
    arg_cols: Tuple[str, ...]       # aux column names for fn args
    offset: int = 1                 # lag/lead row offset
    default: Optional[object] = None   # lag/lead default literal
    frame: Optional[Tuple[Optional[int], Optional[int]]] = None


@dataclasses.dataclass(frozen=True)
class OutItem:
    """One output column of the windowed statement."""
    name: str
    expr: object                    # E.Expr over base + __w columns, or
    #                                 the string '*' (star passthrough)


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    windows: Tuple[WindowCol, ...]
    items: Tuple[OutItem, ...]
    # deferred ordering epilogue (applied AFTER the window columns):
    order_by: Tuple[Tuple[object, bool], ...]   # (expr, ascending)
    limit: Optional[int]
    offset: int
    aux_cols: Tuple[str, ...]       # every __w_* helper added to base


def _has_window(e) -> bool:
    if e is None or isinstance(e, str):
        return False
    return any(isinstance(n, E.WindowCall) for n in E.walk(e))


def extract(ctx, stmt) -> Optional[Tuple[A.SelectStmt, WindowPlan]]:
    """Return ``(base_stmt, plan)`` when ``stmt`` has window calls, else
    ``None``. Raises :class:`WindowUnsupported` for shapes the pass
    cannot honor (window calls outside the SELECT list, DISTINCT, ...).
    """
    if not isinstance(stmt, A.SelectStmt):
        return None
    gb_exprs = () if stmt.group_by is None \
        or isinstance(stmt.group_by, A.GroupingSets) else tuple(stmt.group_by)
    # detect windows ANYWHERE — a window in WHERE/HAVING/GROUP BY must
    # reach the rejection below, not fall through to a host tier that
    # has no window evaluator at all
    if not any(_has_window(it.expr) for it in stmt.items) \
            and not any(_has_window(o.expr) for o in stmt.order_by) \
            and not _has_window(stmt.where) \
            and not _has_window(stmt.having) \
            and not any(_has_window(g) for g in gb_exprs):
        return None
    from spark_druid_olap_tpu.utils.config import WINDOW_ENABLED
    if not ctx.config.get(WINDOW_ENABLED):
        raise WindowUnsupported(
            "window functions are disabled (sdot.window.enabled=false)")
    for label, e in (("WHERE", stmt.where), ("HAVING", stmt.having)):
        if _has_window(e):
            raise WindowUnsupported(
                f"window functions are not allowed in {label}")
    gb = stmt.group_by
    if gb is not None and not isinstance(gb, A.GroupingSets):
        if any(_has_window(g) for g in gb):
            raise WindowUnsupported(
                "window functions are not allowed in GROUP BY")
    if stmt.distinct:
        raise WindowUnsupported(
            "SELECT DISTINCT with window functions is not supported")

    # output name per select item (the normal tiers' naming rule)
    named: List[Tuple[A.SelectItem, Optional[str], bool]] = []
    for i, it in enumerate(stmt.items):
        if it.expr == "*" or (isinstance(it.expr, E.Column)
                              and it.expr.name == "*"):
            named.append((it, None, False))
            continue
        if it.alias:
            name = it.alias
        elif isinstance(it.expr, E.Column):
            name = it.expr.name
        else:
            name = f"_c{i}"
        named.append((it, name, _has_window(it.expr)))

    # window inputs reuse matching output columns when the statement
    # already selects the same expression; bare columns are aliased to
    # their own name (the engine names plain dimension outputs by the
    # underlying column, so a synthetic alias would not survive the
    # pushdown tier); everything else gets a __w_* helper column
    aux: Dict[E.Expr, str] = {
        it.expr: nm for it, nm, hw in named
        if nm is not None and not hw}
    aux_order: List[Tuple[str, E.Expr]] = []
    counters = {"p": 0, "o": 0, "a": 0}

    def aux_col(e: E.Expr, kind: str) -> str:
        if _has_window(e):
            raise WindowUnsupported("nested window functions")
        name = aux.get(e)
        if name is None:
            if isinstance(e, E.Column):
                name = e.name
            else:
                name = f"__w_{kind}{counters[kind]}"
                counters[kind] += 1
            aux[e] = name
            aux_order.append((name, e))
        return name

    windows: List[WindowCol] = []
    by_call: Dict[E.WindowCall, str] = {}

    def lower_call(c: E.WindowCall) -> str:
        slot = by_call.get(c)
        if slot is not None:
            return slot
        if c.fn not in SUPPORTED_FNS:
            raise WindowUnsupported(f"window function {c.fn}()")
        if c.fn in RANKING_FNS + OFFSET_FNS and not c.order_by:
            raise WindowUnsupported(f"{c.fn}() requires ORDER BY")
        part = tuple(aux_col(p, "p") for p in c.partition_by)
        order = tuple((aux_col(o, "o"), asc) for o, asc in c.order_by)
        offset, default = 1, None
        args = c.args
        if c.fn in OFFSET_FNS:
            if not args:
                raise WindowUnsupported(f"{c.fn}() needs an argument")
            if len(args) >= 2:
                if not isinstance(args[1], E.Literal) \
                        or not isinstance(args[1].value, int):
                    raise WindowUnsupported(
                        f"{c.fn}() offset must be an integer literal")
                offset = args[1].value
            if len(args) >= 3:
                if not isinstance(args[2], E.Literal):
                    raise WindowUnsupported(
                        f"{c.fn}() default must be a literal")
                default = args[2].value
            args = args[:1]
        if c.fn in RANKING_FNS and args:
            raise WindowUnsupported(f"{c.fn}() takes no arguments")
        if c.fn == "count" and args \
                and isinstance(args[0], E.Column) and args[0].name == "*":
            args = ()
        arg_cols = tuple(aux_col(a, "a") for a in args)
        if c.fn in ("sum", "min", "max", "avg") and not arg_cols:
            raise WindowUnsupported(f"window {c.fn}() needs an argument")
        if c.frame is not None and c.fn not in AGG_FNS:
            raise WindowUnsupported(
                f"{c.fn}() does not accept a ROWS frame")
        slot = f"__w{len(windows)}"
        by_call[c] = slot
        windows.append(WindowCol(
            slot=slot, fn=c.fn, call=c, part_cols=part,
            order_cols=order, arg_cols=arg_cols,
            offset=offset, default=default, frame=c.frame))
        return slot

    def strip(e):
        """Replace every WindowCall in ``e`` with its slot column."""
        return E.transform(
            e, lambda n: E.Column(lower_call(n))
            if isinstance(n, E.WindowCall) else n)

    items: List[OutItem] = []
    base_items: List[A.SelectItem] = []
    for it, name, has_win in named:
        if name is None:                       # star passthrough
            base_items.append(it)
            items.append(OutItem(name="*", expr="*"))
            continue
        if has_win:
            items.append(OutItem(name=name, expr=strip(it.expr)))
        else:
            base_items.append(it if it.alias else
                              dataclasses.replace(it, alias=name))
            items.append(OutItem(name=name, expr=E.Column(name)))

    # deferred ordering: expressions referencing window outputs resolve
    # against the post-pass frame (output aliases are in scope, matching
    # the engine's ORDER BY alias resolution)
    order_by = tuple((strip(o.expr), o.ascending) for o in stmt.order_by)

    base_items.extend(A.SelectItem(expr=e, alias=n) for n, e in aux_order)
    base_stmt = dataclasses.replace(
        stmt, items=tuple(base_items), order_by=(), limit=None, offset=0)
    plan = WindowPlan(
        windows=tuple(windows), items=tuple(items),
        order_by=order_by, limit=stmt.limit, offset=stmt.offset,
        aux_cols=tuple(n for n, _ in aux_order))
    return base_stmt, plan
