"""FilterSpec -> vectorized device predicate masks.

The in-tree replacement for Druid's filter evaluation engine (the reference
only *models* filters — ``FilterSpec`` hierarchy ``DruidQuerySpec.scala:152-281``
— and ships them to Druid). Every filter lowers to a bool [S, R] mask over the
stacked segment tensors:

- selector  -> one integer compare on dictionary codes
- bound     -> two integer compares (sorted global dictionary ⇒ lexicographic
               bounds are code ranges; numeric bounds compare values directly)
- in        -> host ``np.isin`` over the dictionary -> constant code-mask gather
- like/regex/contains -> host regex over the dictionary -> code-mask gather
- expr      -> compiled XLA predicate (replaces the JavaScript filter)
- and/or/not, is-null, time-interval masks

The string->code rewrites live in ``encode/predicates.py``: they are the
dictionary-predicate half of the compressed columnar subsystem (the code
tests evaluate identically on plain or bit-packed codes, so an encoded
store filters without ever decoding a string — or even a code — on
host). This module owns only the device-mask lowering around them.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from spark_druid_olap_tpu.encode import predicates as P
from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ops import expr_compile as EC
from spark_druid_olap_tpu.ops import time_ops
from spark_druid_olap_tpu.ops.scan import ScanContext
from spark_druid_olap_tpu.segment.column import ColumnKind


def lower_filter(f: Optional[S.FilterSpec], ctx: ScanContext):
    """Lower a FilterSpec to a bool mask (None -> None, meaning all-true)."""
    if f is None:
        return None
    if isinstance(f, S.SelectorFilter):
        return _selector(f, ctx)
    if isinstance(f, S.BoundFilter):
        return _bound(f, ctx)
    if isinstance(f, S.InFilter):
        return _in(f, ctx)
    if isinstance(f, S.PatternFilter):
        return _pattern(f, ctx)
    if isinstance(f, S.NullFilter):
        nv = ctx.null_valid(f.dimension)
        valid = ctx.row_valid() if nv is None else nv
        return valid if f.negated else ~valid
    if isinstance(f, S.LogicalFilter):
        return _logical(f, ctx)
    if isinstance(f, S.ExprFilter):
        v = EC.compile_expr(f.expr, ctx)
        return EC._as_bool(v)
    if isinstance(f, S.SpatialFilter):
        return _spatial(f, ctx)
    raise EC.Unsupported(f"filter {type(f).__name__}")


def _false(ctx):
    return jnp.zeros_like(ctx.row_valid())


def _nullsafe(mask, name: str, ctx: ScanContext):
    nv = ctx.null_valid(name)
    return mask if nv is None else (mask & nv)


def _selector(f: S.SelectorFilter, ctx):
    kind = ctx.kind(f.dimension)
    if f.value is None:
        nv = ctx.null_valid(f.dimension)
        return ~nv if nv is not None else _false(ctx)
    if kind == ColumnKind.DIM:
        code = P.selector_code(ctx.ds.dims[f.dimension], f.value)
        if code < 0:
            return _false(ctx)
        return _nullsafe(ctx.col(f.dimension) == code, f.dimension, ctx)
    if kind in (ColumnKind.LONG, ColumnKind.DOUBLE):
        v = float(f.value) if kind == ColumnKind.DOUBLE else int(float(f.value))
        return _nullsafe(ctx.col(f.dimension) == v, f.dimension, ctx)
    if kind == ColumnKind.DATE:
        return ctx.col(f.dimension) == time_ops.date_literal_to_days(f.value)
    if kind == ColumnKind.TIME:
        # same literal policy as _time_bound: naive literals are
        # session-local, zoned ones absolute
        ms = time_ops.literal_to_utc_millis(f.value, ctx.tz)
        day, rem = divmod(ms, time_ops.MILLIS_PER_DAY)
        return (ctx.col(f.dimension) == day) & (ctx.time_ms() == rem)
    raise EC.Unsupported(f"selector on {kind}")


def _bound(f: S.BoundFilter, ctx):
    kind = ctx.kind(f.dimension)
    if kind == ColumnKind.DIM and not f.numeric:
        lo, hi = P.bound_code_range(
            ctx.ds.dims[f.dimension], f.lower, f.upper,
            f.lower_strict, f.upper_strict)
        if lo >= hi:
            return _false(ctx)
        codes = ctx.col(f.dimension)
        mask = None
        if lo > 0:
            mask = codes >= lo
        if hi < ctx.ds.dims[f.dimension].cardinality:
            m2 = codes < hi
            mask = m2 if mask is None else (mask & m2)
        if mask is None:
            nv = ctx.null_valid(f.dimension)
            return nv if nv is not None else ctx.row_valid()
        return _nullsafe(mask, f.dimension, ctx)
    if kind == ColumnKind.DIM and f.numeric:
        # numeric ordering over string dictionary: host-parse to LUT
        vals = ctx.dictionary(f.dimension)
        lut = np.array([_try_float(s) for s in vals], dtype=np.float32)
        arr = EC._take_lut(lut, ctx.col(f.dimension))
        return _nullsafe(_range_mask(arr, f, float), f.dimension, ctx)
    if kind in (ColumnKind.LONG, ColumnKind.DOUBLE):
        conv = float if kind == ColumnKind.DOUBLE else (lambda x: int(float(x)))
        return _nullsafe(_range_mask(ctx.col(f.dimension), f, conv),
                         f.dimension, ctx)
    if kind == ColumnKind.DATE:
        return _range_mask(ctx.col(f.dimension), f,
                           time_ops.date_literal_to_days)
    if kind == ColumnKind.TIME:
        return _time_bound(f, ctx)
    raise EC.Unsupported(f"bound on {kind}")


def _try_float(s):
    try:
        return float(s)
    except (TypeError, ValueError):
        return np.nan


def _range_mask(arr, f: S.BoundFilter, conv):
    mask = None
    if f.lower is not None:
        lo = conv(f.lower)
        m = (arr > lo) if f.lower_strict else (arr >= lo)
        mask = m
    if f.upper is not None:
        hi = conv(f.upper)
        m = (arr < hi) if f.upper_strict else (arr <= hi)
        mask = m if mask is None else (mask & m)
    return mask if mask is not None else (arr == arr)


def _time_bound(f: S.BoundFilter, ctx):
    days = ctx.col(f.dimension)
    ms = ctx.time_ms()
    mask = None

    if f.lower is not None:
        lo = time_ops.literal_to_utc_millis(f.lower, ctx.tz)
        d, r = divmod(lo, time_ops.MILLIS_PER_DAY)
        cmp = (ms > r) if f.lower_strict else (ms >= r)
        m = (days > d) | ((days == d) & cmp)
        mask = m
    if f.upper is not None:
        hi = time_ops.literal_to_utc_millis(f.upper, ctx.tz)
        d, r = divmod(hi, time_ops.MILLIS_PER_DAY)
        cmp = (ms < r) if f.upper_strict else (ms <= r)
        m = (days < d) | ((days == d) & cmp)
        mask = m if mask is None else (mask & m)
    return mask if mask is not None else ctx.row_valid()


def _in(f: S.InFilter, ctx):
    kind = ctx.kind(f.dimension)
    if isinstance(f.values, E.FrozenIntSet):
        # semi-join-scale membership: dense spans hit a packed-bitmap
        # gather, wide spans binary-search the sorted constant (shared
        # lowering, EC.int_set_membership)
        if kind not in (ColumnKind.LONG, ColumnKind.DATE):
            raise EC.Unsupported("large integer IN set over non-integer")
        vals = f.values.array
        if len(vals) == 0:
            return _false(ctx)
        arr = ctx.col(f.dimension)
        if arr.dtype != jnp.int64 and (
                int(vals[0]) < -(2**31) or int(vals[-1]) >= 2**31):
            raise EC.Unsupported("IN-set values exceed 32-bit column range")
        return _nullsafe(EC.int_set_membership(arr, vals),
                         f.dimension, ctx)
    if kind == ColumnKind.DIM:
        mask = P.in_code_mask(ctx.dictionary(f.dimension), f.values)
        return _nullsafe(EC._take_mask(mask, ctx.col(f.dimension)),
                         f.dimension, ctx)
    arr = ctx.col(f.dimension)
    out = None
    for v in f.values:
        if kind == ColumnKind.DATE:
            b = arr == time_ops.date_literal_to_days(v)
        elif kind == ColumnKind.DOUBLE:
            b = arr == float(v)
        else:
            b = arr == int(float(v))
        out = b if out is None else (out | b)
    return _nullsafe(out if out is not None else _false(ctx),
                     f.dimension, ctx)


def _pattern(f: S.PatternFilter, ctx):
    if ctx.kind(f.dimension) != ColumnKind.DIM:
        raise EC.Unsupported("pattern filter on non-string column")
    try:
        mask = P.pattern_code_mask(ctx.dictionary(f.dimension), f.kind,
                                   f.pattern,
                                   like_to_regex=EC.like_to_regex)
    except ValueError:
        raise EC.Unsupported(f"pattern kind {f.kind}") from None
    return _nullsafe(EC._take_mask(mask, ctx.col(f.dimension)),
                     f.dimension, ctx)


def _spatial(f: S.SpatialFilter, ctx):
    """Rectangular bound over the spatial dim's axis columns: fused per-axis
    inclusive range compares (the row-mask half; segment bounding-box
    pruning happens host-side in ``Datasource.prune_segments``)."""
    out = None
    for ax, lo, hi in zip(f.axes, f.min_coords, f.max_coords):
        arr = ctx.col(ax)
        m = None
        if lo is not None and np.isfinite(lo):
            m = arr >= lo
        if hi is not None and np.isfinite(hi):
            m2 = arr <= hi
            m = m2 if m is None else (m & m2)
        if m is not None:
            m = _nullsafe(m, ax, ctx)
            out = m if out is None else (out & m)
    return out if out is not None else ctx.row_valid()


def _logical(f: S.LogicalFilter, ctx):
    if f.op == "not":
        # BOOLEAN not (planner-generated wrappers — EXISTS encodings —
        # rely on it; SQL-level NOT gets its Kleene null guards added by
        # the builder at construction, builder._kleene_not)
        inner = lower_filter(f.fields[0], ctx)
        return ctx.row_valid() if inner is None else ~inner
    masks = [lower_filter(x, ctx) for x in f.fields]
    if f.op == "or":
        # an all-true (None) operand makes the whole OR all-true
        if not masks or any(m is None for m in masks):
            return None
    else:
        masks = [m for m in masks if m is not None]
        if not masks:
            return None
    out = masks[0]
    for m in masks[1:]:
        out = (out & m) if f.op == "and" else (out | m)
    return out


def interval_mask(intervals, ctx: ScanContext):
    """Residual device mask for time intervals (after host-side segment
    pruning; segments straddling an interval edge need the row-level mask).

    ≈ the reference's ``QueryIntervals`` constraints that Druid applies
    per-segment."""
    if not intervals or ctx.ds.time is None:
        return None
    days = ctx.col(ctx.ds.time.name)
    ms = ctx.time_ms()
    out = None
    for lo, hi in intervals:
        dlo, rlo, dhi, rhi = time_ops.interval_day_range(lo, hi)
        # open-ended interval bounds carry +-2^63-scale ms; their day
        # numbers overflow the i32 lanes on a 32-bit backend. Scanned days
        # all lie in [min_day, max_day], so clamping one day past that
        # range preserves the mask exactly.
        dlo = min(max(dlo, ctx.min_day - 1), ctx.max_day + 1)
        dhi = min(max(dhi, ctx.min_day - 1), ctx.max_day + 1)
        m_lo = (days > dlo) | ((days == dlo) & (ms >= rlo))
        m_hi = (days < dhi) | ((days == dhi) & (ms < rhi))
        m = m_lo & m_hi
        out = m if out is None else (out | m)
    return out


def columns_of_filter(f: Optional[S.FilterSpec]):
    """Source columns a filter touches (for array binding)."""
    if f is None:
        return set()
    if isinstance(f, (S.SelectorFilter, S.BoundFilter, S.InFilter,
                      S.PatternFilter, S.NullFilter)):
        return {f.dimension}
    if isinstance(f, S.SpatialFilter):
        return set(f.axes)
    if isinstance(f, S.LogicalFilter):
        out = set()
        for x in f.fields:
            out |= columns_of_filter(x)
        return out
    if isinstance(f, S.ExprFilter):
        from spark_druid_olap_tpu.ir import expr as E
        return E.columns_in(f.expr)
    return set()
