"""Dense group-by aggregation kernels with TPU-exact integer numerics.

The compute heart of the engine — the in-tree replacement for Druid's
historical-node groupBy/timeseries engine (the reference ships
``GroupByQuerySpec``/``TimeSeriesQuerySpec`` JSON to Druid,
``DruidQuerySpec.scala:638-744``; the actual scan/aggregate loop was never in
the repo. Here it is). Druid's aggregators are exact longs/doubles
(``DruidQuerySpec.scala:283-377``); matching that on a TPU — where f64 is
unsupported and i64 is emulated — is the point of the routing below.

Design (TPU-first):

- Group keys are **fused dictionary codes**: ``key = ((c0*card1)+c1)*card2+...``
  — dense in ``[0, K)`` because dictionaries are global and sorted. No hashing,
  no dynamic shapes.
- For small/medium K the kernel is a **blocked one-hot matmul**: scan over row
  blocks, ``acc += onehot(key).T @ values`` — sums/counts ride the MXU at f32
  throughput. min/max use masked VPU reductions per block.
- For large K it falls back to XLA ``segment_sum`` (scatter-add).
- Filtered-out rows get the sentinel key ``K`` which one-hot-misses every
  column (matmul path) / lands in a dropped overflow slot (scatter path):
  filtering is free, never a compaction.
- The output is a fixed-shape ``[K]``-family partial per chip — the shape ICI
  collectives want (replacing the reference's historical->broker HTTP merge,
  ``DruidStrategy.scala:349-360`` + ``PostAggregate.aggOp``).

Numeric routes (planned statically per aggregation by :func:`plan_route`):

- ``f64``   — CPU with x64: plain f64 accumulation, exact. One output array.
- ``ff``    — f32 backend (TPU): per-block sums + **compensated (Kahan)
  cross-block carry**. Outputs ``<name>.acc`` / ``<name>.c``; the true total
  is ``acc + c`` combined in f64 on host. Exact for integers when every block
  partial is exactly representable (guaranteed by the lane/route choice);
  ~1e-7-relative for floats (in-block MXU rounding only — the carry removes
  cross-block error growth).
- ``lanes`` — wide integers on the f32 matmul path: values split into four
  8-bit lanes, one matmul column per lane (block lane sums < 2^24 => exact
  f32), Kahan carries per lane, host combine ``sum(lane_l << 8l)`` => exact
  int64 totals up to ~2^47.
- ``limbs`` — integers on the scatter path: values split into 16-bit lanes,
  row-chunked i32 ``segment_sum`` (chunk partials bounded < 2^31), partials
  decomposed into four 16-bit limbs accumulated in i32 over a ``lax.scan``,
  renormalized with carry propagation. Host combine => exact int64. Renormed
  limbs are < 2^16, so cross-chip ``psum`` in i32 is exact for <= 2^15 chips.
- ``i32`` / ``f32`` — min/max/anyvalue in the value's own dtype with
  I32_MAX/I32_MIN / +-F32_MAX empty-group sentinels. Never round-trips an
  integer through f32 (the storage dtype for LONG/DATE/codes is i32, so i32
  compares are exact).

Cross-chip merge: routes with ``merged=True`` (limbs, i32/f32 min-max, f64)
merge on-device via psum/pmin/pmax inside shard_map; ``ff``/``lanes`` pairs
would lose low bits in an f32 psum, so they are returned **per chip**
(out_spec along the segment axis) and combined exactly in f64 on host — the
analog of the reference's historical-mode Spark-side final aggregate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

F32_MAX = jnp.float32(3.4e38)
I32_MAX = np.int32(2**31 - 1)
I32_MIN = np.int32(-(2**31))
I64_MAX = np.int64(2**63 - 1)
I64_MIN = np.int64(-(2**63))
N_LIMBS = 4
N_LANES = 4
FFL_LANES = 128              # 'ffl' route: per-VPU-lane compensated pairs
_CHUNK_ROWS = 1 << 14        # scatter-path row chunk: 2^16 * 2^14 < 2^31


def _x64() -> bool:
    return bool(jax.config.jax_enable_x64) and jax.default_backend() == "cpu"


@dataclasses.dataclass
class AggInput:
    """One lowered aggregation: kind in {'count','sum','min','max'};
    ``values`` is the [S, R] input (None for count); ``mask`` an optional
    per-agg filter mask (filtered aggregations, reference
    FilteredAggregationSpec). ``is_int``/``maxabs`` are static metadata
    driving the numeric route (column min/max from segment metadata)."""

    name: str
    kind: str
    values: Optional[object] = None
    mask: Optional[object] = None
    is_int: bool = False
    maxabs: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Route:
    """Static numeric route for one aggregation (see module docstring)."""

    name: str
    kind: str                 # count|sum|min|max
    tag: str                  # f64|i64|ff|lanes|limbs|i32|f32
    n_lanes: int = 1
    merged: bool = True       # device-collective merge vs per-chip host merge

    def outputs(self, n_keys: int):
        """[(output_name, flat_length, dtype_str)] this route emits."""
        if self.tag == "f64":
            return [(self.name, n_keys, "f64")]
        if self.tag == "i64":
            return [(self.name, n_keys, "i64")]
        if self.tag == "ff":
            return [(self.name + ".acc", n_keys, "f32"),
                    (self.name + ".c", n_keys, "f32")]
        if self.tag == "ffl":
            # fused-pallas sums: one compensated (acc, c) pair PER VPU
            # LANE — the 128-lane reduction happens in f64 on host, so
            # per-lane exactness is all the kernel must guarantee
            return [(self.name + ".acc", n_keys * FFL_LANES, "f32"),
                    (self.name + ".c", n_keys * FFL_LANES, "f32")]
        if self.tag == "lanes":
            return [(self.name + ".acc", n_keys * self.n_lanes, "f32"),
                    (self.name + ".c", n_keys * self.n_lanes, "f32")]
        if self.tag == "limbs":
            return [(self.name + ".limbs", n_keys * N_LIMBS, "i32")]
        if self.tag == "s64":
            # sorted-run wide int sums (ops/sorted_groupby.py): an exact
            # 64-bit total as an (hi: i32, lo: u32-bitcast-i32) limb pair
            return [(self.name + ".hi", n_keys, "i32"),
                    (self.name + ".lo", n_keys, "i32")]
        if self.tag == "i32":
            return [(self.name, n_keys, "i32")]
        return [(self.name, n_keys, "f32")]


def choose_path(n_keys: int, matmul_max: int) -> str:
    """'matmul' (one-hot MXU) vs 'scatter' (XLA segment ops)."""
    if _x64():
        # x64 only happens off-TPU; scatter keeps native-i64 sums exact at
        # any magnitude (and CPU BLAS loses to scatter-add anyway)
        return "scatter"
    if jax.default_backend() == "cpu" and n_keys > 64:
        # the one-hot matmul only pays off on the MXU; CPU BLAS loses badly
        # to vectorized scatter-add at moderate K (TPC-H q9 on CPU: 31x)
        return "scatter"
    return "matmul" if n_keys <= matmul_max else "scatter"


def plan_route(name: str, kind: str, is_int: bool, maxabs: Optional[float],
               path: str, blk: int,
               n_rows: Optional[int] = None) -> Route:
    """Decide the numeric route for one aggregation. Static — callable at
    plan time (no traced values)."""
    if kind in ("min", "max"):
        if _x64():
            # native-64-bit compares: i64 exact for wide ints, f64 for
            # doubles; 32-bit backends keep the i32/f32 routes
            return Route(name, kind, "i64" if is_int else "f64")
        return Route(name, kind, "i32" if is_int else "f32")
    if _x64():
        # native-i64 sums are exact at any magnitude; f64 for doubles
        return Route(name, kind, "i64" if (is_int or kind == "count")
                     else "f64")
    if path == "scatter":
        if kind == "count" or is_int:
            if n_rows is not None and maxabs is not None \
                    and maxabs * n_rows < 2**31:
                # the WHOLE table's contribution fits i32: one exact
                # scatter-add pass, no limb splitting/chunk scan (the
                # q18-class hot path — sum(l_quantity) over 1.5M keys)
                return Route(name, kind, "i32")
            return Route(name, kind, "limbs")
        return Route(name, kind, "ff", merged=False)
    # matmul path
    if kind == "count":
        # mask contributes 1.0 per row; block sums <= blk < 2^24 => exact
        return Route(name, kind, "ff", merged=False)
    if is_int:
        if maxabs is not None and maxabs * blk < 2**24:
            return Route(name, kind, "ff", merged=False)
        return Route(name, kind, "lanes", n_lanes=N_LANES, merged=False)
    return Route(name, kind, "ff", merged=False)


def plan_routes(inputs: Sequence[AggInput], n_keys: int,
                matmul_max: int, pallas_max: int = 0,
                n_rows: Optional[int] = None) -> Dict[str, Route]:
    path = choose_path(n_keys, matmul_max)
    blk = _block_size(n_keys, 1 << 30)
    use_pallas = False
    if pallas_max:
        from spark_druid_olap_tpu.ops import pallas_groupby as PG
        use_pallas = PG.eligible(n_keys, inputs, pallas_max,
                                 n_rows=n_rows)
    out = {}
    for a in inputs:
        if use_pallas and a.kind in ("sum", "count"):
            # the fused kernel's sums travel as per-lane Kahan pairs
            out[a.name] = Route(a.name, a.kind, "ffl", merged=False)
        else:
            out[a.name] = plan_route(a.name, a.kind, a.is_int, a.maxabs,
                                     path, blk, n_rows=n_rows)
    return out


def run_weighted_partials(run_values, run_lengths, n_keys: int,
                          run_sums=None) -> Dict[str, np.ndarray]:
    """RLE-aware host partials: aggregate run-at-a-time instead of
    row-at-a-time. A run of length L with key k contributes L to
    count[k] in one add — the count partial IS the run length — and a
    pre-reduced per-run metric sum lands in sum[k] the same way, so a
    group-by over an RLE-encoded dimension touches O(runs) values
    (encode/exec.py:rle_groupby drives this over encoded chunks; keys
    outside [0, n_keys) — filtered sentinels — drop, matching the
    device kernels' overflow-slot semantics). Exact: counts accumulate
    in int64, sums in f64."""
    counts = np.zeros(n_keys, dtype=np.int64)
    out = {"count": counts}
    run_values = np.asarray(run_values)
    run_lengths = np.asarray(run_lengths, dtype=np.int64)
    if len(run_lengths) == 0:
        if run_sums is not None:
            out["sum"] = np.zeros(n_keys, dtype=np.float64)
        return out
    keep = (run_values >= 0) & (run_values < n_keys)
    v = run_values[keep].astype(np.int64)
    np.add.at(counts, v, run_lengths[keep])
    if run_sums is not None:
        sums = np.zeros(n_keys, dtype=np.float64)
        np.add.at(sums, v, np.asarray(run_sums, dtype=np.float64)[keep])
        out["sum"] = sums
    return out


def fuse_keys(code_arrays: Sequence[object], cards: Sequence[int]):
    """Fuse per-dim codes into one dense int32 key in [0, prod(cards))."""
    assert len(code_arrays) == len(cards) and len(cards) > 0
    key = code_arrays[0].astype(jnp.int32)
    for codes, card in zip(code_arrays[1:], cards[1:]):
        key = key * jnp.int32(card) + codes.astype(jnp.int32)
    total = 1
    for c in cards:
        total *= int(c)
    return key, total


def unfuse_key(indices, cards: Sequence[int]):
    """Host-side inverse of fuse_keys: group index -> per-dim codes."""
    out = []
    rem = np.asarray(indices, dtype=np.int64)
    for card in reversed(list(cards)):
        out.append(rem % card)
        rem = rem // card
    return list(reversed(out))


# =============================================================================
# host-side combine of route outputs -> final numpy values
# =============================================================================

def combine_route(route: Route, out: Dict[str, np.ndarray],
                  n_keys: int) -> np.ndarray:
    """Route outputs (possibly with a leading per-chip axis for unmerged
    routes in sharded mode) -> one exact [n_keys] f64/i64-valued array.

    min/max sentinels are preserved (caller maps them to null)."""
    def chips(x, cols=1):
        x = np.asarray(x)
        return x.reshape(-1, n_keys * cols)      # [n_chips, K*cols]

    if route.tag == "f64":
        return np.asarray(out[route.name], np.float64)
    if route.tag == "i64":
        return np.asarray(out[route.name], np.int64)
    if route.tag == "ff":
        acc = chips(out[route.name + ".acc"]).astype(np.float64)
        c = chips(out[route.name + ".c"]).astype(np.float64)
        return (acc + c).sum(axis=0)
    if route.tag == "ffl":
        acc = chips(out[route.name + ".acc"], FFL_LANES).astype(np.float64)
        c = chips(out[route.name + ".c"], FFL_LANES).astype(np.float64)
        return (acc + c).sum(axis=0).reshape(n_keys, FFL_LANES).sum(axis=1)
    if route.tag == "lanes":
        ln = route.n_lanes
        acc = chips(out[route.name + ".acc"], ln).astype(np.float64)
        c = chips(out[route.name + ".c"], ln).astype(np.float64)
        tot = (acc + c).sum(axis=0).reshape(n_keys, ln)
        scale = np.float64(256.0) ** np.arange(ln)
        return tot @ scale
    if route.tag == "s64":
        hi = np.asarray(out[route.name + ".hi"]).astype(np.int64)
        lo = np.asarray(out[route.name + ".lo"]).view(np.uint32) \
            .astype(np.int64)
        return (hi << 32) | lo
    if route.tag == "limbs":
        limbs = np.asarray(out[route.name + ".limbs"]) \
            .reshape(n_keys, N_LIMBS).astype(np.int64)
        val = np.zeros(n_keys, dtype=np.int64)
        carry = np.zeros(n_keys, dtype=np.int64)
        for i in range(N_LIMBS):
            v = limbs[:, i] + carry
            if i < N_LIMBS - 1:
                carry = v >> 16
                val += (v & 0xFFFF) << (16 * i)
            else:
                val += v << (16 * i)
        return val
    return np.asarray(out[route.name])


def int_lanes8(v):
    """Split i32 values into four 8-bit lanes (top lane signed)."""
    v = v.astype(jnp.int32)
    return [(v & 0xFF).astype(jnp.float32),
            ((v >> 8) & 0xFF).astype(jnp.float32),
            ((v >> 16) & 0xFF).astype(jnp.float32),
            (v >> 24).astype(jnp.float32)]


# =============================================================================
# kernels
# =============================================================================

def dense_groupby(key, mask, n_keys: int, inputs: List[AggInput],
                  routes: Dict[str, Route],
                  matmul_max: int = 4096) -> Dict[str, object]:
    """Aggregate ``inputs`` grouped by dense ``key`` under ``mask``.

    key: int32 [S, R] (or any shape); mask: bool same shape (row validity &
    query filter already folded in). Returns dict output_name -> array per
    each route's ``outputs`` contract. Callers must include a '__rows__'
    count input (used to drop empty groups — Druid groupBy only emits
    existing groups).
    """
    key = jnp.where(mask, key, jnp.int32(n_keys))
    path = choose_path(n_keys, matmul_max)

    if any(r.tag == "ffl" for r in routes.values()):
        # plan_routes is the single source of truth for the fused-kernel
        # decision (it assigns 'ffl' to every sum/count iff eligible);
        # re-deriving eligibility here from local shapes could disagree
        # with the planned route set
        from spark_druid_olap_tpu.ops import pallas_groupby as PG
        flat = PG.pallas_dense_groupby(key, n_keys, [
            dataclasses.replace(
                a, values=None if a.values is None
                else a.values.reshape(-1),
                mask=None if a.mask is None else a.mask.reshape(-1))
            for a in inputs])
        return _pallas_to_routes(flat, inputs, routes)
    if path == "scatter":
        return _scatter_groupby(key, mask, n_keys, inputs, routes)
    return _matmul_groupby(key.reshape(-1), mask.reshape(-1), n_keys,
                           inputs, routes)


def _pallas_to_routes(flat: Dict[str, object], inputs: List[AggInput],
                      routes: Dict[str, Route]) -> Dict[str, object]:
    """Adapt the pallas kernel's outputs to the route contract: sums and
    counts arrive as [K, 128] per-lane Kahan (acc, comp) pairs for the
    'ffl' route; min/max arrive as reduced [K] f32 (exact under the
    eligible() gate, so route-dtype conversion is lossless)."""
    out: Dict[str, object] = {}
    for a in inputs:
        r = routes[a.name]
        v = flat[a.name]
        if r.tag == "ffl":
            acc, comp = v                        # [K, 128] each
            out[r.name + ".acc"] = acc.reshape(-1)
            out[r.name + ".c"] = comp.reshape(-1)  # Neumaier: acc + comp
        elif r.tag == "i32":
            big = jnp.abs(v) >= F32_MAX
            iv = jnp.clip(v, -2.0**31 + 1, 2.0**31 - 1).astype(jnp.int32)
            sent = I32_MAX if r.kind == "min" else I32_MIN
            out[r.name] = jnp.where(big, jnp.int32(sent), iv)
        elif r.tag == "f64":
            if r.kind in ("min", "max"):
                # kernel empty-group sentinel (+-3.4e38) -> the f64
                # route's +-inf sentinel, or the group would decode as a
                # huge value instead of NULL
                big = jnp.abs(v) >= F32_MAX
                sent = jnp.inf if r.kind == "min" else -jnp.inf
                out[r.name] = jnp.where(big, sent, v.astype(jnp.float64))
            else:
                out[r.name] = v.astype(jnp.float64)
        elif r.tag == "i64":
            big = jnp.abs(v) >= F32_MAX          # empty-group f32 sentinel
            sent = I64_MAX if r.kind == "min" else I64_MIN
            out[r.name] = jnp.where(
                big, sent, jnp.round(v).astype(jnp.int64))
        else:
            out[r.name] = v
    return out


def _block_size(n_keys: int, n: int) -> int:
    # keep the onehot block around ~16M f32 elements
    target = max(1024, (1 << 24) // max(n_keys, 1))
    target = min(target, 1 << 16)
    return int(min(n, (target // 1024) * 1024 or 1024))


def _matmul_groupby(key, mask, n_keys, inputs, routes):
    n = key.shape[0]
    blk = _block_size(n_keys, n)
    nb = -(-n // blk)
    padded = nb * blk
    x64 = _x64()
    sum_dtype = jnp.float64 if x64 else jnp.float32

    def prep(arr, fill, dtype=None):
        arr = arr.reshape(-1)
        if dtype is not None:
            arr = arr.astype(dtype)
        if padded > n:
            arr = jnp.pad(arr, (0, padded - n), constant_values=fill)
        return arr.reshape(nb, blk)

    keys = prep(key, n_keys)
    masks = prep(mask, False)

    # Sum-matmul columns: each (agg, lane). count contributes its mask as
    # 1.0; 'lanes' aggs contribute 4 byte-lane columns.
    sum_aggs = [a for a in inputs if a.kind in ("sum", "count")]
    minmax = [a for a in inputs if a.kind in ("min", "max")]
    col_of = {}              # agg name -> (start_col, n_lanes)
    sum_cols = []            # list of [nb, blk] f32/f64 value blocks
    sum_masks = []           # matching effective-mask blocks
    col_is_count = []        # static per-column flag
    for a in sum_aggs:
        r = routes[a.name]
        am = masks if a.mask is None else prep(a.mask, False)
        start = len(sum_cols)
        if a.kind == "count":
            col_of[a.name] = (start, 1)
            sum_cols.append(masks)             # placeholder; mask is value
            sum_masks.append(am)
            col_is_count.append(True)
        elif r.tag == "lanes":
            col_of[a.name] = (start, r.n_lanes)
            for lane in int_lanes8(a.values):
                sum_cols.append(prep(lane, 0, sum_dtype))
                sum_masks.append(am)
                col_is_count.append(False)
        else:
            col_of[a.name] = (start, 1)
            sum_cols.append(prep(a.values, 0, sum_dtype))
            sum_masks.append(am)
            col_is_count.append(False)
    m_cols = len(sum_cols)

    mm_route = [routes[a.name] for a in minmax]
    _mm_dt = {"i32": jnp.int32, "f64": jnp.float64}
    mm_vals = [prep(a.values, 0,
                    _mm_dt.get(routes[a.name].tag, jnp.float32))
               for a in minmax]
    mm_masks = [prep(a.mask, False) if a.mask is not None else masks
                for a in minmax]

    iota = jnp.arange(n_keys, dtype=jnp.int32)

    def body(carry, xs):
        k_blk, m_blk, svals, smasks, mvals, mmasks = xs
        onehot = (k_blk[:, None] == iota[None, :])               # [blk, K]
        acc_sums, comp, acc_min, acc_max = carry
        if m_cols:
            cols = []
            for is_cnt, v, am in zip(col_is_count, svals, smasks):
                eff = am & m_blk
                if is_cnt:
                    cols.append(eff.astype(sum_dtype))
                else:
                    cols.append(v * eff.astype(sum_dtype))
            x = jnp.stack(cols, axis=1)                          # [blk, M]
            blk_sums = jax.lax.dot(onehot.astype(sum_dtype).T, x,
                                   preferred_element_type=sum_dtype)
            if x64:
                acc_sums = acc_sums + blk_sums
            else:
                # Kahan: exact carries keep integer totals exact (block
                # sums are exactly representable by route construction)
                y = blk_sums - comp
                t = acc_sums + y
                comp = (t - acc_sums) - y
                acc_sums = t
        new_min, new_max = list(acc_min), list(acc_max)
        for i, (r, v, am) in enumerate(zip(mm_route, mvals, mmasks)):
            eff = am & m_blk
            sel = onehot & eff[:, None]
            if r.tag == "i32":
                lo_s, hi_s = I32_MIN, I32_MAX
            elif r.tag == "f64":
                lo_s, hi_s = -jnp.inf, jnp.inf
            else:
                lo_s, hi_s = -F32_MAX, F32_MAX
            if r.kind == "min":
                cur = jnp.min(jnp.where(sel, v[:, None], hi_s), axis=0)
                new_min[i] = jnp.minimum(acc_min[i], cur)
            else:
                cur = jnp.max(jnp.where(sel, v[:, None], lo_s), axis=0)
                new_max[i] = jnp.maximum(acc_max[i], cur)
        return (acc_sums, comp, new_min, new_max), None

    sval_xs = sum_cols

    def mm_init(r, kind):
        if r.tag == "i32":
            fill = I32_MAX if kind == "min" else I32_MIN
            return jnp.full((n_keys,), fill, dtype=jnp.int32)
        if r.tag == "f64":
            fill = jnp.inf if kind == "min" else -jnp.inf
            return jnp.full((n_keys,), fill, dtype=jnp.float64)
        fill = F32_MAX if kind == "min" else -F32_MAX
        return jnp.full((n_keys,), fill, dtype=jnp.float32)

    init = (jnp.zeros((n_keys, m_cols), dtype=sum_dtype),
            jnp.zeros((n_keys, m_cols), dtype=sum_dtype),
            [mm_init(r, "min") for r in mm_route],
            [mm_init(r, "max") for r in mm_route])
    (sums, comp, mins, maxs), _ = jax.lax.scan(
        body, init, (keys, masks, sval_xs, sum_masks, mm_vals, mm_masks))

    out: Dict[str, object] = {}
    for a in sum_aggs:
        r = routes[a.name]
        start, nl = col_of[a.name]
        if r.tag == "f64":
            out[r.name] = sums[:, start]
        else:
            acc = sums[:, start: start + nl]
            c = -comp[:, start: start + nl]     # true sum = acc - comp
            if nl == 1:
                acc, c = acc[:, 0], c[:, 0]
            else:
                acc, c = acc.reshape(-1), c.reshape(-1)
            out[r.name + ".acc"] = acc
            out[r.name + ".c"] = c
    for i, a in enumerate(minmax):
        out[a.name] = mins[i] if a.kind == "min" else maxs[i]
    return out


def _kahan_axis0(arr):
    """Compensated sum over axis 0 of [S, K] f32 -> (acc, c) with
    true total == acc + c (f64-combined on host)."""
    def step(carry, row):
        acc, comp = carry
        y = row - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp), None

    init = (jnp.zeros(arr.shape[1:], arr.dtype),
            jnp.zeros(arr.shape[1:], arr.dtype))
    (acc, comp), _ = jax.lax.scan(step, init, arr)
    return acc, -comp


def _scatter_groupby(key, mask, n_keys, inputs, routes):
    """Large-K path: XLA segment ops per route (see module docstring)."""
    out: Dict[str, object] = {}
    num = n_keys + 1  # overflow slot for masked-out rows
    if key.ndim == 1:
        key = key[None, :]
        mask = mask[None, :]
    x64 = _x64()

    def seg2d(a):
        return a.reshape(key.shape)

    def seg_sum(a, am, dtype):
        """Masked per-segment scatter-add in ``dtype``, summed across
        segments: the one shared body of the i32/i64/f64 sum routes."""
        if a.values is None:                 # count: the mask is the value
            v = am.astype(dtype)
        else:
            v = jnp.where(am, seg2d(a.values).astype(dtype),
                          jnp.zeros((), dtype))
        per = jax.vmap(lambda x, k: jax.ops.segment_sum(x, k, num))(v, key)
        return per.sum(axis=0)[:n_keys]

    for a in inputs:
        r = routes[a.name]
        am = mask if a.mask is None else (mask & seg2d(a.mask))
        if r.tag in ("f64", "i64") and r.kind in ("min", "max"):
            if r.tag == "i64":
                sent = I64_MAX if r.kind == "min" else I64_MIN
                v = jnp.where(am, seg2d(a.values).astype(jnp.int64), sent)
            else:
                sent = jnp.inf if r.kind == "min" else -jnp.inf
                v = jnp.where(am, seg2d(a.values).astype(jnp.float64), sent)
            op = jax.ops.segment_min if r.kind == "min" \
                else jax.ops.segment_max
            per = jax.vmap(lambda x, k: op(x, k, num))(v, key)
            red = per.min(axis=0) if r.kind == "min" else per.max(axis=0)
            out[r.name] = red[:n_keys]
        elif r.tag == "i64":
            # native 64-bit sums: exact at any magnitude (x64 backends only)
            out[r.name] = seg_sum(a, am, jnp.int64)
        elif r.tag == "f64":
            out[r.name] = seg_sum(a, am, jnp.float64)
        elif r.tag == "i32" and r.kind in ("sum", "count"):
            # single-pass exact i32 scatter-add (static bound
            # maxabs * total_rows < 2^31 — no limb splitting needed)
            out[r.name] = seg_sum(a, am, jnp.int32)
        elif r.tag == "limbs":
            ones = jnp.ones(key.shape, jnp.int32)
            v = ones if a.kind == "count" else seg2d(a.values) \
                .astype(jnp.int32)
            v = jnp.where(am, v, 0)
            k_eff = jnp.where(am, key, jnp.int32(n_keys))
            out[r.name + ".limbs"] = _limb_scatter_sum(v, k_eff, n_keys)
        elif r.tag == "ff":
            v = seg2d(a.values).astype(jnp.float32) * am.astype(jnp.float32)
            per_seg = jax.vmap(lambda x, k: jax.ops.segment_sum(x, k, num))(
                v, key)
            acc, c = _kahan_axis0(per_seg[:, :n_keys])
            out[r.name + ".acc"] = acc
            out[r.name + ".c"] = c
        elif r.kind == "min":
            if r.tag == "i32":
                v = jnp.where(am, seg2d(a.values).astype(jnp.int32), I32_MAX)
                dt_min = jax.vmap(
                    lambda x, k: jax.ops.segment_min(x, k, num))(v, key)
                out[r.name] = dt_min.min(axis=0)[:n_keys]
            else:
                v = jnp.where(am, seg2d(a.values).astype(jnp.float32),
                              F32_MAX)
                per = jax.vmap(
                    lambda x, k: jax.ops.segment_min(x, k, num))(v, key)
                out[r.name] = per.min(axis=0)[:n_keys]
        elif r.kind == "max":
            if r.tag == "i32":
                v = jnp.where(am, seg2d(a.values).astype(jnp.int32), I32_MIN)
                per = jax.vmap(
                    lambda x, k: jax.ops.segment_max(x, k, num))(v, key)
                out[r.name] = per.max(axis=0)[:n_keys]
            else:
                v = jnp.where(am, seg2d(a.values).astype(jnp.float32),
                              -F32_MAX)
                per = jax.vmap(
                    lambda x, k: jax.ops.segment_max(x, k, num))(v, key)
                out[r.name] = per.max(axis=0)[:n_keys]
        else:
            raise ValueError(f"route {r.tag}/{r.kind}")
    return out


def renorm_limbs(l0, l1, l2, l3):
    """Propagate carries so limbs 0..2 land in [0, 2^16) (top limb signed,
    two's-complement correct for negative totals). Needed after a psum of
    independently-renormalized per-chip limbs."""
    c0 = l0 >> 16
    l0 = l0 & 0xFFFF
    l1 = l1 + c0
    c1 = l1 >> 16
    l1 = l1 & 0xFFFF
    l2 = l2 + c1
    c2 = l2 >> 16
    l2 = l2 & 0xFFFF
    l3 = l3 + c2
    return l0, l1, l2, l3


def literal_limbs(v: int):
    """The four 16-bit limbs of a python int in the renormalized layout
    (limbs 0..2 unsigned, top limb signed/arithmetic)."""
    v = int(v)
    return ((v & 0xFFFF), (v >> 16) & 0xFFFF, (v >> 32) & 0xFFFF, v >> 48)


def limbs_compare(limbs, lit: int, op: str):
    """Exact device comparison of renormalized limb totals vs an int
    literal: lexicographic from the signed top limb down (lower limbs are
    unsigned, so per-limb i32 compares are exact at any total magnitude).
    ``limbs`` is [n_keys, 4]; returns bool [n_keys]."""
    l = renorm_limbs(limbs[:, 0], limbs[:, 1], limbs[:, 2], limbs[:, 3])
    t = literal_limbs(lit)
    eq = None
    gt = None
    for i in (3, 2, 1, 0):
        li = l[i]
        ti = jnp.int32(t[i])
        gi = li > ti
        ei = li == ti
        if gt is None:
            gt, eq = gi, ei
        else:
            gt = gt | (eq & gi)
            eq = eq & ei
    if op == ">":
        return gt
    if op == ">=":
        return gt | eq
    if op == "<":
        return ~(gt | eq)
    if op == "<=":
        return ~gt
    if op == "=":
        return eq
    return ~eq                                     # '!='


def _limb_scatter_sum(values, key, n_keys: int):
    """Exact 64-bit grouped integer sum without i64/f64: 16-bit value lanes,
    row-chunked i32 segment_sums, 16-bit limb accumulation over a scan.

    values: i32 [S, R] (masked rows already 0); key: i32 [S, R] (masked rows
    at sentinel n_keys). Returns renormalized i32 limbs flat [n_keys*4]
    (limbs 0..2 in [0, 2^16), top limb signed).
    """
    num = n_keys + 1
    total = int(np.prod(values.shape))
    rc = min(_CHUNK_ROWS, total)
    n_chunks = -(-total // rc)
    pad = n_chunks * rc - total
    v = values.reshape(-1)
    k = key.reshape(-1)
    if pad:
        v = jnp.pad(v, (0, pad))
        k = jnp.pad(k, (0, pad), constant_values=n_keys)
    v = v.reshape(n_chunks, rc)
    k = k.reshape(n_chunks, rc)

    renorm = renorm_limbs

    def step(limbs, xs):
        vc, kc = xs
        lo = vc & 0xFFFF                       # [rc] in [0, 2^16)
        hi = vc >> 16                          # signed
        p_lo = jax.ops.segment_sum(lo, kc, num)   # < 2^30
        p_hi = jax.ops.segment_sum(hi, kc, num)   # |.| < 2^29
        l0 = limbs[0] + (p_lo & 0xFFFF)
        l1 = limbs[1] + (p_lo >> 16) + (p_hi & 0xFFFF)
        l2 = limbs[2] + (p_hi >> 16)
        # per-step renorm keeps every limb < 2^16 regardless of chunk
        # count, so no row-count ceiling (carries land in the top limb)
        return list(renorm(l0, l1, l2, limbs[3])), None

    init = [jnp.zeros(num, jnp.int32) for _ in range(N_LIMBS)]
    limbs, _ = jax.lax.scan(step, init, (v, k))
    stacked = jnp.stack(list(renorm(*limbs)), axis=1)   # [num, 4]
    return stacked[:n_keys].reshape(-1)


def route_score(route: Route, out: Dict[str, object], n_keys: int,
                axis_name: Optional[str] = None):
    """Device-side per-key value of one aggregation reconstructed from its
    route outputs — the *selection* score for top-k epilogues.

    Exact for f64/i64/i32/f32 routes; f32-rounded (~1e-7 relative) for the
    split-representation routes (ff pairs, byte lanes, 16-bit limbs). The
    final ordering of the selected candidates is still done with the exact
    host combine, so rounding here only affects which keys make the
    candidate set — callers add slack beyond the requested limit. Inside
    shard_map pass ``axis_name``: per-chip partial routes (merged=False)
    are psum'd to the global value; merged routes are already global.
    """
    t = route.tag
    if t in ("f64", "i64"):
        return out[route.name].astype(
            jnp.float64 if _x64() else jnp.float32)
    if t == "ffl":
        v = (out[route.name + ".acc"] + out[route.name + ".c"]) \
            .reshape(n_keys, FFL_LANES).sum(axis=1)
    elif t == "ff":
        v = out[route.name + ".acc"] + out[route.name + ".c"]
    elif t == "lanes":
        acc = out[route.name + ".acc"].reshape(n_keys, route.n_lanes)
        c = out[route.name + ".c"].reshape(n_keys, route.n_lanes)
        scale = jnp.float32(256.0) ** jnp.arange(
            route.n_lanes, dtype=jnp.float32)
        v = ((acc + c) * scale[None, :]).sum(axis=1)
    elif t == "limbs":
        limbs = out[route.name + ".limbs"].reshape(n_keys, N_LIMBS) \
            .astype(jnp.float32)
        scale = jnp.float32(65536.0) ** jnp.arange(
            N_LIMBS, dtype=jnp.float32)
        v = (limbs * scale[None, :]).sum(axis=1)
    elif t == "s64":
        hi = out[route.name + ".hi"].astype(jnp.float32)
        lo = jax.lax.bitcast_convert_type(
            out[route.name + ".lo"], jnp.uint32).astype(jnp.float32)
        v = hi * jnp.float32(4294967296.0) + lo
    elif t == "i32":
        v = out[route.name].astype(jnp.float32)
    else:
        v = out[route.name]
    if axis_name is not None and not route.merged:
        v = jax.lax.psum(v, axis_name)
    return v


def route_null_mask(route: Route, out: Dict[str, object]):
    """Device bool mask of keys whose min/max metric is NULL (the
    empty-group sentinel survived: every contributing row was masked by
    the per-agg filter). None for sum/count routes (their NULL identity is
    0 — indistinguishable from a true zero sum by design)."""
    if route.kind not in ("min", "max"):
        return None
    v = out[route.name]
    if route.tag == "i32":
        sent = I32_MAX if route.kind == "min" else I32_MIN
    elif route.tag == "i64":
        sent = I64_MAX if route.kind == "min" else I64_MIN
    elif route.tag == "f64":
        sent = jnp.inf if route.kind == "min" else -jnp.inf
    else:
        sent = F32_MAX if route.kind == "min" else -F32_MAX
    return v == sent


def merge_partials(partials: Dict[str, object], routes: Dict[str, Route],
                   axis_name: str) -> Dict[str, object]:
    """Cross-chip merge of per-chip partials via ICI collectives (inside
    shard_map) for the ``merged`` routes. ≈ the broker merge / Spark-side
    final HashAggregate (reference DruidStrategy.scala:349-360). Unmerged
    (ff/lanes) outputs must be returned per-chip by the caller."""
    out = {}
    for name, arr in partials.items():
        base = name.split(".")[0]
        r = routes.get(base)
        if r is None:
            out[name] = jax.lax.psum(arr, axis_name)
        elif not r.merged:
            out[name] = arr                    # caller keeps per-chip
        elif r.kind == "min":
            out[name] = jax.lax.pmin(arr, axis_name)
        elif r.kind == "max":
            out[name] = jax.lax.pmax(arr, axis_name)
        else:                                  # limbs / f64 / i32 sums
            out[name] = jax.lax.psum(arr, axis_name)
    return out


# Sketch register algebras by sketch family — the runtime source of
# truth the lint pass (tools/sdlint/mergeclosure.py) cross-checks each
# AGG_CLOSURE ``merge`` declaration against. Keep this a plain literal.
SKETCH_MERGE_OPS = {"hll": "max", "theta": "min", "kll": "minsum"}


def merge_lane_partials(out, routes: Dict[str, Route],
                        sketch_kinds: Dict[str, str], axis_name: str):
    """Cross-chip merge of ONE lane's complete output dict — the single
    mergeable-partial layout every sharded program (solo executor cores
    and the mesh execution tier, parallel/meshexec.py) folds with:

    - dense routes via :func:`merge_partials` — exactly the register
      algebra ``AGG_CLOSURE.merge`` declares (``psum`` sums/counts,
      ``pmin``/``pmax`` extrema); unmerged ff/lanes pairs stay per-chip
      for the exact f64 host combine,
    - sketch registers via their own register algebra: HLL rho registers
      are maxima (``hll.merge_registers``), theta k-min registers are
      minima (``theta.merge_registers``), KLL survivor registers are a
      lex-min over (tiebreak, value) plus an exact count psum
      (``kll.merge_registers``) — never plain addition.

    ``sketch_kinds`` maps output name -> "hll" | "theta" | "kll" for the
    lane's register-valued aggregations (algebra per SKETCH_MERGE_OPS).
    """
    from spark_druid_olap_tpu.ops import hll as _hll
    from spark_druid_olap_tpu.ops import kll as _kll
    from spark_druid_olap_tpu.ops import theta as _theta
    dense = {k: v for k, v in out.items() if k not in sketch_kinds}
    merged = merge_partials(dense, routes, axis_name)
    folds = {"hll": _hll.merge_registers, "theta": _theta.merge_registers,
             "kll": _kll.merge_registers}
    for name, sk in sketch_kinds.items():
        merged[name] = folds[sk](out[name], axis_name)
    return merged
