"""Dense group-by aggregation kernels.

The compute heart of the engine — the in-tree replacement for Druid's
historical-node groupBy/timeseries engine (the reference ships
``GroupByQuerySpec``/``TimeSeriesQuerySpec`` JSON to Druid,
``DruidQuerySpec.scala:638-744``; the actual scan/aggregate loop was never in
the repo. Here it is).

Design (TPU-first):

- Group keys are **fused dictionary codes**: ``key = ((c0*card1)+c1)*card2+...``
  — dense in ``[0, K)`` because dictionaries are global and sorted. No hashing,
  no dynamic shapes.
- For small/medium K the kernel is a **blocked one-hot matmul**: scan over row
  blocks, ``acc += onehot(key).T @ values`` — sums/counts ride the MXU at
  bf16/f32 throughput instead of relying on scatter-add. min/max use masked
  VPU reductions per block.
- For large K it falls back to XLA ``segment_sum`` (scatter-add).
- Filtered-out rows get the sentinel key ``K`` which one-hot-misses every
  column (matmul path) / lands in a dropped overflow slot (scatter path):
  filtering is free, never a compaction.
- The output is a fixed-shape ``[K]`` partial per chip — exactly the shape ICI
  collectives want: cross-chip merge is ``psum``/``pmin``/``pmax`` (replacing
  the reference's historical->broker HTTP merge,
  ``DruidStrategy.scala:349-360`` + ``PostAggregate.aggOp``).
"""

from __future__ import annotations

import dataclasses
from functools import reduce
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

F32_MAX = jnp.float32(3.4e38)


@dataclasses.dataclass
class AggInput:
    """One lowered aggregation: kind in {'count','sum','min','max'};
    ``values`` is the [S, R] input (None for count); ``mask`` an optional
    per-agg filter mask (filtered aggregations,
    reference FilteredAggregationSpec)."""

    name: str
    kind: str
    values: Optional[object] = None
    mask: Optional[object] = None


def fuse_keys(code_arrays: Sequence[object], cards: Sequence[int]):
    """Fuse per-dim codes into one dense int32 key in [0, prod(cards))."""
    assert len(code_arrays) == len(cards) and len(cards) > 0
    key = code_arrays[0].astype(jnp.int32)
    for codes, card in zip(code_arrays[1:], cards[1:]):
        key = key * jnp.int32(card) + codes.astype(jnp.int32)
    total = 1
    for c in cards:
        total *= int(c)
    return key, total


def unfuse_key(indices, cards: Sequence[int]):
    """Host-side inverse of fuse_keys: group index -> per-dim codes."""
    import numpy as np
    out = []
    rem = np.asarray(indices, dtype=np.int64)
    for card in reversed(list(cards)):
        out.append(rem % card)
        rem = rem // card
    return list(reversed(out))


def default_sum_dtype():
    """f64 accumulation on CPU (exact differential tests, cheap there); f32 on
    TPU where the MXU does the work and f64 would be software-emulated."""
    if jax.default_backend() == "cpu" and jax.config.jax_enable_x64:
        return jnp.float64
    return jnp.float32


def dense_groupby(key, mask, n_keys: int, inputs: List[AggInput],
                  matmul_max: int = 4096,
                  sum_dtype=None, pallas_max: int = 0) -> Dict[str, object]:
    """Aggregate ``inputs`` grouped by dense ``key`` under ``mask``.

    key: int32 [S, R] (or any shape); mask: bool same shape (row validity &
    query filter already folded in). Returns dict name -> [n_keys] array,
    plus '__rows__' (matched-row count per group, used to drop empty groups —
    Druid groupBy only emits existing groups).

    Kernel selection: fused Pallas single-pass kernel for small K on TPU
    (``pallas_max``), MXU one-hot matmul up to ``matmul_max``, XLA
    scatter-add above.
    """
    key = jnp.where(mask, key, jnp.int32(n_keys))
    inputs = list(inputs) + [AggInput("__rows__", "count")]
    if sum_dtype is None:
        sum_dtype = default_sum_dtype()

    if pallas_max:
        from spark_druid_olap_tpu.ops import pallas_groupby as PG
    if pallas_max and PG.supported(n_keys, inputs, pallas_max):
        return PG.pallas_dense_groupby(key, n_keys, [
            dataclasses.replace(
                a, values=None if a.values is None else a.values.reshape(-1),
                mask=None if a.mask is None else a.mask.reshape(-1))
            for a in inputs])
    if jax.default_backend() == "cpu" and n_keys > 64:
        # the one-hot matmul only pays off on the MXU; CPU BLAS loses badly
        # to vectorized scatter-add at moderate K (TPC-H q9 on CPU: 31x)
        return _scatter_groupby(key, mask, n_keys, inputs, sum_dtype)
    if n_keys <= matmul_max:
        return _matmul_groupby(key.reshape(-1), mask.reshape(-1), n_keys,
                               inputs, sum_dtype)
    return _scatter_groupby(key, mask, n_keys, inputs, sum_dtype)


def _block_size(n_keys: int, n: int) -> int:
    # keep the onehot block around ~16M f32 elements
    target = max(1024, (1 << 24) // max(n_keys, 1))
    target = min(target, 1 << 16)
    return int(min(n, (target // 1024) * 1024 or 1024))


def _matmul_groupby(key, mask, n_keys, inputs, sum_dtype):
    n = key.shape[0]
    blk = _block_size(n_keys, n)
    nb = -(-n // blk)
    padded = nb * blk

    def prep(arr, fill):
        arr = arr.reshape(-1)
        if padded > n:
            arr = jnp.pad(arr, (0, padded - n), constant_values=fill)
        return arr.reshape(nb, blk)

    keys = prep(key, n_keys)
    masks = prep(mask, False)

    # columns of the sum matmul: count-likes contribute their mask as 1.0
    sum_cols = [a for a in inputs if a.kind in ("sum", "count")]
    minmax = [a for a in inputs if a.kind in ("min", "max")]
    sum_vals = [prep(a.values, 0) if a.kind == "sum" else None
                for a in sum_cols]
    sum_masks = [prep(a.mask, False) if a.mask is not None else None
                 for a in sum_cols]
    mm_vals = [prep(a.values, 0) for a in minmax]
    mm_masks = [prep(a.mask, False) if a.mask is not None else None
                for a in minmax]

    iota = jnp.arange(n_keys, dtype=jnp.int32)

    def body(carry, xs):
        k_blk, m_blk, svals, smasks, mvals, mmasks = xs
        onehot = (k_blk[:, None] == iota[None, :])               # [blk, K]
        acc_sums, acc_min, acc_max = carry
        if sum_cols:
            cols = []
            for a, v, am in zip(sum_cols, svals, smasks):
                eff = m_blk if am is None else (m_blk & am)
                if a.kind == "count":
                    cols.append(eff.astype(sum_dtype))
                else:
                    cols.append(v.astype(sum_dtype)
                                * eff.astype(sum_dtype))
            x = jnp.stack(cols, axis=1)                          # [blk, M]
            # block dot rides the MXU (f32 on TPU); cross-block carry in the
            # widest available float so counts and large sums stay exact
            blk_sums = jax.lax.dot(onehot.astype(sum_dtype).T, x,
                                   preferred_element_type=sum_dtype)
            acc_sums = acc_sums + blk_sums.astype(acc_sums.dtype)  # [K, M]
        new_min, new_max = list(acc_min), list(acc_max)
        for i, (a, v, am) in enumerate(zip(minmax, mvals, mmasks)):
            eff = m_blk if am is None else (m_blk & am)
            sel = onehot & eff[:, None]
            vf = v.astype(jnp.float32)
            if a.kind == "min":
                cur = jnp.min(jnp.where(sel, vf[:, None], F32_MAX), axis=0)
                new_min[i] = jnp.minimum(acc_min[i], cur)
            else:
                cur = jnp.max(jnp.where(sel, vf[:, None], -F32_MAX), axis=0)
                new_max[i] = jnp.maximum(acc_max[i], cur)
        return (acc_sums, new_min, new_max), None

    # scan xs must be arrays; None masks are represented by reusing `masks`
    # (equivalent: eff == m_blk) to keep the pytree static.
    smask_xs = [m if m is not None else masks for m in sum_masks]
    mmask_xs = [m if m is not None else masks for m in mm_masks]
    sval_xs = [v if v is not None else masks for v in sum_vals]

    acc_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    init = (jnp.zeros((n_keys, len(sum_cols)), dtype=acc_dtype),
            [jnp.full((n_keys,), F32_MAX) for _ in minmax],
            [jnp.full((n_keys,), -F32_MAX) for _ in minmax])
    (sums, mins, maxs), _ = jax.lax.scan(
        body, init, (keys, masks, sval_xs, smask_xs, mm_vals, mmask_xs))

    out: Dict[str, object] = {}
    for i, a in enumerate(sum_cols):
        out[a.name] = sums[:, i]
    for i, a in enumerate(minmax):
        out[a.name] = mins[i] if a.kind == "min" else maxs[i]
    return out


def _scatter_groupby(key, mask, n_keys, inputs, sum_dtype):
    """Large-K path: per-segment XLA segment_sum/min/max, then widest-float
    reduction across the segment axis."""
    out: Dict[str, object] = {}
    num = n_keys + 1  # overflow slot for masked-out rows
    if key.ndim == 1:
        key = key[None, :]
        mask = mask[None, :]
    acc_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

    def seg2d(a):
        return a.reshape(key.shape)

    for a in inputs:
        am = mask if a.mask is None else (mask & seg2d(a.mask))
        if a.kind == "count":
            vals = am.astype(jnp.float32)
            per_seg = jax.vmap(lambda v, k: jax.ops.segment_sum(v, k, num))(
                vals, key)
            out[a.name] = per_seg.astype(acc_dtype).sum(axis=0)[:n_keys]
        elif a.kind == "sum":
            v = seg2d(a.values).astype(sum_dtype) * am.astype(sum_dtype)
            per_seg = jax.vmap(lambda x, k: jax.ops.segment_sum(x, k, num))(
                v, key)
            out[a.name] = per_seg.astype(acc_dtype).sum(axis=0)[:n_keys]
        elif a.kind == "min":
            v = jnp.where(am, seg2d(a.values).astype(jnp.float32), F32_MAX)
            per_seg = jax.vmap(lambda x, k: jax.ops.segment_min(x, k, num))(
                v, key)
            out[a.name] = per_seg.min(axis=0)[:n_keys]
        elif a.kind == "max":
            v = jnp.where(am, seg2d(a.values).astype(jnp.float32), -F32_MAX)
            per_seg = jax.vmap(lambda x, k: jax.ops.segment_max(x, k, num))(
                v, key)
            out[a.name] = per_seg.max(axis=0)[:n_keys]
        else:
            raise ValueError(a.kind)
    return out


def merge_partials(partials: Dict[str, object], inputs: List[AggInput],
                   axis_name: str) -> Dict[str, object]:
    """Cross-chip merge of per-chip [K] partials via ICI collectives
    (inside shard_map). ≈ the broker merge / Spark-side final HashAggregate
    (reference DruidStrategy.scala:349-360)."""
    kinds = {a.name: a.kind for a in inputs}
    kinds["__rows__"] = "count"
    out = {}
    for name, arr in partials.items():
        k = kinds.get(name, "sum")
        if k in ("sum", "count"):
            out[name] = jax.lax.psum(arr, axis_name)
        elif k == "min":
            out[name] = jax.lax.pmin(arr, axis_name)
        elif k == "max":
            out[name] = jax.lax.pmax(arr, axis_name)
        else:
            out[name] = jax.lax.psum(arr, axis_name)
    return out
