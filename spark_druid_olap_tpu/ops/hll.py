"""HyperLogLog approximate count-distinct, grouped, on device.

Druid-parity capability: the reference pushes ``count(distinct x)`` down as a
``cardinality``/``hyperUnique`` aggregation (``AggregationSpec``
``DruidQuerySpec.scala:340-360``, planner side
``AggregateTransform.ApproximateCountAggregate:454-479``); the sketch itself
ran inside Druid. This module is that sketch engine:

- hash: murmur3 finalizer over int32 dictionary codes / values (VPU ops);
- register index = low ``p`` bits, rho = leading-zero count of the remaining
  bits (``lax.clz``) + 1;
- grouped register maxima via one ``segment_max`` over the fused
  ``group_key * m + register`` space — [K, m] registers in one scatter pass;
- host-side harmonic-mean estimation with the standard small/large-range
  corrections (matches Druid's default 2^11 registers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _murmur_fmix32(x):
    """murmur3 finalizer — avalanches int32 values (uint32 wraparound)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hll_registers(key, mask, values, n_keys: int, log2m: int = 11):
    """Per-group HLL register maxima.

    key: [N] int32 dense group key (sentinel n_keys for masked-out rows);
    values: [N] int32 (dictionary codes or integer-viewed values).
    Returns int32 [n_keys, m] register array (rho values, 0 = empty).
    """
    m = 1 << log2m
    h = _murmur_fmix32(values.reshape(-1))
    reg = (h & jnp.uint32(m - 1)).astype(jnp.int32)
    w = h >> jnp.uint32(log2m)            # (32 - p) significant bits
    # rho = position of first 1-bit in w within (32-p) bits, 1-based;
    # w == 0 -> (32 - p) + 1
    clz = jax.lax.clz(w.astype(jnp.int32))  # counts over 32 bits
    rho = jnp.where(w == 0, jnp.int32(32 - log2m + 1),
                    clz - jnp.int32(log2m) + 1).astype(jnp.int32)
    key = key.reshape(-1)
    mask = mask.reshape(-1)
    fused = jnp.where(mask, key, jnp.int32(n_keys)) * jnp.int32(m) + reg
    regs = jax.ops.segment_max(
        rho, fused, num_segments=(n_keys + 1) * m, indices_are_sorted=False)
    regs = jnp.maximum(regs, 0)           # segment_max fills empty with dtype-min
    return regs[: n_keys * m].reshape(n_keys, m)


def merge_registers(regs, axis_name: str):
    """Cross-chip merge = elementwise max (inside shard_map)."""
    return jax.lax.pmax(regs, axis_name)


def estimate(regs: np.ndarray) -> np.ndarray:
    """Host-side HLL estimate per group from [K, m] registers."""
    regs = np.asarray(regs)
    k, m = regs.shape
    if m >= 128:
        alpha = 0.7213 / (1 + 1.079 / m)
    elif m == 64:
        alpha = 0.709
    elif m == 32:
        alpha = 0.697
    else:
        alpha = 0.673
    z = np.sum(np.power(2.0, -regs.astype(np.float64)), axis=1)
    e = alpha * m * m / z
    zeros = np.sum(regs == 0, axis=1)
    small = (e <= 2.5 * m) & (zeros > 0)
    with np.errstate(divide="ignore"):
        lin = m * np.log(m / np.maximum(zeros, 1).astype(np.float64))
    e = np.where(small, lin, e)
    big = e > (1 << 32) / 30.0
    e = np.where(big, -(1 << 32) * np.log1p(-e / (1 << 32)), e)
    return e
