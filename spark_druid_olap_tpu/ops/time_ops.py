"""Calendar / time-bucketing kernels — pure int32, XLA-friendly.

Replaces two reference facilities at once:

- Druid's ``timeFormat``/``timeParsing`` extraction functions and query
  granularities (reference ``DruidQuerySpec.scala:31-103``,
  ``DruidQueryGranularity.scala``), and
- the Joda-backed JavaScript date code generation
  (``jscodegen/JSDateTime.scala``).

Everything operates on **int32 days since 1970-01-01 UTC** (plus int32
millis-in-day when sub-day precision is needed) — never int64 on device. The
civil-calendar conversion uses Howard Hinnant's ``civil_from_days`` algorithm
expressed in vectorized integer ops, so year/month/day extraction compiles to
a handful of VPU instructions with no lookup tables.
"""

from __future__ import annotations

import datetime as _dt

import jax.numpy as jnp
import numpy as np

MILLIS_PER_DAY = 86_400_000


def interval_day_range(lo_ms: int, hi_ms: int):
    """Split a [lo_ms, hi_ms) interval into the (day, millis-in-day)
    split the engine stores time in: (day_lo, rem_lo, day_hi, rem_hi).
    Shared by the device residual mask (ops/filters.py:interval_mask)
    and the FoR-domain chunk pruning (encode/exec.py) — a fordelta time
    chunk whose header day bounds miss [day_lo, day_hi] is skipped
    without decoding, the same arithmetic either way."""
    day_lo, rem_lo = divmod(int(lo_ms), MILLIS_PER_DAY)
    day_hi, rem_hi = divmod(int(hi_ms), MILLIS_PER_DAY)
    return day_lo, rem_lo, day_hi, rem_hi


def civil_from_days(days):
    """days-since-epoch -> (year, month, day), vectorized int32.

    Hinnant's algorithm (http://howardhinnant.github.io/date_algorithms.html),
    valid for +/- ~5.8M years; all intermediates fit int32 for any realistic
    OLAP time range.
    """
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096), 365)                # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))               # [0, 365]
    mp = jnp.floor_divide(5 * doy + 2, 153)                  # [0, 11]
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1          # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)                       # [1, 12]
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y: int, m: int, d: int) -> int:
    """Host-side inverse (for lowering date literals)."""
    return (_dt.date(y, m, d) - _dt.date(1970, 1, 1)).days


def date_literal_to_days(value) -> int:
    """Lower a date literal ('1995-03-15', date, datetime, numpy datetime64)
    to days-since-epoch."""
    if isinstance(value, (int, np.integer)):
        return int(value)
    if isinstance(value, np.datetime64):
        return int(value.astype("datetime64[D]").astype(np.int64))
    if isinstance(value, _dt.datetime):
        value = value.date()
    if isinstance(value, _dt.date):
        return (value - _dt.date(1970, 1, 1)).days
    s = str(value).strip()[:10]
    y, m, d = (int(p) for p in s.split("-"))
    return days_from_civil(y, m, d)


def date_literal_to_millis(value) -> int:
    if isinstance(value, str) and ("T" in value or " " in value.strip()):
        value = _dt.datetime.fromisoformat(
            value.strip().replace("Z", "+00:00"))
    if isinstance(value, _dt.datetime):
        # keep sub-day precision (the parser lowers `timestamp '...'` to
        # a datetime; flooring it to days would silently widen filters)
        if value.tzinfo is not None:
            value = value.astimezone(_dt.timezone.utc).replace(tzinfo=None)
        return int((value - _dt.datetime(1970, 1, 1))
                   .total_seconds() * 1000)
    if isinstance(value, np.datetime64):
        return int(value.astype("datetime64[ms]").astype(np.int64))
    return date_literal_to_days(value) * MILLIS_PER_DAY


def literal_is_zoned(value) -> bool:
    """True when a time literal carries an EXPLICIT zone/offset — it is
    then an absolute instant and must NOT be re-shifted by the session
    timezone."""
    if isinstance(value, _dt.datetime):
        return value.tzinfo is not None
    if isinstance(value, str):
        s = value.strip()
        if "T" in s or " " in s:
            try:
                return _dt.datetime.fromisoformat(
                    s.replace("Z", "+00:00")).tzinfo is not None
            except ValueError:
                return False
    return False


def literal_to_utc_millis(value, tz: str) -> int:
    """The ONE policy for time-literal lowering: zoned literals are
    absolute instants; naive ones mean session-local wall clock
    (reference: spark.sparklinedata.tz.id driving DateTimeExtractor)."""
    ms = date_literal_to_millis(value)
    from spark_druid_olap_tpu.ops import timezone as TZ
    if not TZ.is_utc(tz) and not literal_is_zoned(value):
        ms = TZ.local_naive_to_utc_millis(tz, ms)
    return ms


# -- field extraction ---------------------------------------------------------

def extract_field(field: str, days, ms_in_day=None):
    """Extract a calendar field from int32 day numbers (VPU-vectorized)."""
    if field == "epoch_day":
        return days
    if field in ("year", "month", "day", "quarter"):
        y, m, d = civil_from_days(days)
        if field == "year":
            return y
        if field == "month":
            return m
        if field == "day":
            return d
        return jnp.floor_divide(m - 1, 3) + 1
    if field == "dow":
        # ISO: Monday=1..Sunday=7; day 0 (1970-01-01) was a Thursday
        return jnp.mod(days + 3, 7) + 1
    if field == "doy":
        y, _, _ = civil_from_days(days)
        jan1 = days_of_jan1(y)
        return days - jan1 + 1
    if field == "week":
        # week index since epoch, Monday-aligned (for bucketing, not ISO week#)
        return jnp.floor_divide(days + 3, 7)
    if field == "hour":
        assert ms_in_day is not None
        return jnp.floor_divide(ms_in_day, 3_600_000)
    if field == "minute":  # minute-of-hour (SQL EXTRACT semantics)
        assert ms_in_day is not None
        return jnp.mod(jnp.floor_divide(ms_in_day, 60_000), 60)
    if field == "second":  # second-of-minute
        assert ms_in_day is not None
        return jnp.mod(jnp.floor_divide(ms_in_day, 1000), 60)
    raise ValueError(f"unsupported time field {field!r}")


def days_of_jan1(y):
    """days-since-epoch of January 1st of year ``y`` (vectorized)."""
    yp = y - 1
    # days before year y since year 0, Gregorian
    d = 365 * yp + jnp.floor_divide(yp, 4) - jnp.floor_divide(yp, 100) \
        + jnp.floor_divide(yp, 400) + 1
    return d - 719163  # days from 0000-01-01 to 1970-01-01 is 719162 (+1 offset)


def year_month_index(days):
    """Monotone month index (year*12 + month-1) — a month-granularity bucket
    id that is order-preserving and cheap to decode."""
    y, m, _ = civil_from_days(days)
    return y * 12 + (m - 1)


# -- granularity bucketing ----------------------------------------------------

def bucket_and_cardinality(kind: str, days, ms_in_day, min_day: int,
                           max_day: int, duration_millis=None):
    """Map each row to a dense granularity-bucket id in [0, card).

    Returns (bucket int32 array, card, decode) where ``decode(idx)`` is a
    host-side function from bucket id -> representative epoch-millis (bucket
    start), used to materialize the output time column
    (≈ Druid result rows' "timestamp" field).
    """
    if kind == "all":
        return jnp.zeros_like(days), 1, lambda i: np.int64(min_day) * MILLIS_PER_DAY
    if kind == "day":
        card = max_day - min_day + 1
        return days - min_day, card, \
            lambda i: (np.int64(i) + min_day) * MILLIS_PER_DAY
    if kind == "week":
        lo = (min_day + 3) // 7
        hi = (max_day + 3) // 7
        card = hi - lo + 1
        return jnp.floor_divide(days + 3, 7) - lo, card, \
            lambda i: (np.int64(i + lo) * 7 - 3) * MILLIS_PER_DAY
    if kind == "month":
        lo = _host_year_month_index(min_day)
        hi = _host_year_month_index(max_day)
        card = hi - lo + 1
        return year_month_index(days) - lo, card, \
            lambda i: _month_index_to_millis(int(i) + lo)
    if kind == "quarter":
        lo = _host_year_month_index(min_day) // 3
        hi = _host_year_month_index(max_day) // 3
        card = hi - lo + 1
        return jnp.floor_divide(year_month_index(days), 3) - lo, card, \
            lambda i: _month_index_to_millis((int(i) + lo) * 3)
    if kind == "year":
        y_lo = _host_civil(min_day)[0]
        y_hi = _host_civil(max_day)[0]
        card = y_hi - y_lo + 1
        y, _, _ = civil_from_days(days)
        return y - y_lo, card, \
            lambda i: np.int64(days_from_civil(int(i) + y_lo, 1, 1)) * MILLIS_PER_DAY
    if kind == "hour":
        lo = min_day * 24
        card = (max_day + 1) * 24 - lo
        b = days * 24 + jnp.floor_divide(ms_in_day, 3_600_000) - lo
        return b, card, lambda i: (np.int64(i) + lo) * 3_600_000
    if kind == "minute":
        lo = min_day * 1440
        card = (max_day + 1) * 1440 - lo
        b = days * 1440 + jnp.floor_divide(ms_in_day, 60_000) - lo
        return b, card, lambda i: (np.int64(i) + lo) * 60_000
    if kind == "duration":
        assert duration_millis is not None
        g = int(duration_millis)
        if g % MILLIS_PER_DAY == 0:
            gd = g // MILLIS_PER_DAY
            lo = min_day // gd
            card = max_day // gd - lo + 1
            return jnp.floor_divide(days, gd) - lo, card, \
                lambda i: (np.int64(i) + lo) * gd * MILLIS_PER_DAY
        if MILLIS_PER_DAY % g == 0:
            per_day = MILLIS_PER_DAY // g
            lo = min_day * per_day
            card = (max_day + 1) * per_day - lo
            b = days * per_day + jnp.floor_divide(ms_in_day, g) - lo
            return b, card, lambda i: (np.int64(i) + lo) * g
        raise ValueError(
            f"duration {g}ms neither divides nor is divisible by a day; "
            "unsupported on the int32 device path")
    raise ValueError(f"unsupported granularity {kind!r}")


def _host_civil(day: int):
    d = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(day))
    return d.year, d.month, d.day


def _host_year_month_index(day: int) -> int:
    y, m, _ = _host_civil(day)
    return y * 12 + (m - 1)


def _month_index_to_millis(idx: int) -> np.int64:
    y, m = divmod(int(idx), 12)
    return np.int64(days_from_civil(y, m + 1, 1)) * MILLIS_PER_DAY


GRANULARITY_FIELDS = {"year": "year", "quarter": "quarter", "month": "month",
                      "week": "week", "day": "day", "hour": "hour",
                      "minute": "minute"}
