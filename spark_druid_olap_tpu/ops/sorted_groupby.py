"""Sorted-run aggregation for the hashed group-by tier.

The hashed tier's slot assignment (``hash_groupby.build_slots``) already
pays ONE ``lax.sort`` over the fused key pairs — ~1.3ms/6M rows on a v5e,
plus ~4ms per extra payload operand. The existing aggregation then
scatters every aggregation's values into its slot (~40ms per 6M-row
scatter on v5e, XLA's measured cost regardless of index order) — q18-class
programs stack ~6 of those. This module replaces the scatters entirely:

- **Ride the aggregation values as sort payloads.** After the sort, every
  group's rows are one contiguous run.
- **Sums** become prefix-sum + run-boundary difference. Integer sums run
  in (emulated) int64 — two's-complement prefix wrap-around cancels in
  the difference, so any per-group total that fits i64 is EXACT (wider
  than the 4-limb route's practical range, with no chunked carry scan).
  Counts fit i32 by construction.
- **Float sums** use a SEGMENTED compensated scan (TwoSum carry inside an
  ``associative_scan`` that resets at run starts) — per-group error stays
  ~log2(run) ulps of the GROUP total. A plain prefix-sum difference would
  carry the PREFIX magnitude's cancellation error into small groups,
  which is why the naive version is wrong and this one is not.
- **min/max** use a segmented scan with the same reset flag.
- **Per-group finals** sit at each run's LAST row; a ``searchsorted``
  over the (sorted, nondecreasing) group-id vector finds the T run-end
  positions — log2(N) rounds of T-probe 1D gathers (take1d discipline),
  ~log2(6M) * T probes total, versus 6M scatter updates per agg.

Outputs keep the hashed tier's existing contracts (``groupby.Route``
outputs / ``combine_route`` / host key-wise merge): ``i32`` for counts
and provably-in-range int sums, the new ``s64`` hi/lo pair for wide int
sums, the ``ff`` (acc, c) pair for float sums, ``i32``/``f32``(/x64
``i64``/``f64``) sentinel min-max. Table keys/'__unres__' match
``build_slots`` exactly (sorted occupied prefix, EMPTY padding).

Backend economics: on TPU the sort is ~30x cheaper than one scatter, so
this path wins whenever >=1 aggregation exists; the CPU fallback's x64
sort is the expensive op (~0.3s/M rows measured) while its scatters are
cheap, so the executor gates this to TPU backends (config-overridable —
tests force it on CPU for differential coverage).

≈ reference scope: the groupBy v2 per-segment aggregation the reference
delegated to Druid historicals (``DruidQuerySpec.scala:638-683``); the
sort-based formulation is original TPU design.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_druid_olap_tpu.ops import hash_groupby as H
from spark_druid_olap_tpu.ops.groupby import (
    AggInput,
    F32_MAX,
    I32_MAX,
    I32_MIN,
    I64_MAX,
    I64_MIN,
    Route,
    _x64,
)

SUPPORTED_KINDS = ("count", "sum", "min", "max")


def plan_sorted_routes(inputs: List[AggInput],
                       n_rows: Optional[int] = None) -> Optional[Dict[str, Route]]:
    """Routes for the sorted-run core, or None when some aggregation kind
    is outside its reach (sketches -> caller keeps the scatter path).
    Static — callable at plan time."""
    out: Dict[str, Route] = {}
    for a in inputs:
        if a.kind not in SUPPORTED_KINDS:
            return None
        if a.kind in ("min", "max"):
            if _x64():
                out[a.name] = Route(a.name, a.kind,
                                    "i64" if a.is_int else "f64")
            else:
                out[a.name] = Route(a.name, a.kind,
                                    "i32" if a.is_int else "f32")
        elif a.kind == "count":
            out[a.name] = Route(a.name, a.kind,
                                "i64" if _x64() else "i32")
        elif a.is_int:
            if _x64():
                out[a.name] = Route(a.name, a.kind, "i64")
            elif n_rows is not None and a.maxabs is not None \
                    and a.maxabs * n_rows < 2**31:
                out[a.name] = Route(a.name, a.kind, "i32")
            else:
                out[a.name] = Route(a.name, a.kind, "s64")
        else:
            out[a.name] = Route(a.name, a.kind,
                                "f64" if _x64() else "ff", merged=False)
    return out


def _seg_scan(flag, vals, combine_vals):
    """Segmented scan: inclusive scan of ``vals`` that RESETS wherever
    ``flag`` is True (run starts). Classic associative segmented-scan
    lifting: op((f1,v1),(f2,v2)) = (f1|f2, f2 ? v2 : combine(v1,v2))."""
    def op(a, b):
        fa, va = a[0], a[1:]
        fb, vb = b[0], b[1:]
        keep_b = fb
        merged = combine_vals(va, vb)
        vals_out = tuple(jnp.where(keep_b, y, m)
                         for y, m in zip(vb, merged))
        return (fa | fb,) + vals_out

    res = jax.lax.associative_scan(op, (flag,) + tuple(vals))
    return res[1:]


def _two_sum(a, b):
    """Knuth TwoSum: s + e == a + b exactly (f32)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def _end_positions(gid_sorted, T: int):
    """Run-end position of each of the first ``T`` group ids — binary
    search over the nondecreasing [N] gid vector: log2(N) rounds of
    T-probe 1D gathers (cheap) instead of any N-update scatter."""
    n = gid_sorted.shape[0]
    q = jnp.arange(T, dtype=jnp.int32)
    lo = jnp.zeros((T,), jnp.int32)
    hi = jnp.full((T,), n, jnp.int32)
    steps = int(np.ceil(np.log2(max(n, 2)))) + 1

    def body(_, st):
        lo_, hi_ = st
        mid = (lo_ + hi_) // 2
        mid_c = jnp.clip(mid, 0, n - 1)
        gv = jnp.take(gid_sorted, mid_c)     # 1D gather (take1d shape)
        less_eq = gv <= q
        lo_ = jnp.where(less_eq & (lo_ < hi_), mid + 1, lo_)
        hi_ = jnp.where((~less_eq) & (lo_ < hi_), mid, hi_)
        return lo_, hi_

    lo, _ = jax.lax.fori_loop(0, steps, body, (lo, hi))
    # lo = first index with gid > g == one past run end
    return jnp.clip(lo - 1, 0, n - 1), lo


def _cumsum64(v32):
    """Inclusive prefix sum of i32 values in TRUE 64-bit on a 32-bit
    backend (jnp.int64 silently canonicalizes to i32 there): the value is
    a (hi: i32, lo: u32) limb pair combined with add-with-carry in an
    associative scan. 64-bit limb addition is associative, so the scan is
    exact; the run-boundary difference then subtracts with borrow."""
    lo = v32.astype(jnp.uint32)
    hi = jnp.where(v32 < 0, jnp.int32(-1), jnp.int32(0))

    def op(a, b):
        ahi, alo = a
        bhi, blo = b
        slo = alo + blo                       # u32 wrap
        carry = (slo < alo).astype(jnp.int32)
        return ahi + bhi + carry, slo

    return jax.lax.associative_scan(op, (hi, lo))


def _sub64(ahi, alo, bhi, blo):
    """(a - b) on (hi i32, lo u32) pairs, with borrow."""
    lo = alo - blo
    borrow = (alo < blo).astype(jnp.int32)
    return ahi - bhi - borrow, lo


def sorted_hash_groupby(khi, klo, valid, T: int, inputs: List[AggInput],
                        routes: Dict[str, Route]) -> Dict[str, object]:
    """One-sort hashed group-by: returns the same output dict the
    ``build_slots`` + ``dense_groupby`` pair produces — route outputs per
    ``Route.outputs(T)`` plus '__tkhi__', '__tklo__', '__unres__'."""
    x64 = _x64()
    n = khi.reshape(-1).shape[0]
    khi_f = jnp.where(valid.reshape(-1), khi.reshape(-1).astype(jnp.int32),
                      H.EMPTY)
    klo_f = jnp.where(valid.reshape(-1), klo.reshape(-1).astype(jnp.int32),
                      H.EMPTY)

    # payloads: pre-masked per-agg value vectors (masking BEFORE the sort
    # keeps the per-agg filter masks off the sort operand list)
    payloads = []
    meta = []                      # (agg, route, payload slice indices)
    for a in inputs:
        r = routes[a.name]
        base = valid.reshape(-1)
        am = base if a.mask is None else (base & a.mask.reshape(-1))
        if a.kind == "count":
            payloads.append(am.astype(jnp.int32))
            meta.append((a, r, (len(payloads) - 1,)))
            continue
        v = a.values.reshape(-1)
        if a.kind in ("min", "max"):
            if r.tag == "i32":
                sent = I32_MAX if a.kind == "min" else I32_MIN
                v = jnp.where(am, v.astype(jnp.int32), sent)
            elif r.tag == "i64":
                sent = I64_MAX if a.kind == "min" else I64_MIN
                v = jnp.where(am, v.astype(jnp.int64), sent)
            elif r.tag == "f64":
                sent = jnp.float64(np.inf if a.kind == "min" else -np.inf)
                v = jnp.where(am, v.astype(jnp.float64), sent)
            else:
                sent = F32_MAX if a.kind == "min" else -F32_MAX
                v = jnp.where(am, v.astype(jnp.float32), sent)
        else:
            if r.tag in ("i32", "s64", "i64"):
                v = jnp.where(am, v.astype(
                    jnp.int64 if (x64 and r.tag == "i64")
                    else jnp.int32), 0)
            else:
                v = jnp.where(am, v.astype(
                    jnp.float64 if r.tag == "f64" else jnp.float32), 0.0)
        payloads.append(v)
        meta.append((a, r, (len(payloads) - 1,)))

    ops = jax.lax.sort((khi_f, klo_f) + tuple(payloads), num_keys=2)
    skh, skl = ops[0], ops[1]
    sorted_payloads = ops[2:]

    new = (skh != jnp.roll(skh, 1)) | (skl != jnp.roll(skl, 1))
    new = new.at[0].set(True)
    gid = jnp.cumsum(new.astype(jnp.int32)) - 1
    occupied_row = skh != H.EMPTY
    unresolved = jnp.sum((occupied_row & (gid >= T)).astype(jnp.int32))

    end_pos, first_after = _end_positions(gid, T)
    # group g occupied iff some row has gid == g AND its key is real
    g_occ = (first_after > jnp.concatenate(
        [jnp.zeros(1, jnp.int32), first_after[:-1]])) \
        & (jnp.take(skh, end_pos) != H.EMPTY)
    tk_hi = jnp.where(g_occ, jnp.take(skh, end_pos), H.EMPTY)
    tk_lo = jnp.where(g_occ, jnp.take(skl, end_pos), H.EMPTY)

    prev_end = jnp.concatenate(
        [jnp.full((1,), -1, jnp.int32), end_pos[:-1]])

    out: Dict[str, object] = {}
    for (a, r, pidx) in meta:
        v = sorted_payloads[pidx[0]]
        if a.kind in ("min", "max"):
            comb = (lambda x, y: tuple(jnp.minimum(a_, b_)
                                       for a_, b_ in zip(x, y))) \
                if a.kind == "min" else \
                (lambda x, y: tuple(jnp.maximum(a_, b_)
                                    for a_, b_ in zip(x, y)))
            scanned, = _seg_scan(new, (v,), comb)
            finals = jnp.take(scanned, end_pos)
            if r.tag == "i32":
                sent = I32_MAX if a.kind == "min" else I32_MIN
            elif r.tag == "i64":
                sent = I64_MAX if a.kind == "min" else I64_MIN
            elif r.tag == "f64":
                sent = jnp.float64(np.inf if a.kind == "min" else -np.inf)
            else:
                sent = F32_MAX if a.kind == "min" else -F32_MAX
            out[r.name] = jnp.where(g_occ, finals, sent)
        elif r.tag in ("i32",) and a.kind in ("count", "sum"):
            # wrap-exact mod 2^32: per-group totals fit i32 by the route
            # gate, so the two's-complement prefix difference is exact
            c = jnp.cumsum(v.astype(jnp.int32))
            tot = jnp.take(c, end_pos) - jnp.where(
                prev_end < 0, 0, jnp.take(c, jnp.maximum(prev_end, 0)))
            out[r.name] = jnp.where(g_occ, tot, 0)
        elif r.tag == "i64":
            # x64 CPU: native 64-bit prefix sums, exact at any magnitude
            c = jnp.cumsum(v.astype(jnp.int64))
            tot = jnp.take(c, end_pos) - jnp.where(
                prev_end < 0, jnp.int64(0),
                jnp.take(c, jnp.maximum(prev_end, 0)))
            out[r.name] = jnp.where(g_occ, tot, jnp.int64(0))
        elif r.tag == "s64":
            chi, clo = _cumsum64(v.astype(jnp.int32))
            ehi = jnp.take(chi, end_pos)
            elo = jnp.take(clo, end_pos)
            phi = jnp.where(prev_end < 0, jnp.int32(0),
                            jnp.take(chi, jnp.maximum(prev_end, 0)))
            plo = jnp.where(prev_end < 0, jnp.uint32(0),
                            jnp.take(clo, jnp.maximum(prev_end, 0)))
            thi, tlo = _sub64(ehi, elo, phi, plo)
            out[r.name + ".hi"] = jnp.where(g_occ, thi, 0)
            out[r.name + ".lo"] = jax.lax.bitcast_convert_type(
                jnp.where(g_occ, tlo, jnp.uint32(0)), jnp.int32)
        elif r.tag == "f64":
            scanned, = _seg_scan(new, (v,),
                                 lambda x, y: (x[0] + y[0],))
            out[r.name] = jnp.where(g_occ, jnp.take(scanned, end_pos), 0.0)
        else:
            # float sums: segmented COMPENSATED scan — (sum, err) pairs
            # combined with TwoSum so the error term never carries the
            # prefix magnitude into a small group's total
            def comb(xa, xb):
                s, e = _two_sum(xa[0], xb[0])
                return (s, e + xa[1] + xb[1])
            acc, comp = _seg_scan(new, (v, jnp.zeros_like(v)), comb)
            out[r.name + ".acc"] = jnp.where(
                g_occ, jnp.take(acc, end_pos), 0.0)
            out[r.name + ".c"] = jnp.where(
                g_occ, jnp.take(comp, end_pos), 0.0)

    out["__tkhi__"] = tk_hi
    out["__tklo__"] = tk_lo
    out["__unres__"] = unresolved.reshape(1)
    return out
