"""Session timezone support.

≈ ``spark.sparklinedata.tz.id`` driving every time bucketing/extraction in
the reference (``DruidPlanner.scala:73-76``, ``DateTimeExtractor.scala``,
Joda zones inside Druid's granularity engine). The TPU translation: time is
stored as UTC (days + ms-in-day int32 pairs); a non-UTC session shifts each
row to LOCAL wall-clock time before bucketing/field extraction via a
per-UTC-day offset LUT embedded in the compiled program.

The LUT holds the zone's UTC offset at each UTC day start: exact for all
fixed-offset zones, and exact for DST zones everywhere except rows inside
the one transition hour itself (the offset is sampled per day, not per
instant) — the same day-level granularity Druid's segment-time pruning
works at. Calendar DATE columns and date literals are wall-clock values
already and never shift; only the instant-valued time column does.
"""

from __future__ import annotations

import datetime
import functools

import numpy as np

MILLIS_PER_DAY = 86_400_000


def is_utc(tz_id) -> bool:
    return not tz_id or str(tz_id).upper() in ("UTC", "Z", "GMT", "ETC/UTC",
                                               "ETC/GMT", "+00:00", "UTC+0")


@functools.lru_cache(maxsize=32)
def _zone(tz_id: str):
    if tz_id.startswith(("+", "-")):
        # fixed-offset spelling ±HH:MM
        sign = 1 if tz_id[0] == "+" else -1
        hh, mm = tz_id[1:].split(":") if ":" in tz_id else (tz_id[1:], "0")
        return datetime.timezone(
            sign * datetime.timedelta(hours=int(hh), minutes=int(mm)))
    from zoneinfo import ZoneInfo
    return ZoneInfo(tz_id)


@functools.lru_cache(maxsize=64)
def day_offset_lut(tz_id: str, min_day: int, max_day: int) -> np.ndarray:
    """UTC offset (ms, int32) at each UTC day start in [min_day, max_day]."""
    zone = _zone(tz_id)
    n = max(1, max_day - min_day + 1)
    out = np.empty(n, np.int32)
    for i in range(n):
        dt = datetime.datetime.fromtimestamp(
            (min_day + i) * 86_400, tz=datetime.timezone.utc)
        out[i] = int(zone.utcoffset(dt).total_seconds() * 1000)
    out.setflags(write=False)
    return out


def local_naive_to_utc_millis(tz_id: str, naive_ms: int) -> int:
    """UTC instant of a local wall-clock millisecond value (used for date
    literals in WHERE: `ts >= date '1994-01-01'` means local midnight)."""
    dt = (datetime.datetime(1970, 1, 1)
          + datetime.timedelta(milliseconds=int(naive_ms)))
    off = _zone(tz_id).utcoffset(dt.replace(tzinfo=_zone(tz_id)))
    return int(naive_ms) - int(off.total_seconds() * 1000)


def shift_days_ms(days, ms_in_day, lut: np.ndarray, base_day: int):
    """Traced: UTC (days, ms_in_day) -> LOCAL (days, ms_in_day)."""
    import jax.numpy as jnp
    from spark_druid_olap_tpu.ops.expr_compile import take1d
    idx = jnp.clip(days - jnp.int32(base_day), 0, len(lut) - 1)
    off = take1d(lut, idx)
    tot = ms_in_day + off
    dsh = jnp.floor_divide(tot, MILLIS_PER_DAY)
    return days + dsh, tot - dsh * jnp.int32(MILLIS_PER_DAY)


def shift_millis_np(ms: np.ndarray, tz_id: str) -> np.ndarray:
    """Host: UTC epoch-ms -> local wall-clock ms (numpy)."""
    if len(ms) == 0 or is_utc(tz_id):
        return np.asarray(ms, np.int64)
    ms = np.asarray(ms, np.int64)
    day = np.floor_divide(ms, MILLIS_PER_DAY)
    lo, hi = int(day.min()), int(day.max())
    if hi - lo > 400_000:      # ~1100 years: sentinel/garbage timestamps
        raise ValueError(
            f"timezone shift over an implausible day range [{lo}, {hi}]")
    lut = day_offset_lut(tz_id, lo, hi)
    return ms + lut[(day - lo).astype(np.int64)]
