"""Expression -> XLA compiler.

The in-tree replacement for the reference's JavaScript code-generation tier
(``jscodegen/JSCodeGenerator.scala:59-66`` compiles Catalyst expressions to JS
functions shipped into Druid; ``JSCast.scala``/``JSDateTime.scala`` supply
casts and Joda date math). Here the same expression surface compiles straight
to jnp ops inside the scan program — and, like ``JSCodeGenerator`` returning
``None`` on unsupported nodes, this compiler raises :class:`Unsupported` so
the planner can fall back to a host-side residual instead of failing the
query.

Value model (three-valued logic is handled at the planner; here a null row's
payload is garbage-but-defined and masked upstream):

- ``NumValue``  — f32/i32 array
- ``BoolValue`` — bool array
- ``TimeValue`` — int32 days (+ optional int32 ms-in-day)
- ``StrValue``  — dictionary codes + *host-side* per-code string values; all
  string functions transform the (small) host dictionary, never device data —
  the dictionary-functional trick that makes string ops free on TPU.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ops import time_ops
from spark_druid_olap_tpu.ops import timezone as _tz
from spark_druid_olap_tpu.ops.scan import ScanContext
from spark_druid_olap_tpu.segment.column import ColumnKind


class Unsupported(Exception):
    """Expression not compilable to the device path (≈ JSCodeGenerator bails
    with None); planner handles via host residual."""


@dataclasses.dataclass
class NumValue:
    arr: object
    is_float: bool


@dataclasses.dataclass
class BoolValue:
    arr: object


@dataclasses.dataclass
class TimeValue:
    days: object
    ms_in_day: Optional[object] = None


@dataclasses.dataclass
class StrValue:
    codes: object                 # device int32 codes
    host_values: np.ndarray       # object array: code -> string


def take1d(table, idx):
    """Gather ``table[idx]`` with the index array flattened to 1D.

    XLA TPU lowers a gather whose indices carry the scan programs' 2D
    (8,128)-tiled layout into a serialized while loop (~60ms per 6M rows
    on v5e, measured); the same gather over a 1D T(1024) layout compiles
    to a fast vectorized path (~free for small LUTs, ~10ms for
    multi-MB tables). EVERY in-program gather must go through here."""
    tdev = jnp.asarray(table)
    shape = jnp.shape(idx)
    flat = jnp.take(tdev, idx.reshape(-1), axis=0)
    return flat.reshape(shape + tdev.shape[1:])


def _range_chain(ranges, arr):
    """Membership as fused range compares: [(lo, hi)] inclusive."""
    out = None
    for lo, hi in ranges:
        m = (arr == lo) if lo == hi else ((arr >= lo) & (arr <= hi))
        out = m if out is None else (out | m)
    return out


def _mask_ranges(mask: np.ndarray):
    """Maximal runs of True as [(lo, hi)] inclusive code ranges."""
    sel = np.nonzero(mask)[0]
    if len(sel) == 0:
        return []
    brk = np.nonzero(np.diff(sel) > 1)[0]
    starts = np.concatenate([[0], brk + 1])
    ends = np.concatenate([brk, [len(sel) - 1]])
    return [(int(sel[s]), int(sel[e])) for s, e in zip(starts, ends)]


_CHAIN_MAX_RANGES = 24


def _take_mask(mask: np.ndarray, codes):
    """Per-code host mask applied to device codes.

    Small selections lower to FUSED range-compare chains (free on the
    VPU); a dictionary gather — even the 1D form — costs ~7ms/M rows on
    v5e, a pure random-access tax. Sorted dictionaries make prefix-LIKE
    and small-IN selections a handful of ranges."""
    mask = np.asarray(mask)
    ranges = _mask_ranges(mask)
    if len(ranges) <= _CHAIN_MAX_RANGES:
        if not ranges:
            return jnp.zeros(jnp.shape(codes), bool)
        return _range_chain(ranges, codes)
    inv = _mask_ranges(~mask)
    if len(inv) <= _CHAIN_MAX_RANGES:
        if not inv:
            return jnp.ones(jnp.shape(codes), bool)
        return ~_range_chain(inv, codes)
    return take1d(mask, codes)


# digest -> (k0, k_last, dense_values) for near-dense keyed tables; the
# dense form is shared across programs (tables are content-addressed)
_DENSE_TABLES: dict = {}
_DENSE_MAX_SPAN = 1 << 23          # 8M slots (32MB f32) hard cap
_DENSE_MAX_EXPAND = 8              # span <= 8x the key count


def _dense_lookup_table(tab, default, probe_dtype):
    """(k0, k_last, dense_values) when ``tab``'s integer keys are dense
    enough that a direct-addressed [span] array is a better lookup than
    binary search; None otherwise (incl. keys outside the PROBE dtype's
    range — the binary-search path keeps its Unsupported/32-bit guards).
    Holes/fill carry the miss value so an in-range probe of an absent key
    reads exactly what a miss returns. Values are f64 on x64 and f32
    otherwise — the same precision the binary-search gather delivers."""
    if len(tab) == 0:
        return None
    k0, k1 = int(tab.keys[0]), int(tab.keys[-1])
    if probe_dtype != jnp.int64 and (k0 < -(2**31) or k1 >= 2**31):
        return None
    span = k1 - k0 + 1
    if span > _DENSE_MAX_SPAN or span > _DENSE_MAX_EXPAND * len(tab):
        return None
    fill = np.nan if default is None else float(default)
    x64 = bool(jax.config.jax_enable_x64)
    ck = (tab._digest, fill, x64)
    got = _DENSE_TABLES.get(ck)
    if got is None:
        dense = np.full(span, fill, np.float64 if x64 else np.float32)
        dense[tab.keys - k0] = tab.values
        if len(_DENSE_TABLES) > 64:
            _DENSE_TABLES.clear()
        got = _DENSE_TABLES[ck] = (k0, k1, dense)
    return got


def _take_lut(lut: np.ndarray, codes):
    return take1d(np.asarray(lut), codes)


def like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def _as_num(v, ctx) -> NumValue:
    if isinstance(v, NumValue):
        return v
    if isinstance(v, BoolValue):
        return NumValue(v.arr.astype(jnp.int32), False)
    if isinstance(v, TimeValue):
        return NumValue(v.days, False)
    if isinstance(v, StrValue):
        # cast string dim -> number via host-parsed lookup table
        lut = np.zeros(len(v.host_values), dtype=np.float32)
        for i, s in enumerate(v.host_values):
            try:
                lut[i] = float(s)
            except (TypeError, ValueError):
                lut[i] = np.nan
        return NumValue(_take_lut(lut, v.codes), True)
    raise Unsupported(f"cannot treat {type(v).__name__} as numeric")


def compile_expr(e: E.Expr, ctx: ScanContext):
    """Compile an expression tree to a device value over the scan context."""
    if isinstance(e, E.Column):
        return _column_value(e.name, ctx)
    if isinstance(e, E.Literal):
        return _literal_value(e.value)
    if isinstance(e, E.BinaryOp):
        return _binary(e, ctx)
    if isinstance(e, E.Comparison):
        return _comparison(e.op, compile_expr(e.left, ctx),
                           compile_expr(e.right, ctx), ctx)
    if isinstance(e, E.And):
        out = None
        for p in e.parts:
            b = _as_bool(compile_expr(p, ctx))
            out = b if out is None else out & b
        return BoolValue(out if out is not None else
                         jnp.ones_like(ctx.row_valid()))
    if isinstance(e, E.Or):
        out = None
        for p in e.parts:
            b = _as_bool(compile_expr(p, ctx))
            out = b if out is None else out | b
        return BoolValue(out)
    if isinstance(e, E.Not):
        return BoolValue(~_as_bool(compile_expr(e.child, ctx)))
    if isinstance(e, E.IsNull):
        if isinstance(e.child, E.Column):
            nv = ctx.null_valid(e.child.name)
            valid = ctx.row_valid() if nv is None else nv
            return BoolValue(valid if e.negated else ~valid)
        # a computed expression's NULLs are NaN-coded ONLY when no input
        # column is nullable (nullable column payloads are zero-FILLED in
        # storage, invisible to isnan): KeyedLookup misses and 0/0 are
        # NaN, column-sourced NULLs are not
        if any(ctx.null_valid(c) is not None
               for c in E.columns_in(e.child)):
            raise Unsupported("IS NULL on expression over nullable columns")
        v = compile_expr(e.child, ctx)
        if isinstance(v, NumValue) and v.is_float:
            isnull = jnp.isnan(v.arr)
            return BoolValue(~isnull if e.negated else isnull)
        raise Unsupported("IS NULL on computed expression")
    if isinstance(e, E.InList):
        v = compile_expr(e.child, ctx)
        b = _in_list(v, e.values, ctx)
        return BoolValue(~b if e.negated else b)
    if isinstance(e, E.KeyedLookup2):
        # composite-key broadcast join: manual binary search over the
        # lexicographically-sorted (k1, k2) pair arrays — ~21 gather
        # rounds, no int64 required on 32-bit backends
        if not (isinstance(e.key1, E.Column)
                and isinstance(e.key2, E.Column)):
            raise Unsupported("pair lookup over computed keys")
        n1 = _as_num(compile_expr(e.key1, ctx), ctx)
        n2 = _as_num(compile_expr(e.key2, ctx), ctx)
        if n1.is_float or n2.is_float:
            raise Unsupported("pair lookup over float key expression")
        tab = e.table
        wide = (n1.arr.dtype == jnp.int64 or n2.arr.dtype == jnp.int64)
        # probes keep their own width: table keys are int32-range by
        # FrozenKeyedTable2's invariant, but int64 PROBE values outside
        # that range must miss, never truncate into a false match
        kdt = jnp.int64 if wide else jnp.int32
        miss = jnp.asarray(np.nan if e.default is None else e.default,
                           jnp.float64 if wide else jnp.float32)
        if len(tab) == 0:
            return NumValue(jnp.full(jnp.shape(n1.arr), miss), True)
        k1 = jnp.asarray(tab.keys1.astype(
            np.int64 if wide else np.int32))
        k2 = jnp.asarray(tab.keys2.astype(
            np.int64 if wide else np.int32))
        vdev = jnp.asarray(tab.values)
        shape = jnp.shape(n1.arr)
        # the search runs over FLATTENED probes: per-round table gathers
        # with 2D-tiled indices hit XLA TPU's serialized-gather lowering
        # (see take1d) — in 1D each round is a cheap vectorized gather
        a = n1.arr.astype(kdt).reshape(-1)
        b = n2.arr.astype(kdt).reshape(-1)
        n = len(tab)
        lo = jnp.zeros_like(a)
        hi = jnp.full_like(a, n)
        steps = int(np.ceil(np.log2(max(n, 2)))) + 1

        def body(_, st):
            lo_, hi_ = st
            mid = (lo_ + hi_) // 2
            mid_c = jnp.clip(mid, 0, n - 1)
            m1 = k1[mid_c]
            m2 = k2[mid_c]
            less = (m1 < a) | ((m1 == a) & (m2 < b))
            lo_ = jnp.where(less & (lo_ < hi_), mid + 1, lo_)
            hi_ = jnp.where((~less) & (lo_ < hi_), mid, hi_)
            return lo_, hi_

        lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
        idx = jnp.clip(lo, 0, n - 1)
        found = ((k1[idx] == a) & (k2[idx] == b)).reshape(shape)
        for key_col in (e.key1, e.key2):
            nv = ctx.null_valid(key_col.name)
            if nv is not None:
                found = found & nv     # NULL key: empty set -> miss
        return NumValue(jnp.where(found, vdev[idx].reshape(shape), miss),
                        True)
    if isinstance(e, E.KeyedLookup):
        # broadcast-join gather: binary search the sorted key array, take
        # the value; misses read ``default`` (NaN = SQL NULL: comparisons
        # come out false) — the device form of a decorrelated correlated
        # scalar subquery. NULL key rows are zero-FILLED in storage, so
        # the key column's validity must mask the gather or they would
        # read key 0's group.
        if not isinstance(e.key, E.Column):
            raise Unsupported("keyed lookup over computed key")
        n = _as_num(compile_expr(e.key, ctx), ctx)
        tab = e.table
        if n.is_float:
            raise Unsupported("keyed lookup over float key expression")
        miss = jnp.asarray(np.nan if e.default is None else e.default,
                           jnp.float64 if n.arr.dtype == jnp.int64
                           else jnp.float32)
        if len(tab) == 0:
            return NumValue(jnp.full(jnp.shape(n.arr), miss), True)
        dense = _dense_lookup_table(tab, e.default, n.arr.dtype)
        if dense is not None:
            # direct-addressed fast path: TPC-H-class surrogate keys are
            # near-dense, so ONE gather into a [span] value array replaces
            # ~log2(n) binary-search gather rounds (measured ~14x on v5e
            # for a 6M-probe/1.5M-key lookup — the q17/q21 hot path)
            k0, k1v, dvals = dense
            arr = n.arr
            in_range = (arr >= k0) & (arr <= k1v)
            nv = ctx.null_valid(e.key.name)
            if nv is not None:
                in_range = in_range & nv
            idx = jnp.clip(arr - k0, 0, dvals.shape[0] - 1)
            if idx.dtype == jnp.int64:
                idx = idx.astype(jnp.int32)   # span bounded; i32 gather
            return NumValue(jnp.where(in_range, take1d(dvals, idx), miss),
                            True)
        keys = tab.keys
        if n.arr.dtype == jnp.int64:
            kdev = jnp.asarray(keys)
            arr = n.arr
        else:
            if int(keys[0]) < -(2**31) or int(keys[-1]) >= 2**31:
                raise Unsupported("lookup keys exceed 32-bit range")
            kdev = jnp.asarray(keys.astype(np.int32))
            arr = n.arr.astype(jnp.int32)
        vdev = jnp.asarray(tab.values)        # f32 off-x64, f64 on x64
        shape = jnp.shape(arr)
        flat = arr.reshape(-1)                # 1D search/gathers: take1d
        idx = jnp.clip(jnp.searchsorted(kdev, flat), 0, len(keys) - 1)
        found = (kdev[idx] == flat).reshape(shape)
        nv = ctx.null_valid(e.key.name)
        if nv is not None:
            # NULL key: 'inner.k = NULL' matches nothing, so the subquery
            # aggregates the EMPTY set -> miss value (and never key 0's
            # group, which the zero-filled storage would otherwise read)
            found = found & nv
        return NumValue(jnp.where(found, vdev[idx].reshape(shape), miss),
                        True)
    if isinstance(e, E.Between):
        v = compile_expr(e.child, ctx)
        lo = _comparison(">=", v, compile_expr(e.low, ctx), ctx)
        hi = _comparison("<=", v, compile_expr(e.high, ctx), ctx)
        b = _as_bool(lo) & _as_bool(hi)
        return BoolValue(~b if e.negated else b)
    if isinstance(e, E.Like):
        v = compile_expr(e.child, ctx)
        if not isinstance(v, StrValue):
            raise Unsupported("LIKE on non-string")
        rx = re.compile(like_to_regex(e.pattern))
        mask = np.array([bool(rx.match(s)) for s in v.host_values])
        b = _take_mask(mask, v.codes)
        return BoolValue(~b if e.negated else b)
    if isinstance(e, E.Func):
        return _func(e, ctx)
    if isinstance(e, E.Cast):
        return _cast(e, ctx)
    if isinstance(e, E.Case):
        return _case(e, ctx)
    raise Unsupported(f"unsupported node {type(e).__name__}")


def _column_value(name: str, ctx: ScanContext):
    kind = ctx.kind(name)
    arr = ctx.col(name)
    if kind == ColumnKind.DIM:
        return StrValue(arr, ctx.dictionary(name))
    if kind == ColumnKind.DOUBLE:
        return NumValue(arr, True)
    if kind == ColumnKind.LONG:
        return NumValue(arr, False)
    if kind == ColumnKind.DATE:
        return TimeValue(arr, None)
    if kind == ColumnKind.TIME:
        days, ms = arr, ctx.time_ms()
        if not _tz.is_utc(ctx.tz):
            # expressions see the instant in session-local wall-clock time,
            # matching the planner's tz-aware dimension extractions
            lut = _tz.day_offset_lut(ctx.tz, ctx.min_day - 1,
                                     ctx.max_day + 1)
            days, ms = _tz.shift_days_ms(days, ms, lut, ctx.min_day - 1)
        return TimeValue(days, ms)
    raise Unsupported(f"column kind {kind}")


def _literal_value(v):
    if isinstance(v, bool):
        return BoolValue(jnp.asarray(v))
    if isinstance(v, (int, np.integer)):
        return NumValue(jnp.asarray(v, dtype=jnp.int32), False)
    if isinstance(v, (float, np.floating)):
        return NumValue(jnp.asarray(v, dtype=jnp.float32), True)
    if isinstance(v, str):
        return _HostStr(v)
    import datetime as _dt
    if isinstance(v, (_dt.date, _dt.datetime, np.datetime64)):
        return TimeValue(jnp.asarray(time_ops.date_literal_to_days(v),
                                     dtype=jnp.int32))
    raise Unsupported(f"literal {v!r}")


@dataclasses.dataclass
class _HostStr:
    """A string literal — stays host-side until it meets a StrValue/TimeValue."""
    s: str


def _binary(e: E.BinaryOp, ctx):
    lv = compile_expr(e.left, ctx)
    rv = compile_expr(e.right, ctx)
    # date +/- integer days (TPC-H: date '1998-12-01' - 90)
    if isinstance(lv, TimeValue) and isinstance(rv, NumValue) and e.op in "+-":
        d = rv.arr if e.op == "+" else -rv.arr
        return TimeValue(lv.days + d.astype(jnp.int32), lv.ms_in_day)
    if isinstance(lv, _HostStr):
        lv = _promote_hoststr(lv, rv)
    if isinstance(rv, _HostStr):
        rv = _promote_hoststr(rv, lv)
    ln, rn = _as_num(lv, ctx), _as_num(rv, ctx)
    is_float = ln.is_float or rn.is_float or e.op == "/"
    a, b = ln.arr, rn.arr
    if is_float:
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
    if e.op == "+":
        return NumValue(a + b, is_float)
    if e.op == "-":
        return NumValue(a - b, is_float)
    if e.op == "*":
        return NumValue(a * b, is_float)
    if e.op == "/":
        return NumValue(a / b, True)
    if e.op == "%":
        return NumValue(jnp.mod(a, b), is_float)
    raise Unsupported(f"operator {e.op}")


def _promote_hoststr(h: _HostStr, other):
    """Decide what a string literal means from the other operand's type."""
    if isinstance(other, TimeValue):
        return TimeValue(jnp.asarray(time_ops.date_literal_to_days(h.s),
                                     dtype=jnp.int32))
    if isinstance(other, NumValue):
        try:
            f = float(h.s)
        except ValueError:
            raise Unsupported(f"string literal {h.s!r} in numeric context")
        return NumValue(jnp.asarray(np.float32(f)), True)
    return h


_CMP = {"=": lambda a, b: a == b, "!=": lambda a, b: a != b,
        "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
        ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}


def _comparison(op: str, lv, rv, ctx):
    # string-literal vs column promotions
    if isinstance(lv, _HostStr) and isinstance(rv, _HostStr):
        raise Unsupported("literal-literal comparison should be folded")
    if isinstance(lv, _HostStr):
        flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        return _comparison(flipped, rv, lv, ctx)
    if isinstance(rv, _HostStr):
        if isinstance(lv, StrValue):
            import operator
            pyop = {"=": operator.eq, "!=": operator.ne, "<": operator.lt,
                    "<=": operator.le, ">": operator.gt, ">=": operator.ge}[op]
            mask = np.array([pyop(s, rv.s) for s in lv.host_values])
            return BoolValue(_take_mask(mask, lv.codes))
        rv = _promote_hoststr(rv, lv)
    if isinstance(lv, TimeValue) and isinstance(rv, TimeValue):
        ldays = lv.days
        rdays = rv.days
        if lv.ms_in_day is None and rv.ms_in_day is None:
            return BoolValue(_CMP[op](ldays, rdays))
        lms = lv.ms_in_day if lv.ms_in_day is not None else 0
        rms = rv.ms_in_day if rv.ms_in_day is not None else 0
        if op in ("=", "!="):
            eq = (ldays == rdays) & (lms == rms)
            return BoolValue(eq if op == "=" else ~eq)
        lt = (ldays < rdays) | ((ldays == rdays) & (lms < rms))
        eq = (ldays == rdays) & (lms == rms)
        out = {"<": lt, "<=": lt | eq, ">": ~(lt | eq), ">=": ~lt}[op]
        return BoolValue(out)
    if isinstance(lv, StrValue) and isinstance(rv, StrValue):
        if lv.host_values is rv.host_values:
            return BoolValue(_CMP[op](lv.codes, rv.codes))
        raise Unsupported("comparison between two different string dims")
    ln, rn = _as_num(lv, ctx), _as_num(rv, ctx)
    a, b = ln.arr, rn.arr
    if ln.is_float or rn.is_float:
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
    return BoolValue(_CMP[op](a, b))


def _as_bool(v):
    if isinstance(v, BoolValue):
        return v.arr
    if isinstance(v, NumValue):
        return v.arr != 0
    raise Unsupported(f"cannot use {type(v).__name__} as boolean")


def int_set_runs(vals: np.ndarray):
    """Contiguous [lo, hi] runs of a sorted int array, or None when the
    set is not chain-eligible (too many values relative to its span AND
    too many runs). This is THE predicate for "does int_set_membership
    lower to a fused range-compare chain?" — the compaction planner's
    staged-filter split must agree with it, or chain-cheap conjuncts get
    needlessly staged post-compaction (and scattered gather-heavy small
    sets sneak in pre-compaction)."""
    if len(vals) == 0:
        return []
    lo_v, hi_v = int(vals[0]), int(vals[-1])
    span = hi_v - lo_v + 1
    if len(vals) > 2 * _CHAIN_MAX_RANGES and span > 4 * len(vals):
        return None
    arr64 = vals.astype(np.int64)
    brk = np.nonzero(np.diff(arr64) > 1)[0]
    starts = np.concatenate([[0], brk + 1])
    ends = np.concatenate([brk, [len(arr64) - 1]])
    runs = [(int(arr64[s]), int(arr64[e])) for s, e in zip(starts, ends)]
    return runs if len(runs) <= _CHAIN_MAX_RANGES else None


def int_set_lowers_to_chain(vals: np.ndarray) -> bool:
    """Whether membership in ``vals`` compiles to compare chains (free on
    the VPU) rather than a gather (bitmap probe / binary search)."""
    return int_set_runs(vals) is not None


def int_set_membership(arr, vals: np.ndarray):
    """Device membership of integer ``arr`` (i32/i64) in a sorted,
    nonempty int array whose values fit arr's dtype.

    Dense spans (<= 2^26) lower to a packed-BITMAP gather — one gather
    + bit test per row (the decorrelated-EXISTS hot path: TPC-H q21's
    sets span the orderkey range; <= 8MB of bitmap rides into the
    program as a constant). Wider spans binary-search the sorted
    constant (~log2 n gather rounds). Shared by the filter tier
    (ops/filters._in) and the compiled-expression tier (_in_list)."""
    if len(vals) == 0:
        # constant-false (ADVICE r4: empty set used to crash on vals[0])
        return jnp.zeros(arr.shape, dtype=jnp.bool_)
    lo_v, hi_v = int(vals[0]), int(vals[-1])
    span = hi_v - lo_v + 1
    # small or near-contiguous sets: fused range-compare chain beats
    # any gather (a 6M-row gather is ~40ms on v5e; compares are free)
    runs = int_set_runs(vals)
    if runs is not None:
        if not runs:
            # empty set: membership is constant-false (ADVICE r4 — the
            # nonempty precondition used to make this an unbound 'out')
            return jnp.zeros(arr.shape, dtype=jnp.bool_)
        lit = (lambda v: jnp.asarray(v, arr.dtype))
        out = None
        for lo, hi in runs:
            m = (arr == lit(lo)) if lo == hi \
                else ((arr >= lit(lo)) & (arr <= lit(hi)))
            out = m if out is None else (out | m)
        return out
    # bitmap only when reasonably DENSE (or small): a sparse thousand-key
    # set under the span cap would bake megabytes of mostly-zero constant
    # into the program where binary search needs kilobytes
    if span <= (1 << 26) and (span <= (1 << 20)
                              or span <= 64 * len(vals)):
        off_np = vals.astype(np.int64) - lo_v
        words = np.zeros((span + 31) // 32, dtype=np.uint32)
        np.bitwise_or.at(
            words, off_np >> 5,
            np.left_shift(np.uint32(1), (off_np & 31).astype(np.uint32)))
        inrange = (arr >= lo_v) & (arr <= hi_v)
        # out-of-range rows may wrap in the subtraction; where() masks
        # them to offset 0 before the gather
        off = jnp.where(inrange, arr - jnp.asarray(lo_v, arr.dtype),
                        0).astype(jnp.int32)
        bit = (take1d(words, off >> 5) >> (off & 31).astype(jnp.uint32)) \
            & jnp.uint32(1)
        return inrange & (bit == jnp.uint32(1))
    dev = jnp.asarray(vals.astype(
        np.int64 if arr.dtype == jnp.int64 else np.int32))
    shape = jnp.shape(arr)
    flat = arr.reshape(-1)                    # 1D search/gather: take1d
    idx = jnp.clip(jnp.searchsorted(dev, flat), 0, len(vals) - 1)
    return (dev[idx] == flat).reshape(shape)


def _in_list(v, values, ctx):
    if isinstance(values, E.FrozenIntSet):
        vals = values.array
        if len(vals) == 0:
            if isinstance(v, StrValue):
                return jnp.zeros_like(v.codes, dtype=bool)
            n0 = _as_num(v, ctx)
            return jnp.zeros_like(n0.arr, dtype=bool)
        n = _as_num(v, ctx)
        if n.is_float:
            # f32 compares collide for keys >= 2^24; let the host evaluate
            raise Unsupported("large integer IN set over float expression")
        if n.arr.dtype == jnp.int64:
            arr = n.arr
        else:
            # a 32-bit probe can't hold out-of-range values, but the set
            # must not wrap when narrowed
            if int(vals[0]) < -(2**31) or int(vals[-1]) >= 2**31:
                raise Unsupported("IN-set values exceed 32-bit range")
            arr = n.arr.astype(jnp.int32)
        return int_set_membership(arr, vals)
    if isinstance(v, StrValue):
        vs = set(values)
        mask = np.array([s in vs for s in v.host_values])
        return _take_mask(mask, v.codes)
    if isinstance(v, TimeValue):
        days = np.array([time_ops.date_literal_to_days(x) for x in values],
                        dtype=np.int32)
        out = jnp.zeros_like(v.days, dtype=bool)
        for d in days:
            out = out | (v.days == int(d))
        return out
    n = _as_num(v, ctx)
    out = None
    for x in values:
        b = n.arr == (jnp.float32(x) if n.is_float else jnp.int32(x))
        out = b if out is None else out | b
    return out if out is not None else jnp.zeros_like(n.arr, dtype=bool)


_STR_FUNCS = {
    "lower": lambda s: s.lower(),
    "upper": lambda s: s.upper(),
    "trim": lambda s: s.strip(),
    "ltrim": lambda s: s.lstrip(),
    "rtrim": lambda s: s.rstrip(),
    "reverse": lambda s: s[::-1],
}

_TIME_FIELDS = {"year", "month", "day", "quarter", "dow", "doy", "week",
                "hour", "minute", "second"}


def _func(e: E.Func, ctx):
    name = e.name.lower()
    if name in _TIME_FIELDS:
        v = compile_expr(e.args[0], ctx)
        v = _coerce_time(v)
        return NumValue(time_ops.extract_field(
            name, v.days, v.ms_in_day if v.ms_in_day is not None else None),
            False)
    if name in ("date_trunc", "trunc"):
        grain = _literal_str(e.args[0]).lower()
        v = _coerce_time(compile_expr(e.args[1], ctx))
        return _date_trunc(grain, v)
    if name in ("date_add", "dateadd"):
        v = _coerce_time(compile_expr(e.args[0], ctx))
        n = _as_num(compile_expr(e.args[1], ctx), ctx)
        return TimeValue(v.days + n.arr.astype(jnp.int32), v.ms_in_day)
    if name in ("date_sub",):
        v = _coerce_time(compile_expr(e.args[0], ctx))
        n = _as_num(compile_expr(e.args[1], ctx), ctx)
        return TimeValue(v.days - n.arr.astype(jnp.int32), v.ms_in_day)
    if name == "datediff":
        a = _coerce_time(compile_expr(e.args[0], ctx))
        b = _coerce_time(compile_expr(e.args[1], ctx))
        return NumValue(a.days - b.days, False)
    if name == "add_months":
        v = _coerce_time(compile_expr(e.args[0], ctx))
        n = _as_num(compile_expr(e.args[1], ctx), ctx)
        y, m, d = time_ops.civil_from_days(v.days)
        mi = y * 12 + (m - 1) + n.arr.astype(jnp.int32)
        ny = jnp.floor_divide(mi, 12)
        nm = jnp.mod(mi, 12) + 1
        start = _month_start(ny, nm)
        mi2 = mi + 1
        nstart = _month_start(jnp.floor_divide(mi2, 12), jnp.mod(mi2, 12) + 1)
        nd = jnp.minimum(d, nstart - start)  # clamp to month length
        return TimeValue(start + nd - 1, None)
    if name in _STR_FUNCS or name in ("substr", "substring", "concat",
                                      "replace", "lpad", "rpad",
                                      "regexp_extract", "__lookup_pairs"):
        return _str_func(name, e, ctx)
    if name in ("length", "char_length"):
        v = compile_expr(e.args[0], ctx)
        if not isinstance(v, StrValue):
            raise Unsupported("length of non-string")
        lut = np.array([len(s) for s in v.host_values], dtype=np.int32)
        return NumValue(_take_lut(lut, v.codes), False)
    if name == "abs":
        n = _as_num(compile_expr(e.args[0], ctx), ctx)
        return NumValue(jnp.abs(n.arr), n.is_float)
    if name in ("round", "floor", "ceil", "sqrt", "exp", "ln", "log"):
        n = _as_num(compile_expr(e.args[0], ctx), ctx)
        a = n.arr.astype(jnp.float32)
        if name == "round":
            if len(e.args) > 1:
                k = float(10 ** _literal_num(e.args[1]))
                return NumValue(jnp.round(a * k) / k, True)
            return NumValue(jnp.round(a), True)
        fn = {"floor": jnp.floor, "ceil": jnp.ceil, "sqrt": jnp.sqrt,
              "exp": jnp.exp, "ln": jnp.log, "log": jnp.log}[name]
        return NumValue(fn(a), True)
    if name in ("power", "pow"):
        a = _as_num(compile_expr(e.args[0], ctx), ctx)
        b = _as_num(compile_expr(e.args[1], ctx), ctx)
        return NumValue(jnp.power(a.arr.astype(jnp.float32),
                                  b.arr.astype(jnp.float32)), True)
    from spark_druid_olap_tpu.utils.host_eval import EXTRA_FUNCTIONS
    if name in EXTRA_FUNCTIONS and len(e.args) == 1:
        # module-contributed scalar fn over a string dim: vectorize through
        # the dictionary (host transform + code re-gather), so custom
        # functions still push down
        v = compile_expr(e.args[0], ctx)
        if isinstance(v, StrValue):
            fn = EXTRA_FUNCTIONS[name]
            newvals = np.array([fn(s) for s in v.host_values], dtype=object)
            return StrValue(v.codes, newvals)
    raise Unsupported(f"function {name}")


def _coerce_time(v) -> TimeValue:
    if isinstance(v, TimeValue):
        return v
    if isinstance(v, _HostStr):
        return TimeValue(jnp.asarray(time_ops.date_literal_to_days(v.s),
                                     dtype=jnp.int32))
    if isinstance(v, StrValue):
        lut = np.array([time_ops.date_literal_to_days(s) if s else 0
                        for s in v.host_values], dtype=np.int32)
        return TimeValue(_take_lut(lut, v.codes))
    raise Unsupported("expected a date/time value")


def _date_trunc(grain: str, v: TimeValue):
    if grain == "day":
        return TimeValue(v.days, None)
    if grain == "week":
        return TimeValue(jnp.floor_divide(v.days + 3, 7) * 7 - 3, None)
    y, m, _ = time_ops.civil_from_days(v.days)
    if grain == "year":
        return TimeValue(_month_start(y, jnp.ones_like(m)), None)
    if grain == "quarter":
        qm = (jnp.floor_divide(m - 1, 3) * 3) + 1
        return TimeValue(_month_start(y, qm), None)
    if grain == "month":
        return TimeValue(_month_start(y, m), None)
    raise Unsupported(f"date_trunc grain {grain}")


_MONTH_OFFSETS = np.array([0, 31, 59, 90, 120, 151, 181, 212, 243, 273, 304,
                           334], dtype=np.int32)


def _month_start(y, m):
    """days-since-epoch of (y, m, 1), vectorized."""
    jan1 = time_ops.days_of_jan1(y)
    off = take1d(_MONTH_OFFSETS, m - 1)
    leap = ((jnp.mod(y, 4) == 0) & (jnp.mod(y, 100) != 0)) | (jnp.mod(y, 400) == 0)
    return jan1 + off + (leap & (m > 2)).astype(jnp.int32)


def _str_func(name, e: E.Func, ctx):
    """String functions = host transforms of the dictionary, then re-gather."""
    v = compile_expr(e.args[0], ctx)
    if isinstance(v, _HostStr):
        raise Unsupported("string fn on literal should be constant-folded")
    if not isinstance(v, StrValue):
        raise Unsupported(f"{name} on non-string")
    if name in _STR_FUNCS:
        fn = _STR_FUNCS[name]
        newvals = np.array([fn(s) for s in v.host_values], dtype=object)
        return StrValue(v.codes, newvals)
    if name in ("substr", "substring"):
        start = int(_literal_num(e.args[1]))
        ln = int(_literal_num(e.args[2])) if len(e.args) > 2 else None
        i0 = start - 1 if start > 0 else start
        newvals = np.array(
            [s[i0: i0 + ln] if ln is not None else s[i0:]
             for s in v.host_values], dtype=object)
        return StrValue(v.codes, newvals)
    if name == "concat":
        parts = [compile_expr(a, ctx) for a in e.args]
        strs = [p for p in parts if isinstance(p, StrValue)]
        if len(strs) != 1:
            raise Unsupported("concat supports exactly one column argument")
        sv = strs[0]
        out = []
        for code in range(len(sv.host_values)):
            pieces = []
            for p in parts:
                pieces.append(p.s if isinstance(p, _HostStr)
                              else sv.host_values[code])
            out.append("".join(pieces))
        return StrValue(sv.codes, np.array(out, dtype=object))
    if name == "replace":
        old = _literal_str(e.args[1])
        new = _literal_str(e.args[2])
        newvals = np.array([s.replace(old, new) for s in v.host_values],
                           dtype=object)
        return StrValue(v.codes, newvals)
    if name in ("lpad", "rpad"):
        n = int(_literal_num(e.args[1]))
        fill = _literal_str(e.args[2]) if len(e.args) > 2 else " "
        fn = (lambda s: s.rjust(n, fill)) if name == "lpad" \
            else (lambda s: s.ljust(n, fill))
        newvals = np.array([fn(s) for s in v.host_values], dtype=object)
        return StrValue(v.codes, newvals)
    if name == "regexp_extract":
        rx = re.compile(_literal_str(e.args[1]))
        idx = int(_literal_num(e.args[2])) if len(e.args) > 2 else 1

        def rex(s):
            m = rx.search(s) if isinstance(s, str) else None
            return m.group(idx) if m is not None else None
        newvals = np.array([rex(s) for s in v.host_values], dtype=object)
        return StrValue(v.codes, newvals)
    if name == "__lookup_pairs":
        if not isinstance(e.args[1], E.Literal):
            raise Unsupported("lookup table must be a literal")
        table = dict(e.args[1].value)
        newvals = np.array([table.get(s) for s in v.host_values],
                           dtype=object)
        return StrValue(v.codes, newvals)
    raise Unsupported(f"string function {name}")


def _literal_str(e: E.Expr) -> str:
    if isinstance(e, E.Literal) and isinstance(e.value, str):
        return e.value
    raise Unsupported("expected string literal argument")


def _literal_num(e: E.Expr):
    if isinstance(e, E.Literal) and isinstance(e.value, (int, float)):
        return e.value
    raise Unsupported("expected numeric literal argument")


def _cast(e: E.Cast, ctx):
    v = compile_expr(e.child, ctx)
    to = e.to.lower()
    if to in ("double", "float", "decimal"):
        n = _as_num(v, ctx)
        return NumValue(n.arr.astype(jnp.float32), True)
    if to in ("long", "int", "bigint", "integer"):
        n = _as_num(v, ctx)
        return NumValue(n.arr.astype(jnp.int32), False)
    if to in ("date", "timestamp"):
        return _coerce_time(v)
    if to in ("string", "varchar"):
        if isinstance(v, StrValue):
            return v
        raise Unsupported("cast to string of non-dim (needs host residual)")
    raise Unsupported(f"cast to {to}")


def _case(e: E.Case, ctx):
    branches = [(_as_bool(compile_expr(c, ctx)), compile_expr(v, ctx))
                for c, v in e.branches]
    other = compile_expr(e.otherwise, ctx) if e.otherwise is not None \
        else NumValue(jnp.asarray(0, dtype=jnp.int32), False)
    vals = [v for _, v in branches] + [other]
    if any(isinstance(v, (StrValue, _HostStr)) for v in vals):
        raise Unsupported("CASE producing strings (host residual)")
    is_float = any(_as_num(v, ctx).is_float for v in vals)
    out = _as_num(other, ctx).arr
    if is_float:
        out = out.astype(jnp.float32)
    for cond, v in reversed(branches):
        val = _as_num(v, ctx).arr
        if is_float:
            val = val.astype(jnp.float32)
        out = jnp.where(cond, val, out)
    return NumValue(out, is_float)
