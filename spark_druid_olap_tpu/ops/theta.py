"""Theta-sketch-class approximate distinct counting: a k-mins sketch.

≈ the reference mapping Druid ``thetaSketch`` metric columns to approximate
distinct counts (``DruidDataSource.scala:24-40``; Druid's theta sketch is a
KMV — k minimum hash values — structure). The TPU-shaped equivalent keeps,
per group, the MINIMUM of k independent uniform hashes of the value: a
"k-mins" sketch. Identical update/merge algebra to KMV (set union = element
-wise min), identical estimator family, and it maps onto the engine's
existing exact-min machinery:

- update   = per-lane ``segment_min`` into a dense ``[n_keys, k]`` f32 table
- merge    = elementwise min — across chips via ``lax.pmin`` on ICI, across
  waves/hash partials via ``np.minimum`` on host
- estimate = MLE for n given k independent Beta(1, n) minima:
  ``n_hat = k / sum(min_j) - 1`` (empty group: every lane at the 1.0 clip
  gives n_hat = 0 exactly)

Relative error ~ 1/sqrt(k) (k=64 -> ~12.5%), the same class as Druid's
default-size theta sketches; lanes are compile-time constants so the whole
sketch fuses into the scan program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

K_LANES = 64
_SENTINEL = np.float32(2.0)     # > any hash; empty-group marker pre-clip


def _hash01(v, seed: int):
    """Value -> uniform (0, 1] float32, per-lane independent."""
    h = v.astype(jnp.uint32) * jnp.uint32(0x9E3779B1) \
        ^ jnp.uint32((0x85EBCA6B * (2 * seed + 1)) & 0xFFFFFFFF)
    h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return ((h >> jnp.uint32(8)).astype(jnp.float32)
            * jnp.float32(1.0 / (1 << 24))) + jnp.float32(1e-7)


def theta_registers(key, mask, values, n_keys: int,
                    k: int = K_LANES):
    """Per-group k-mins registers: ``[n_keys, k]`` f32 lane minima."""
    if key.ndim == 1:
        key = key[None, :]
        mask = mask[None, :]
    v = values.reshape(key.shape)
    num = n_keys + 1
    k_eff = jnp.where(mask, key, jnp.int32(n_keys))
    lanes = []
    for j in range(k):
        hv = jnp.where(mask, _hash01(v, j), _SENTINEL)
        per = jax.vmap(
            lambda x, kk: jax.ops.segment_min(x, kk, num))(hv, k_eff)
        lanes.append(per.min(axis=0)[:n_keys])
    return jnp.stack(lanes, axis=1)


def merge_registers(regs, axis_name: str):
    """Cross-chip union: elementwise min over the mesh axis."""
    return jax.lax.pmin(regs, axis_name)


def estimate(regs: np.ndarray) -> np.ndarray:
    """[n_keys, k] lane minima -> per-group distinct estimates."""
    r = np.minimum(np.asarray(regs, np.float64), 1.0)
    k = r.shape[1]
    s = r.sum(axis=1)
    return np.maximum(k / np.maximum(s, 1e-12) - 1.0, 0.0)
