"""The declared merge-closure of every aggregate the engine registers.

Adding an aggregation kind touches four places that must stay mutually
consistent or waves / multi-host shards / rollups / shared-scan quietly
break: the executor's kind table (``parallel/executor.py:_AGG_KIND``),
the cross-chip merge (``ops/groupby.py:merge_partials``), the rollup
re-aggregation table (``mv/match.py``), and the shared-scan demux
(``parallel/sharedscan.py``). This module is the single declaration the
``mergeclosure`` sdlint pass cross-checks against all four — register
the new kind HERE first and the linter will point at every site that
still needs teaching.

Fields per druid-level kind:

- ``route``  — the internal lowered kind (``ops/groupby.py`` Route
  vocabulary: count/sum/min/max) or the sketch name for sketches.
- ``dtype``  — accumulator dtype name as ``numpy`` spells it.
- ``reagg``  — the kind literal ``mv/match.py`` re-aggregates stored
  partials with (losslessly merge-closed), or None when rollup must
  reject it (sketch registers are not closed over stored partials).
- ``sketch`` — "hll"/"theta" for register-valued aggregates that need
  their own shared-scan demux + wave-merge handling, else None.
- ``merge``  — for sketches, the register algebra cross-chip merges
  must use: "max" (HLL rho registers), "min" (theta k-min hashes), or
  "minsum" (KLL lane lex-minima + exact level-count sums). Summing
  min-valued registers double-counts silently; the ``mesh`` sdlint pass
  checks ``ops/<sketch>.py:merge_registers`` against this field, and
  the ``mergeclosure`` pass cross-checks it against the runtime merge
  table (``ops/groupby.py:SKETCH_MERGE_OPS``).

Kept import-free and ``ast.literal_eval``-parseable on purpose: sdlint
reads this file without importing it (and so without jax installed).
"""

AGG_CLOSURE = {
    "count":       {"route": "count", "dtype": "int64",
                    "reagg": "count", "sketch": None},
    "longsum":     {"route": "sum", "dtype": "int64",
                    "reagg": "longsum", "sketch": None},
    "doublesum":   {"route": "sum", "dtype": "float64",
                    "reagg": "doublesum", "sketch": None},
    "longmin":     {"route": "min", "dtype": "int64",
                    "reagg": "longmin", "sketch": None},
    "longmax":     {"route": "max", "dtype": "int64",
                    "reagg": "longmax", "sketch": None},
    "doublemin":   {"route": "min", "dtype": "float64",
                    "reagg": "doublemin", "sketch": None},
    "doublemax":   {"route": "max", "dtype": "float64",
                    "reagg": "doublemax", "sketch": None},
    "cardinality": {"route": "hll", "dtype": "int64",
                    "reagg": None, "sketch": "hll", "merge": "max"},
    "thetasketch": {"route": "theta", "dtype": "int64",
                    "reagg": None, "sketch": "theta", "merge": "min"},
    "quantile":    {"route": "kll", "dtype": "float64",
                    "reagg": None, "sketch": "kll", "merge": "minsum"},
    "anyvalue":    {"route": "max", "dtype": "float64",
                    "reagg": "anyvalue", "sketch": None},
}
