"""Pallas mega-kernel: one hand-scheduled kernel per fused shared-scan wave.

The shared-scan tier (parallel/sharedscan.py) already runs a dashboard
storm as ONE bind + ONE XLA dispatch per segment wave, but the fused
jaxpr's VMEM schedule is implicit: XLA materializes per-lane masks and
one-hot intermediates in HBM, and every lane's aggregation re-streams the
union columns. This module lowers the group's FusionPlan (the CSE'd
predicate DAG + per-lane residuals + agg sets, planner/fusion.py) to ONE
hand-written ``pl.pallas_call``: union columns tile through VMEM exactly
once per wave, shared predicate sub-expressions evaluate once per tile
(the trace-time ``CSECache`` runs INSIDE the kernel body), and every
lane's filtered aggregates accumulate in a resident scratch block — the
whole-pipeline native-compilation move of Flare (arxiv 1703.08219) and
the device-side operator design of GPU-Presto (arxiv 2606.24647).

How lane semantics stay exact: ``ScanContext`` (ops/scan.py) is shape-
agnostic — every method is elementwise over ``arrays`` plus host
metadata — so the kernel body constructs a REAL ``ScanContext`` over the
``[block_rows, 128]`` tiles read from its refs and reuses the engine's
own lowering verbatim: ``ops.filters.lower_filter`` through the fusion
planner's ``CSECache`` (with ``prelower``, so cross-lane shared masks
compute once per tile), the planned dimension builders, ``fuse_keys``,
and each ``AggPlan``'s value/mask builders. The kernel never re-implements
query semantics; it re-schedules them.

Scratch accumulator layout (one f32 ``[out_rows, 128]`` block, resident
across grid steps — TPU grids are sequential, so the output block is a
legal cross-step accumulator, same contract as ops/pallas_groupby.py):

- per lane, per key ``k``: a stripe of ``rpk`` rows — two rows (Neumaier
  acc + comp) per sum/count, one row (±F32_MAX sentinel) per min/max —
  shared row-offset/init/accumulate helpers with pallas_groupby.
- per in-kernel theta sketch: ``n_keys * K_LANES`` rows of per-VPU-lane
  hash minima (exact min algebra: bit-identical to
  ``ops.theta.theta_registers``; the 128-lane reduction is an XLA
  epilogue in the same jit).

Fallback matrix (every reject lowers through the unchanged jaxpr-fused
program — routing tiers never change; see docs/KERNELS.md):

- ``sdot.pallas.wave.enabled`` off, non-TPU backend without
  ``SDOT_PALLAS=interpret``, or group wider than
  ``sdot.pallas.wave.max.lanes``  -> jaxpr path (static precheck).
- any lane whose planned sum/count routes are not 'ffl' (i.e.
  ``pallas_groupby.eligible`` declined: numeric bounds, key cap) -> jaxpr.
- lane lowering that traces non-elementwise primitives (LUT gathers from
  pattern/extraction dims, tz-shifted granularities, ...) -> jaxpr,
  caught by a chip-independent 8x128 trace probe against a Mosaic-safe
  primitive whitelist, NOT by a device compile error.
- HLL registers (scatter-max over 2^log2m buckets — infeasible in a
  VMEM-tiled scratch block at the default m=2048) and theta sketches
  over the in-kernel row cap: computed by the existing XLA register ops
  in the SAME jit after the kernel — still one kernel launch per wave,
  at the cost of one extra XLA stream of the sketch lanes' columns.

Interpreter mode (``SDOT_PALLAS=interpret`` on CPU) runs the identical
kernel through ``pl.pallas_call(..., interpret=True)`` — the
chip-independent CI differential against the jaxpr path.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from spark_druid_olap_tpu.ops import filters as F
from spark_druid_olap_tpu.ops import groupby as G
from spark_druid_olap_tpu.ops import hll as HLL
from spark_druid_olap_tpu.ops import kll as KLL
from spark_druid_olap_tpu.ops import pallas_groupby as PG
from spark_druid_olap_tpu.ops import theta as TH
from spark_druid_olap_tpu.ops.scan import ScanContext, array_dtype
from spark_druid_olap_tpu.planner import fusion as FU

LANES = PG.LANES

# in-kernel theta cap: a sketch's scratch stripe is n_keys * K_LANES rows;
# past this the registers compute in the XLA epilogue instead (the j*k
# unrolled min loop also grows the kernel trace linearly with this)
THETA_KERNEL_MAX_ROWS = 256

# total scratch rows the wave accumulator block may occupy (2MiB f32 at
# 128 lanes); wider storms fall back to the jaxpr program
MAX_OUT_ROWS = 4096


class WaveFallback(Exception):
    """Raised at build time when the group cannot lower to the wave
    kernel; the caller builds the jaxpr-fused program instead."""


# =============================================================================
# eligibility
# =============================================================================

def wave_eligible(lanes, max_lanes: int) -> bool:
    """Static precheck from plan metadata only — callable on EVERY fused
    execution (warm program-cache runs included) so the compile signature
    and the dispatch path always agree. The numeric gates ride on the
    planned routes: ``plan_routes`` assigns 'ffl' to a lane's sums/counts
    iff ``pallas_groupby.eligible`` accepted the lane (backend, key cap,
    f32-exactness bounds), so requiring every sum/count route to be 'ffl'
    inherits the proven per-lane gates without re-deriving them."""
    env = os.environ.get("SDOT_PALLAS", "")
    if env == "0":
        return False
    if env != "interpret" and not PG._tpu_backend():
        return False
    if max_lanes <= 0 or len(lanes) > max_lanes:
        return False
    for lp in lanes:
        for r in lp.routes.values():
            if r.kind in ("sum", "count") and r.tag != "ffl":
                return False
        for p in lp.agg_plans:
            if p.kind not in ("count", "sum", "min", "max", "hll",
                              "theta", "kll"):
                return False
    return True


# Mosaic-safe primitives a lane's mask/key/value builders may trace.
# Anything outside (gather/take LUTs, sorts, scans, dots) rejects the
# lane at build time — deterministically, on any backend.
_SAFE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "max", "min",
    "and", "or", "xor", "not", "eq", "ne", "lt", "le", "gt", "ge",
    "select_n", "convert_element_type", "bitcast_convert_type",
    "broadcast_in_dim", "reshape", "squeeze", "iota", "clamp",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "neg", "abs", "sign", "floor", "ceil", "round", "is_finite",
    "exp", "log", "sqrt", "rsqrt", "stop_gradient", "copy",
    "nextafter", "sub_f", "add_any",
})
_CALL_PRIMS = frozenset({"pjit", "closed_call", "custom_jvp_call",
                         "custom_vjp_call", "remat2", "checkpoint"})


def _check_jaxpr(jaxpr) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _CALL_PRIMS:
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", v)
                if hasattr(inner, "eqns"):
                    _check_jaxpr(inner)
            continue
        if name not in _SAFE_PRIMS:
            raise WaveFallback(f"lane lowering traces non-elementwise "
                               f"primitive {name!r}")


def _lane_parts(lp, ctx: ScanContext, cse: Optional[FU.CSECache]):
    """One lane's traced parts over ``ctx`` — the engine's own builders,
    shared verbatim between the trace probe, the kernel body, and the
    sketch epilogue (the jaxpr path composes the same calls, which is
    what makes the differential bit-exact by construction)."""
    base = ctx.row_valid()
    fm = cse.lower(lp.q.filter) if cse is not None \
        else F.lower_filter(lp.q.filter, ctx)
    if fm is not None:
        base = base & fm
    im = cse.interval(lp.q.intervals) if cse is not None \
        else F.interval_mask(lp.q.intervals, ctx)
    if im is not None:
        base = base & im
    if lp.dim_plans:
        codes = [p.build(ctx) for p in lp.dim_plans]
        key, _ = G.fuse_keys(codes, [p.card for p in lp.dim_plans])
    else:
        key = jnp.zeros(base.shape, dtype=jnp.int32)
    dense = []
    sketch = []
    for p in lp.agg_plans:
        vals = p.build_values(ctx)
        am = p.build_mask(ctx, cse=cse)
        if p.kind in ("hll", "theta", "kll"):
            sketch.append((p, vals, am))
        else:
            dense.append((p.kind, p.spec.name, vals, am))
    dense.append(("count", "__rows__", None, None))
    return base, key, dense, sketch


# =============================================================================
# layout
# =============================================================================

class _LaneLayout:
    """Scratch rows one lane owns inside the wave accumulator block."""

    __slots__ = ("base", "offs", "rpk", "dense_meta", "theta_base",
                 "theta_epilogue", "hll", "kll", "next_row")

    def __init__(self, lp, base_row: int):
        dense_kinds = [p.kind for p in lp.agg_plans
                       if p.kind not in ("hll", "theta", "kll")] + ["count"]
        self.offs, self.rpk = PG._row_offsets(
            [(k, None, None) for k in dense_kinds])
        self.base = base_row
        row = base_row + self.rpk * lp.n_keys
        # metas drive the route adaptation (G._pallas_to_routes)
        self.dense_meta = [
            G.AggInput(p.spec.name, p.kind, is_int=p.is_int,
                       maxabs=p.maxabs)
            for p in lp.agg_plans
            if p.kind not in ("hll", "theta", "kll")]
        self.dense_meta.append(
            G.AggInput("__rows__", "count", is_int=True, maxabs=1.0))
        self.theta_base: Dict[str, int] = {}
        self.theta_epilogue: List[str] = []
        self.hll: List[str] = []
        self.kll: List[str] = []
        for p in lp.agg_plans:
            if p.kind == "theta":
                stripe = lp.n_keys * TH.K_LANES
                if stripe <= THETA_KERNEL_MAX_ROWS:
                    self.theta_base[p.spec.name] = row
                    row += stripe
                else:
                    self.theta_epilogue.append(p.spec.name)
            elif p.kind == "hll":
                self.hll.append(p.spec.name)
            elif p.kind == "kll":
                # survivor registers need a segment_min scatter over
                # (key, level, lane) — XLA epilogue, same as HLL
                self.kll.append(p.spec.name)
        self.next_row = row


def _prep_dtype(dt) -> object:
    """Kernel-side dtype of one union array after input prep: validity
    masks ship as i8 (converted back to bool tiles in the kernel body),
    narrow integer codes widen to i32 (uniform Mosaic tiling), everything
    else keeps its (device-canonicalized) dtype.

    Encoded segments (encode/) do NOT change this contract: chunks
    decode to their logical dtype at fault time (tier/store.py), so the
    kernel always sees the same widened tiles whether the cold bytes
    were bit-packed, RLE, or raw — compression buys host I/O and hot-set
    residency, never a divergent Mosaic tiling. Feeding packed codes
    straight into the kernel would need a per-codec unpack prologue and
    a different (data-dependent) tile plan; see docs/KERNELS.md."""
    dt = jnp.zeros((), dtype=dt).dtype      # apply x64 canonicalization
    if dt == jnp.bool_:
        return jnp.int8
    if dt.kind == "i" and dt.itemsize < 4:
        return jnp.int32
    return dt


# =============================================================================
# program build
# =============================================================================

def build_wave_fn(ds, lanes, min_day: int, max_day: int, fplan, *,
                  union_names, tz: str, log2m: int, tile_bytes: int,
                  kll_lanes: int = KLL.K_LANES):
    """Lower a fused group to the wave mega-kernel.

    Returns ``(wave_fn, info)`` where ``wave_fn(arrays)`` maps the wave's
    device bind to a per-lane list of route-conformant output dicts
    (exactly what ``_build_fused_program``'s per-lane ``dense_groupby`` +
    sketch stages produce, so the engine's packers/decoders downstream
    are untouched), and ``info`` carries the static launch accounting
    (block_rows, tiles per dispatch, scratch rows, VMEM estimate).
    Raises :class:`WaveFallback` when any lane cannot lower.
    """
    names = list(union_names)
    probe_tiles = {}
    bool_names = set()
    for k in names:
        dt = np.dtype(array_dtype(ds, k))
        if dt == np.bool_:
            bool_names.add(k)
            probe_tiles[k] = jnp.zeros((8, LANES), dtype=jnp.bool_)
        else:
            pdt = _prep_dtype(dt)
            probe_tiles[k] = jnp.zeros((8, LANES), dtype=pdt)

    # ---- chip-independent trace probe: every lane's builders must stay
    # inside the Mosaic-safe elementwise set on a fake [8, 128] tile
    def probe(tiles):
        ctx = ScanContext(ds, tiles, min_day, max_day, tz=tz)
        cse = FU.CSECache(ctx)
        if fplan is not None:
            cse.prelower(fplan)
        outs = []
        for lp in lanes:
            base, key, dense, sketch = _lane_parts(lp, ctx, cse)
            outs += [base, key]
            outs += [v for _, _, v, _ in dense if v is not None]
            outs += [m for _, _, _, m in dense if m is not None]
            # sketch VALUES/masks trace in-kernel only for in-kernel
            # theta; HLL + epilogue theta run in XLA where anything goes
        return outs

    try:
        jx = jax.make_jaxpr(probe)(probe_tiles)
    except WaveFallback:
        raise
    except Exception as e:  # noqa: BLE001 — any trace failure -> jaxpr path
        raise WaveFallback(f"lane trace failed: {e}") from e
    _check_jaxpr(jx.jaxpr)

    # ---- scratch layout
    layouts: List[_LaneLayout] = []
    row = 0
    for lp in lanes:
        lay = _LaneLayout(lp, row)
        row = lay.next_row
        layouts.append(lay)
    out_rows = -(-row // 8) * 8                  # f32 sublane tile align
    if out_rows > MAX_OUT_ROWS:
        raise WaveFallback(f"scratch block {out_rows} rows exceeds "
                           f"{MAX_OUT_ROWS}")

    # in-kernel theta values must ALSO pass the probe (they trace inside
    # the kernel); check them against the same whitelist
    def probe_theta(tiles):
        ctx = ScanContext(ds, tiles, min_day, max_day, tz=tz)
        cse = FU.CSECache(ctx)
        outs = []
        for lp, lay in zip(lanes, layouts):
            if not lay.theta_base:
                continue
            for p in lp.agg_plans:
                if p.spec.name in lay.theta_base:
                    outs.append(p.build_values(ctx))
                    m = p.build_mask(ctx, cse=cse)
                    if m is not None:
                        outs.append(m)
        return outs

    if any(lay.theta_base for lay in layouts):
        try:
            _check_jaxpr(jax.make_jaxpr(probe_theta)(probe_tiles).jaxpr)
        except WaveFallback:
            raise
        except Exception as e:  # noqa: BLE001
            raise WaveFallback(f"theta trace failed: {e}") from e

    # ---- tile shape against the VMEM budget (planner/fusion.py)
    itemsizes = [np.dtype(_prep_dtype(np.dtype(array_dtype(ds, k))))
                 .itemsize for k in names]
    int_maxabs = [p.maxabs for lp in lanes for p in lp.agg_plans
                  if p.kind == "sum" and p.is_int and p.maxabs]
    block_rows = FU.plan_wave_tiles(itemsizes, int_maxabs, out_rows,
                                    int(tile_bytes))
    n_in = len(names)

    # per-row identity column, broadcast once at step 0 (one [out_rows, 1]
    # f32 operand instead of an unrolled store per accumulator row —
    # pallas kernels cannot close over array constants); comp rows and
    # alignment pads stay 0
    init_col = np.zeros((out_rows, 1), dtype=np.float32)
    for lp, lay in zip(lanes, layouts):
        for m, meta in enumerate(lay.dense_meta):
            for k in range(lp.n_keys):
                r = lay.base + k * lay.rpk + lay.offs[m]
                init_col[r, 0] = PG._INIT[meta.kind]
        for tbase in lay.theta_base.values():
            init_col[tbase: tbase + lp.n_keys * TH.K_LANES, 0] = 2.0

    # ---- the kernel
    def kernel(*refs):
        init_ref = refs[n_in]
        out_ref = refs[n_in + 1]
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:, :] = jnp.broadcast_to(init_ref[:],
                                             (out_rows, LANES))

        tiles = {}
        for i, name in enumerate(names):
            x = refs[i][:]
            tiles[name] = (x != 0) if name in bool_names else x
        ctx = ScanContext(ds, tiles, min_day, max_day, tz=tz)
        cse = FU.CSECache(ctx)
        if fplan is not None:
            cse.prelower(fplan)                  # shared masks: once/tile
        for lp, lay in zip(lanes, layouts):
            base, key, dense, sketch = _lane_parts(lp, ctx, cse)
            kb = jnp.where(base, key.astype(jnp.int32),
                           jnp.int32(lp.n_keys))
            for k in range(lp.n_keys):
                mk = kb == k
                for m, (kind, _, vals, am) in enumerate(dense):
                    eff = mk if am is None else (mk & am)
                    v32 = None if vals is None \
                        else vals.astype(jnp.float32)
                    part = PG.block_partial(kind, eff, v32)
                    PG.accumulate_rows(
                        out_ref, lay.base + k * lay.rpk + lay.offs[m],
                        kind, part)
            for p, vals, am in sketch:
                tbase = lay.theta_base.get(p.spec.name)
                if tbase is None:
                    continue                     # epilogue sketch
                eff = base if am is None else (base & am)
                for j in range(TH.K_LANES):
                    hv = jnp.where(eff, TH._hash01(vals, j), 2.0)
                    for k in range(lp.n_keys):
                        r = tbase + k * TH.K_LANES + j
                        part = jnp.min(jnp.where(kb == k, hv, 2.0),
                                       axis=0)
                        out_ref[r, :] = jnp.minimum(out_ref[r, :], part)

    interpret = PG._interpret()
    tile = block_rows * LANES
    blk = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))
    out_blk = pl.BlockSpec((out_rows, LANES), lambda i: (0, 0))
    need_epilogue = any(lay.hll or lay.theta_epilogue or lay.kll
                        for lay in layouts)

    def wave_fn(arrays):
        n = 1
        for d in arrays[names[0]].shape:
            n *= int(d)
        n_pad = -(-max(n, 1) // tile) * tile
        ops = []
        for name in names:
            a = arrays[name].reshape(-1)
            if name in bool_names:
                a = a.astype(jnp.int8)
            elif a.dtype.kind == "i" and a.dtype.itemsize < 4:
                a = a.astype(jnp.int32)
            if n_pad > n:
                a = jnp.pad(a, (0, n_pad - n))   # pads row_valid=0 rows
            ops.append(a.reshape(n_pad // LANES, LANES))
        ops.append(jnp.asarray(init_col))        # step-0 identity column
        out = pl.pallas_call(
            kernel,
            grid=(n_pad // tile,),
            in_specs=[blk] * n_in
            + [pl.BlockSpec((out_rows, 1), lambda i: (0, 0))],
            out_specs=out_blk,
            out_shape=jax.ShapeDtypeStruct((out_rows, LANES), jnp.float32),
            interpret=interpret,
        )(*ops)

        epi = None
        if need_epilogue:
            # sketches the scratch block cannot hold (HLL scatter-max,
            # wide theta) reuse the engine's XLA register ops in the SAME
            # jit — still one kernel launch; the sketch lanes' columns
            # stream once more through XLA
            ctx = ScanContext(ds, arrays, min_day, max_day, tz=tz)
            epi = FU.CSECache(ctx)
            if fplan is not None:
                epi.prelower(fplan)
            epi = (ctx, epi)

        results = []
        for lp, lay in zip(lanes, layouts):
            block = out[lay.base: lay.base + lp.n_keys * lay.rpk, :] \
                .reshape(lp.n_keys, lay.rpk, LANES)
            flat = {}
            for m, meta in enumerate(lay.dense_meta):
                off = lay.offs[m]
                if meta.kind in ("count", "sum"):
                    flat[meta.name] = (block[:, off, :],
                                       block[:, off + 1, :])
                elif meta.kind == "min":
                    flat[meta.name] = jnp.min(block[:, off, :], axis=-1)
                else:
                    flat[meta.name] = jnp.max(block[:, off, :], axis=-1)
            routed = G._pallas_to_routes(flat, lay.dense_meta, lp.routes)
            for name, tbase in lay.theta_base.items():
                tb = out[tbase: tbase + lp.n_keys * TH.K_LANES, :] \
                    .reshape(lp.n_keys, TH.K_LANES, LANES)
                routed[name] = jnp.min(tb, axis=-1)      # exact min union
            if lay.hll or lay.theta_epilogue or lay.kll:
                ctx, cse = epi
                base, key, _, sketch = _lane_parts(lp, ctx, cse)
                for p, vals, am in sketch:
                    nm = p.spec.name
                    if nm in lay.theta_base:
                        continue
                    m = base if am is None else (base & am)
                    if p.kind == "hll":
                        routed[nm] = HLL.hll_registers(
                            key, m, vals, lp.n_keys, log2m)
                    elif p.kind == "kll":
                        tcol = ctx.col(ds.time.name) \
                            if ds.time is not None else None
                        routed[nm] = KLL.kll_registers(
                            key, m, vals, tcol, lp.n_keys, kll_lanes)
                    else:
                        routed[nm] = TH.theta_registers(
                            key, m, vals, lp.n_keys)
            results.append(routed)
        return results

    info = {
        "block_rows": int(block_rows),
        "out_rows": int(out_rows),
        "lanes": len(lanes),
        "interpret": bool(interpret),
        "theta_inkernel": sum(len(lay.theta_base) for lay in layouts),
        "sketch_epilogue": sum(len(lay.hll) + len(lay.theta_epilogue)
                               + len(lay.kll) for lay in layouts),
        # double-buffered input tiles + the resident scratch block
        "vmem_bytes": int(block_rows * LANES * sum(itemsizes) * 2
                          + out_rows * LANES * 4),
    }
    return wave_fn, info
