"""Scan context: the bridge between host-side metadata (dictionaries, column
kinds) and the traced device arrays inside a compiled query program.

A ``ScanContext`` is constructed inside the jitted query function: the device
arrays it holds are **tracers** (function inputs), while the dictionaries and
cardinalities it consults are host constants — so dictionary-derived predicate
masks become small embedded constants in the compiled executable, and no
string ever reaches the device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from spark_druid_olap_tpu.segment.column import ColumnKind
from spark_druid_olap_tpu.segment.store import Datasource

TIME_MS_KEY = "__time_ms__"
ROW_VALID_KEY = "__rows__"
NULL_VALID_PREFIX = "__nulls__"


@dataclasses.dataclass
class ScanContext:
    """Host metadata + traced device arrays for one scan program."""

    ds: Datasource
    arrays: Dict[str, object]          # name -> traced [S, R] array
    min_day: int                       # over the selected segments
    max_day: int
    tz: str = "UTC"                    # session timezone (instants shift)

    # -- device array access --------------------------------------------------
    def col(self, name: str):
        if name not in self.arrays:
            raise KeyError(
                f"column {name!r} not bound into this scan program "
                f"(bound: {sorted(self.arrays)})")
        arr = self.arrays[name]
        dt = getattr(arr, "dtype", None)
        if dt is not None and dt.kind == "i" and dt.itemsize < 4:
            # narrow storage (i8/i16 codes and small longs) widens on
            # read: HBM holds the narrow bytes, kernels see i32
            arr = arr.astype(jnp.int32)
        return arr

    def row_valid(self):
        return self.arrays[ROW_VALID_KEY]

    def time_ms(self):
        return self.arrays.get(TIME_MS_KEY)

    def null_valid(self, name: str):
        """Validity mask for a nullable column, or None if non-nullable."""
        return self.arrays.get(NULL_VALID_PREFIX + name)

    # -- host metadata --------------------------------------------------------
    def kind(self, name: str) -> ColumnKind:
        return self.ds.column_kind(name)

    def is_time(self, name: str) -> bool:
        return self.ds.time is not None and name == self.ds.time.name

    def dictionary(self, name: str) -> np.ndarray:
        return self.ds.dims[name].dictionary

    def date_bounds(self, name: str):
        """(min_day, max_day) for a TIME or DATE column — bounds any
        granularity/extraction bucket cardinality."""
        if self.is_time(name):
            return self.min_day, self.max_day
        m = self.ds.metrics[name]
        lo, hi = m.min, m.max
        return int(lo if lo is not None else 0), int(hi if hi is not None else 0)


@dataclasses.dataclass
class CompactScanContext(ScanContext):
    """Late-materialization view over a parent scan: after the filter
    mask is evaluated on the full [S, R] arrays, surviving row positions
    are sorted to a static [M] prefix (``keep``) and every later column
    access gathers through it — so group-key building, value derivation,
    and aggregation all run at O(M) instead of O(N). This is the
    columnar-engine move Druid's historicals make with bitmap-index row
    lists; the TPU form keeps shapes static via a planner-chosen budget
    with on-device overflow detection (host retries uncompacted).

    Gathers are 1D [M]-probe (`take1d` cost model: ~7ms per million
    probes on v5e), so a selective filter turns a 6M-row scan's
    downstream work into single-digit milliseconds."""

    keep: object = None                # int32 [M] flat row positions

    def __post_init__(self):
        self._cache = {}

    def _gather(self, name: str, arr):
        hit = self._cache.get(name)
        if hit is None:
            flat = arr.reshape(-1)
            hit = self._cache[name] = flat[self.keep]
        return hit

    def col(self, name: str):
        return self._gather(name, super().col(name))

    def row_valid(self):
        return self._gather(ROW_VALID_KEY, super().row_valid())

    def time_ms(self):
        t = super().time_ms()
        return None if t is None else self._gather(TIME_MS_KEY, t)

    def null_valid(self, name: str):
        nv = super().null_valid(name)
        return None if nv is None else self._gather(
            NULL_VALID_PREFIX + name, nv)


def array_names(ds: Datasource, columns, need_time_ms: bool):
    """The array keys a scan program over ``columns`` binds."""
    names = list(columns)
    for name in columns:
        # metadata-only nulls check: building the stacked validity here
        # (the old spelling) would fault whole columns on a tiered store
        # just to PLAN the array list
        col = ds.dims.get(name) or ds.metrics.get(name)
        if col is not None and col.has_nulls():
            names.append(NULL_VALID_PREFIX + name)
    if need_time_ms and ds.time is not None:
        names.append(TIME_MS_KEY)
    names.append(ROW_VALID_KEY)
    return names


def array_dtype(ds: Datasource, key: str):
    """Host dtype of one stacked array (shape-only program tracing)."""
    if key == ROW_VALID_KEY or key.startswith(NULL_VALID_PREFIX):
        return np.bool_
    if key == TIME_MS_KEY:
        return ds.time.ms_dtype()
    if key in ds.dims:
        return ds.dims[key].data_dtype()
    if key in ds.metrics:
        return ds.metrics[key].data_dtype()
    if ds.time is not None and key == ds.time.name:
        return ds.time.data_dtype()
    return np.int32


def _stacked_by_key(ds: Datasource, key: str) -> np.ndarray:
    """The [S, R] stacked tensor behind one array key (S = local segments
    on a multi-host partial store)."""
    if key == ROW_VALID_KEY:
        return ds.stacked_row_validity()
    if key == TIME_MS_KEY:
        return ds.stacked_time_ms()
    if key.startswith(NULL_VALID_PREFIX):
        return ds.stacked_null_validity(key[len(NULL_VALID_PREFIX):])
    return ds.stacked(key)


def build_array(ds: Datasource, key: str,
                segment_indices: Optional[np.ndarray] = None,
                pad_segments_to: Optional[int] = None) -> np.ndarray:
    """Materialize one host-side stacked array by key.

    ``segment_indices`` selects (pruned) segments; ``pad_segments_to`` pads
    the segment axis with empty segments so the compiled program shape is
    stable across prunings (compile-cache friendliness) and divisible by the
    mesh size.
    """
    tb = getattr(ds, "_tier_build", None)
    if tb is not None:
        # tiered store: fault only the requested segments' chunks into
        # the stacked layout (tier/handles.py). Encoded chunks decode
        # inside the fault (tier/store.py), so this path returns
        # logical-dtype rows either way — the device never sees packed
        # bytes. None means the key is metadata-only (row validity) —
        # fall through to the base path.
        out = tb(key, segment_indices, pad_segments_to)
        if out is not None:
            return out
    if ds.is_partial:
        # global segment ids -> local block (only this host's segments may
        # be requested; the multi-host layout guarantees that). The
        # "all segments" default means the LOCAL set here — the only set
        # this process can materialize.
        idx = ds.local_seg_ids if segment_indices is None \
            else np.asarray(segment_indices, np.int64)
        arr = build_array_blocks(ds, key, idx)
    else:
        arr = _stacked_by_key(ds, key)
        if segment_indices is not None and (
                len(segment_indices) != ds.num_segments
                or not np.array_equal(segment_indices,
                                      np.arange(ds.num_segments))):
            arr = arr[segment_indices]
    if pad_segments_to is not None and arr.shape[0] < pad_segments_to:
        pad = np.zeros((pad_segments_to - arr.shape[0],) + arr.shape[1:],
                       dtype=arr.dtype)
        arr = np.concatenate([arr, pad], axis=0)
    return arr


def build_array_blocks(ds: Datasource, key: str,
                       seg_ids: np.ndarray) -> np.ndarray:
    """[len(seg_ids), R] host block for a multi-host layout slice: global
    segment ids; ``-1`` entries are padding (zero rows, row-validity
    False). On a partial store, a non-padding id not held locally is a
    layout bug and raises (the callback must never fabricate remote
    data)."""
    seg_ids = np.asarray(seg_ids, np.int64)
    arr = _stacked_by_key(ds, key)
    if ds.is_partial:
        pos = np.where(
            seg_ids >= 0,
            ds._local_pos[np.clip(seg_ids, 0, ds.num_segments - 1)], -1)
        missing = (seg_ids >= 0) & (pos < 0)
        if missing.any():
            raise RuntimeError(
                f"host {ds.host_id} asked for non-local segments "
                f"{seg_ids[missing][:8].tolist()} of {ds.name!r}")
    else:
        pos = seg_ids
    out = np.zeros((len(seg_ids),) + arr.shape[1:], dtype=arr.dtype)
    ok = pos >= 0
    if ok.any():
        out[ok] = arr[pos[ok]]
    return out


def required_arrays(ds: Datasource, columns, need_time_ms: bool,
                    segment_indices: Optional[np.ndarray] = None,
                    pad_segments_to: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Materialize every host-side stacked array a program needs."""
    return {k: build_array(ds, k, segment_indices, pad_segments_to)
            for k in array_names(ds, columns, need_time_ms)}
